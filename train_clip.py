#!/usr/bin/env python
"""CLIP training CLI, TPU-native.

The reference ships a trainable ``CLIP`` (dalle_pytorch.py:229-305) and uses
it to rerank generations (generate_images clip=..., dalle_pytorch.py:503-505)
but provides no training app for it — its README trains CLIP with an
inline-code block only. This CLI closes that gap with the same app surface as
train_dalle.py: folder dataset of image + same-stem caption files, compiled
sharded train step over a dp x fsdp x tp mesh, checkpoint/resume carrying all
hparams, wandb/console metrics, pre-flight save. The resulting checkpoint
plugs into ``generate.py --clip_path`` for sampling-time reranking.
"""

import argparse
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax


def parse_args():
    parser = argparse.ArgumentParser(description="Train CLIP on TPU")
    parser.add_argument("--image_text_folder", type=str, required=True,
                        help="folder of images + same-stem .txt captions")
    parser.add_argument("--clip_path", type=str, default=None,
                        help="path to a partially trained CLIP to resume")
    parser.add_argument("--clip_output_file_name", type=str, default="clip")
    parser.add_argument("--truncate_captions", action="store_true")
    parser.add_argument("--chinese", action="store_true")
    parser.add_argument("--hug", action="store_true")
    parser.add_argument("--bpe_path", type=str, default=None)
    parser.add_argument("--fp16", "--bf16", dest="bf16", action="store_true")
    parser.add_argument("--wandb", action="store_true")
    parser.add_argument("--wandb_name", default="clip_train")
    parser.add_argument("--seed", type=int, default=42)

    mesh_group = parser.add_argument_group("Mesh settings")
    mesh_group.add_argument("--fsdp", type=int, default=1)
    mesh_group.add_argument("--tp", type=int, default=1)

    model_group = parser.add_argument_group("Model settings")
    model_group.add_argument("--dim_text", type=int, default=512)
    model_group.add_argument("--dim_image", type=int, default=512)
    model_group.add_argument("--dim_latent", type=int, default=512)
    model_group.add_argument("--text_enc_depth", type=int, default=6)
    model_group.add_argument("--text_seq_len", type=int, default=256)
    model_group.add_argument("--text_heads", type=int, default=8)
    model_group.add_argument("--visual_enc_depth", type=int, default=6)
    model_group.add_argument("--visual_heads", type=int, default=8)
    model_group.add_argument("--visual_image_size", type=int, default=256)
    model_group.add_argument("--visual_patch_size", type=int, default=32)

    train_group = parser.add_argument_group("Training settings")
    train_group.add_argument("--epochs", default=20, type=int)
    train_group.add_argument("--save_every_n_steps", default=1000, type=int)
    train_group.add_argument("--batch_size", default=32, type=int)
    train_group.add_argument("--learning_rate", default=3e-4, type=float)
    train_group.add_argument("--clip_grad_norm", default=0.5, type=float)
    return parser.parse_args()


def main():
    args = parse_args()

    from dalle_pytorch_tpu.data import (
        ChineseTokenizer,
        DataLoader,
        HugTokenizer,
        SimpleTokenizer,
        TextImageDataset,
    )
    from dalle_pytorch_tpu.models.clip import CLIP
    from dalle_pytorch_tpu.models.factory import clip_from_checkpoint, save_clip_checkpoint
    from dalle_pytorch_tpu.parallel import (
        create_train_state,
        init_distributed,
        make_runtime,
        make_train_step,
    )
    from dalle_pytorch_tpu.utils import MetricsLogger, Throughput

    init_distributed()
    runtime = make_runtime(fsdp=args.fsdp, tp=args.tp)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32

    if args.chinese:
        tokenizer = ChineseTokenizer()
    elif args.hug:
        tokenizer = HugTokenizer(args.bpe_path)
    else:
        tokenizer = SimpleTokenizer(args.bpe_path)

    if args.clip_path:
        clip, resume_params, meta = clip_from_checkpoint(args.clip_path)
        start_epoch = int(meta.get("epoch", -1)) + 1
        if clip.dtype != dtype:
            clip = clip.clone(dtype=dtype)
    else:
        clip = CLIP(
            dim_text=args.dim_text,
            dim_image=args.dim_image,
            dim_latent=args.dim_latent,
            num_text_tokens=tokenizer.vocab_size,
            text_enc_depth=args.text_enc_depth,
            text_seq_len=args.text_seq_len,
            text_heads=args.text_heads,
            visual_enc_depth=args.visual_enc_depth,
            visual_heads=args.visual_heads,
            visual_image_size=args.visual_image_size,
            visual_patch_size=args.visual_patch_size,
            dtype=dtype,
        )
        resume_params = None
        start_epoch = 0

    dataset = TextImageDataset(
        args.image_text_folder,
        text_len=clip.text_seq_len,
        image_size=clip.visual_image_size,
        truncate_captions=args.truncate_captions,
        tokenizer=tokenizer,
        shuffle=True,
        seed=args.seed,
    )
    assert len(dataset) > 0, f"no image-text pairs found at {args.image_text_folder}"
    loader = DataLoader(
        dataset,
        args.batch_size,
        shuffle=True,
        seed=args.seed,
        process_index=runtime.process_index,
        process_count=runtime.process_count,
    )

    logger = MetricsLogger(
        project="clip_train",
        run_name=args.wandb_name,
        config=vars(args),
        enabled=runtime.is_root_worker(),
        use_wandb=args.wandb,
    )

    text0 = jnp.zeros((2, clip.text_seq_len), jnp.int32)
    image0 = jnp.zeros(
        (2, clip.visual_image_size, clip.visual_image_size, clip.channels)
    )
    if resume_params is not None:
        params = resume_params
    else:
        params = jax.jit(clip.init)(jax.random.key(args.seed), text0, image0)["params"]
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    logger.log_text(f"CLIP {n_params:,} params | mesh {dict(runtime.mesh.shape)}")

    optimizer = optax.chain(
        optax.clip_by_global_norm(args.clip_grad_norm),
        optax.adam(args.learning_rate),
    )
    state, shardings = create_train_state(params, optimizer, runtime)
    if args.clip_path:
        # keep Adam moments across resume (same contract as train_dalle.py)
        from dalle_pytorch_tpu.models.factory import restore_opt_state
        from dalle_pytorch_tpu.parallel import shard_pytree

        host_opt = restore_opt_state(
            args.clip_path, jax.tree_util.tree_map(np.asarray, state.opt_state)
        )
        if host_opt is not None:
            state = state._replace(
                opt_state=shard_pytree(host_opt, shardings.opt_state)
            )
    del params, resume_params

    def loss_fn(p, batch, rng):
        # the text mask marks real (non-pad) tokens for masked-mean pooling
        # (reference README's CLIP block passes an explicit mask)
        return clip.apply(
            {"params": p},
            batch["text"],
            batch["image"],
            text_mask=batch["text"] != 0,
            return_loss=True,
        )

    step_fn = make_train_step(loss_fn, optimizer, runtime, shardings)

    ckpt_path = f"{args.clip_output_file_name}.ckpt"

    def save(epoch):
        host_params = runtime.to_host(state.params)
        host_opt = runtime.to_host(state.opt_state)
        if not runtime.is_root_worker():
            return
        save_clip_checkpoint(
            ckpt_path, clip, host_params,
            extra={"epoch": epoch}, opt_state=host_opt,
        )

    save(start_epoch - 1)  # pre-flight: fail fast on misconfiguration

    throughput = Throughput(window=10)
    global_step = 0
    for epoch in range(start_epoch, args.epochs):
        for i, batch in enumerate(loader):
            train_batch = {
                "text": batch["text"],
                "image": jnp.asarray(batch["image"], dtype),
            }
            state, loss = step_fn(state, train_batch, jax.random.key(global_step))

            if i % 10 == 9 or i == 0:
                logger.log(
                    {"loss": float(loss), "epoch": epoch, "iter": i},
                    step=global_step,
                )
                logger.log_text(
                    f"step {global_step}: loss={float(loss):.4f} epoch={epoch}"
                )
            rate = throughput.update(args.batch_size)
            if rate is not None:
                logger.log({"sample_per_sec": rate}, step=global_step)
            if global_step % args.save_every_n_steps == args.save_every_n_steps - 1:
                save(epoch)
            global_step += 1
        save(epoch)
        logger.log_text(f"epoch {epoch} complete")

    logger.finish()


if __name__ == "__main__":
    main()
