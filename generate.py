#!/usr/bin/env python
"""Inference CLI: text -> images from a trained DALL-E checkpoint.

Mirrors the reference ``generate.py`` surface: checkpoint carries all hparams
(no model flags needed), prompts split on '|', batched generation, numbered
outputs per prompt under --outputs_dir, optional text completion (--gentxt).

Image generation runs through the continuous-batching serving ENGINE
(dalle_pytorch_tpu/serving): each image is a ``Request`` with its own seed,
decoded over the paged KV cache with admission control and typed outcomes —
the CLI exercises the same code path production serving does, instead of a
parallel one-shot path that only looks similar. Models the engine cannot
serve (gMLP layers) fall back to the fused scan decoder
(models/sampling.py) with a printed note.

The checkpoint is refused unless it verifies against its manifest sidecar
(sha256+size, utils/checkpoint.py) — a torn or bit-rotted file exits with a
typed error instead of deserializing garbage.
"""

import argparse
import sys
from pathlib import Path


def parse_args():
    parser = argparse.ArgumentParser(description="Generate images from a DALL-E checkpoint")
    parser.add_argument("--dalle_path", type=str, required=True)
    parser.add_argument("--text", type=str, required=True,
                        help="prompt(s); multiple prompts split on |")
    parser.add_argument("--num_images", type=int, default=128)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--top_k", type=float, default=0.9,
                        help="fractional top-k filter threshold (reference top_k thres)")
    parser.add_argument("--temperature", type=float, default=1.0)
    parser.add_argument("--outputs_dir", type=str, default="./outputs")
    parser.add_argument("--bpe_path", type=str, default=None)
    parser.add_argument("--hug", action="store_true")
    parser.add_argument("--chinese", action="store_true")
    parser.add_argument("--gentxt", action="store_true",
                        help="complete the prompt with the model before generating images")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fp16", "--bf16", dest="bf16", action="store_true",
                        help="serve in bf16: halves HBM weight traffic, the "
                             "decode bottleneck (analog of the reference's "
                             "fp16 generation)")
    parser.add_argument("--int8", action="store_true",
                        help="weight-only int8 serving: quantize the Dense "
                             "kernels per output channel at load time, "
                             "halving weight reads again vs bf16 (the "
                             "reference has no quantized path)")
    # local weight files for checkpoints trained against a frozen pretrained
    # VAE (whose weights are not bundled in the DALLE checkpoint)
    parser.add_argument("--vqgan_model_path", type=str, default=None)
    parser.add_argument("--vqgan_config_path", type=str, default=None)
    parser.add_argument("--openai_enc_path", type=str, default=None)
    parser.add_argument("--openai_dec_path", type=str, default=None)
    parser.add_argument("--clip_path", type=str, default=None,
                        help="CLIP checkpoint (train_clip.py) to score "
                             "generations; images are saved best-first "
                             "(reference generate_images clip rerank, "
                             "dalle_pytorch.py:503-505)")
    return parser.parse_args()


def _engine_images(engine, dalle, prompt_row, num_images, tag, seed):
    """Generate ``num_images`` images for one prompt through the (shared,
    reused across prompts) serving engine and its post-decode pipeline:
    one Request per image, each with its own (seed, position)-addressed
    sampling stream and a per-prompt ``tag`` namespacing its id. Tokens,
    the VAE decode, and (when the engine carries a CLIP) the rerank score
    all come back on the RequestResult — the CLI and production serving
    share ONE rerank path (serving/postdecode.py). Every request must
    COMPLETE here (no deadlines, roomy stage queue, default pool) — any
    other outcome, including a typed-degraded one, is a bug surfaced as a
    RuntimeError, never a silently missing image."""
    import numpy as np

    from dalle_pytorch_tpu.serving import Outcome, Request

    ids = [f"{tag}-img{i}" for i in range(num_images)]
    for i, rid in enumerate(ids):
        rejected = engine.submit(Request(
            request_id=rid,
            prompt=np.asarray(prompt_row, np.int32),
            max_new_tokens=dalle.image_seq_len,
            seed=seed + i,
        ))
        assert rejected is None, rejected
    results = engine.run()
    bad = {
        rid: results[rid].outcome.value for rid in ids
        if results[rid].outcome is not Outcome.COMPLETED
    }
    if bad:
        raise RuntimeError(f"engine failed requests: {bad}")
    images = np.stack([results[rid].image for rid in ids])
    scores = None
    if engine.postdecode is not None and engine.postdecode.rerank:
        scores = np.asarray(
            [results[rid].rerank_score for rid in ids], np.float32
        )
    return images, scores


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from dalle_pytorch_tpu.data import ChineseTokenizer, HugTokenizer, SimpleTokenizer
    from dalle_pytorch_tpu.models import generate_image_tokens, generate_texts
    from dalle_pytorch_tpu.models.factory import dalle_from_checkpoint
    from dalle_pytorch_tpu.models.vae import denormalize
    from dalle_pytorch_tpu.serving import EngineUnsupportedModel
    from dalle_pytorch_tpu.utils.checkpoint import (
        CheckpointError, check_checkpoint_file,
    )

    try:
        check_checkpoint_file(args.dalle_path)
    except CheckpointError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        print(
            "refusing to load an unverifiable checkpoint; regenerate it or "
            "restore from a verified save", file=sys.stderr,
        )
        sys.exit(2)
    dalle, params, vae, vae_params, meta = dalle_from_checkpoint(
        args.dalle_path,
        vae_weight_paths={
            k: getattr(args, k)
            for k in (
                "openai_enc_path", "openai_dec_path",
                "vqgan_config_path", "vqgan_model_path",
            )
        },
    )
    assert vae is not None, "checkpoint carries no VAE — cannot decode images"

    if args.bf16 or args.int8:
        from dalle_pytorch_tpu.utils.quantize import prepare_for_serving

        dalle, params = prepare_for_serving(dalle, params, int8=args.int8)

    if args.chinese:
        tokenizer = ChineseTokenizer()
    elif args.hug:
        tokenizer = HugTokenizer(args.bpe_path)
    else:
        tokenizer = SimpleTokenizer(args.bpe_path)

    clip = clip_params = None
    if args.clip_path:
        from dalle_pytorch_tpu.models.factory import clip_from_checkpoint

        clip, clip_params, _ = clip_from_checkpoint(args.clip_path)

    texts = [t.strip() for t in args.text.split("|") if t.strip()]
    outputs_dir = Path(args.outputs_dir)

    key = jax.random.key(args.seed)

    # ONE engine reused across prompts (the decode caches are allocated at
    # construction). The VAE decode and CLIP rerank ride as post-decode
    # STAGES on the engine (serving/postdecode.py) so the CLI and
    # production serving share one request→image path; the stage queue is
    # sized to the full image count so no request ever hits the typed
    # backlog-degrade policy here. gMLP models get the fused-scan fallback
    # with an ad-hoc decode/rerank instead.
    engine = None
    try:
        from dalle_pytorch_tpu.serving import (
            Engine, EngineConfig, StageConfig, StageSpec,
        )

        engine = Engine(
            dalle, params,
            EngineConfig(
                max_batch=args.batch_size,
                queue_limit=max(args.num_images, 1),
                filter_thres=args.top_k,
                temperature=args.temperature,
            ),
            stages=StageSpec(
                vae, vae_params, clip, clip_params,
                config=StageConfig(
                    batch=args.batch_size,
                    queue_limit=max(args.num_images, 1),
                ),
            ),
        )
    except EngineUnsupportedModel as e:
        print(
            f"serving engine unavailable for this model ({e}); "
            "falling back to the fused scan decoder",
            file=sys.stderr,
        )

    decode = None
    if engine is None:
        decode = jax.jit(
            lambda seq: vae.apply({"params": vae_params}, seq, method="decode")
        )

    for pi, text in enumerate(texts):
        if args.gentxt:
            prompt_ids = jnp.asarray([tokenizer.encode(text)], jnp.int32)
            key, sub = jax.random.split(key)
            _, completed = generate_texts(
                dalle, params, sub, prompt_ids, tokenizer=tokenizer,
                filter_thres=args.top_k, temperature=args.temperature,
            )
            text = completed[0].strip() if completed else text
            print(f"completed prompt: {text}")

        prompt_row = np.asarray(
            tokenizer.tokenize([text], dalle.text_seq_len, truncate_text=True)
        )[0]

        if engine is not None:
            images, scores = _engine_images(
                engine, dalle, prompt_row, args.num_images, tag=f"p{pi}",
                seed=args.seed * 1_000_003 + pi * 65_537,
            )
            images = denormalize(images, getattr(vae, "normalization", None))
            if scores is not None:
                # rerank: save best-scoring generations first (reference
                # dalle_pytorch.py:503-505); the scores were produced by
                # the engine's post-decode stage, so the CLI ordering and
                # serving's rerank agree bit-for-bit
                images = images[np.argsort(-scores)]
        else:
            tokens = jnp.asarray(
                np.repeat(prompt_row[None], args.batch_size, axis=0)
            )
            chunks = []
            for _ in range(-(-args.num_images // args.batch_size)):
                key, sub = jax.random.split(key)
                chunks.append(np.asarray(generate_image_tokens(
                    dalle, params, tokens, sub,
                    filter_thres=args.top_k, temperature=args.temperature,
                )))
            seqs = np.concatenate(chunks)[: args.num_images]

            images = []
            for s in range(0, len(seqs), args.batch_size):
                chunk = seqs[s : s + args.batch_size]
                n = len(chunk)
                if n < args.batch_size:  # pad ragged tail for the jit shape
                    chunk = np.concatenate(
                        [chunk,
                         np.repeat(chunk[-1:], args.batch_size - n, axis=0)]
                    )
                images.append(np.asarray(decode(jnp.asarray(chunk)))[:n])
            images = np.concatenate(images)

            images = denormalize(images, getattr(vae, "normalization", None))

            if clip is not None:
                # fallback-only ad-hoc rerank (the engine path gets its
                # scores from the shared post-decode stage instead)
                clip_imgs = jax.image.resize(
                    jnp.asarray(images),
                    (len(images), clip.visual_image_size,
                     clip.visual_image_size, 3),
                    method="bilinear",
                )
                clip_text = jnp.asarray(
                    tokenizer.tokenize(
                        [text], clip.text_seq_len, truncate_text=True
                    )
                ).repeat(len(images), axis=0)
                scores = clip.apply(
                    {"params": clip_params}, clip_text, clip_imgs,
                    text_mask=clip_text != 0,
                )
                images = images[np.argsort(-np.asarray(scores))]

        sub_dir = outputs_dir / text.replace(" ", "_")[:100]
        sub_dir.mkdir(parents=True, exist_ok=True)
        for i, arr in enumerate(images):
            Image.fromarray((arr * 255).astype(np.uint8)).save(
                sub_dir / f"{i}.png"
            )
        (sub_dir / "caption.txt").write_text(text)
        print(f"created {len(images)} images at '{sub_dir}'")


if __name__ == "__main__":
    main()
