#!/usr/bin/env python
"""Inference CLI: text -> images from a trained DALL-E checkpoint.

Mirrors the reference ``generate.py`` surface: checkpoint carries all hparams
(no model flags needed), prompts split on '|', batched generation, numbered
outputs per prompt under --outputs_dir, optional text completion (--gentxt).
Sampling runs the KV-cached scan decoder (one compile, O(seq) per token)
instead of the reference's full re-forward per token
(dalle_pytorch.py:481-486).
"""

import argparse
from pathlib import Path


def parse_args():
    parser = argparse.ArgumentParser(description="Generate images from a DALL-E checkpoint")
    parser.add_argument("--dalle_path", type=str, required=True)
    parser.add_argument("--text", type=str, required=True,
                        help="prompt(s); multiple prompts split on |")
    parser.add_argument("--num_images", type=int, default=128)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--top_k", type=float, default=0.9,
                        help="fractional top-k filter threshold (reference top_k thres)")
    parser.add_argument("--temperature", type=float, default=1.0)
    parser.add_argument("--outputs_dir", type=str, default="./outputs")
    parser.add_argument("--bpe_path", type=str, default=None)
    parser.add_argument("--hug", action="store_true")
    parser.add_argument("--chinese", action="store_true")
    parser.add_argument("--gentxt", action="store_true",
                        help="complete the prompt with the model before generating images")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fp16", "--bf16", dest="bf16", action="store_true",
                        help="serve in bf16: halves HBM weight traffic, the "
                             "decode bottleneck (analog of the reference's "
                             "fp16 generation)")
    parser.add_argument("--int8", action="store_true",
                        help="weight-only int8 serving: quantize the Dense "
                             "kernels per output channel at load time, "
                             "halving weight reads again vs bf16 (the "
                             "reference has no quantized path)")
    # local weight files for checkpoints trained against a frozen pretrained
    # VAE (whose weights are not bundled in the DALLE checkpoint)
    parser.add_argument("--vqgan_model_path", type=str, default=None)
    parser.add_argument("--vqgan_config_path", type=str, default=None)
    parser.add_argument("--openai_enc_path", type=str, default=None)
    parser.add_argument("--openai_dec_path", type=str, default=None)
    parser.add_argument("--clip_path", type=str, default=None,
                        help="CLIP checkpoint (train_clip.py) to score "
                             "generations; images are saved best-first "
                             "(reference generate_images clip rerank, "
                             "dalle_pytorch.py:503-505)")
    return parser.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from dalle_pytorch_tpu.data import ChineseTokenizer, HugTokenizer, SimpleTokenizer
    from dalle_pytorch_tpu.models import generate_image_tokens, generate_texts
    from dalle_pytorch_tpu.models.factory import dalle_from_checkpoint
    from dalle_pytorch_tpu.models.vae import denormalize

    assert Path(args.dalle_path).exists(), f"checkpoint not found at {args.dalle_path}"
    dalle, params, vae, vae_params, meta = dalle_from_checkpoint(
        args.dalle_path,
        vae_weight_paths={
            k: getattr(args, k)
            for k in (
                "openai_enc_path", "openai_dec_path",
                "vqgan_config_path", "vqgan_model_path",
            )
        },
    )
    assert vae is not None, "checkpoint carries no VAE — cannot decode images"

    if args.bf16 or args.int8:
        from dalle_pytorch_tpu.utils.quantize import prepare_for_serving

        dalle, params = prepare_for_serving(dalle, params, int8=args.int8)

    if args.chinese:
        tokenizer = ChineseTokenizer()
    elif args.hug:
        tokenizer = HugTokenizer(args.bpe_path)
    else:
        tokenizer = SimpleTokenizer(args.bpe_path)

    clip = clip_params = None
    if args.clip_path:
        from dalle_pytorch_tpu.models.factory import clip_from_checkpoint

        clip, clip_params, _ = clip_from_checkpoint(args.clip_path)

    texts = [t.strip() for t in args.text.split("|") if t.strip()]
    outputs_dir = Path(args.outputs_dir)

    key = jax.random.key(args.seed)
    decode = jax.jit(
        lambda seq: vae.apply({"params": vae_params}, seq, method="decode")
    )

    for text in texts:
        if args.gentxt:
            prompt_ids = jnp.asarray([tokenizer.encode(text)], jnp.int32)
            key, sub = jax.random.split(key)
            _, completed = generate_texts(
                dalle, params, sub, prompt_ids, tokenizer=tokenizer,
                filter_thres=args.top_k, temperature=args.temperature,
            )
            text = completed[0].strip() if completed else text
            print(f"completed prompt: {text}")

        tokens = tokenizer.tokenize(
            [text], dalle.text_seq_len, truncate_text=True
        ).repeat(args.batch_size, axis=0)
        tokens = jnp.asarray(tokens)

        images = []
        for _ in range(-(-args.num_images // args.batch_size)):
            key, sub = jax.random.split(key)
            img_seq = generate_image_tokens(
                dalle, params, tokens, sub,
                filter_thres=args.top_k, temperature=args.temperature,
            )
            images.append(np.asarray(decode(img_seq)))
        images = np.concatenate(images)[: args.num_images]

        images = denormalize(images, getattr(vae, "normalization", None))

        if clip is not None:
            # rerank: save best-scoring generations first (reference
            # dalle_pytorch.py:503-505)
            clip_imgs = jax.image.resize(
                jnp.asarray(images),
                (len(images), clip.visual_image_size, clip.visual_image_size, 3),
                method="bilinear",
            )
            clip_text = jnp.asarray(
                tokenizer.tokenize([text], clip.text_seq_len, truncate_text=True)
            ).repeat(len(images), axis=0)
            scores = clip.apply(
                {"params": clip_params}, clip_text, clip_imgs,
                text_mask=clip_text != 0,
            )
            order = np.argsort(-np.asarray(scores))
            images = images[order]

        sub_dir = outputs_dir / text.replace(" ", "_")[:100]
        sub_dir.mkdir(parents=True, exist_ok=True)
        for i, arr in enumerate(images):
            Image.fromarray((arr * 255).astype(np.uint8)).save(
                sub_dir / f"{i}.png"
            )
        (sub_dir / "caption.txt").write_text(text)
        print(f"created {len(images)} images at '{sub_dir}'")


if __name__ == "__main__":
    main()
