"""Test harness: force an 8-device virtual CPU platform so mesh/sharding
tests run anywhere — the TPU-native analog of the reference's DummyBackend
(dummy_backend.py), per SURVEY.md §4.

Note: the platform override must go through jax.config (not just the
JAX_PLATFORMS env var) because site hooks may have already pinned a
platform list; the explicit config update wins as long as no backend has
been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The suite is XLA-compile dominated (tiny shapes, hundreds of unique
# programs); skipping XLA's optimization pipeline cuts the cold full run
# ~35% without changing program semantics (measured: test_moe.py 85 -> 55 s).
# Runtime of the tiny test shapes is negligible either way; the TPU
# benchmarks (bench.py) never import this file and stay fully optimized.
# Exported via the environment so CLI-subprocess e2e tests and the
# multiprocess workers inherit it; set to 0 to override.
# The blanket disable means parity tests exercise the UNOPTIMIZED pipeline;
# the always-on counterweight is tests/test_optimized_smoke.py, a small
# tier-1 subset (decode parity + attention parity) that re-enables the
# optimization passes for its own compiles.
os.environ.setdefault("JAX_DISABLE_MOST_OPTIMIZATIONS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
# the explicit update matters: the axon site hook imports jax before this
# file runs, so the env var alone arrives too late for THIS process (it
# still reaches CLI/worker subprocesses, whose env is inherited)
jax.config.update(
    "jax_disable_most_optimizations",
    os.environ.get("JAX_DISABLE_MOST_OPTIMIZATIONS", "1") != "0",
)

# persistent compilation cache: the suite is dominated by XLA compiles
# (every jit at these tiny shapes is seconds), and re-runs hit the disk
# cache — measured ~5x faster grad compiles warm. Safe to delete any time.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

assert jax.local_device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

# fault injection must be OFF unless a test arms it explicitly — an armed
# env var would silently poison every download/shard/checkpoint test
assert not os.environ.get("DALLE_TPU_FAULTS"), (
    f"DALLE_TPU_FAULTS={os.environ['DALLE_TPU_FAULTS']!r} is set; the test "
    "suite requires fault injection off (tests arm FAULTS programmatically)"
)

# ... and the registry itself must start inert, with every production site
# (including the serving sites PR 3 added) known to it — a site name typo'd
# out of KNOWN_SITES would arm nothing and silently test nothing
from dalle_pytorch_tpu.utils.faults import FAULTS as _FAULTS  # noqa: E402
from dalle_pytorch_tpu.utils.faults import KNOWN_SITES as _SITES  # noqa: E402

assert not _FAULTS.active(), "fault registry armed at session start"
for _site in ("page_exhaust", "prefill_fail", "decode_stall",
              "request_cancel", "download", "ckpt_corrupt",
              "telemetry_sink_fail",
              # fleet sites (serving/router.py, PR 6)
              "replica_crash", "replica_stall", "health_flap"):
    assert _site in _SITES, f"production fault site {_site!r} unregistered"

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_resilience_registries():
    """Keep the process-wide fault registry, counters, gauges, histograms,
    and telemetry hermetic: a test that arms faults or trips metrics must
    not leak into the next."""
    from dalle_pytorch_tpu.utils.faults import FAULTS
    from dalle_pytorch_tpu.utils.metrics import counters, gauges, histograms
    from dalle_pytorch_tpu.utils.telemetry import TELEMETRY

    FAULTS.reset()
    counters.reset()
    gauges.reset()
    histograms.reset()
    TELEMETRY.reset()
    yield
    FAULTS.reset()
    counters.reset()
    gauges.reset()
    histograms.reset()
    TELEMETRY.reset()


def pytest_collection_modifyitems(config, items):
    """Data-driven slow tier: tests listed in tests/slow_tests.txt (measured
    > ~2 s cold on the reference 1-CPU box; regenerate from
    `pytest --durations=0`) get the ``slow`` marker in addition to any
    literal @pytest.mark.slow. `-m "not slow"` is the fast tier."""
    import pytest as _pytest

    listing = os.path.join(os.path.dirname(__file__), "slow_tests.txt")
    if not os.path.exists(listing):
        return
    with open(listing) as f:
        slow = {
            line.strip() for line in f
            if line.strip() and not line.startswith("#")
        }
    for item in items:
        if item.nodeid in slow:
            item.add_marker(_pytest.mark.slow)
