"""Test harness: force an 8-device virtual CPU platform so mesh/sharding
tests run anywhere — the TPU-native analog of the reference's DummyBackend
(dummy_backend.py), per SURVEY.md §4.

Note: the platform override must go through jax.config (not just the
JAX_PLATFORMS env var) because site hooks may have already pinned a
platform list; the explicit config update wins as long as no backend has
been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.local_device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)
