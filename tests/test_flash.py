"""Parity tests for the Pallas flash-attention kernel (ops/flash_attention.py)
against the dense masked oracle (ops.attention.dense_attend), forward AND
gradients, at realistic sequence lengths — including the flagship DALL-E
seq 1280 — in interpret mode on CPU.

Reference semantics being matched: dense causal attention
(/root/reference/dalle_pytorch/attention.py:71-79) and DeepSpeed
variable-sparsity block attention (attention.py:338-351).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import masks as masks_lib
from dalle_pytorch_tpu.ops.attention import dense_attend
from dalle_pytorch_tpu.ops.flash_attention import StaticMask, flash_attention


def _qkv(key, b, h, n, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, n, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def _oracle(q, k, v, mask_np):
    scale = q.shape[-1] ** -0.5
    return dense_attend(q * scale, k, v, jnp.asarray(mask_np)[None, None])


def _flash(q, k, v, causal, pattern, block):
    return flash_attention(
        q, k, v,
        causal=causal,
        pattern_mask=pattern,
        sm_scale=q.shape[-1] ** -0.5,
        block_q=block,
        block_k=block,
        interpret=True,
    )


@pytest.mark.parametrize("n,block", [(128, 64), (256, 128)])
def test_causal_forward_parity(n, block):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 3, n, 64)
    out = _flash(q, k, v, True, None, block)
    ref = _oracle(q, k, v, masks_lib.causal_mask(n))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n,block", [(128, 64), (256, 128)])
def test_causal_grad_parity(n, block):
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 2, n, 64)
    mask = masks_lib.causal_mask(n)

    def f_flash(q, k, v):
        return (_flash(q, k, v, True, None, block) ** 2).sum()

    def f_ref(q, k, v):
        return (_oracle(q, k, v, mask) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_block_sparse_forward_parity():
    n = 256
    mask = masks_lib.block_sparse_mask(n, block_size=16, text_seq_len=64, seed=3)
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 2, n, 64)
    out = _flash(q, k, v, True, StaticMask(mask), 64)
    ref = _oracle(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_block_sparse_grad_parity():
    n = 128
    mask = masks_lib.block_sparse_mask(n, block_size=16, text_seq_len=32, seed=5)
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 2, n, 64)

    def f_flash(q, k, v):
        return (_flash(q, k, v, True, StaticMask(mask), 32) ** 2).sum()

    def f_ref(q, k, v):
        return (_oracle(q, k, v, mask) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_fully_masked_rows_zero_output_and_grads():
    """A query row masked in every block must emit 0 output and leak no
    gradient (ADVICE.md round-1 finding: m stays NEG_INF so p became 1)."""
    n = 64
    mask = np.tril(np.ones((n, n), dtype=bool))
    mask[5, :] = False  # row 5 sees nothing
    mask[40, :] = False
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 1, n, 64)
    out = _flash(q, k, v, False, StaticMask(mask), 32)
    np.testing.assert_allclose(out[0, 0, 5], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 40], 0.0, atol=1e-6)

    def f(q, k, v):
        return (_flash(q, k, v, False, StaticMask(mask), 32) ** 2).sum()

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(dq[0, 0, 5], 0.0, atol=1e-6)
    # dk/dv get no contribution from the masked rows: compare against the
    # oracle with those rows excluded
    mask_j = jnp.asarray(mask)[None, None]

    def f_ref(q, k, v):
        out = dense_attend(q * (64**-0.5), k, v, mask_j)
        live = jnp.asarray(mask.any(axis=1), jnp.float32)[None, None, :, None]
        return ((out * live) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(dk, g_ref[1], atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(dv, g_ref[2], atol=5e-4, rtol=5e-4)


@pytest.mark.slow
def test_flagship_seq_1280_forward_parity():
    """The exact shape that crashed round 1: seq 1280 (= 256 text + 1024
    image), block 128 — forward parity vs the dense oracle."""
    n, block = 1280, 128
    q, k, v = _qkv(jax.random.PRNGKey(6), 1, 2, n, 64)
    out = _flash(q, k, v, True, None, block)
    ref = _oracle(q, k, v, masks_lib.causal_mask(n))
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)


@pytest.mark.slow
def test_flagship_seq_1280_grad_runs():
    n, block = 1280, 128
    q, k, v = _qkv(jax.random.PRNGKey(7), 1, 1, n, 64)

    def f(q, k, v):
        return _flash(q, k, v, True, None, block).sum()

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(np.asarray(dq)).all()
    assert np.isfinite(np.asarray(dk)).all()
    assert np.isfinite(np.asarray(dv)).all()


def test_bfloat16_forward_close():
    n = 128
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 2, n, 64, jnp.bfloat16)
    out = _flash(q, k, v, True, None, 64)
    ref = _oracle(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        masks_lib.causal_mask(n),
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref, atol=5e-2, rtol=5e-2
    )


@pytest.mark.slow
def test_flagship_production_block_parity():
    """seq 1280 at the PRODUCTION block size (_flash_block(1280) — one
    whole-row block), not a test-sized one: block-size-dependent code
    (diagonal classification, scratch shapes, the kb==0 / kb==nk-1
    epilogues) must be exercised at the configuration the flagship model
    actually dispatches to."""
    from dalle_pytorch_tpu.ops.attention import _flash_block

    n = 1280
    block = _flash_block(n)
    assert block == 1280, "update this test if the block heuristic changes"
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 2, n, 64)
    out = _flash(q, k, v, True, None, block)
    mask = masks_lib.causal_mask(n)
    want = _oracle(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    # gradient PARITY at the single-block configuration (nk == 1, so the
    # kb==0 and kb==nk-1 epilogues coincide) — finiteness alone would miss
    # a wrong accumulation there
    cot = jax.random.normal(jax.random.PRNGKey(7), out.shape)

    def flash_loss(q, k, v):
        return (_flash(q, k, v, True, None, block) * cot).sum()

    def oracle_loss(q, k, v):
        return (_oracle(q, k, v, mask) * cot).sum()

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    want_g = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want_g, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name} mismatch at production block",
        )
