"""Parity tests for the Pallas flash-attention kernel (ops/flash_attention.py)
against the dense masked oracle (ops.attention.dense_attend), forward AND
gradients, at realistic sequence lengths — including the flagship DALL-E
seq 1280 — in interpret mode on CPU.

Reference semantics being matched: dense causal attention
(/root/reference/dalle_pytorch/attention.py:71-79) and DeepSpeed
variable-sparsity block attention (attention.py:338-351).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import masks as masks_lib
from dalle_pytorch_tpu.ops.attention import dense_attend
from dalle_pytorch_tpu.ops.flash_attention import StaticMask, flash_attention


def _qkv(key, b, h, n, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, n, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


def _oracle(q, k, v, mask_np):
    scale = q.shape[-1] ** -0.5
    return dense_attend(q * scale, k, v, jnp.asarray(mask_np)[None, None])


def _flash(q, k, v, causal, pattern, block):
    return flash_attention(
        q, k, v,
        causal=causal,
        pattern_mask=pattern,
        sm_scale=q.shape[-1] ** -0.5,
        block_q=block,
        block_k=block,
        interpret=True,
    )


@pytest.mark.parametrize("n,block", [(128, 64), (256, 128)])
def test_causal_forward_parity(n, block):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 3, n, 64)
    out = _flash(q, k, v, True, None, block)
    ref = _oracle(q, k, v, masks_lib.causal_mask(n))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n,block", [(128, 64), (256, 128)])
def test_causal_grad_parity(n, block):
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 2, n, 64)
    mask = masks_lib.causal_mask(n)

    def f_flash(q, k, v):
        return (_flash(q, k, v, True, None, block) ** 2).sum()

    def f_ref(q, k, v):
        return (_oracle(q, k, v, mask) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_block_sparse_forward_parity():
    n = 256
    mask = masks_lib.block_sparse_mask(n, block_size=16, text_seq_len=64, seed=3)
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 2, n, 64)
    out = _flash(q, k, v, True, StaticMask(mask), 64)
    ref = _oracle(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_block_sparse_grad_parity():
    n = 128
    mask = masks_lib.block_sparse_mask(n, block_size=16, text_seq_len=32, seed=5)
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 2, n, 64)

    def f_flash(q, k, v):
        return (_flash(q, k, v, True, StaticMask(mask), 32) ** 2).sum()

    def f_ref(q, k, v):
        return (_oracle(q, k, v, mask) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_fully_masked_rows_zero_output_and_grads():
    """A query row masked in every block must emit 0 output and leak no
    gradient (ADVICE.md round-1 finding: m stays NEG_INF so p became 1)."""
    n = 64
    mask = np.tril(np.ones((n, n), dtype=bool))
    mask[5, :] = False  # row 5 sees nothing
    mask[40, :] = False
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 1, n, 64)
    out = _flash(q, k, v, False, StaticMask(mask), 32)
    np.testing.assert_allclose(out[0, 0, 5], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 40], 0.0, atol=1e-6)

    def f(q, k, v):
        return (_flash(q, k, v, False, StaticMask(mask), 32) ** 2).sum()

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(dq[0, 0, 5], 0.0, atol=1e-6)
    # dk/dv get no contribution from the masked rows: compare against the
    # oracle with those rows excluded
    mask_j = jnp.asarray(mask)[None, None]

    def f_ref(q, k, v):
        out = dense_attend(q * (64**-0.5), k, v, mask_j)
        live = jnp.asarray(mask.any(axis=1), jnp.float32)[None, None, :, None]
        return ((out * live) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(dk, g_ref[1], atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(dv, g_ref[2], atol=5e-4, rtol=5e-4)


@pytest.mark.slow
def test_flagship_seq_1280_forward_parity():
    """The exact shape that crashed round 1: seq 1280 (= 256 text + 1024
    image), block 128 — forward parity vs the dense oracle."""
    n, block = 1280, 128
    q, k, v = _qkv(jax.random.PRNGKey(6), 1, 2, n, 64)
    out = _flash(q, k, v, True, None, block)
    ref = _oracle(q, k, v, masks_lib.causal_mask(n))
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)


@pytest.mark.slow
def test_flagship_seq_1280_grad_runs():
    n, block = 1280, 128
    q, k, v = _qkv(jax.random.PRNGKey(7), 1, 1, n, 64)

    def f(q, k, v):
        return _flash(q, k, v, True, None, block).sum()

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(np.asarray(dq)).all()
    assert np.isfinite(np.asarray(dk)).all()
    assert np.isfinite(np.asarray(dv)).all()


def test_bfloat16_forward_close():
    n = 128
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 2, n, 64, jnp.bfloat16)
    out = _flash(q, k, v, True, None, 64)
    ref = _oracle(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        masks_lib.causal_mask(n),
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref, atol=5e-2, rtol=5e-2
    )


# --------------------------------------------------- runtime key-padding mask


def _km_oracle(q, k, v, mask_np, km):
    """Dense oracle with a runtime (b, n) key mask folded in. Rows whose
    every key is masked follow the kernel's contract: exactly 0 output."""
    scale = q.shape[-1] ** -0.5
    allowed = jnp.asarray(mask_np)[None, None] & km[:, None, None, :]
    out = dense_attend(q * scale, k, v, allowed)
    live = jnp.any(allowed, axis=-1)[..., None]
    return jnp.where(live, out, 0.0)


def _rand_key_mask(key, b, n, fully_masked_batch=0):
    km = jax.random.uniform(key, (b, n)) > 0.3
    if fully_masked_batch is not None:
        km = km.at[fully_masked_batch].set(False)
    return km


def test_key_mask_forward_parity():
    """Ref attention.py:71-74 pad-mask semantics through the flash kernel:
    random key masks, one batch with EVERY key masked (all rows -> 0)."""
    b, h, n, d, block = 3, 2, 128, 64, 64
    q, k, v = _qkv(jax.random.PRNGKey(10), b, h, n, d)
    km = _rand_key_mask(jax.random.PRNGKey(11), b, n)
    out = flash_attention(
        q, k, v, key_mask=km, causal=True,
        sm_scale=d**-0.5, block_q=block, block_k=block, interpret=True,
    )
    ref = _km_oracle(q, k, v, masks_lib.causal_mask(n), km)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # the fully-masked batch is exactly zero
    np.testing.assert_allclose(out[0], 0.0, atol=0.0)


def test_key_mask_grad_parity():
    b, h, n, d, block = 2, 2, 128, 64, 64
    q, k, v = _qkv(jax.random.PRNGKey(12), b, h, n, d)
    km = _rand_key_mask(jax.random.PRNGKey(13), b, n, fully_masked_batch=None)
    # hand-mask a few single rows' entire key set via the causal prefix:
    # key 0 masked makes row 0 fully masked
    km = km.at[:, 0].set(False)
    mask_np = masks_lib.causal_mask(n)

    def f_flash(q, k, v):
        o = flash_attention(
            q, k, v, key_mask=km, causal=True,
            sm_scale=d**-0.5, block_q=block, block_k=block, interpret=True,
        )
        return (o**2).sum()

    def f_ref(q, k, v):
        return (_km_oracle(q, k, v, mask_np, km) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b_, atol=5e-4, rtol=5e-4)


def test_key_mask_with_block_sparse_pattern():
    """Key mask composes with a static sparse pattern (both stream through
    the same kernel)."""
    n, block = 128, 32
    mask = masks_lib.block_sparse_mask(n, block_size=16, text_seq_len=32, seed=7)
    q, k, v = _qkv(jax.random.PRNGKey(14), 2, 2, n, 64)
    km = _rand_key_mask(jax.random.PRNGKey(15), 2, n, fully_masked_batch=None)
    out = flash_attention(
        q, k, v, key_mask=km, causal=True, pattern_mask=StaticMask(mask),
        sm_scale=64**-0.5, block_q=block, block_k=block, interpret=True,
    )
    ref = _km_oracle(q, k, v, mask, km)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_key_mask_noncausal():
    """CLIP's masked non-causal text encoder shape: no pattern operand at
    all (analytic all-dense visit map) + runtime key mask."""
    n, block = 256, 128
    q, k, v = _qkv(jax.random.PRNGKey(16), 2, 2, n, 64)
    km = _rand_key_mask(jax.random.PRNGKey(17), 2, n, fully_masked_batch=None)
    out = flash_attention(
        q, k, v, key_mask=km, causal=False,
        sm_scale=64**-0.5, block_q=block, block_k=block, interpret=True,
    )
    ref = _km_oracle(q, k, v, np.ones((n, n), bool), km)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_key_mask_keeps_linear_memory():
    """The VERDICT round-2 regression guard: a key-padding mask must NOT
    bounce attention to the dense path — no (n, n)-shaped buffer may appear
    anywhere in the lowered computation (fwd or bwd)."""
    import re

    b, h, n, d, block = 2, 2, 256, 64, 128
    q, k, v = _qkv(jax.random.PRNGKey(18), b, h, n, d)
    km = _rand_key_mask(jax.random.PRNGKey(19), b, n, fully_masked_batch=None)

    def loss(q, k, v, km):
        o = flash_attention(
            q, k, v, key_mask=km, causal=True,
            sm_scale=d**-0.5, block_q=block, block_k=block, interpret=True,
        )
        return (o**2).sum()

    hlo = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, k, v, km).as_text()
    square = re.compile(rf"\[(?:\d+,)*{n},{n}\]")
    offenders = [l for l in hlo.split("\n") if square.search(l)]
    assert not offenders, f"(n, n) buffers materialized:\n" + "\n".join(offenders[:5])


def test_pattern_attention_masked_dispatches_flash(monkeypatch):
    """ops/attention.py no longer gates the flash path on mask is None: a
    masked full-causal PatternAttention must call flash_attention, and its
    output must match the dense fallback."""
    from dalle_pytorch_tpu.ops import attention as attention_mod

    b, n, dim = 2, 128, 128
    module = attention_mod.PatternAttention(
        dim=dim, seq_len=n, attn_type="full", causal=True, heads=2, dim_head=64
    )
    x = jax.random.normal(jax.random.PRNGKey(20), (b, n, dim))
    mask = _rand_key_mask(jax.random.PRNGKey(21), b, n, fully_masked_batch=None)
    # keep row 0 live (bos-like): a fully-masked row would legitimately
    # differ between flash (0) and dense fallback (uniform average)
    mask = mask.at[:, 0].set(True)
    params = module.init(jax.random.PRNGKey(0), x, mask=mask)

    calls = []
    real_flash = attention_mod.flash_attention
    real_fused = attention_mod.fused_qkv_attention

    def spy_flash(*args, **kw):
        calls.append(kw.get("key_mask"))
        return real_flash(*args, **kw)

    def spy_fused(qkv, key_mask, *args, **kw):
        calls.append(key_mask)
        return real_fused(qkv, key_mask, *args, **kw)

    monkeypatch.setattr(attention_mod, "flash_attention", spy_flash)
    monkeypatch.setattr(attention_mod, "fused_qkv_attention", spy_fused)
    out_flash = module.apply(params, x, mask=mask)
    assert calls and calls[0] is not None, "masked call bypassed the flash kernel"

    out_dense = module.apply(params, x, mask=mask, force_dense=True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dense), atol=2e-5, rtol=2e-5
    )


# ------------------------------------------------- packed-qkv fused kernel


def _rand_rotary(n, d, key):
    """A pair-constant angle table like the real DALL-E one (repeat-2
    structure is what makes the in-kernel inverse rotation valid)."""
    from dalle_pytorch_tpu.ops.flash_attention import StaticTable

    half = jax.random.normal(key, (n, d // 2))
    table = jnp.repeat(half, 2, axis=-1)
    return StaticTable(np.asarray(table))


def test_fused_qkv_matches_unfused_through_transformer():
    """The packed single-block path (split/reshape/transpose/rotary all
    inside the kernel) must match the dense reference path through a real
    Transformer — forward AND parameter gradients, with and without a
    key-padding mask, rotary on."""
    from dalle_pytorch_tpu.models.transformer import Transformer

    # depth 1 / n 128 is the smallest config the packed path admits
    # (n % 128 == 0, heads % hpb == 0); layer stacking is covered elsewhere
    kw = dict(dim=128, depth=1, seq_len=128, causal=True, heads=2, dim_head=64,
              image_fmap_size=8, rotary_emb=True)
    tr = Transformer(**kw)
    tr_dense = Transformer(**kw, use_flash=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 128))
    mask = (jax.random.uniform(jax.random.PRNGKey(1), (2, 128)) > 0.3).at[:, 0].set(True)
    params = tr.init(jax.random.PRNGKey(2), x)

    import dalle_pytorch_tpu.ops.attention as A
    calls = []
    real = A.fused_qkv_attention

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    A.fused_qkv_attention = spy
    try:
        for m in (None, mask):
            np.testing.assert_allclose(
                np.asarray(tr.apply(params, x, mask=m)),
                np.asarray(tr_dense.apply(params, x, mask=m)),
                atol=3e-4, rtol=3e-4,
            )
        # gradients: the masked case only (unmasked grads are pinned by
        # test_causal_grad_parity and test_fused_qkv_direct_parity)
        gf = jax.tree_util.tree_leaves(
            jax.grad(lambda p: (tr.apply(p, x, mask=mask) ** 2).sum())(params)
        )
        gd = jax.tree_util.tree_leaves(
            jax.grad(lambda p: (tr_dense.apply(p, x, mask=mask) ** 2).sum())(params)
        )
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3)
    finally:
        A.fused_qkv_attention = real
    assert calls, "fused path never dispatched"


def test_fused_qkv_direct_parity():
    """fused_qkv_attention vs the per-head pipeline it replaces: split ->
    (b, h, n, d) -> rotary on q, k AND v -> masked dense attention."""
    from dalle_pytorch_tpu.ops.flash_attention import fused_qkv_attention
    from dalle_pytorch_tpu.ops.rotary import apply_rotary_emb

    b, n, h, d = 2, 128, 2, 64
    qkv = jax.random.normal(jax.random.PRNGKey(3), (b, n, 3 * h * d))
    km = _rand_key_mask(jax.random.PRNGKey(4), b, n, fully_masked_batch=None)
    km = km.at[:, 0].set(True)
    rot = _rand_rotary(n, d, jax.random.PRNGKey(5))

    def reference(qkv):
        q, k, v = (t.reshape(b, n, h, d).transpose(0, 2, 1, 3)
                   for t in jnp.split(qkv, 3, axis=-1))
        table = jnp.asarray(rot.table)[None, None]
        q, k, v = (apply_rotary_emb(table, t) for t in (q, k, v))
        allowed = jnp.asarray(masks_lib.causal_mask(n))[None, None] & km[:, None, None, :]
        out = dense_attend(q * d**-0.5, k, v, allowed)
        return out.transpose(0, 2, 1, 3).reshape(b, n, h * d)

    def fused(qkv):
        return fused_qkv_attention(
            qkv, km, h, d, rot, True, None, d**-0.5, True
        )

    np.testing.assert_allclose(
        np.asarray(fused(qkv)), np.asarray(reference(qkv)), atol=2e-5, rtol=2e-5
    )
    cot = jax.random.normal(jax.random.PRNGKey(6), (b, n, h * d))
    g_fused = jax.grad(lambda q_: (fused(q_) * cot).sum())(qkv)
    g_ref = jax.grad(lambda q_: (reference(q_) * cot).sum())(qkv)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_ref), atol=5e-4, rtol=5e-4
    )


@pytest.mark.slow
def test_flagship_seq1280_key_mask_parity():
    """Masked parity at the flagship seq 1280 (VERDICT round-2 item 1)."""
    n, block = 1280, 128
    q, k, v = _qkv(jax.random.PRNGKey(22), 1, 2, n, 64)
    km = _rand_key_mask(jax.random.PRNGKey(23), 1, n, fully_masked_batch=None)
    km = km.at[:, 0].set(True)
    out = flash_attention(
        q, k, v, key_mask=km, causal=True,
        sm_scale=64**-0.5, block_q=block, block_k=block, interpret=True,
    )
    ref = _km_oracle(q, k, v, masks_lib.causal_mask(n), km)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)


@pytest.mark.slow
def test_flagship_production_block_parity():
    """seq 1280 at the PRODUCTION block size (_flash_block(1280) — one
    whole-row block), not a test-sized one: block-size-dependent code
    (diagonal classification, scratch shapes, the kb==0 / kb==nk-1
    epilogues) must be exercised at the configuration the flagship model
    actually dispatches to."""
    from dalle_pytorch_tpu.ops.attention import _flash_block

    n = 1280
    block = _flash_block(n)
    assert block == 1280, "update this test if the block heuristic changes"
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 2, n, 64)
    out = _flash(q, k, v, True, None, block)
    mask = masks_lib.causal_mask(n)
    want = _oracle(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    # gradient PARITY at the single-block configuration (nk == 1, so the
    # kb==0 and kb==nk-1 epilogues coincide) — finiteness alone would miss
    # a wrong accumulation there
    cot = jax.random.normal(jax.random.PRNGKey(7), out.shape)

    def flash_loss(q, k, v):
        return (_flash(q, k, v, True, None, block) * cot).sum()

    def oracle_loss(q, k, v):
        return (_oracle(q, k, v, mask) * cot).sum()

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    want_g = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want_g, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name} mismatch at production block",
        )


def test_fused_qkv_supported_vmem_bound():
    """The n cap must come from the backward's VMEM footprint (4 (n,n) f32
    temporaries x heads-per-block against the 100 MB limit with headroom),
    not a fixed constant: at d=64 (hpb=2) n=2048 needs ~134 MB and must be
    rejected, while the flagship n=1280 (~52 MB) stays admitted."""
    from dalle_pytorch_tpu.ops.flash_attention import fused_qkv_supported

    assert fused_qkv_supported(1280, 16, 64)
    assert fused_qkv_supported(1536, 16, 64)  # 75.5 MB — compiles on v5e
    assert not fused_qkv_supported(1792, 16, 64)  # 102 MB — over budget
    assert not fused_qkv_supported(2048, 16, 64)
    # smaller heads-per-block (d=128, hpb=1) halves the footprint: 2048
    # needs ~67 MB and fits
    assert fused_qkv_supported(2048, 8, 128)
    assert not fused_qkv_supported(1280 + 64, 16, 64)  # alignment still holds


def test_rot_tables_reject_non_pair_constant():
    """_inv_rot_block is only a valid VJP for pair-constant angle tables
    (table[:, 0::2] == table[:, 1::2]); a foreign table violating that must
    be rejected loudly instead of yielding silently wrong gradients."""
    from dalle_pytorch_tpu.ops.flash_attention import StaticTable, _rot_tables

    good = np.repeat(np.linspace(0, 1, 8 * 4).reshape(8, 4), 2, axis=1)
    cos, sin = _rot_tables(StaticTable(good.astype(np.float32)), 8, 8, jnp.float32)
    assert cos.shape == (8, 8)

    bad = good.copy()
    bad[:, 1] += 0.5  # break one pair
    with pytest.raises(AssertionError, match="pair-constant"):
        _rot_tables(StaticTable(bad.astype(np.float32)), 8, 8, jnp.float32)
