"""Model-layer tests: DiscreteVAE, DALLE (forward, loss, decode parity), CLIP,
and the scan-based sampling loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import (
    init_decode_cache,
    CLIP,
    DALLE,
    DiscreteVAE,
    generate_image_tokens,
    gumbel_softmax,
)
from dalle_pytorch_tpu.models.dalle import NEG_INF


def small_dalle(**kw):
    defaults = dict(
        dim=32,
        depth=2,
        num_text_tokens=16,
        text_seq_len=4,
        num_image_tokens=12,
        image_fmap_size=2,
        heads=2,
        dim_head=8,
        attn_types=("full", "axial_row"),
        shift_tokens=True,
        rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


def dalle_inputs(dalle, b=2, seed=0):
    rng = np.random.RandomState(seed)
    text = jnp.asarray(
        rng.randint(1, dalle.num_text_tokens, size=(b, dalle.text_seq_len)), jnp.int32
    )
    image = jnp.asarray(
        rng.randint(0, dalle.num_image_tokens, size=(b, dalle.image_seq_len)), jnp.int32
    )
    return text, image


# ------------------------------------------------------------------- VAE


class TestDiscreteVAE:
    def make(self, **kw):
        defaults = dict(
            image_size=16, num_tokens=8, codebook_dim=16, num_layers=2, hidden_dim=8
        )
        defaults.update(kw)
        return DiscreteVAE(**defaults)

    def test_forward_and_loss(self):
        vae = self.make(num_resnet_blocks=1, kl_div_loss_weight=0.01)
        img = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3), jnp.float32)
        params = vae.init({"params": jax.random.key(0), "gumbel": jax.random.key(1)}, img)
        loss, recons = vae.apply(
            params, img, return_loss=True, return_recons=True,
            rngs={"gumbel": jax.random.key(2)},
        )
        assert recons.shape == img.shape
        assert np.isfinite(float(loss))

    def test_codebook_indices_and_decode(self):
        vae = self.make()
        img = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3), jnp.float32)
        params = vae.init({"params": jax.random.key(0), "gumbel": jax.random.key(1)}, img)
        idx = vae.apply(params, img, method=DiscreteVAE.get_codebook_indices)
        assert idx.shape == (2, vae.image_seq_len)
        assert int(idx.min()) >= 0 and int(idx.max()) < vae.num_tokens
        out = vae.apply(params, idx, method=DiscreteVAE.decode)
        assert out.shape == img.shape

    def test_smooth_l1_mode(self):
        vae = self.make(smooth_l1_loss=True)
        img = jnp.asarray(np.random.RandomState(0).rand(1, 16, 16, 3), jnp.float32)
        params = vae.init({"params": jax.random.key(0), "gumbel": jax.random.key(1)}, img)
        loss = vae.apply(params, img, return_loss=True, rngs={"gumbel": jax.random.key(2)})
        assert np.isfinite(float(loss))

    def test_straight_through_is_hard(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(3, 7), jnp.float32)
        hard = gumbel_softmax(logits, jax.random.key(0), 0.9, hard=True)
        np.testing.assert_allclose(np.sort(np.asarray(hard))[:, -1], 1.0, atol=1e-6)
        np.testing.assert_allclose(hard.sum(-1), 1.0, atol=1e-6)

    def test_kl_matches_torch_quirk(self):
        """The reference's kl_div(batchmean) divides by input.size(0)=1 — i.e.
        it's a SUM (dalle_pytorch.py:213-220). Check our loss tracks that."""
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        logits_np = np.random.RandomState(0).randn(2, 4, 8).astype(np.float32)
        log_qy = F.log_softmax(torch.tensor(logits_np), dim=-1)
        log_uniform = torch.log(torch.tensor([1.0 / 8]))
        ref = F.kl_div(log_uniform, log_qy, None, None, "batchmean", log_target=True)

        lq = jax.nn.log_softmax(jnp.asarray(logits_np), axis=-1)
        ours = jnp.sum(jnp.exp(lq) * (lq + jnp.log(8.0)))
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)


# ------------------------------------------------------------------ DALLE


class TestDALLE:
    def test_forward_logits_and_mask(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        logits = dalle.apply({"params": params}, text, image)
        assert logits.shape == (2, dalle.total_seq_len, dalle.total_tokens)
        logits = np.asarray(logits)
        # text positions may not predict image tokens, and vice versa
        assert (logits[:, : dalle.text_seq_len, dalle.num_text_tokens_ext :] <= NEG_INF).all()
        assert (logits[:, dalle.text_seq_len :, : dalle.num_text_tokens_ext] <= NEG_INF).all()

    def test_loss_finite_and_pad_remap_matters(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        loss = dalle.apply({"params": params}, text, image, return_loss=True)
        assert np.isfinite(float(loss))
        # zero-padded text must hit the unique per-position pad embeddings
        text0 = text.at[:, -2:].set(0)
        loss0 = dalle.apply({"params": params}, text0, image, return_loss=True)
        assert float(loss0) != float(loss)

    @pytest.mark.parametrize("mode", ["reversible", "remat"])
    def test_memory_modes_train(self, mode):
        dalle = small_dalle(
            reversible=(mode == "reversible"), remat=(mode == "remat"), shift_tokens=False
        )
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]

        def loss_fn(p):
            return dalle.apply({"params": p}, text, image, return_loss=True)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        flat = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in flat)

    def test_split_head_loss_matches_masked_ce(self):
        """The block-diagonal head loss must equal the reference's masked
        full-vocab log_softmax CE exactly (the logits mask is block-diagonal,
        so skipping the dead blocks changes no value and no gradient)."""
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        loss = float(dalle.apply({"params": params}, text, image, return_loss=True))

        logits = dalle.apply({"params": params}, text, image)  # masked, f32
        labels = np.concatenate(
            (
                np.asarray(dalle.remap_text(text))[:, 1:],
                np.asarray(image) + dalle.num_text_tokens_ext,
            ),
            axis=1,
        )
        lp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
        tll = np.take_along_axis(lp, labels[..., None], axis=-1)[..., 0]
        tl = dalle.text_seq_len
        ref = (-tll[:, :tl].mean() + dalle.loss_img_weight * -tll[:, tl:].mean()) / (
            dalle.loss_img_weight + 1
        )
        np.testing.assert_allclose(loss, ref, atol=2e-3)

    def test_text_only_forward(self):
        dalle = small_dalle()
        text, _ = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, None)["params"]
        logits = dalle.apply({"params": params}, text)
        assert logits.shape == (2, dalle.text_len_internal, dalle.total_tokens)

    @pytest.mark.parametrize(
        "kw",
        [
            dict(),
            dict(rotary_emb=False),
            dict(attn_types=("conv_like", "axial_col"), stable=True),
            dict(attn_types=("full", "mlp"), rotary_emb=False),
        ],
    )
    def test_decode_matches_forward(self, kw):
        """KV-cached decode_step must reproduce the full-forward logits at
        every position — the core correctness contract for fast sampling."""
        dalle = small_dalle(**kw)
        text, image = dalle_inputs(dalle, b=2)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        full_logits = np.asarray(dalle.apply({"params": params}, text, image))

        internal = np.concatenate(
            (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
        )
        # first decode call only materializes the cache (attention returns
        # zeros without advancing state) — init explicitly, then replay
        cache = init_decode_cache(dalle, params, batch_size=2)
        for i in range(dalle.total_seq_len):
            step_logits, mutated = dalle.apply(
                {"params": params, "cache": cache},
                jnp.asarray(internal[:, i]),
                jnp.array(i, jnp.int32),
                method=DALLE.decode_step,
                mutable=["cache"],
            )
            cache = mutated["cache"]
            np.testing.assert_allclose(
                np.asarray(step_logits),
                full_logits[:, i],
                atol=2e-3,
                rtol=1e-3,
                err_msg=f"decode/forward mismatch at position {i} (config {kw})",
            )

    @pytest.mark.parametrize(
        "kw",
        [
            dict(),
            dict(rotary_emb=False),
            dict(attn_types=("conv_like", "axial_col"), stable=True),
            dict(attn_types=("full", "mlp"), rotary_emb=False),
        ],
    )
    def test_prefill_matches_sequential_decode(self, kw):
        """prefill_step (one parallel pass over the text prompt) must leave
        the caches and logits exactly as T sequential decode_step calls."""
        dalle = small_dalle(**kw)
        text, image = dalle_inputs(dalle, b=2)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = np.asarray(dalle.remap_text(text))
        T = dalle.text_len_internal

        # sequential reference
        cache = init_decode_cache(dalle, params, batch_size=2)
        for i in range(T):
            seq_logits, mutated = dalle.apply(
                {"params": params, "cache": cache},
                jnp.asarray(internal[:, i]),
                jnp.array(i, jnp.int32),
                method=DALLE.decode_step,
                mutable=["cache"],
            )
            cache = mutated["cache"]

        # parallel prefill
        cache2 = init_decode_cache(dalle, params, batch_size=2)
        pre_logits, mutated2 = dalle.apply(
            {"params": params, "cache": cache2},
            jnp.asarray(internal[:, :T]),
            method=DALLE.prefill_step,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(pre_logits), np.asarray(seq_logits), atol=2e-3, rtol=1e-3
        )
        flat1 = jax.tree_util.tree_leaves_with_path(cache)
        flat2 = jax.tree_util.tree_leaves_with_path(mutated2["cache"])
        for (p1, a), (p2, b) in zip(flat1, flat2):
            assert p1 == p2
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=1e-3,
                err_msg=f"cache mismatch at {jax.tree_util.keystr(p1)} ({kw})",
            )

    @pytest.mark.parametrize("kw", [dict(), dict(attn_types=("conv_like", "axial_row"))])
    def test_windowed_decode_and_image_head_match_full(self, kw):
        """A decode step against frontier-sized (truncated) K/V caches with
        the image-only sliced head must equal the full-cache, full-head
        step: truncated-away rows are masked (exp(-inf) = 0 contributions
        either way, ops/attention.py:_decode_attend) and the sliced head
        computes the exact same output columns (models/dalle.py:_head_image).
        Tolerance covers summation-order drift only (the narrower einsum
        chunks its reduction differently; ~1 ulp observed on CPU)."""
        dalle = small_dalle(**kw)
        text, image = dalle_inputs(dalle, b=2)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = dalle.remap_text(text)
        T = dalle.text_len_internal

        # pin the 4-D format: this test's truncate_kv exercises the
        # flat/4-D row-windowing path (batch 2 now defaults to the paged
        # cache, whose page-granular windowing is covered by
        # tests/test_paged_kv.py)
        cache = init_decode_cache(dalle, params, batch_size=2, cache_format="4d")
        _, mutated = dalle.apply(
            {"params": params, "cache": cache},
            internal,
            method=DALLE.prefill_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        tok = image[:, 0]
        pos = jnp.array(T, jnp.int32)

        full, _ = dalle.apply(
            {"params": params, "cache": cache}, tok, pos,
            method=DALLE.decode_step, mutable=["cache"],
        )
        ext = dalle.num_text_tokens_ext

        def truncate_kv(cache, W):
            def fn(path, x):
                if getattr(path[-1], "key", None) in ("cached_key", "cached_value"):
                    return x[:, :W]
                return x

            return jax.tree_util.tree_map_with_path(fn, cache)

        for window in (T + 1, T + 3, None):
            small = cache if window is None else truncate_kv(cache, window)
            sliced, _ = dalle.apply(
                {"params": params, "cache": small}, tok, pos,
                image_only=True,
                method=DALLE.decode_step, mutable=["cache"],
            )
            assert sliced.shape == (2, dalle.num_image_tokens)
            np.testing.assert_allclose(
                np.asarray(sliced), np.asarray(full[:, ext:]),
                atol=1e-5, rtol=1e-5,
                err_msg=f"window={window} ({kw})",
            )

    def test_flat_kv_cache_format_matches_4d(self, monkeypatch):
        """The flat (b, L, h*d) K/V cache format (the measured batch-8
        serving layout, ops/attention.py:_decode_caches) must sample the
        exact same tokens as the default 4-D format — the rank only changes
        the array shape XLA lays out, never the arithmetic."""
        dalle = small_dalle()
        text, image = dalle_inputs(dalle, b=2)
        params = dalle.init(jax.random.key(0), text, image)["params"]

        monkeypatch.setenv("DALLE_TPU_FLAT_KV", "0")
        toks_4d = generate_image_tokens(dalle, params, text, jax.random.key(7))
        jax.clear_caches()  # cache shapes differ; force a fresh trace
        monkeypatch.setenv("DALLE_TPU_FLAT_KV", "1")
        toks_flat = generate_image_tokens(dalle, params, text, jax.random.key(7))
        np.testing.assert_array_equal(np.asarray(toks_4d), np.asarray(toks_flat))


# ------------------------------------------------------------------- CLIP


class TestCLIP:
    def make(self):
        return CLIP(
            dim_text=32,
            dim_image=32,
            dim_latent=16,
            num_text_tokens=50,
            text_enc_depth=1,
            text_seq_len=8,
            text_heads=2,
            visual_enc_depth=1,
            visual_heads=2,
            visual_image_size=16,
            visual_patch_size=8,
        )

    def test_similarity_and_loss(self):
        clip = self.make()
        rng = np.random.RandomState(0)
        text = jnp.asarray(rng.randint(0, 50, size=(3, 8)), jnp.int32)
        image = jnp.asarray(rng.rand(3, 16, 16, 3), jnp.float32)
        mask = jnp.asarray(rng.rand(3, 8) > 0.2)
        params = clip.init(jax.random.key(0), text, image, mask)
        sim = clip.apply(params, text, image, mask)
        assert sim.shape == (3,)
        loss = clip.apply(params, text, image, mask, return_loss=True)
        assert np.isfinite(float(loss))


# --------------------------------------------------------------- sampling


class TestSampling:
    def test_generate_image_tokens(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        img_seq = generate_image_tokens(dalle, params, text, jax.random.key(1))
        assert img_seq.shape == (2, dalle.image_seq_len)
        seq = np.asarray(img_seq)
        assert (seq >= 0).all() and (seq < dalle.num_image_tokens).all()

    def test_priming_preserved(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        prime = image[:, :2]
        img_seq = generate_image_tokens(
            dalle, params, text, jax.random.key(1), prime_tokens=prime
        )
        np.testing.assert_array_equal(np.asarray(img_seq[:, :2]), np.asarray(prime))

    def test_sampling_is_deterministic_per_key(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        a = generate_image_tokens(dalle, params, text, jax.random.key(7))
        b = generate_image_tokens(dalle, params, text, jax.random.key(7))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_generate_texts(self):
        from dalle_pytorch_tpu.models import generate_texts

        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        tokens, texts = generate_texts(dalle, params, jax.random.key(0))
        assert tokens.shape == (1, dalle.text_seq_len)
        assert texts is None
        toks = np.asarray(tokens)
        assert int(toks[0, 0]) == 0  # starts at <bos>
        assert (toks >= 0).all() and (toks < dalle.num_text_tokens_ext).all()
        # prompt tokens are preserved
        prompt = jnp.asarray([[0, 5, 9]], jnp.int32)
        tokens, _ = generate_texts(dalle, params, jax.random.key(1), prompt)
        np.testing.assert_array_equal(np.asarray(tokens[:, :3]), np.asarray(prompt))

    def test_generate_images_pipeline(self):
        """Full text -> pixels pipeline including VAE priming and CLIP rerank
        (images/scores shapes, finiteness, truncation of overlong text)."""
        from dalle_pytorch_tpu.models import generate_images

        vae = DiscreteVAE(
            image_size=8, num_tokens=12, codebook_dim=16, num_layers=2, hidden_dim=8
        )
        img = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 3), jnp.float32)
        vae_vars = vae.init(
            {"params": jax.random.key(0), "gumbel": jax.random.key(1)}, img
        )
        dalle = small_dalle(num_image_tokens=12, image_fmap_size=2)
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]

        clip = CLIP(
            dim_text=16, dim_image=16, dim_latent=8, num_text_tokens=64,
            text_enc_depth=1, text_seq_len=dalle.text_seq_len, text_heads=2,
            visual_enc_depth=1, visual_heads=2, visual_image_size=8,
            visual_patch_size=4,
        )
        clip_vars = clip.init(jax.random.key(0), text, img)

        # overlong text must be truncated for both decode and rerank
        long_text = jnp.pad(text, ((0, 0), (0, 3)), constant_values=1)
        images, scores = generate_images(
            dalle, params, vae, {"params": vae_vars["params"]}, long_text,
            jax.random.key(2), clip=clip, clip_variables=clip_vars, img=img,
        )
        assert images.shape == (2, 8, 8, 3)
        assert scores.shape == (2,)
        assert bool(jnp.isfinite(images).all()) and bool(jnp.isfinite(scores).all())
