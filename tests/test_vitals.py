"""Engine vitals layer (utils/vitals.py) and its metrics substrate
(ISSUE 19): windowed histogram deltas that never reset the cumulative
Prometheus series, the gauge ring's sliding reductions, the
once-per-signature cost ledger, and the Vitals windows the controller
consumes — all pure host arithmetic, no engine required."""

import math

import pytest

from dalle_pytorch_tpu.utils.metrics import (
    GaugeRing,
    Histogram,
    HistogramCheckpoint,
    gauges,
)
from dalle_pytorch_tpu.utils.vitals import (
    CostLedger,
    Vitals,
    peaks_for,
)


# ------------------------------------------------------------ GaugeRing


class TestGaugeRing:
    def test_empty_window_is_zero(self):
        r = GaugeRing(4)
        assert r.values() == []
        assert r.window() == {
            "count": 0.0, "last": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0,
        }

    def test_partial_fill(self):
        r = GaugeRing(4)
        r.push(1.0)
        r.push(3.0)
        assert r.values() == [1.0, 3.0]
        w = r.window()
        assert w["count"] == 2.0 and w["last"] == 3.0
        assert w["mean"] == 2.0 and w["min"] == 1.0 and w["max"] == 3.0

    def test_wraparound_drops_oldest(self):
        r = GaugeRing(3)
        for v in (1.0, 2.0, 3.0, 4.0, 5.0):
            r.push(v)
        assert r.values() == [3.0, 4.0, 5.0]
        w = r.window()
        assert w["min"] == 3.0 and w["max"] == 5.0 and w["last"] == 5.0

    def test_capacity_one(self):
        r = GaugeRing(1)
        r.push(7.0)
        r.push(9.0)
        assert r.values() == [9.0]
        assert r.window()["mean"] == 9.0


# -------------------------------------------- Histogram.snapshot_delta


class TestSnapshotDelta:
    def test_window_excludes_pre_checkpoint(self):
        h = Histogram()
        for v in (0.001, 0.002, 0.003):
            h.observe(v)
        ck = h.checkpoint()
        h.observe(10.0)
        h.observe(20.0)
        d = h.snapshot_delta(ck)
        assert d["count"] == 2.0
        assert d["sum"] == pytest.approx(30.0)
        assert d["mean"] == pytest.approx(15.0)
        # window p50 lands in the 10s decade, far from the 1ms samples
        assert d["p50"] > 1.0
        # cumulative series untouched
        assert h.count == 5 and h.snapshot()["count"] == 5

    def test_none_checkpoint_is_lifetime(self):
        h = Histogram()
        h.observe(1.0)
        h.observe(2.0)
        d = h.snapshot_delta(None)
        assert d["count"] == 2.0 and d["sum"] == pytest.approx(3.0)

    def test_empty_window(self):
        h = Histogram()
        h.observe(1.0)
        ck = h.checkpoint()
        d = h.snapshot_delta(ck)
        assert d["count"] == 0.0 and d["sum"] == pytest.approx(0.0)
        assert d["p50"] == 0.0 and d["p99"] == 0.0

    def test_geometry_mismatch_degrades_to_lifetime(self):
        h = Histogram()
        h.observe(1.0)
        alien = HistogramCheckpoint(counts=(0, 0), count=0, sum=0.0,
                                    max=-math.inf)
        d = h.snapshot_delta(alien)
        assert d["count"] == 1.0

    def test_stale_checkpoint_after_reset_degrades(self):
        # a checkpoint NEWER than the current state (someone rebuilt the
        # histogram) must not produce negative windows
        h = Histogram()
        for _ in range(5):
            h.observe(1.0)
        ck = h.checkpoint()
        h2 = Histogram()
        h2.observe(2.0)
        d = h2.snapshot_delta(ck)
        assert d["count"] == 1.0 and d["sum"] == pytest.approx(2.0)

    def test_window_percentiles_track_window_not_lifetime(self):
        h = Histogram()
        for _ in range(100):
            h.observe(0.001)
        ck = h.checkpoint()
        for _ in range(10):
            h.observe(100.0)
        # lifetime p50 still sits at the 1ms mass; the window's is 100s
        assert h.percentile(50) < 0.01
        d = h.snapshot_delta(ck)
        assert d["p50"] > 50.0

    def test_checkpoint_charges_nothing_to_cumulative(self):
        h = Histogram()
        h.observe(1.0)
        before = h.snapshot()
        h.checkpoint()
        h.snapshot_delta(h.checkpoint())
        assert h.snapshot() == before


# ----------------------------------------------------------- CostLedger


class TestCostLedger:
    def test_charge_once_per_signature(self):
        led = CostLedger()
        assert led.charge("iteration", 100.0, 200.0)
        assert not led.charge("iteration", 999.0, 999.0)  # first wins
        assert led.entry("iteration") == {
            "flops": 100.0, "bytes_accessed": 200.0,
        }
        assert led.has("iteration") and not led.has("decode")
        assert led.entry("decode") is None

    def test_roofline_frac_binding_roof(self):
        led = CostLedger()
        led.charge("it", 1e12, 1e12)
        peaks = {"flops": 2e12, "bytes_ps": 1e12}
        # over 1s: flops frac 0.5, bytes frac 1.0 -> the binding roof
        assert led.roofline_frac("it", 1.0, peaks) == pytest.approx(1.0)
        # over 2s both halve
        assert led.roofline_frac("it", 2.0, peaks) == pytest.approx(0.5)

    def test_roofline_degenerate_inputs(self):
        led = CostLedger()
        led.charge("it", 1e12, 1e12)
        peaks = {"flops": 1e12, "bytes_ps": 1e12}
        assert led.roofline_frac("it", 0.0, peaks) == 0.0  # FakeClock dt=0
        assert led.roofline_frac("it", 1.0, None) == 0.0   # unknown device
        assert led.roofline_frac("other", 1.0, peaks) == 0.0  # uncharged

    def test_peaks_table(self):
        assert peaks_for("TPU v5 lite")["flops"] > 0
        assert peaks_for("cpu") is None
        assert peaks_for(None) is None


# --------------------------------------------------------------- Vitals


def feed(v, n, *, dt=1.0, drafted=0, accepted=0, hits=0, misses=0,
         dl=0, terms=0, occ=0.5, stage=0.0, jit=None, t0=0.0):
    """Push n iterations of CUMULATIVE samples growing linearly."""
    for i in range(1, n + 1):
        v.observe_iteration(
            now=t0 + i * dt, occupancy=occ, stage_queued=stage,
            spec_drafted=drafted * i, spec_accepted=accepted * i,
            prefix_hits=hits * i, prefix_misses=misses * i,
            deadline_misses=dl * i, terminations=terms * i,
            jit_name=jit,
        )


class TestVitals:
    def test_windowed_accept_rate(self):
        v = Vitals(window=8)
        feed(v, 20, drafted=4, accepted=3)
        snap = v.snapshot()
        assert snap["spec_accept_rate"] == pytest.approx(0.75)
        assert snap["spec_drafted"] == pytest.approx(4 * 7)  # window deltas
        assert snap["iterations"] == 20.0

    def test_rate_is_windowed_not_lifetime(self):
        # 10 iterations at accept 1.0, then 10 at accept 0 — the window
        # must read ~0 while the lifetime frac would read ~0.5
        v = Vitals(window=4)
        for i in range(1, 11):
            v.observe_iteration(
                now=float(i), occupancy=0.5, stage_queued=0,
                spec_drafted=4 * i, spec_accepted=4 * i,
                prefix_hits=0, prefix_misses=0,
                deadline_misses=0, terminations=0,
            )
        for i in range(11, 21):
            v.observe_iteration(
                now=float(i), occupancy=0.5, stage_queued=0,
                spec_drafted=4 * i, spec_accepted=40,
                prefix_hits=0, prefix_misses=0,
                deadline_misses=0, terminations=0,
            )
        assert v.snapshot()["spec_accept_rate"] == pytest.approx(0.0)

    def test_gap_and_miss_rate(self):
        v = Vitals(window=8)
        feed(v, 10, dt=0.25, dl=1, terms=4)
        snap = v.snapshot()
        assert snap["decode_gap_s"] == pytest.approx(0.25)
        assert snap["deadline_miss_rate"] == pytest.approx(0.25)
        assert snap["occupancy"] == pytest.approx(0.5)

    def test_zero_denominators(self):
        v = Vitals(window=4)
        feed(v, 2)
        snap = v.snapshot()
        assert snap["spec_accept_rate"] == 0.0
        assert snap["prefix_hit_frac"] == 0.0
        assert snap["deadline_miss_rate"] == 0.0
        assert snap["roofline_frac"] == 0.0

    def test_roofline_live_gauge(self):
        v = Vitals(window=4, peaks={"flops": 1e9, "bytes_ps": 1e9})
        v.ledger.charge("iteration", 5e8, 1e8)
        feed(v, 4, dt=1.0, jit="iteration")
        assert v.snapshot()["roofline_frac"] == pytest.approx(0.5)

    def test_publish_sets_registered_gauges(self):
        v = Vitals(window=4)
        feed(v, 6, drafted=4, accepted=2, hits=1, misses=1)
        snap = v.publish(gauges)
        assert gauges.get("serve.vitals.spec_accept_rate") == pytest.approx(
            snap["spec_accept_rate"]
        )
        assert gauges.get("serve.vitals.prefix_hit_frac") == pytest.approx(0.5)
        assert gauges.get("serve.vitals.decode_gap_s") == pytest.approx(1.0)
        assert gauges.get("serve.vitals.occupancy") == pytest.approx(0.5)
        assert gauges.get("serve.vitals.deadline_miss_rate") == 0.0
        assert gauges.get("serve.vitals.stage_lag") == 0.0
        assert gauges.get("serve.vitals.roofline_frac") == 0.0

    def test_snapshot_keys_are_stable(self):
        # a deterministic controller must never branch on key existence
        v = Vitals(window=4)
        keys0 = set(v.snapshot())
        feed(v, 10, drafted=4, accepted=4)
        assert set(v.snapshot()) == keys0
