"""Known-bad telemetry-name fixture against fx_names_registry.py.
AST-parsed only."""

counters = gauges = histograms = TELEMETRY = None  # parsed, never run


def emit(reason):
    counters.inc("fx.known")                       # clean
    counters.inc("fx.typo")                        # line 9: DTL041
    gauges.set("fx.known", 1.0)                    # line 10: DTL041 (kind)
    histograms.observe("fx.wait_s", 0.1)           # clean
    histograms.observe("fx.request_s", 0.1)        # clean: span duration
    TELEMETRY.event("fx.evt", detail=1)            # clean
    TELEMETRY.span("fx.request")                   # clean
    counters.inc(f"fx.reasons.{reason}")           # clean: head matches
    counters.inc(f"fx.bogus.{reason}")             # line 16: DTL041 (head)
