"""Exercise corpus for the fault-site fixture: drills that arm
used_site (programmatic) and dead_site (env-spec fragment).

undrilled_site=1 — this docstring MENTIONS a drill spec, and that must
NOT count: documentation of a drill is not a drill (docstrings are
excluded from the exercise corpus), so undrilled_site still raises
DTL033."""

SPEC = "dead_site=1"


def drill(faults):
    faults.arm("used_site", 2)
