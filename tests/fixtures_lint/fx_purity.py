"""Known-bad jit-purity fixture (tests/test_static_analysis.py).

NEVER imported — AST-parsed only. Each violation's line number is pinned
by the test, so keep edits append-only or fix the test's expectations.
"""

import time

import jax
import numpy as np
from functools import partial

HISTORY = []          # mutable module global (DTL014 bait)
LIMIT = 4             # immutable global: never flagged


@jax.jit
def bad_branch(x):
    if x > 0:                        # line 19: DTL011
        return -x
    return x


@partial(jax.jit, static_argnums=(1,))
def bad_sync(x, n):
    if n > 2:                        # static arg: NOT a finding
        x = x + 1
    y = x * LIMIT
    v = float(y)                     # line 29: DTL012 (propagated taint)
    w = x.item()                     # line 30: DTL012
    return x + v + w


@jax.jit
def bad_clock(x):
    t = time.time()                  # line 36: DTL013
    return x + t + len(HISTORY)      # line 37: DTL014


def _helper(y):
    return y * np.random.rand()      # line 41: DTL013 (reached from jit)


@jax.jit
def reaches_impure(x):
    return _helper(x)


@jax.jit
def structure_check(x, mask=None):
    if mask is None:                 # is-None: NOT a finding
        return x
    return x * mask


@jax.jit
def suppressed_branch(x):
    # legit-looking dynamic branch a reviewer accepted with a reason:
    if x.sum() > 0:  # dtl: disable=DTL011
        return x
    return -x


@jax.jit
def baselined_loop(x):
    while x > 0:                     # line 66: DTL011 — grandfathered in
        x = x - 1                    # fx_baseline.json, not fixed yet
    return x


@jax.jit
def twin_branches(x):
    if x > 0:                        # line 73: DTL011, anchor ...:If
        x = x + 1
    if x < 0:                        # line 75: DTL011, anchor ...:If#2 —
        x = x - 1                    # colliding anchors get occurrence
    return x                         # suffixes so a baseline entry can
                                     # only ever excuse ONE violation
