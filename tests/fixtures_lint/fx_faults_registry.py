"""Miniature fault registry for the fault-site checker fixtures.
AST-parsed only."""

KNOWN_SITES = frozenset({
    "used_site",       # taken + exercised: clean
    "dead_site",       # exercised but never taken: DTL032
    "undrilled_site",  # taken but never exercised: DTL033
})

_VALUE_SITES = frozenset()
