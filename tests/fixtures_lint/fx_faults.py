"""Known-bad fault-site fixture: take-sites against the miniature
registry (fx_faults_registry.py). AST-parsed only."""


class _FakeFaults:
    def take(self, site):
        return False

    def maybe_raise(self, site, exc):
        pass


FAULTS = _FakeFaults()


def production_path():
    if FAULTS.take("used_site"):            # clean
        return "boom"
    if FAULTS.take("undrilled_site"):       # clean here; DTL033 at registry
        return "boom"
    if FAULTS.take("typo_site"):            # line 21: DTL031 (unregistered)
        return "boom"
    FAULTS.maybe_raise("typo_site_2", OSError())   # line 23: DTL031
    return "ok"
