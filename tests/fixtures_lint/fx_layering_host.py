"""Known-bad layering fixture: a 'host-only' module importing the jax
stack, top-level and lazily. AST-parsed only, never imported."""

import jax                     # line 4: DTL021


def lazy_offender():
    import flax                # line 8: DTL021 (function-level counts too)

    return flax, jax
