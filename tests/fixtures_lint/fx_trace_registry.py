"""Known-bad trace-audit fixtures (tests/test_static_analysis.py).

A miniature entry-point registry with >=2 seeded violations per DTL1xx
checker family, paired with fx_trace_contract.json. Loaded by FILE PATH
through ``lint.trace.audit._load_registry`` exactly like the real
registry; every jit here is a few-op toy so the whole fixture audit
traces in milliseconds.

Seeded violations (pinned in TestTrace):

* DTL101 — ``fx.uncommitted`` registered here, absent from the contract
* DTL102 — ``fx.ghost`` present only in the contract
* DTL111/DTL113 — ``fx.drift`` produces two signatures; the contract
  lists one and budgets one
* DTL112 — the contract lists a ``float32[12]`` signature for
  ``fx.drift`` that this registry never produces
* DTL121 — ``fx.not_donated`` declares a donated arg its jit does not
  donate; ``fx.undeclared`` donates without declaring
* DTL122 — ``fx.unaliased`` donates an arg no output can alias;
  ``fx.plain`` declares donation on a non-jitted callable
* DTL131/DTL132 — ``fx.chatty`` embeds two debug callbacks and returns
  three host-visible outputs against budgets of 0/1
* DTL141 — ``fx.fat`` and ``fx.fat2`` exceed their byte budgets;
  ``fx.fat3`` also exceeds but is inline-suppressed (the escape hatch)
"""

from functools import partial

import jax
import jax.numpy as jnp

from lint.trace.types import EntryPoint, Signature

_PATH = "tests/fixtures_lint/fx_trace_registry.py"
_SDS = jax.ShapeDtypeStruct
_F8 = _SDS((8,), jnp.float32)


@partial(jax.jit, donate_argnums=(0,))
def _donated_ok(x, y):
    return x + y, jnp.sum(y)


@jax.jit
def _not_donated(x, y):
    return x + y


@partial(jax.jit, donate_argnums=(0,))
def _unaliased(x, y):
    # x is donated but every output is a scalar: nothing can alias it
    return jnp.sum(x) + jnp.sum(y)


def _plain(x):
    return x * 2.0


@jax.jit
def _chatty(x):
    jax.debug.print("x={x}", x=x)
    jax.debug.print("again={x}", x=x)
    return x * 2, x + 1, x - 1


@jax.jit
def _fat(x):
    return jnp.concatenate([x, x], 0)


@jax.jit
def _fat2(x):
    return jnp.tile(x, 3)


@jax.jit
def _fat3(x):  # dtl: disable=DTL141
    return jnp.tile(x, 4)


@jax.jit
def _drift(x):
    return x * 2


def _ep(name, symbol, fn, sigs, donate=None, lower="auto"):
    return EntryPoint(
        name=name, path=_PATH, symbol=symbol, fn=fn,
        signatures=sigs, static_argnums=(),
        donate=donate or {},
        lower=(getattr(fn, "lower", None) if lower == "auto" else lower),
    )


def build_entry_points():
    return [
        _ep("fx.donate_ok", "_donated_ok", _donated_ok,
            [Signature("s", (_F8, _F8))], donate={"x": 0}),
        _ep("fx.not_donated", "_not_donated", _not_donated,
            [Signature("s", (_F8, _F8))], donate={"x": 0}),
        _ep("fx.undeclared", "_donated_ok", _donated_ok,
            [Signature("s", (_F8, _F8))], donate={}),
        _ep("fx.unaliased", "_unaliased", _unaliased,
            [Signature("s", (_F8, _F8))], donate={"x": 0}),
        _ep("fx.plain", "_plain", _plain,
            [Signature("s", (_F8,))], donate={"x": 0}, lower=None),
        _ep("fx.chatty", "_chatty", _chatty, [Signature("s", (_F8,))]),
        _ep("fx.fat", "_fat", _fat, [Signature("s", (_F8,))]),
        _ep("fx.fat2", "_fat2", _fat2, [Signature("s", (_F8,))]),
        _ep("fx.fat3", "_fat3", _fat3, [Signature("s", (_F8,))]),
        _ep("fx.drift", "_drift", _drift, [
            Signature("w4", (_SDS((4,), jnp.float32),)),
            Signature("w6", (_SDS((6,), jnp.float32),)),
        ]),
        _ep("fx.uncommitted", "_plain", _plain,
            [Signature("s", (_F8,))], lower=None),
    ]
