"""Known-bad shard-audit fixtures (tests/test_static_analysis.py).

A miniature mesh-aware entry-point registry with >=2 seeded violations
per DTL15x checker family, paired with fx_shard_contract.json. Loaded by
FILE PATH through ``lint.trace.audit._load_registry`` exactly like the
real registry; every jit here is a few-op toy over a 2-device ("x",)
host mesh so the whole fixture audit runs in seconds.

Seeded violations (pinned in TestShard):

* DTL151 — ``fx.noisy`` lowers two shard_map all-reduces against a
  contract budget of one; ``fx.unlisted`` lowers a collective-permute
  the contract does not list at all; ``fx.sneaky`` is over budget like
  fx.noisy but inline-suppressed on its def line (the escape hatch)
* DTL152 — ``fx.drifted`` declares an expected P("x") arg sharding its
  jit is NOT lowered with (the ``:lowered`` code-level drift that
  --emit-contract cannot clear); ``fx.stale_contract`` matches its own
  lowering but the committed contract entry carries a doctored digest
  and param-spec map (the ``:contract`` drift that re-emitting clears)
* DTL153 — ``fx.replicated`` declares two rule-sharded parameter
  intents whose lowered arguments are fully replicated
* DTL154 — ``fx.resharder`` carries two in-program
  with_sharding_constraint sites against a budget of zero,
  ``fx.resharder2`` three against a budget of one
* DTL155 — ``fx.uncommitted`` is registered here but absent from the
  contract; ``fx.ghost`` exists only in the contract
* ``fx.clean`` (lowered) and ``fx.partitioned`` (compiled on the mesh,
  with the one GSPMD all-reduce its contracted-dim matmul implies)
  match their contract entries exactly and must stay finding-free.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lint.shard.types import ShardEntry

_PATH = "tests/fixtures_lint/fx_shard_registry.py"
_SDS = jax.ShapeDtypeStruct
_F8 = _SDS((8,), jnp.float32)
_F88 = _SDS((8, 8), jnp.float32)


def _mesh():
    return Mesh(np.asarray(jax.devices()[:2]), ("x",))


def _shard_map(fn, mesh, in_specs, out_specs):
    from dalle_pytorch_tpu.ops.jax_compat import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)


def _noisy(x):
    mesh = _mesh()
    f = _shard_map(lambda a: jax.lax.psum(jax.lax.psum(a, "x"), "x"),
                   mesh, P("x"), P())
    return f(x)


def _sneaky(x):  # dtl: disable=DTL151
    mesh = _mesh()
    f = _shard_map(lambda a: jax.lax.psum(jax.lax.psum(a, "x"), "x"),
                   mesh, P("x"), P())
    return f(x)


def _unlisted(x):
    mesh = _mesh()
    f = _shard_map(
        lambda a: jax.lax.ppermute(a, "x", [(0, 1), (1, 0)]),
        mesh, P("x"), P("x"),
    )
    return f(x)


def _plain(x):
    return x * 2.0


def _two_args(w1, w2):
    return w1 + w2


def _resharder(x):
    mesh = _mesh()
    y = jax.lax.with_sharding_constraint(
        x * 2, NamedSharding(mesh, P("x")))
    z = jax.lax.with_sharding_constraint(
        y + 1, NamedSharding(mesh, P()))
    return z


def _resharder2(x):
    mesh = _mesh()
    y = jax.lax.with_sharding_constraint(
        x * 2, NamedSharding(mesh, P("x")))
    z = jax.lax.with_sharding_constraint(
        y + 1, NamedSharding(mesh, P()))
    w = jax.lax.with_sharding_constraint(
        z * 3, NamedSharding(mesh, P("x")))
    return w


def _matmul(a, b):
    return a @ b


def _hlo(spec, ndim):
    return str(NamedSharding(_mesh(), spec)._to_xla_hlo_sharding(ndim))


def _jit_lower(fn, args, in_specs=None, out_specs=None):
    mesh = _mesh()
    kw = {}
    if in_specs is not None:
        kw["in_shardings"] = tuple(
            NamedSharding(mesh, s) for s in in_specs
        )
    if out_specs is not None:
        # every fixture jit returns ONE array; PartitionSpec is itself a
        # tuple subclass, so never iterate it
        kw["out_shardings"] = NamedSharding(mesh, out_specs)
    return jax.jit(fn, **kw).lower(*args)


def _ep(name, symbol, lower, **kw):
    return ShardEntry(
        name=name, path=_PATH, symbol=symbol, mesh_axes={"x": 2},
        lower=lower, **kw,
    )


def build_entry_points():
    return [
        _ep("fx.clean", "_plain",
            lambda: _jit_lower(_plain, (_F8,))),
        _ep("fx.noisy", "_noisy",
            lambda: _jit_lower(_noisy, (_F8,), in_specs=(P("x"),),
                               out_specs=P())),
        _ep("fx.sneaky", "_sneaky",
            lambda: _jit_lower(_sneaky, (_F8,), in_specs=(P("x"),),
                               out_specs=P())),
        _ep("fx.unlisted", "_unlisted",
            lambda: _jit_lower(_unlisted, (_F8,), in_specs=(P("x"),),
                               out_specs=P("x"))),
        _ep("fx.drifted", "_plain",
            lambda: _jit_lower(_plain, (_F8,)),
            arg_paths=("[0]",),
            in_shardings=(_hlo(P("x"), 1),)),
        _ep("fx.stale_contract", "_plain",
            lambda: _jit_lower(_plain, (_F8,))),
        _ep("fx.replicated", "_two_args",
            lambda: _jit_lower(_two_args, (_F8, _F8)),
            param_intents=(
                {"path": "w1", "rule": r"w1$", "requested": P("x"),
                 "spec": P("x"), "intent_sharded": True, "sharded": True,
                 "arg": 0},
                {"path": "w2", "rule": r"w2$", "requested": P("x"),
                 "spec": P("x"), "intent_sharded": True, "sharded": True,
                 "arg": 1},
            )),
        _ep("fx.resharder", "_resharder",
            lambda: _jit_lower(_resharder, (_F8,))),
        _ep("fx.resharder2", "_resharder2",
            lambda: _jit_lower(_resharder2, (_F8,))),
        _ep("fx.partitioned", "_matmul",
            lambda: _jit_lower(_matmul, (_F88, _F88),
                               in_specs=(P(None, "x"), P("x", None)),
                               out_specs=P()),
            partitioned=True),
        _ep("fx.uncommitted", "_plain",
            lambda: _jit_lower(_plain, (_F8,))),
    ]
