"""Known-bad lock-ORDER fixtures (DTL052; tests/test_static_analysis.py).

AST-parsed only, never imported. Seeds: two order-inversion cycles (one
in a table-less lock-owning class, one in a ``_GUARDED_BY`` class whose
first edge sits in a ``*_locked`` method — ordering is checked
everywhere, the DTL051 exemption does not apply), one non-reentrant
self-deadlock, a sanctioned RLock reentry (clean), one inline-suppressed
cycle, and the baseline-grandfathering escape (the test supplies the
baseline file).
"""

import threading


class CycleAB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0

    def forward(self):
        with self._a:
            with self._b:          # line 23: DTL052 a->b vs b->a below
                self.x += 1

    def backward(self):
        with self._b:
            with self._a:
                self.x -= 1


class SelfDeadlock:
    def __init__(self):
        self._m = threading.Lock()

    def outer(self):
        with self._m:
            with self._m:          # line 38: DTL052 plain-Lock re-acquire
                pass


class ReentrantOK:
    def __init__(self):
        self._r = threading.RLock()

    def outer(self):
        with self._r:
            with self._r:          # RLock reentry: sanctioned, clean
                pass


class CycleSuppressed:
    def __init__(self):
        self._c = threading.Lock()
        self._d = threading.Lock()

    def one(self):
        with self._c:
            with self._d:  # dtl: disable=DTL052
                pass

    def two(self):
        with self._d:
            with self._c:
                pass


class CycleBaselined:
    _GUARDED_BY = {"_e": ("val",)}

    def __init__(self):
        self._e = threading.Lock()
        self._f = threading.Lock()
        self.val = 0

    def one_locked(self):
        with self._e:
            with self._f:          # line 78: DTL052 (baselined in test)
                pass

    def two(self):
        with self._f:
            with self._e:
                pass


class ClosureNotAnEdge:
    """A nested def merely DEFINED under a lock runs later, without it:
    its acquisitions are NOT ordering edges, so the g/h orders here are
    deadlock-free and must stay clean."""

    def __init__(self):
        self._g = threading.Lock()
        self._h = threading.Lock()

    def spawn(self):
        with self._g:
            def worker():
                with self._h:      # runs on another thread, _g not held
                    pass
            return worker

    def other(self):
        with self._h:
            with self._g:
                pass
