"""Miniature telemetry-name registry for the name-checker fixtures.
AST-parsed only."""

SPANS = frozenset({
    "fx.request",
})

EVENTS = frozenset({
    "fx.evt",
})

COUNTERS = frozenset({
    "fx.known",
    "fx.reasons.alpha",
    "fx.undocumented",   # absent from fx_names_doc.md: DTL042
    "fx.wait",           # PREFIX of the documented `fx.wait_s`: still
                         # DTL042 — doc matching is whole-token, not
                         # substring
})

GAUGES = frozenset({
    "fx.level",
})

HISTOGRAMS = frozenset({
    "fx.wait_s",
})
