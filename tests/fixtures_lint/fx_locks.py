"""Known-bad lock-discipline fixture. AST-parsed only."""

import threading


class Guarded:
    _GUARDED_BY = {"_lock": ("_items", "count")}

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self.count = 0          # __init__ exempt: object not shared yet

    def ok(self):
        with self._lock:
            self._items.append(1)
            self.count += 1

    def ok_nested_lambda(self):
        with self._lock:
            return sorted(self._items, key=lambda x: x + self.count)

    def bad_write(self):
        self._items.append(1)   # line 24: DTL051

    def bad_read(self):
        return self.count       # line 27: DTL051 (torn reads count too)

    def _bump_locked(self):
        self.count += 1         # *_locked convention: caller holds lock

    def suppressed_read(self):
        return self.count  # dtl: disable=DTL051


class MalformedTable:
    _GUARDED_BY = [("_lock", ("_items",))]   # line 37: DTL051 — not a dict

    def __init__(self):
        self._items = []


class TypoField:
    _GUARDED_BY = {"_lock": ("_queu",)}      # typo: __init__ sets _queue —
                                             # line 43: DTL051

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
