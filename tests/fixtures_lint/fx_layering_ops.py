"""Known-bad layering fixture: an 'ops-layer' module reaching up into
serving. AST-parsed only, never imported."""

from dalle_pytorch_tpu.serving import engine           # line 4: DTL021
from dalle_pytorch_tpu.serving.types import Request    # line 5: DTL021
# the from-parent spelling must be caught too (the module lands in the
# alias list, not in node.module):
from dalle_pytorch_tpu import serving as srv           # line 8: DTL021

__all__ = ["engine", "Request", "srv"]
