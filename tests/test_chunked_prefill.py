"""Chunked prefill + one-step-lookahead decode — the iteration-scheduler
contracts pinned deterministically on CPU:

- model-level BIT-parity: any sequence of ``DALLE.prefill_chunk`` calls
  covering the prompt (widths >= 2, ragged tails included) produces a
  cache and final logits bitwise identical to one monolithic
  ``prefill_step``;
- engine-level BIT-parity: chunked and monolithic engines, lookahead on
  and off, all sample identical tokens — and preempt-and-requeue replay
  stays bit-identical with chunking and lookahead on;
- the ``TokenBudget`` policy: decode charged first, chunk-quantum grants,
  head-of-line order, forward-progress floor;
- chunk-granular faults: ``prefill_fail`` fires per chunk, retry resumes
  from the last COMPLETED chunk (never from scratch), attempts exhaust to
  the typed outcome; deadlines and cancellation land BETWEEN chunks with
  pages freed that same iteration;
- TTFT accounting: set at first-token production, once per request,
  carried in the result and the ``serve.ttft_s`` histogram.

Page size 2 (env override), as in tests/test_serving.py, so the tiny
model exercises real page-boundary growth.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE, init_decode_cache
from dalle_pytorch_tpu.models.sampling import set_decode_offsets
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
    TokenBudget,
    check_accounting,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, histograms


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=prompt(i), max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def run_requests(model, n=3, max_new=4, **cfg_kw):
    eng = make_engine(model, **cfg_kw)
    for i in range(n):
        assert eng.submit(req(i, max_new=max_new)) is None
    eng.run(max_steps=500)
    check_accounting(eng)
    return eng


def tokens_of(eng):
    return {
        rid: None if r.tokens is None else np.asarray(r.tokens)
        for rid, r in eng.results.items()
    }


# -------------------------------------------------- TokenBudget (pure)


class TestTokenBudget:
    def test_decode_charged_first_then_chunk_quanta(self):
        tb = TokenBudget(budget=10, chunk=4)
        # 3 decode tokens leave 7: one full chunk + the 3-token remainder
        # of the first prefill, nothing for the second
        assert tb.plan(3, [7, 8]) == [7, 0]

    def test_grants_follow_head_of_line(self):
        tb = TokenBudget(budget=10, chunk=4)
        assert tb.plan(0, [4, 8]) == [4, 4]
        assert tb.plan(0, [12, 8]) == [8, 0]

    def test_forward_progress_floor(self):
        """Decode saturating the budget must not deadlock prefill: the
        head prefill still gets exactly one chunk."""
        tb = TokenBudget(budget=4, chunk=4)
        assert tb.plan(4, [12, 8]) == [4, 0]
        assert tb.plan(400, [12]) == [4]

    def test_ragged_tail_granted(self):
        tb = TokenBudget(budget=16, chunk=4)
        assert tb.plan(0, [6]) == [6]  # 4 + the 2-token tail

    def test_unbounded_budget(self):
        tb = TokenBudget(budget=None, chunk=4)
        assert tb.plan(99, [12, 5]) == [12, 5]

    def test_engine_rejects_one_token_chunks(self, model):
        with pytest.raises(ValueError, match="prefill_chunk"):
            make_engine(model, prefill_chunk=1)


# ------------------------------------------- model-level bit parity


class TestPrefillChunkParity:
    @pytest.mark.parametrize("rotary", [True, False])
    def test_chunkings_bit_identical_to_monolithic(self, rotary):
        """THE tentpole contract at the model layer: every multi-token
        chunking of the prompt — including a ragged final chunk — writes a
        cache and produces final logits BITWISE identical to one
        monolithic prefill_step."""
        dalle = small_dalle(rotary_emb=rotary)
        rng = np.random.RandomState(0)
        text = jnp.asarray(rng.randint(1, 16, size=(1, 4)), jnp.int32)
        image = jnp.asarray(rng.randint(0, 12, size=(1, 4)), jnp.int32)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = dalle.remap_text(text)
        T = dalle.text_len_internal  # 5
        fresh = set_decode_offsets(
            init_decode_cache(dalle, params, 1, cache_format="paged"),
            jnp.zeros((1,), jnp.int32),
        )
        logits_m, mut = dalle.apply(
            {"params": params, "cache": fresh}, internal,
            image_only=True, method=DALLE.prefill_step, mutable=["cache"],
        )
        cache_m = mut["cache"]

        for chunks in ((2, 3), (3, 2), (5,)):
            assert sum(chunks) == T
            cache = fresh
            s = 0
            for c in chunks:
                final = s + c == T
                logits, mut = dalle.apply(
                    {"params": params, "cache": cache},
                    internal[:, s:s + c], jnp.int32(s),
                    return_logits=final, image_only=final,
                    method=DALLE.prefill_chunk, mutable=["cache"],
                )
                cache = mut["cache"]
                s += c
            for (pm, lm), (pc, lc) in zip(
                jax.tree_util.tree_leaves_with_path(cache_m),
                jax.tree_util.tree_leaves_with_path(cache),
            ):
                assert bool(jnp.all(lm == lc)), (
                    f"cache leaf {pm} diverged for chunking {chunks}"
                )
            np.testing.assert_array_equal(
                np.asarray(logits), np.asarray(logits_m),
                err_msg=f"final logits diverged for chunking {chunks}",
            )

    def test_image_only_head_matches_full_head_slice(self, model):
        """prefill's image_only head is the full head's [ext:] slice,
        bitwise — the serving engine samples from it in both the
        monolithic and chunked paths."""
        dalle, params = model
        internal = dalle.remap_text(jnp.asarray(prompt(0)[None], jnp.int32))
        fresh = set_decode_offsets(
            init_decode_cache(dalle, params, 1, cache_format="paged"),
            jnp.zeros((1,), jnp.int32),
        )
        full, _ = dalle.apply(
            {"params": params, "cache": fresh}, internal,
            method=DALLE.prefill_step, mutable=["cache"],
        )
        img, _ = dalle.apply(
            {"params": params, "cache": fresh}, internal,
            image_only=True, method=DALLE.prefill_step, mutable=["cache"],
        )
        np.testing.assert_array_equal(
            np.asarray(img),
            np.asarray(full[:, dalle.num_text_tokens_ext:]),
        )


# ------------------------------------------- engine-level bit parity


class TestChunkedEngineParity:
    def test_chunked_vs_monolithic_bit_identical(self, model):
        """Acceptance: chunked prefill at several chunk sizes (2 -> ragged
        3-token tail; 3 -> ragged 2-token tail; 4 -> the 1-token-tail
        merge rule collapses to one width-5 chunk) produces tokens
        bit-identical to the monolithic engine."""
        mono = tokens_of(run_requests(model))
        for chunk in (2, 3, 4):
            chunked = tokens_of(run_requests(model, prefill_chunk=chunk))
            for rid, toks in mono.items():
                np.testing.assert_array_equal(
                    chunked[rid], toks,
                    err_msg=f"chunk={chunk} diverged for {rid}",
                )

    def test_lookahead_off_parity(self, model):
        base = tokens_of(run_requests(model))
        for cfg in (
            dict(decode_lookahead=False),
            dict(decode_lookahead=False, prefill_chunk=2),
        ):
            got = tokens_of(run_requests(model, **cfg))
            for rid, toks in base.items():
                np.testing.assert_array_equal(got[rid], toks, err_msg=str(cfg))

    def test_preempt_replay_bit_identical_chunked_lookahead(self, model):
        """Acceptance: preempt-and-requeue replay stays BIT-identical with
        chunked prefill AND lookahead decode on (the (seed, position) keys
        make tokens independent of when they are sampled or read back)."""
        FAULTS.reset()
        counters.reset()
        clean = tokens_of(run_requests(model, prefill_chunk=2))
        FAULTS.configure("page_exhaust=1")
        eng = run_requests(model, prefill_chunk=2)
        assert FAULTS.fired.get("page_exhaust") == 1
        assert counters.get("serve.preempted") >= 1
        assert any(r.preempt_count > 0 for r in eng.results.values())
        for rid, r in eng.results.items():
            assert r.outcome is Outcome.COMPLETED, (rid, r)
            np.testing.assert_array_equal(np.asarray(r.tokens), clean[rid])
        assert eng.pool.used == 0


# --------------------------------------- chunk-granular fault drills


class TestChunkFaults:
    def test_chunk_fault_resumes_from_last_completed_chunk(self, model):
        """A prefill_fail mid-prompt must NOT restart the prefill: the
        already-written chunks survive and the retry resumes exactly at
        the failed chunk."""
        FAULTS.reset()
        counters.reset()
        clean = tokens_of(run_requests(model, n=1, prefill_chunk=2,
                                       token_budget=1))
        # token_budget=1 -> exactly one chunk per iteration (the
        # forward-progress floor); T=5 chunks as (2, 3)
        eng = make_engine(model, prefill_chunk=2, token_budget=1)
        assert eng.submit(req(0)) is None
        eng.step()  # claim + first chunk
        slot = next(s for s in eng.slots if s)
        assert slot.phase == "prefill" and slot.filled == 2
        FAULTS.arm("prefill_fail", 1)
        eng.step()  # the FINAL chunk faults
        assert FAULTS.fired.get("prefill_fail") == 1
        slot = next(s for s in eng.slots if s)
        assert slot.filled == 2, "progress was rolled back on a chunk fault"
        eng.run(max_steps=200)
        check_accounting(eng)
        res = eng.results["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert res.prefill_attempts == 1
        assert counters.get("serve.prefill_retries") == 1
        # 2 chunks in the clean run + 2 in the faulted run: the fault cost
        # a retry ITERATION but zero re-run chunks — resume, not restart —
        # and the tokens still match the clean run bit-for-bit
        assert counters.get("serve.prefill_chunks") == 4
        np.testing.assert_array_equal(np.asarray(res.tokens), clean["r0"])

    def test_chunk_fault_exhausts_attempts_typed(self, model):
        FAULTS.reset()
        FAULTS.arm("prefill_fail", 5)
        eng = make_engine(model, prefill_chunk=2, prefill_attempts=2)
        assert eng.submit(req(0)) is None
        eng.run(max_steps=200)
        check_accounting(eng)
        res = eng.results["r0"]
        assert res.outcome is Outcome.PREFILL_FAILED
        assert res.prefill_attempts == 2
        assert res.tokens is None
        assert eng.pool.used == 0

    def test_mid_prefill_deadline_frees_pages_that_iteration(self, model):
        """Acceptance: a deadline arriving mid-prefill terminates BETWEEN
        chunks, with the pages back in the pool the iteration the deadline
        sweeps — not at the end of the prompt."""
        eng = make_engine(model, prefill_chunk=2, token_budget=1,
                          clock=FakeClock(step_dt=1.0))
        assert eng.submit(req(0, deadline=0.5)) is None
        eng.step()  # t=0: claim + first chunk; prompt pages held
        assert eng.pool.used > 0
        slot = next(s for s in eng.slots if s)
        assert slot.phase == "prefill" and 0 < slot.filled < eng.T
        eng.step()  # t=1 > deadline: sweeps mid-prefill
        assert eng.pool.used == 0, "mid-prefill deadline did not free pages"
        res = eng.results["r0"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert res.tokens is None  # never produced a token
        assert res.ttft_s is None
        eng.run(max_steps=50)
        check_accounting(eng)

    def test_cancel_mid_prefill(self, model):
        eng = make_engine(model, prefill_chunk=2, token_budget=1)
        assert eng.submit(req(0)) is None
        eng.step()
        slot = next(s for s in eng.slots if s)
        assert slot.phase == "prefill"
        eng.cancel("r0")
        eng.step()
        assert eng.pool.used == 0
        res = eng.results["r0"]
        assert res.outcome is Outcome.CANCELLED
        assert res.tokens is None
        eng.run(max_steps=50)
        check_accounting(eng)

    def test_combined_overload_chunked_all_accounted(self, model):
        """Acceptance: the combined overload + mid-prefill-deadline +
        chunk-fault drill — aggregate demand far over the pool, a bounded
        queue, deadlines tight enough to land mid-prefill (token_budget=1
        stretches every prefill across iterations), and injected
        page_exhaust + chunk-granular prefill_fail. Every submitted
        request must end in exactly one typed outcome, counters sum to
        100%, and the pool drains."""
        FAULTS.reset()
        counters.reset()
        FAULTS.configure("page_exhaust=1,prefill_fail=2")
        clock = FakeClock(step_dt=1.0)
        eng = make_engine(
            model, clock=clock, max_batch=2, page_budget=7, queue_limit=3,
            prefill_attempts=3, prefill_chunk=2, token_budget=1,
        )
        immediate = []
        for i in range(8):
            r = eng.submit(req(
                i, max_new=4,
                deadline=None if i % 2 else 2.0 + 3 * i,
                priority=i % 3,
            ))
            if r is not None:
                immediate.append(r)
        eng.run(max_steps=1000)
        check_accounting(eng)
        outcomes = eng.stats()["outcomes"]
        assert sum(outcomes.values()) == 8
        assert outcomes["rejected"] == len(immediate) > 0
        assert outcomes["deadline_exceeded"] >= 1  # the tight deadlines bit
        assert FAULTS.fired.get("prefill_fail") == 2
        assert FAULTS.fired.get("page_exhaust") == 1
        assert eng.pool.used == 0
        for r in eng.results.values():
            assert r.outcome in (
                Outcome.COMPLETED, Outcome.REJECTED,
                Outcome.DEADLINE_EXCEEDED, Outcome.PREEMPT_CAP,
                Outcome.CANCELLED, Outcome.PREFILL_FAILED,
            ), r


# ------------------------------------------------------------- TTFT


class TestTtft:
    def test_ttft_in_results_and_histogram(self, model):
        counters.reset()
        histograms.reset()
        eng = run_requests(model, prefill_chunk=2)
        for r in eng.results.values():
            assert r.outcome is Outcome.COMPLETED
            assert r.ttft_s is not None and r.ttft_s >= 0
            # first token lands at or after admission
            assert r.ttft_s >= r.queue_latency_s
            assert "ttft_s" in r.to_json()
        h = histograms.get("serve.ttft_s")
        assert h is not None and h.count == 3  # once per request

    def test_ttft_survives_preemption(self, model):
        """A preempted-and-replayed request keeps its ORIGINAL ttft: the
        replay regenerates the same first token bit-identically, so the
        client-visible first production is the honest latency."""
        FAULTS.reset()
        FAULTS.arm("page_exhaust", 1)
        eng = run_requests(model)
        preempted = [
            r for r in eng.results.values() if r.preempt_count > 0
        ]
        assert preempted
        for r in preempted:
            assert r.ttft_s is not None
            # requeued AFTER its first token: the recorded ttft predates
            # the final admission's queue latency
            assert r.ttft_s <= r.total_latency_s
