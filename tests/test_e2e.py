"""Scripted end-to-end pipeline check, the analog of the reference's
``examples/rainbow_dalle.ipynb`` (41 cells: synthetic shapes dataset ->
train DiscreteVAE -> train DALLE -> sample; SURVEY.md §4).

Drives the REAL CLI mains (train_vae.py / train_dalle.py / generate.py) via
sys.argv on a tiny synthetic "rainbow shapes" dataset, asserting that
training moves the loss and that generation produces correctly-shaped,
denormalized images on disk.
"""

import os
import signal
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from PIL import Image

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO)) if str(REPO) not in sys.path else None

IMAGE_SIZE = 32
COLORS = {
    "red": (220, 40, 40),
    "green": (40, 200, 60),
    "blue": (50, 70, 230),
    "yellow": (230, 220, 50),
}
SHAPES = ("square", "circle")


def _draw(color, shape):
    arr = np.zeros((IMAGE_SIZE, IMAGE_SIZE, 3), np.uint8)
    c = np.array(COLORS[color], np.uint8)
    yy, xx = np.mgrid[:IMAGE_SIZE, :IMAGE_SIZE]
    if shape == "square":
        m = (abs(yy - 16) < 9) & (abs(xx - 16) < 9)
    else:
        m = (yy - 16) ** 2 + (xx - 16) ** 2 < 81
    arr[m] = c
    return arr


@pytest.fixture(scope="module")
def shapes_dataset(tmp_path_factory):
    """16 image/caption pairs: every (color, shape) combo, twice."""
    root = tmp_path_factory.mktemp("rainbow")
    i = 0
    for _ in range(2):
        for color in COLORS:
            for shape in SHAPES:
                stem = root / f"sample_{i:03d}"
                Image.fromarray(_draw(color, shape)).save(stem.with_suffix(".png"))
                stem.with_suffix(".txt").write_text(f"a {color} {shape}")
                i += 1
    return root


def _run_cli(monkeypatch, module, argv):
    monkeypatch.setattr(sys, "argv", [f"{module.__name__}.py"] + argv)
    module.main()


def _capture_losses(monkeypatch):
    """Patch MetricsLogger.log to record every logged 'loss'; returns the
    list the values accumulate into."""
    from dalle_pytorch_tpu.utils import MetricsLogger

    losses = []
    orig_log = MetricsLogger.log

    def capture(self, logs, step=None):
        if "loss" in logs:
            losses.append(float(logs["loss"]))
        return orig_log(self, logs, step=step)

    monkeypatch.setattr(MetricsLogger, "log", capture)
    return losses


@pytest.fixture(scope="module")
def trained_vae(shapes_dataset, tmp_path_factory):
    import train_vae

    work = tmp_path_factory.mktemp("vae_work")
    ckpt = work / "vae.ckpt"
    argv = [
        "--image_folder", str(shapes_dataset),
        "--image_size", str(IMAGE_SIZE),
        "--num_layers", "2",
        "--num_tokens", "64",
        "--emb_dim", "32",
        "--hidden_dim", "16",
        "--num_resnet_blocks", "1",
        "--batch_size", "8",
        "--epochs", "4",
        "--learning_rate", "3e-3",
        "--output_file_name", str(ckpt),
        "--samples_dir", str(work / "samples"),
    ]
    mp = pytest.MonkeyPatch()
    try:
        _run_cli(mp, train_vae, argv)
    finally:
        mp.undo()
    assert ckpt.exists()
    return ckpt


def _vae_loss(vae, params, images, key):
    loss = vae.apply(
        {"params": params}, images, return_loss=True,
        temp=jnp.asarray(1.0), rngs={"gumbel": key},
    )
    return float(loss)


def test_vae_training_reduces_recon_loss(trained_vae, shapes_dataset):
    from dalle_pytorch_tpu.models.factory import vae_from_checkpoint

    vae, params, meta = vae_from_checkpoint(str(trained_vae))
    imgs = np.stack(
        [np.asarray(Image.open(p), np.float32) / 255.0
         for p in sorted(shapes_dataset.glob("*.png"))[:8]]
    )
    key = jax.random.key(0)
    fresh = jax.jit(vae.init)(
        {"params": jax.random.key(123), "gumbel": key}, jnp.asarray(imgs)
    )["params"]
    trained_loss = _vae_loss(vae, params, imgs, key)
    fresh_loss = _vae_loss(vae, fresh, imgs, key)
    assert np.isfinite(trained_loss)
    assert trained_loss < fresh_loss, (
        f"VAE training did not reduce loss: {trained_loss} vs fresh {fresh_loss}"
    )


@pytest.fixture(scope="module")
def trained_dalle(shapes_dataset, trained_vae, tmp_path_factory):
    import train_dalle

    work = tmp_path_factory.mktemp("dalle_work")
    out = work / "dalle"
    argv = [
        "--image_text_folder", str(shapes_dataset),
        "--vae_path", str(trained_vae),
        "--dim", "64",
        "--depth", "2",
        "--heads", "2",
        "--dim_head", "16",
        "--text_seq_len", "16",
        "--batch_size", "8",
        "--epochs", "6",
        "--learning_rate", "1e-3",
        "--truncate_captions",
        "--dalle_output_file_name", str(out),
        # exercise the profiler-trace flag (the --flops_profiler analog)
        "--profile_trace_dir", str(work / "trace"),
        "--profile_step", "2",
    ]
    mp = pytest.MonkeyPatch()
    try:
        losses = _capture_losses(mp)
        mp.chdir(work)
        _run_cli(mp, train_dalle, argv)
    finally:
        mp.undo()
    ckpt = Path(f"{out}.ckpt")
    assert ckpt.exists()
    # loss at the end of training (12 steps) must be below the first-step
    # loss — the notebook's "training works" assertion
    assert len(losses) >= 2
    assert losses[-1] < losses[0], f"DALLE loss did not decrease: {losses}"
    # the jax.profiler trace window must have produced an xplane dump
    assert list((work / "trace").rglob("*.xplane.pb")), "no profiler trace written"
    return ckpt


@pytest.mark.parametrize(
    "mesh_flags, attn_types",
    [
        (["--sp", "2", "--tp", "2"], "full,axial_row"),
        (["--pp", "2", "--pp_microbatches", "2"], "full"),
    ],
    ids=["sp2_tp2", "pp2"],
)
def test_train_cli_parallel_modes(shapes_dataset, trained_vae, tmp_path,
                                  monkeypatch, mesh_flags, attn_types):
    """train_dalle must run end-to-end with sequence parallelism (ring +
    Ulysses) and pipeline parallelism (GPipe) over the virtual 8-device mesh
    — the CLI analog of the model-level parity tests."""
    import train_dalle

    out = tmp_path / "dalle_par"
    argv = [
        "--image_text_folder", str(shapes_dataset),
        "--vae_path", str(trained_vae),
        "--dim", "64",
        "--depth", "2",
        "--heads", "4",
        "--dim_head", "16",
        "--text_seq_len", "16",
        "--batch_size", "8",
        "--epochs", "1",
        "--learning_rate", "1e-3",
        "--truncate_captions",
        "--attn_types", attn_types,
        "--dalle_output_file_name", str(out),
        *mesh_flags,
    ]
    losses = _capture_losses(monkeypatch)
    monkeypatch.chdir(tmp_path)
    _run_cli(monkeypatch, train_dalle, argv)
    assert Path(f"{out}.ckpt").exists()
    assert losses and all(np.isfinite(losses))


def test_generate_cli_produces_images(trained_dalle, tmp_path):
    import generate

    outputs = tmp_path / "outputs"
    argv = [
        "--dalle_path", str(trained_dalle),
        "--text", "a red square|a blue circle",
        "--num_images", "2",
        "--batch_size", "2",
        "--outputs_dir", str(outputs),
    ]
    mp = pytest.MonkeyPatch()
    try:
        _run_cli(mp, generate, argv)
    finally:
        mp.undo()

    for prompt_dir in ("a_red_square", "a_blue_circle"):
        d = outputs / prompt_dir
        assert (d / "caption.txt").exists()
        pngs = sorted(d.glob("*.png"))
        assert len(pngs) == 2
        arr = np.asarray(Image.open(pngs[0]))
        assert arr.shape == (IMAGE_SIZE, IMAGE_SIZE, 3)
        assert arr.dtype == np.uint8


def test_generate_cli_int8(trained_dalle, tmp_path):
    """--int8 quantized serving through the real CLI (load-time bf16 cast +
    per-channel kernel quantization, utils/quantize.py)."""
    import generate

    outputs = tmp_path / "outputs_int8"
    argv = [
        "--dalle_path", str(trained_dalle),
        "--text", "a green circle",
        "--num_images", "1",
        "--batch_size", "1",
        "--int8",
        "--outputs_dir", str(outputs),
    ]
    mp = pytest.MonkeyPatch()
    try:
        _run_cli(mp, generate, argv)
    finally:
        mp.undo()
    pngs = sorted((outputs / "a_green_circle").glob("*.png"))
    assert len(pngs) == 1
    arr = np.asarray(Image.open(pngs[0]))
    assert arr.shape == (IMAGE_SIZE, IMAGE_SIZE, 3)


def test_train_clip_cli_and_rerank(shapes_dataset, trained_dalle, tmp_path):
    """train_clip.py trains end-to-end on the shapes dataset and its
    checkpoint plugs into generate.py --clip_path for sampling-time
    reranking (the reference has CLIP but no trainer for it)."""
    import generate
    import train_clip

    out = tmp_path / "clip"
    argv = [
        "--image_text_folder", str(shapes_dataset),
        "--dim_text", "32",
        "--dim_image", "32",
        "--dim_latent", "32",
        "--text_enc_depth", "1",
        "--text_seq_len", "16",
        "--text_heads", "2",
        "--visual_enc_depth", "1",
        "--visual_heads", "2",
        "--visual_image_size", str(IMAGE_SIZE),
        "--visual_patch_size", "8",
        "--truncate_captions",
        "--batch_size", "8",
        "--epochs", "2",
        "--learning_rate", "2e-3",
        "--clip_output_file_name", str(out),
    ]
    mp = pytest.MonkeyPatch()
    try:
        losses = _capture_losses(mp)
        _run_cli(mp, train_clip, argv)
    finally:
        mp.undo()
    ckpt = Path(f"{out}.ckpt")
    assert ckpt.exists()
    assert losses and all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"CLIP loss did not decrease: {losses}"

    # resume: params AND Adam moments restore (epoch counter advances)
    argv_resume = ["--clip_path", str(ckpt)] + [
        a for a in argv if a not in ("--clip_output_file_name", str(out))
    ] + ["--clip_output_file_name", str(out), "--epochs", "3"]
    mp = pytest.MonkeyPatch()
    try:
        resume_losses = _capture_losses(mp)
        _run_cli(mp, train_clip, argv_resume)
    finally:
        mp.undo()
    assert resume_losses, "resume ran no steps"
    assert all(np.isfinite(resume_losses))

    outputs = tmp_path / "reranked"
    argv = [
        "--dalle_path", str(trained_dalle),
        "--text", "a red square",
        "--num_images", "2",
        "--batch_size", "2",
        "--clip_path", str(ckpt),
        "--outputs_dir", str(outputs),
    ]
    mp = pytest.MonkeyPatch()
    try:
        _run_cli(mp, generate, argv)
    finally:
        mp.undo()
    pngs = sorted((outputs / "a_red_square").glob("*.png"))
    assert len(pngs) == 2


def test_train_dalle_cli_webdataset(shapes_dataset, trained_vae, tmp_path, monkeypatch):
    """train_dalle --wds: the tar-shard streaming pipeline through the real
    CLI (reference train_dalle.py:353-374 WebDataset path)."""
    import tarfile

    import train_dalle

    shard = tmp_path / "shard-0000.tar"
    with tarfile.open(shard, "w") as tf:
        for p in sorted(shapes_dataset.glob("*.png")):
            tf.add(p, arcname=p.name)
            tf.add(p.with_suffix(".txt"), arcname=p.with_suffix(".txt").name)

    out = tmp_path / "dalle_wds"
    argv = [
        "--image_text_folder", str(shard),
        "--wds",
        "--vae_path", str(trained_vae),
        "--dim", "64",
        "--depth", "2",
        "--heads", "2",
        "--dim_head", "16",
        "--text_seq_len", "16",
        "--batch_size", "8",
        "--epochs", "2",
        "--learning_rate", "1e-3",
        "--truncate_captions",
        "--dalle_output_file_name", str(out),
    ]
    losses = _capture_losses(monkeypatch)
    monkeypatch.chdir(tmp_path)
    _run_cli(monkeypatch, train_dalle, argv)
    assert Path(f"{out}.ckpt").exists()
    assert losses and all(np.isfinite(losses))


def test_train_cli_preemption_resume(shapes_dataset, trained_vae, tmp_path):
    """Fault tolerance through the REAL CLI (docs/DESIGN.md §8): SIGTERM
    mid-run -> emergency step-granular checkpoint + clean exit(0); the
    relaunch auto-resumes from the verified step dir and — with a NaN loss
    injected into its first steps — skips the bad step on device, retries
    the batch, and still finishes training.

    Both phases run as real subprocesses — the production topology (every
    launch is its own process; the preemption handler plus actual process
    teardown, the relaunch a fresh process). Re-entering train_dalle.main()
    inside the pytest process after a resume-scale orbax restore has
    produced allocator corruption, and production never does that anyway.
    The NaN fault is armed through the child's DALLE_TPU_FAULTS env —
    the same knob an operator would use."""
    import subprocess

    from dalle_pytorch_tpu.utils import latest_verified_step

    out = tmp_path / "dalle_pre"
    argv = [
        "--image_text_folder", str(shapes_dataset),
        "--vae_path", str(trained_vae),
        "--dim", "64",
        "--depth", "2",
        "--heads", "2",
        "--dim_head", "16",
        "--text_seq_len", "16",
        "--batch_size", "8",
        "--epochs", "4",
        "--learning_rate", "1e-3",
        "--truncate_captions",
        "--dalle_output_file_name", str(out),
        "--telemetry",
        "--telemetry_dir", str(tmp_path / "flight"),
    ]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        # share the suite's persistent compile cache so both phases warm it
        "JAX_COMPILATION_CACHE_DIR": str(REPO / "tests" / ".jax_cache"),
    }
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "train_dalle.py"), *argv],
        cwd=tmp_path, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        # preempt once training is demonstrably under way: the first loss
        # line means the compiled step is running (logger prints flush)
        seen = []
        for line in proc.stdout:
            seen.append(line)
            if line.startswith("step 0: loss"):
                proc.send_signal(signal.SIGTERM)
                break
        # bounded drain: if the emergency save wedges, fail with a
        # diagnostic instead of deadlocking the suite on a pipe read
        tail, _ = proc.communicate(timeout=180)
        code = proc.returncode
    finally:
        if proc.poll() is None:
            proc.kill()
    transcript = "".join(seen) + tail
    assert code == 0, f"preempted run did not exit cleanly:\n{transcript}"
    assert "emergency checkpoint" in tail, transcript
    step = latest_verified_step(f"{out}-cp")
    assert step is not None and step >= 1, transcript

    # the SIGTERM must also leave a valid, parseable flight-recorder file
    # (drained inside the signal handler, before the emergency save): the
    # postmortem contract of docs/DESIGN.md §9
    from dalle_pytorch_tpu.utils.telemetry import validate_flight_file

    flights = sorted((tmp_path / "flight").glob("flight-*.jsonl"))
    assert flights, f"no flight-recorder file written:\n{transcript}"
    summary = validate_flight_file(str(flights[0]))
    assert summary["by_name"].get("train.step"), summary
    assert summary["by_name"].get("train.preempt_signal") == 1, summary

    # relaunch: the startup probe must resume from the emergency step and
    # finish; the injected NaN one step after the resume point exercises
    # the on-device skip + batch retry
    # no persistent compile cache for the resumed process: checkpoint
    # restore + cache deserialization in one process intermittently
    # corrupts the allocator in this jaxlib (observed SIGABRT, 'corrupted
    # double-linked list'); the resume pays one cold compile instead
    renv = {**env, "DALLE_TPU_FAULTS": f"nan_at_step={step + 1}"}
    renv.pop("JAX_COMPILATION_CACHE_DIR")
    relaunch = subprocess.run(
        [sys.executable, str(REPO / "train_dalle.py"), *argv],
        cwd=tmp_path, text=True, timeout=300,
        env=renv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    assert relaunch.returncode == 0, relaunch.stdout
    assert f"resuming from {out}-cp step {step}" in relaunch.stdout
    assert "non-finite loss — update skipped on device, retrying batch (1/" \
        in relaunch.stdout, relaunch.stdout
    assert Path(f"{out}.ckpt").exists()


def test_generate_cli_gentxt(trained_dalle, tmp_path):
    """--gentxt: the model completes the prompt text before generating
    (reference generate.py:104-106)."""
    import generate

    outputs = tmp_path / "outputs_gentxt"
    argv = [
        "--dalle_path", str(trained_dalle),
        "--text", "a red",
        "--num_images", "1",
        "--batch_size", "1",
        "--gentxt",
        "--outputs_dir", str(outputs),
    ]
    mp = pytest.MonkeyPatch()
    try:
        _run_cli(mp, generate, argv)
    finally:
        mp.undo()
    # the completion is model-sampled text; locate outputs by content, not by
    # a predicted directory name (sampled tokens may even contain '/')
    captions = list(outputs.rglob("caption.txt"))
    assert len(captions) == 1
    assert captions[0].read_text().startswith("a red")
    assert len(sorted(captions[0].parent.glob("*.png"))) == 1
