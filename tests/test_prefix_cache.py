"""Cross-request prefix caching (ISSUE 10, ROADMAP 3) — the
content-addressed page index pinned deterministically on CPU:

- chain-index unit behavior: hash-chain addressing with mandatory
  token verification, refcount acquire/release symmetry, leaf-first LRU
  eviction that never victimizes a referenced or interior node, arena
  accounting;
- BIT-parity: a cache-hit request's tokens are identical to the same
  request run cold — full hits (prefill skipped entirely), partial hits
  (chunked resume at the miss boundary), monolithic fallback, across the
  split and fused engines;
- copy-on-write: the partial terminal page is privatized at map time
  (``serve.prefix.cow_copies``); concurrent divergence leaves both the
  diverging request's private copy and the survivor's shared page
  bit-identical vs their cold runs;
- preemption discipline: evicting a cache-hit request drops REFERENCES,
  never arena content — replay and the surviving sibling both stay
  bit-identical, and later requests still hit the same pages;
- the index as eviction tier: unreferenced LRU pages are reclaimed for
  admission BEFORE any running request is preempted;
- fault drills: ``prefix_hash_collide`` (verification rejects the forged
  node, cold fallback, bit-identical tokens) and ``prefix_publish_fail``
  (fail-open: request completes, nothing published);
- refcount accounting in ``Engine.verify_invariants`` mid-flight and at
  drain (the index SURVIVES drain; no request page leaks).

Page size 2 (env override), as in tests/test_serving.py, so the tiny
model's T=5 prompt spans 3 pages with a partial terminal page — the COW
case — and decode crosses page boundaries mid-flight.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
)
from dalle_pytorch_tpu.serving.engine import PREFIX_HOLDER
from dalle_pytorch_tpu.serving.prefix_cache import (
    PrefixCache,
    chain_blocks,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges, histograms


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(scope="module")
def bench_model():
    # the zipf-of-prefixes bench asserts full-hit TTFT < cold TTFT
    # in-bench; that comparison is only physical when cold chunked
    # prefill costs more than the cached admission's one sample
    # dispatch + host sync, so the bench model needs a prompt long
    # enough to span many chunks (T=5 would invert the sign on CPU
    # where per-dispatch overhead dominates toy compute)
    dalle = small_dalle(text_seq_len=48)
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 48)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, rid=None, p=None, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=rid or f"r{i}",
        prompt=prompt(i) if p is None else p,
        max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def run_all(engine, reqs, steps=800):
    for r in reqs:
        assert engine.submit(r) is None
    engine.run(max_steps=steps)
    return {k: list(v.tokens) for k, v in engine.results.items()}


# engine-mode axis shared by the parity suites: monolithic split,
# chunked split, fused (fused requires chunking)
MODES = [
    pytest.param(dict(), id="split-monolithic"),
    pytest.param(dict(prefill_chunk=2), id="split-chunked"),
    pytest.param(dict(prefill_chunk=2, fused_iteration=True), id="fused"),
]


# --------------------------------------------------- chain index (pure)


class TestChainIndex:
    def test_chain_blocks_terminal_partial(self):
        toks = np.arange(5)
        blocks = chain_blocks(toks, 2)
        assert [list(b) for b in blocks] == [[0, 1], [2, 3], [4]]
        # page-aligned prompts have no partial terminal
        assert [len(b) for b in chain_blocks(np.arange(4), 2)] == [2, 2]

    def _publish_chain(self, cache, toks, now=0.0):
        parent = None
        out = []
        for k, block in enumerate(chain_blocks(toks, cache.page_size)):
            page = cache.alloc_page()
            assert page is not None
            parent = cache.insert(
                parent, block, start=k * cache.page_size,
                page_id=page, now=now, ring=object(),
            )
            out.append(parent)
        return out

    def test_probe_matches_shared_prefix_only(self):
        cache = PrefixCache(range(10, 18), page_size=2)
        self._publish_chain(cache, np.asarray([1, 2, 3, 4, 5]))
        # identical prompt: all three nodes, in chain order
        hit = cache.probe(np.asarray([1, 2, 3, 4, 5]), now=1.0)
        assert [n.start for n in hit] == [0, 2, 4]
        assert all(n.last_hit == 1.0 for n in hit)
        # divergence mid-page 1: only the first page matches
        hit = cache.probe(np.asarray([1, 2, 9, 4, 5]), now=2.0)
        assert [n.start for n in hit] == [0]
        # divergence in page 0: nothing
        assert cache.probe(np.asarray([9, 2, 3, 4, 5]), now=3.0) == []
        assert cache.stats.hits == 2 and cache.stats.misses == 1

    def test_probe_verifies_tokens_not_just_hash(self):
        """A forged node at the right digest must be rejected by token
        verification — the hash is an address, never a proof."""
        cache = PrefixCache(range(4), page_size=2)
        (node,) = self._publish_chain(cache, np.asarray([1, 2]))
        # corrupt the stored block in place: the digest still matches the
        # query chain, the contents no longer do
        node.tokens = np.asarray([3, 4], np.int64)
        assert cache.probe(np.asarray([1, 2]), now=1.0) == []
        assert cache.stats.collisions == 1

    def test_refcounts_block_eviction(self):
        cache = PrefixCache(range(8), page_size=2)
        nodes = self._publish_chain(cache, np.asarray([1, 2, 3, 4]))
        cache.acquire(nodes, now=1.0)
        assert cache.evictable() == []
        assert cache.evict_one() is None
        cache.release(nodes)
        # interior node still shielded by its child: leaf-first
        assert [n.start for n in cache.evictable()] == [2]
        assert cache.evict_one().start == 2
        assert cache.evict_one().start == 0
        assert cache.evict_one() is None
        assert cache.free_arena_pages == 8
        cache.verify_invariants()

    def test_eviction_is_lru_by_last_hit(self):
        cache = PrefixCache(range(8), page_size=2)
        self._publish_chain(cache, np.asarray([1, 2]), now=0.0)
        self._publish_chain(cache, np.asarray([5, 6]), now=0.0)
        cache.probe(np.asarray([1, 2]), now=5.0)  # touch chain 1
        assert cache.evict_one().tokens.tolist() == [5, 6]

    def test_release_underflow_asserts(self):
        cache = PrefixCache(range(4), page_size=2)
        nodes = self._publish_chain(cache, np.asarray([1, 2]))
        with pytest.raises(AssertionError):
            cache.release(nodes)

    def test_insert_dedup_violation_asserts(self):
        cache = PrefixCache(range(4), page_size=2)
        self._publish_chain(cache, np.asarray([1, 2]))
        with pytest.raises(AssertionError):
            cache.insert(None, np.asarray([1, 2]), 0, cache.alloc_page(), 0.0)

    def test_upgrade_fills_only_missing_payloads(self):
        cache = PrefixCache(range(4), page_size=2)
        page = cache.alloc_page()
        node = cache.insert(None, np.asarray([1, 2]), 0, page, now=0.0)
        ring1, logits1 = object(), object()
        cache.upgrade(node, ring=ring1, logits=logits1)
        assert node.ring is ring1 and node.logits is logits1
        cache.upgrade(node, ring=object(), logits=object())
        assert node.ring is ring1 and node.logits is logits1  # never replaced

    def test_arena_exhaustion_and_return(self):
        cache = PrefixCache(range(2), page_size=2)
        a, b = cache.alloc_page(), cache.alloc_page()
        assert cache.alloc_page() is None
        cache.return_page(a)
        assert cache.alloc_page() == a


# ------------------------------------------------------- full-hit parity


class TestFullHitParity:
    @pytest.mark.parametrize("mode", MODES)
    def test_warm_tokens_bit_identical_to_cold(self, model, mode):
        cold = run_all(make_engine(model, **mode), [req(0), req(1)])
        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])
        assert counters.get("serve.prefix.misses") == 1
        warm = run_all(eng, [req(0, rid="r0w"), req(1, rid="r1")])
        assert warm["r0w"] == cold["r0"], "full-hit tokens diverged"
        assert warm["r1"] == cold["r1"], "cold sibling diverged"
        assert counters.get("serve.prefix.hits") == 1
        assert eng.prefix.stats.hits == 1
        eng.verify_invariants(idle=True)

    @pytest.mark.parametrize("mode", MODES)
    def test_full_hit_skips_prefill(self, model, mode, monkeypatch):
        """The full-hit request runs NO prefill of its own: the split
        prefill jits are unreachable during the warm run (poisoned here),
        and its dispatch bill — the cached-logits sample plus decode
        steps — never exceeds the cold request's (strictly fewer in the
        chunked modes, whose cold prefill rides extra iterations)."""
        from dalle_pytorch_tpu.serving import engine as engine_mod

        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])
        d_cold = eng.dispatches

        def poisoned(*a, **k):
            raise AssertionError("full hit ran a prefill jit")

        for name in ("_prefill_jit", "_prefill_chunk_jit",
                     "_prefill_last_jit"):
            monkeypatch.setattr(engine_mod, name, poisoned)
        run_all(eng, [req(0, rid="r0w")])
        d_warm = eng.dispatches - d_cold
        assert eng.results["r0w"].outcome is Outcome.COMPLETED
        if mode:  # chunked modes: cold prefill cost extra dispatches
            assert d_warm < d_cold, (d_warm, d_cold)
        else:
            assert d_warm <= d_cold, (d_warm, d_cold)
        assert counters.get("serve.prefix.hits") == 1

    def test_ttft_histogram_split_by_hit_class(self, model):
        eng = make_engine(model, prefix_cache=True)
        run_all(eng, [req(0)])
        assert histograms.get("serve.ttft_cold_s").count == 1
        run_all(eng, [req(0, rid="r0w")])
        assert histograms.get("serve.ttft_full_hit_s").count == 1
        assert histograms.get("serve.ttft_cold_s").count == 1
        assert gauges.get("serve.prefix_hit_frac") == 0.5

    def test_index_survives_drain_and_accounts_pages(self, model):
        """The cache's purpose is CROSS-request reuse: after every request
        drains, the index still holds its pages (charged to the pool) and
        a later identical request still hits."""
        eng = make_engine(model, prefix_cache=True)
        run_all(eng, [req(0)])
        eng.verify_invariants(idle=True)
        n = len(eng.prefix)
        assert n == 3  # T=5, page 2 -> 3 chain pages
        assert eng.pool.held(PREFIX_HOLDER) == n
        assert eng.pool.used == n
        run_all(eng, [req(0, rid="r0w")])
        assert counters.get("serve.prefix.hits") == 1
        eng.verify_invariants(idle=True)


# ---------------------------------------------------- partial-hit parity


def diverge_at(base, j, delta=1):
    """A copy of ``base`` differing exactly at prompt index ``j``."""
    p = np.asarray(base).copy()
    p[j] = ((p[j] - 1 + delta) % 15) + 1
    return p


class TestPartialHitParity:
    @pytest.mark.parametrize("mode", [MODES[1], MODES[2]])
    def test_shared_page_resume_bit_identical(self, model, mode):
        """A prompt sharing one full page with a published chain resumes
        chunked prefill at the miss boundary; tokens match its cold run
        bitwise. Internal row = [bos, t0, t1, t2, t3]: diverging at
        prompt index 2 shares internal positions 0..2 -> chain page 0."""
        pB = diverge_at(prompt(0), 2)
        cold = run_all(
            make_engine(model, **mode), [req(7, rid="rB", p=pB, seed=7)]
        )
        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])
        warm = run_all(eng, [req(7, rid="rB", p=pB, seed=7)])
        assert warm["rB"] == cold["rB"], "partial-hit tokens diverged"
        assert counters.get("serve.prefix.hits") == 1
        assert counters.get("serve.prefix.pages_hit") == 1
        eng.verify_invariants(idle=True)

    def test_monolithic_partial_falls_back_cold(self, model):
        """A split engine without chunking cannot resume mid-prompt: a
        partial chain match is a MISS (no refs leaked) and the request
        runs a full cold prefill, bit-identical."""
        pB = diverge_at(prompt(0), 2)
        cold = run_all(make_engine(model), [req(7, rid="rB", p=pB, seed=7)])
        eng = make_engine(model, prefix_cache=True)
        run_all(eng, [req(0)])
        warm = run_all(eng, [req(7, rid="rB", p=pB, seed=7)])
        assert warm["rB"] == cold["rB"]
        assert counters.get("serve.prefix.hits") == 0
        assert counters.get("serve.prefix.misses") == 2
        assert eng.prefix.total_refs() == 0
        eng.verify_invariants(idle=True)


# ------------------------------------------------------------------ COW


class TestCopyOnWrite:
    @pytest.mark.parametrize("mode", MODES)
    def test_partial_terminal_page_is_privatized(self, model, mode):
        """T=5 is not page-aligned: a full hit COWs the terminal page at
        map time (the first decode write lands inside it), so decode
        never touches arena storage. Counter pinned, and a THIRD
        identical request still hits the unmodified shared pages."""
        cold = run_all(make_engine(model, **mode), [req(0)])
        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])
        warm1 = run_all(eng, [req(0, rid="w1")])
        assert counters.get("serve.prefix.cow_copies") == 1
        warm2 = run_all(eng, [req(0, rid="w2")])
        assert counters.get("serve.prefix.cow_copies") == 2
        assert warm1["w1"] == cold["r0"]
        assert warm2["w2"] == cold["r0"], (
            "decode through the COW'd page corrupted the shared terminal"
        )
        eng.verify_invariants(idle=True)

    @pytest.mark.parametrize("mode", [MODES[1], MODES[2]])
    def test_concurrent_divergence_mid_page(self, model, mode):
        """Two CONCURRENT warm requests over a published prefix, one
        identical (full hit) and one diverging mid-page (partial hit up
        to the divergent page): both must match their cold runs bitwise
        — the diverging request's private pages and the survivor's
        shared mapping never alias."""
        pB = diverge_at(prompt(0), 2)
        reqs = lambda: [  # noqa: E731 - fresh Request objects per engine
            req(0, rid="rA"),
            req(7, rid="rB", p=pB, seed=7),
        ]
        cold = run_all(make_engine(model, **mode), reqs())
        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])  # publisher
        warm = run_all(eng, reqs())
        assert warm["rA"] == cold["rA"], "full-hit request diverged"
        assert warm["rB"] == cold["rB"], "diverging request diverged"
        assert counters.get("serve.prefix.hits") == 2
        eng.verify_invariants(idle=True)


# -------------------------------------------- preemption of shared pages


class TestPreemptionOfSharedPages:
    @pytest.mark.parametrize("mode", MODES)
    def test_preempted_hit_replays_and_sibling_survives(self, model, mode):
        """Preempt-and-requeue of a request MAPPING shared pages: the
        eviction drops references only (arena content untouched —
        ``paged_kv.reset_rows`` guard), replay is bit-identical, the
        concurrently running cold sibling is bit-identical, and a LATER
        warm request still hits the same pages bit-identically."""
        cold = run_all(make_engine(model, **mode), [req(0), req(1)])
        eng = make_engine(model, prefix_cache=True, **mode)
        run_all(eng, [req(0)])
        FAULTS.arm("page_exhaust", 1)
        warm = run_all(eng, [req(0, rid="r0w"), req(1, rid="r1")])
        assert FAULTS.fired.get("page_exhaust") == 1
        assert counters.get("serve.preempted") >= 1
        assert warm["r0w"] == cold["r0"], "replayed hit diverged"
        assert warm["r1"] == cold["r1"], "sibling diverged after eviction"
        eng.verify_invariants(idle=True)
        later = run_all(eng, [req(0, rid="r0x")])
        assert later["r0x"] == cold["r0"], (
            "arena pages corrupted by the eviction reset"
        )
        eng.verify_invariants(idle=True)

    def test_release_asserts_slot_row_bound(self, model):
        """The release reset may only name SLOT rows — an arena row
        through this path would zero shared content for every holder."""
        eng = make_engine(model, prefix_cache=True)
        run_all(eng, [req(0)])
        assert eng.submit(req(0, rid="r0w", max_new=4)) is None
        eng.step()
        slot = next(s for s in eng.slots if s is not None)
        slot.index = eng.config.max_batch  # forge an arena row index
        with pytest.raises(AssertionError, match="arena rows"):
            eng._release_slot(slot)


# --------------------------------------------------- index eviction tier


class TestIndexEvictionTier:
    def test_admission_reclaims_index_before_preempting(self, model):
        """Pool pressure at admission: LRU unreferenced index pages are
        dropped to admit the newcomer; no running request is preempted."""
        n_slot = 5  # pages_for(5 + 4, 2)
        eng = make_engine(
            model, prefix_cache=True, page_budget=n_slot + 4,
            prefix_cache_pages=5, max_batch=1,
        )
        run_all(eng, [req(0)])
        assert len(eng.prefix) == 3
        # distinct prompt: worst case 5 pages, free = 9 - 3(index) = 6
        # ... admits without reclaim; shrink the window with a second
        # publisher first
        run_all(eng, [req(1, rid="q1")])
        assert len(eng.prefix) in (5, 6)  # arena cap may already bite
        free0 = eng.pool.free
        run_all(eng, [req(2, rid="q2")])
        assert eng.results["q2"].outcome is Outcome.COMPLETED
        assert counters.get("serve.prefix.evictions") >= 1, (
            f"admission (free={free0}) should have reclaimed index pages"
        )
        assert counters.get("serve.preempted") == 0, (
            "index reclaim must come BEFORE preemption"
        )
        eng.verify_invariants(idle=True)

    def test_publish_fails_open_when_arena_full_and_referenced(self, model):
        """An arena too small for a second chain whose pages are all
        REFERENCED cannot evict: publish skips fail-open and the request
        still completes."""
        eng = make_engine(
            model, prefix_cache=True, prefix_cache_pages=3, max_batch=2,
        )
        run_all(eng, [req(0)])
        n0 = len(eng.prefix)
        assert n0 >= 1
        # second distinct prompt publishes into a full arena: LRU evicts
        # the first chain leaf-first OR skips — either way accounting holds
        run_all(eng, [req(1, rid="q1")])
        assert eng.results["q1"].outcome is Outcome.COMPLETED
        total = counters.get("serve.prefix.evictions") + counters.get(
            "serve.prefix.publish_skips"
        )
        assert total >= 1
        eng.verify_invariants(idle=True)


# ----------------------------------------------------------- fault drills


class TestFaultDrills:
    def test_prefix_hash_collide_falls_back_cold(self, model):
        """A forged index lookup (hash collision) must be rejected by
        token verification: the engine runs a cold prefill and the tokens
        are bit-identical to an uncached run."""
        cold = run_all(make_engine(model), [req(0)])
        eng = make_engine(model, prefix_cache=True)
        run_all(eng, [req(0)])
        FAULTS.arm("prefix_hash_collide", 1)
        warm = run_all(eng, [req(0, rid="r0c")])
        assert FAULTS.fired.get("prefix_hash_collide") == 1
        assert counters.get("serve.fault_prefix_hash_collide") == 1
        assert eng.prefix.stats.collisions == 1
        assert warm["r0c"] == cold["r0"], (
            "collision fallback served another prompt's K/V"
        )
        eng.verify_invariants(idle=True)

    def test_prefix_publish_fail_is_fail_open(self, model):
        eng = make_engine(model, prefix_cache=True)
        FAULTS.arm("prefix_publish_fail", 1)
        toks = run_all(eng, [req(0)])
        assert FAULTS.fired.get("prefix_publish_fail") == 1
        assert counters.get("serve.fault_prefix_publish_fail") == 1
        assert eng.results["r0"].outcome is Outcome.COMPLETED
        assert len(eng.prefix) == 0, "failed publish leaked index state"
        assert eng.pool.used == 0
        # the NEXT publisher works, and the tokens above were unaffected
        warm = run_all(eng, [req(0, rid="r0b")])
        assert warm["r0b"] == toks["r0"]
        assert len(eng.prefix) == 3
        eng.verify_invariants(idle=True)


# --------------------------------------------------------- release gate


@pytest.mark.slow
def test_serve_smoke_prefix_fault_drills():
    """tools/serve_smoke.py's cold/warm replay must pass clean AND
    compose with each env-armed prefix fault: a forged warm-round probe
    (``prefix_hash_collide``) degrades to cold prefill with bit-identical
    tokens, and a dropped cold-round publish (``prefix_publish_fail``)
    fails open."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for spec in ("prefix_hash_collide=1", "prefix_publish_fail=1"):
        env = dict(os.environ, JAX_PLATFORMS="cpu", DALLE_TPU_FAULTS=spec)
        out = subprocess.run(
            [sys.executable, "tools/serve_smoke.py"],
            capture_output=True, text=True, timeout=600, env=env, cwd=repo,
        )
        assert out.returncode == 0, (spec, out.stderr[-2000:])
        assert "prefix-cache cold/warm replay" in out.stderr, spec


# ------------------------------------------------------ invariants/misc


class TestInvariants:
    def test_midflight_refcount_accounting(self, model):
        """verify_invariants holds at EVERY engine step of a warm run —
        the sum of node refcounts equals the live shared mappings."""
        eng = make_engine(model, prefix_cache=True, prefill_chunk=2,
                          fused_iteration=True)
        run_all(eng, [req(0)])
        pB = diverge_at(prompt(0), 2)
        assert eng.submit(req(0, rid="rA")) is None
        assert eng.submit(req(7, rid="rB", p=pB, seed=7)) is None
        for _ in range(200):
            eng.verify_invariants()
            if not eng.step():
                break
        eng.verify_invariants(idle=True)
        assert eng.prefix.total_refs() == 0

    def test_prefix_cache_off_is_inert(self, model):
        eng = make_engine(model)
        assert eng.prefix is None
        run_all(eng, [req(0)])
        assert counters.get("serve.prefix.hits") == 0
        assert counters.get("serve.prefix.misses") == 0
        eng.verify_invariants(idle=True)

    def test_bench_serve_prefix_record_shape(self, bench_model):
        """bench.py's zipf-of-prefixes record (ISSUE 10 satellite): the
        in-bench acceptance (hit rate > 0.5, cached TTFT p50 < cold,
        bit-identical template tokens, zero in-trace compiles) ran if
        the record returns; pin its field contract here on the longer-
        prompt bench model (see the bench_model fixture for why T=48)."""
        import bench

        rec = bench.bench_serve_prefix(True, model=bench_model, seed=0)
        for k in ("hit_rate", "pages_deduped", "cow_copies",
                  "ttft_cached_p50_ms", "ttft_cached_p95_ms",
                  "ttft_cold_p50_ms", "ttft_cold_p95_ms",
                  "compiles_in_trace", "jit_recompiles_in_trace",
                  "index_pages_resident", "n_templates", "zipf_exponent",
                  "arrival_seed", "max_batch"):
            assert k in rec, k
        assert rec["metric"].startswith("serve_prefix_hit_rate")
        assert rec["hit_rate"] > 0.5
        assert rec["ttft_cached_p50_ms"] < rec["ttft_cold_p50_ms"]
        assert rec["pages_deduped"] > 0
        assert rec["compiles_in_trace"] in (0, -1)
        assert all(
            v in (0, -1) for v in rec["jit_recompiles_in_trace"].values()
        ), rec["jit_recompiles_in_trace"]

    def test_arena_rows_round_up_and_budget_includes_arena(self, model):
        eng = make_engine(model, prefix_cache=True, prefix_cache_pages=7)
        # 7 pages over 5-page rows -> 2 arena rows = 10 arena pages
        assert eng._arena_rows == 2
        assert eng.prefix.arena_total == 10
        assert eng.pool.total == eng.config.max_batch * 5 + 10
        # arena ids start past the slot rows' global pages
        assert min(eng.prefix._free_pages) == eng.config.max_batch * 5
