"""Pipeline-parallelism tests: the GPipe schedule (parallel/pipeline.py) and
the model-level pp execution path must be pure layout changes — identical
outputs and gradients to sequential execution, on the 8-device virtual CPU
mesh (conftest.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.parallel import gpipe, make_runtime, stack_layer_params
from dalle_pytorch_tpu.ops.jax_compat import shard_map


def pp_mesh(n=4):
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("pp",))


def toy_layer(p, x, side, layer_idx, micro_idx):
    return jnp.tanh(x @ p["w"] + p["b"]), jnp.zeros((), jnp.float32)


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_gpipe_matches_sequential(n_micro):
    stages, depth, b, n, d = 4, 8, 8, 6, 16
    rng = np.random.RandomState(0)
    per_layer = [
        {
            "w": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.randn(d) * 0.1, jnp.float32),
        }
        for _ in range(depth)
    ]
    x = jnp.asarray(rng.randn(b, n, d), jnp.float32)

    expected = x
    for p in per_layer:
        expected, _ = toy_layer(p, expected, None, 0, 0)

    stacked = stack_layer_params(per_layer)
    stacked = jax.tree_util.tree_map(
        lambda l: l.reshape(stages, depth // stages, *l.shape[1:]), stacked
    )
    mesh = pp_mesh(stages)
    p_specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)
    fn = jax.jit(
        shard_map(
            functools.partial(
                gpipe, toy_layer, axis_name="pp", n_stages=stages,
                n_micro=n_micro,
            ),
            mesh=mesh,
            in_specs=(p_specs, P(None)),
            out_specs=(P(None), P()),
            check_vma=False,
        )
    )
    out, aux = fn(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)
    assert float(aux) == 0.0


def test_gpipe_gradients_match_sequential():
    stages, depth, b, n, d = 2, 4, 4, 5, 8
    rng = np.random.RandomState(1)
    per_layer = [
        {
            "w": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.randn(d) * 0.1, jnp.float32),
        }
        for _ in range(depth)
    ]
    x = jnp.asarray(rng.randn(b, n, d), jnp.float32)
    w = jnp.asarray(rng.randn(b, n, d), jnp.float32)

    def seq_loss(layers):
        t = x
        for p in layers:
            t, _ = toy_layer(p, t, None, 0, 0)
        return (t * w).sum()

    g_seq = jax.jit(jax.grad(seq_loss))(per_layer)

    mesh = pp_mesh(stages)

    def pp_loss(layers):
        stacked = stack_layer_params(layers)
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape(stages, depth // stages, *l.shape[1:]), stacked
        )
        p_specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)
        out, _ = shard_map(
            functools.partial(
                gpipe, toy_layer, axis_name="pp", n_stages=stages, n_micro=2
            ),
            mesh=mesh,
            in_specs=(p_specs, P(None)),
            out_specs=(P(None), P()),
            check_vma=False,
        )(stacked, x)
        return (out * w).sum()

    g_pp = jax.jit(jax.grad(pp_loss))(per_layer)
    for a, e in zip(
        jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=2e-4)


# --------------------------------------------------------------- model level


def tiny_dalle(pp_axis=None, **kw):
    return DALLE(
        dim=32,
        depth=4,
        num_text_tokens=64,
        text_seq_len=8,
        num_image_tokens=32,
        image_fmap_size=4,
        heads=4,
        dim_head=8,
        attn_types=("full",),
        pp_axis=pp_axis,
        **kw,
    )


def test_dalle_pp_matches_single_device():
    base = tiny_dalle(None)
    pp_model = tiny_dalle("pp")
    rng = np.random.RandomState(2)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32)
    params = base.init(jax.random.key(0), text, image)["params"]

    l0, g0 = jax.jit(
        jax.value_and_grad(
            lambda p: base.apply({"params": p}, text, image, return_loss=True)
        )
    )(params)

    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4)
    with runtime.activate():
        l1, g1 = jax.jit(
            jax.value_and_grad(
                lambda p: pp_model.apply({"params": p}, text, image, return_loss=True)
            )
        )(params)

    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
    for a, e in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=5e-4, rtol=5e-3
        )


def test_dalle_pp_heterogeneous_layers_rejected():
    model = tiny_dalle("pp").clone(attn_types=("full", "axial_row"))
    rng = np.random.RandomState(3)
    text = jnp.asarray(rng.randint(1, 64, size=(2, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    params = model.init(jax.random.key(0), text, image)["params"]
    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4)
    with runtime.activate():
        with pytest.raises(ValueError, match="uniform attention type"):
            model.apply({"params": params}, text, image, return_loss=True)


def test_pp_train_step_end_to_end():
    import optax

    from dalle_pytorch_tpu.parallel import create_train_state, make_train_step

    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4)
    model = tiny_dalle("pp")
    rng = np.random.RandomState(4)
    batch = {
        "text": jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32),
        "image": jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32),
    }
    params = model.init(jax.random.key(0), batch["text"], batch["image"])["params"]
    opt = optax.adam(1e-3)
    state, shardings = create_train_state(params, opt, runtime)

    def loss_fn(p, batch, rng):
        return model.apply(
            {"params": p}, batch["text"], batch["image"], return_loss=True
        )

    step = make_train_step(loss_fn, opt, runtime, shardings)
    losses = []
    for i in range(3):
        state, loss = step(state, batch, jax.random.key(i))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_dalle_pp_with_mask_matches_single_device():
    """Key-padding masks ride the GPipe microbatch schedule (VERDICT r3 ask
    #3): a pp=4 run with a real padding mask must equal sequential."""
    base = tiny_dalle(None)
    pp_model = tiny_dalle("pp")
    rng = np.random.RandomState(7)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    text = text.at[:, -3:].set(0)
    image = jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32)
    mask = text != 0
    params = base.init(jax.random.key(0), text, image)["params"]

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: base.apply({"params": p}, text, image, mask=mask, return_loss=True)
    ))(params)
    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4)
    with runtime.activate():
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: pp_model.apply({"params": p}, text, image, mask=mask, return_loss=True)
        ))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
    for a, e in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=5e-4, rtol=5e-3)


def test_dalle_pp_composes_with_tp():
    """Partial-manual shard_map: only pp is manual, tp stays auto (GSPMD)
    inside the stage — a dp*tp*pp mesh must match sequential."""
    base = tiny_dalle(None)
    pp_model = tiny_dalle("pp")
    rng = np.random.RandomState(8)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32)
    params = base.init(jax.random.key(0), text, image)["params"]

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: base.apply({"params": p}, text, image, return_loss=True)
    ))(params)
    runtime = make_runtime(dp=2, fsdp=1, tp=2, sp=1, pp=2)
    with runtime.activate():
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: pp_model.apply({"params": p}, text, image, return_loss=True)
        ))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
    for a, e in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=5e-4, rtol=5e-3)


def test_dalle_pp_dropout_trains_deterministically():
    """Dropout under pp: per-(layer, microbatch) keys via fold_in — same key
    gives bitwise-identical loss, different keys differ, gradients flow."""
    pp_model = tiny_dalle("pp", attn_dropout=0.1, ff_dropout=0.1)
    rng = np.random.RandomState(9)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32)
    params = tiny_dalle(None).init(jax.random.key(0), text, image)["params"]
    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4)
    with runtime.activate():
        f = jax.jit(lambda p, k: pp_model.apply(
            {"params": p}, text, image, return_loss=True,
            deterministic=False, rngs={"dropout": k}))
        la, lb = float(f(params, jax.random.key(1))), float(f(params, jax.random.key(1)))
        lc = float(f(params, jax.random.key(2)))
        assert la == lb and la != lc
        _, g = jax.jit(jax.value_and_grad(lambda p: pp_model.apply(
            {"params": p}, text, image, return_loss=True,
            deterministic=False, rngs={"dropout": jax.random.key(3)})))(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree_util.tree_leaves(g))


def test_dalle_pp_moe_matches_sequential():
    """MoE under pipeline parallelism (moe_every=1 keeps stages
    homogeneous): loss must equal the sequential MoE model's, and the
    microbatch-averaged Switch aux must track the full-batch aux."""
    kw = dict(ff_experts=4, moe_every=1, moe_capacity_factor=4.0)
    base = tiny_dalle(None, **kw)
    pp_model = tiny_dalle("pp", **kw)
    rng = np.random.RandomState(11)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32)
    params = base.init(jax.random.key(0), text, image)["params"]

    def run(model, runtime=None):
        def go(p):
            out, mut = model.apply(
                {"params": p}, text, image, return_loss=True,
                mutable=["moe_aux"],
            )
            aux = sum(jax.tree_util.tree_leaves(mut["moe_aux"]))
            return out, aux
        if runtime is None:
            return jax.jit(go)(params)
        with runtime.activate():
            return jax.jit(go)(params)

    l0, a0 = run(base)
    l1, a1 = run(pp_model, make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4))
    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
    # generous capacity + identical routing per token => the microbatch
    # average equals the full-batch aux up to routing-statistics noise
    np.testing.assert_allclose(float(a0), float(a1), rtol=0.2)
    assert float(a1) >= 1.0 - 1e-5

    # gradients flow through the pipelined experts, gate AND the aux
    # channel itself (the trainer's objective is loss + w * aux)
    def objective(p):
        out, mut = pp_model.apply(
            {"params": p}, text, image, return_loss=True, mutable=["moe_aux"]
        )
        return out + 1e-2 * sum(jax.tree_util.tree_leaves(mut["moe_aux"]))

    with make_runtime(dp=2, fsdp=1, tp=1, sp=1, pp=4).activate():
        _, g = jax.jit(jax.value_and_grad(objective))(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(x)).all() for x in flat)
    # the aux term must actually reach the gates through the pipeline
    gate_g = g["transformer"]["ff_0"]["fn"]["fn"]["fn"]["gate"]["kernel"]
    assert np.abs(np.asarray(gate_g)).max() > 0
