"""Post-decode pipeline tests (serving/postdecode.py; DESIGN §8.5): the
VAE-decode -> CLIP-rerank stages pinned deterministically on CPU — full
tokens->image->score completion with bit-identical reruns, typed fault
retry and retry-exhaustion degradation (COMPLETED_TOKENS_ONLY /
COMPLETED_UNRANKED), backlog and occupancy-watermark degradation at the
stage boundary, cancel/deadline sweeps mid-stage, the per-iteration
stage budget, journaled stage boundaries, and the ``submit_staged``
crash-replay resume path producing bit-identical completed results.

Every test arming stage faults runs on ``FakeClock(step_dt>0)`` — retry
backoff is clock-elapsed and a real clock never advances enough inside
a tight drive loop.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from serve_smoke import build_tiny_model, build_tiny_stages  # noqa: E402

from dalle_pytorch_tpu.serving import (  # noqa: E402
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    PostDecodePipeline,
    Request,
    StageConfig,
    StageSpec,
)
from dalle_pytorch_tpu.serving.journal import (  # noqa: E402
    RequestJournal,
    image_from_payload,
    replay_unfinished,
)
from dalle_pytorch_tpu.serving.postdecode import (  # noqa: E402
    STAGE_RERANK,
    STAGE_TOKENS,
    STAGE_VAE,
)
from dalle_pytorch_tpu.serving.scheduler import Entry  # noqa: E402
from dalle_pytorch_tpu.utils.faults import FAULTS  # noqa: E402
from dalle_pytorch_tpu.utils.metrics import counters, gauges, histograms  # noqa: E402
from dalle_pytorch_tpu.utils.resilience import RetryPolicy  # noqa: E402


@pytest.fixture(scope="module")
def model():
    """One (dalle, params) for the whole module — every engine test
    shares the prefill/decode jit cache."""
    return build_tiny_model()


@pytest.fixture(scope="module")
def stages():
    """One canonical StageSpec (tiny VAE + CLIP, the trace-contract
    configs) for the whole module — the stage jits compile once."""
    return build_tiny_stages()


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    rng = np.random.RandomState(100 + i)
    return Request(
        request_id=f"r{i}", prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
        max_new_tokens=max_new, **kw,
    )


def staged_engine(model, stages, spec=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    cfg_kw.setdefault("prefill_chunk", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=FakeClock(step_dt=0.05), stages=spec or stages,
    )


def run_all(engine, reqs):
    for r in reqs:
        assert engine.submit(r) is None
    return engine.run()


# -------------------------------------------------------- engine-level


class TestPipelineCompletion:
    def test_full_pipeline_bit_identical_rerun(self, model, stages):
        """tokens -> VAE -> rerank completes with an image and a score,
        and a fresh engine over the same seeds reproduces every field
        bitwise (the determinism the chaos gate's references rely on)."""
        results = run_all(staged_engine(model, stages), [req(i) for i in range(3)])
        for i in range(3):
            res = results[f"r{i}"]
            assert res.outcome is Outcome.COMPLETED, res
            assert res.image is not None and res.image.ndim == 3
            assert res.rerank_score is not None
        again = run_all(staged_engine(model, stages), [req(i) for i in range(3)])
        for i in range(3):
            a, b = results[f"r{i}"], again[f"r{i}"]
            assert np.array_equal(a.tokens, b.tokens)
            assert np.array_equal(a.image, b.image)
            assert a.rerank_score == b.rerank_score
        assert counters.get("serve.stage.vae_images") == 6
        assert counters.get("serve.stage.reranked") == 6

    def test_rerank_off_completes_unscored(self, model, stages):
        """clip=None skips CLIP_RERANK: fully COMPLETED with an image
        and no score (not a degraded outcome)."""
        spec = StageSpec(stages.vae, stages.vae_params)
        res = run_all(staged_engine(model, stages, spec=spec), [req(0)])["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert res.image is not None and res.rerank_score is None
        assert counters.get("serve.stage.reranked") == 0

    def test_transient_fault_retries_then_completes(self, model, stages):
        """One vae_decode_fail burns a retry, backoff elapses on the
        FakeClock, and the request still fully completes."""
        FAULTS.arm("vae_decode_fail", count=1)
        res = run_all(staged_engine(model, stages), [req(0)])["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert res.image is not None and res.rerank_score is not None
        assert counters.get("serve.stage.retries") == 1
        assert counters.get("serve.stage.degraded") == 0


class TestDegradation:
    def test_vae_retry_exhaustion_tokens_only(self, model, stages):
        """Every VAE attempt fails -> the request degrades typed to
        COMPLETED_TOKENS_ONLY with its tokens and no image, never
        stalling the engine."""
        FAULTS.arm("vae_decode_fail", count=3)  # == RetryPolicy.attempts
        res = run_all(staged_engine(model, stages), [req(0)])["r0"]
        assert res.outcome is Outcome.COMPLETED_TOKENS_ONLY, res
        assert res.tokens is not None and res.image is None
        assert res.rerank_score is None
        assert counters.get("serve.stage.degraded") == 1
        assert counters.get("serve.stage.retries") == 2
        assert counters.get("serve.completed_tokens_only") == 1

    def test_rerank_retry_exhaustion_unranked(self, model, stages):
        """Rerank exhaustion keeps the decoded image: COMPLETED_UNRANKED
        with image, no score."""
        FAULTS.arm("rerank_fail", count=3)
        res = run_all(staged_engine(model, stages), [req(0)])["r0"]
        assert res.outcome is Outcome.COMPLETED_UNRANKED, res
        assert res.image is not None and res.rerank_score is None
        assert counters.get("serve.stage.vae_images") == 1
        assert counters.get("serve.completed_unranked") == 1

    def test_stage_timeout_site_degrades(self, model, stages):
        """The shared stage_timeout site exhausts like a stage fault."""
        FAULTS.arm("stage_timeout", count=6)  # both stages draw from it
        res = run_all(staged_engine(model, stages), [req(0)])["r0"]
        assert res.outcome is Outcome.COMPLETED_TOKENS_ONLY
        assert counters.get("serve.stage.timeouts") >= 3


# ------------------------------------------- pipeline-direct (no engine)


def make_pipeline(stages, config=None, occupancy=None, clock=None):
    spec = stages if config is None else StageSpec(
        stages.vae, stages.vae_params, stages.clip, stages.clip_params,
        config=config,
    )
    done = []
    pipe = PostDecodePipeline(
        spec, clock=clock or FakeClock(step_dt=0.05),
        counters=counters, gauges=gauges, histograms=histograms,
        finish=lambda entry, outcome, tokens, image=None, score=None,
        detail=None: done.append(
            (entry.request.request_id, outcome, image, score, detail)),
        occupancy=occupancy,
    )
    return pipe, done


def entry(i, **kw):
    return Entry(request=req(i, **kw), submit_time=0.0, seq=i)


def toks(i):
    return np.full((4,), i % 12, np.int32)


class TestStageBoundaryPressure:
    def test_backlog_degrades_at_entry(self, stages):
        """Backlog >= queue_limit completes the newcomer typed-degraded
        at the door (tokens-only: it never reached the VAE)."""
        pipe, done = make_pipeline(stages, config=StageConfig(queue_limit=2))
        for i in range(3):
            pipe.enqueue(entry(i), toks(i))
        assert len(pipe) == 2 and len(done) == 1
        rid, outcome, image, _, detail = done[0]
        assert rid == "r2" and outcome is Outcome.COMPLETED_TOKENS_ONLY
        assert image is None and detail == "stage_backlog"
        assert counters.get("serve.stage.degraded") == 1

    def test_watermark_degrades_at_entry(self, stages):
        """Fleet occupancy past high_watermark sheds stage work typed;
        a resumed item that already has its image keeps it (UNRANKED)."""
        pipe, done = make_pipeline(
            stages, config=StageConfig(high_watermark=0.5),
            occupancy=lambda: 0.9,
        )
        pipe.enqueue(entry(0), toks(0))
        img = np.zeros((4, 4, 3), np.float32)
        pipe.enqueue(entry(1), toks(1), image=img)
        assert [d[1] for d in done] == [
            Outcome.COMPLETED_TOKENS_ONLY, Outcome.COMPLETED_UNRANKED,
        ]
        assert done[1][2] is img and done[1][4] == "stage_watermark"

    def test_cancel_and_deadline_sweep_mid_stage(self, stages):
        """Parked staged work honors cancellation and deadlines with the
        partial results it holds (image iff VAE already ran)."""
        pipe, done = make_pipeline(stages)
        pipe.enqueue(entry(0), toks(0))
        pipe.enqueue(entry(1, deadline=1e-9), toks(1),
                     image=np.zeros((4, 4, 3), np.float32))
        assert pipe.sweep({"r0"}, now=1.0) == ["r0"]
        assert not pipe and len(done) == 2
        by_rid = {d[0]: d for d in done}
        assert by_rid["r0"][1] is Outcome.CANCELLED
        assert by_rid["r0"][4] == f"cancelled in {STAGE_VAE}"
        assert by_rid["r1"][1] is Outcome.DEADLINE_EXCEEDED
        assert by_rid["r1"][2] is not None  # image survives onto the result
        assert by_rid["r1"][4] == f"deadline in {STAGE_RERANK}"

    def test_stage_budget_bounds_dispatch(self, stages):
        """budget=1: one step dispatches at most one staged image even
        with three parked — stage work cannot crowd out token decode."""
        pipe, _ = make_pipeline(
            stages, config=StageConfig(budget=1, retry=RetryPolicy(
                attempts=1, base_delay=0.0, max_delay=0.0, jitter=0.0,
                retry_on=())),
        )
        for i in range(3):
            pipe.enqueue(entry(i), toks(i))
        assert pipe.step()
        assert counters.get("serve.stage.vae_images") == 1
        assert len(pipe) == 3  # r0 advanced to RERANK, none completed

    def test_rerank_dispatches_before_vae(self, stages):
        """Rerank is head-of-line: the furthest-along item drains first,
        freeing pipeline capacity fastest."""
        pipe, done = make_pipeline(stages, config=StageConfig(budget=1))
        pipe.enqueue(entry(0), toks(0))  # at VAE
        pipe.enqueue(entry(1), toks(1), image=np.zeros((4, 4, 3), np.float32))
        assert pipe.step()
        assert [d[0] for d in done] == ["r1"]
        assert done[0][1] is Outcome.COMPLETED and done[0][3] is not None
        assert counters.get("serve.stage.vae_images") == 0

    def test_stage_boundary_hook_fires(self, stages):
        """on_stage announces tokens-complete and VAE boundaries with
        resumable payloads — exactly what the router journals."""
        pipe, done = make_pipeline(stages)
        seen = []
        pipe.on_stage = lambda rid, stage, payload: seen.append(
            (rid, stage, sorted(payload)))
        pipe.enqueue(entry(0), toks(0))
        while not done:
            assert pipe.step()
        assert seen[0] == ("r0", STAGE_TOKENS, ["tokens"])
        assert seen[1] == ("r0", STAGE_VAE, ["image"])
        # resume paths are announce=False: already-durable records
        pipe.enqueue(entry(1), toks(1), announce=False)
        assert len(seen) == 2


# ------------------------------------------------- crash-replay resume


class TestStagedResume:
    def test_submit_staged_bit_identical(self, model, stages):
        """Resuming from a journaled boundary — tokens only (restart at
        VAE) or tokens+image (restart at RERANK) — reproduces the
        uninterrupted run's completed result bitwise."""
        ref = run_all(staged_engine(model, stages), [req(0)])["r0"]
        eng = staged_engine(model, stages)
        assert eng.submit_staged(req(0), ref.tokens) is None
        from_vae = eng.run()["r0"]
        eng = staged_engine(model, stages)
        assert eng.submit_staged(req(0), ref.tokens, image=ref.image) is None
        from_rerank = eng.run()["r0"]
        for res in (from_vae, from_rerank):
            assert res.outcome is Outcome.COMPLETED
            assert np.array_equal(res.tokens, ref.tokens)
            assert np.array_equal(res.image, ref.image)
            assert res.rerank_score == ref.rerank_score

    def test_journal_records_stages_and_replay_is_idempotent(
            self, model, stages, tmp_path):
        """A journaled completed request leaves stage records for every
        boundary; replay of a clean-shutdown journal re-admits nothing
        (the idempotency half of crash replay)."""
        from dalle_pytorch_tpu.serving import Router, RouterConfig

        dalle, params = model
        jpath = str(tmp_path / "requests.jsonl")
        router = Router(
            dalle, params,
            RouterConfig(n_replicas=1, respawn=False),
            EngineConfig(max_batch=2, prefill_chunk=2),
            clock=FakeClock(step_dt=0.05),
            journal=RequestJournal(jpath), stages=stages,
        )
        assert router.submit(req(0)) is None
        res = router.run()["r0"]
        assert res.outcome is Outcome.COMPLETED
        router._journal.close()
        recorded = RequestJournal.stages(jpath)["r0"]
        assert sorted(recorded) == sorted([STAGE_TOKENS, STAGE_VAE])
        assert recorded[STAGE_TOKENS]["tokens"] == [int(t) for t in res.tokens]
        assert np.array_equal(
            image_from_payload(recorded[STAGE_VAE]["image"]), res.image)
        replayed = replay_unfinished(
            jpath, submit=lambda r: (_ for _ in ()).throw(
                AssertionError("finished request replayed")),
            submit_staged=lambda r, tokens, image=None: (
                _ for _ in ()).throw(
                AssertionError("finished request replayed staged")),
        )
        assert replayed == []
