"""Multi-process (multi-host) execution — the reference's one-process-per-GPU
deployment model (deepspeed_backend.py:36-64, README.md launcher docs) proven
for real: 2 OS processes x 4 virtual CPU devices each rendezvous through
``jax.distributed``, build one global dp x fsdp mesh, and must reproduce the
single-process 8-device run bit-for-tolerance.

Covers the process_count > 1 paths nothing else can execute: cross-process
barrier / average_all / to_host collectives, per-host disjoint DataLoader
sharding, and root-only checkpoint writes observed by the non-root process.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest
from PIL import Image

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "multiprocess_worker.py"
N_SAMPLES = 16


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _make_dataset(root: Path) -> Path:
    data = root / "pairs"
    data.mkdir()
    rng = np.random.RandomState(0)
    for i in range(N_SAMPLES):
        arr = rng.randint(0, 255, size=(16, 16, 3), dtype=np.uint8)
        Image.fromarray(arr).save(data / f"sample_{i:03d}.png")
        (data / f"sample_{i:03d}.txt").write_text(f"a tiny sample {i}")
    return data


@pytest.mark.slow
def test_two_process_parity(tmp_path):
    data_dir = _make_dataset(tmp_path)
    ckpt = tmp_path / "mp.ckpt"
    port = _free_port()

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via jax.config itself
    # file-backed stdio: a worker can never block on a full pipe while its
    # sibling waits in a collective, and nothing needs draining in order
    io_files = []
    procs = []
    for i in range(2):
        out_f = open(tmp_path / f"worker{i}.out", "w+")
        err_f = open(tmp_path / f"worker{i}.err", "w+")
        io_files.append((out_f, err_f))
        procs.append(subprocess.Popen(
            [
                sys.executable, str(WORKER),
                "--process_id", str(i),
                "--num_processes", "2",
                "--coordinator", f"localhost:{port}",
                "--local_devices", "4",
                "--data_dir", str(data_dir),
                "--ckpt", str(ckpt),
            ],
            cwd=REPO, env=env, stdout=out_f, stderr=err_f, text=True,
        ))
    # wait for BOTH workers before asserting anything — failing fast on one
    # would orphan its sibling inside a blocking collective
    outcomes = []
    try:
        for p, (out_f, err_f) in zip(procs, io_files):
            try:
                p.wait(timeout=900)
            finally:
                out_f.seek(0), err_f.seek(0)
                outcomes.append((p.returncode, out_f.read(), err_f.read()))
                out_f.close(), err_f.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for rc, out, err in outcomes:
        assert rc == 0, (
            f"worker failed (rc={rc})\nstdout: {out[-2000:]}\n"
            f"stderr: {err[-3000:]}"
        )
        for line in out.splitlines():
            if line.startswith("MPRESULT "):
                r = json.loads(line[len("MPRESULT "):])
                results[r["process_id"]] = r
    logs = [err[-2000:] for _, _, err in outcomes]
    assert sorted(results) == [0, 1], f"missing worker results; stderr: {logs}"
    r0, r1 = results[0], results[1]

    # both processes observed the same global computation
    assert r0["world_size"] == r1["world_size"] == 8
    assert np.allclose(r0["losses"], r1["losses"], rtol=1e-6), (
        r0["losses"], r1["losses"],
    )
    assert np.isfinite(r0["losses"]).all() and r0["losses"][2] != r0["losses"][0]

    # cross-process scalar mean: (0 + 1) / 2
    assert abs(r0["average_all"] - 0.5) < 1e-6
    assert abs(r1["average_all"] - 0.5) < 1e-6

    # root-only checkpoint write, visible to BOTH processes post-barrier
    assert r0["ckpt_ok"] and r1["ckpt_ok"]

    # per-host data shards: disjoint, equal-sized, covering every sample
    s0, s1 = set(r0["loader_shard"]), set(r1["loader_shard"])
    assert s0.isdisjoint(s1)
    assert len(r0["loader_shard"]) == len(r1["loader_shard"])
    assert s0 | s1 == set(range(N_SAMPLES))

    # numeric parity with the same math run single-process on 8 devices
    from dalle_pytorch_tpu.parallel import make_runtime
    from tests.multiprocess_worker import run_training

    runtime = make_runtime(fsdp=2)
    losses_1p, fp_1p, _ = run_training(runtime)
    rel = [
        abs(a - b) / (abs(b) + 1e-9) for a, b in zip(r0["losses"], losses_1p)
    ]
    assert max(rel) < 5e-3, (
        f"2-process losses {r0['losses']} diverge from single-process "
        f"{losses_1p} (rel {rel})"
    )
    fp_rel = abs(r0["fingerprint"] - fp_1p) / (abs(fp_1p) + 1e-9)
    assert fp_rel < 5e-3, (
        f"update-norm fingerprint {r0['fingerprint']} != {fp_1p} ({fp_rel:.2e})"
    )
