"""Sequence-parallelism tests: ring attention + Ulysses all-to-all parity
against the dense oracle, and full-model sp-vs-single-device equivalence on
the 8-device virtual CPU mesh (conftest.py).

The reference has no sequence parallelism (SURVEY.md §5.7); these tests pin
the TPU-native sp layer: sharding the sequence over the ``sp`` mesh axis must
be a pure layout change — identical forward values and gradients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.ops.attention import PatternAttention, dense_attend
from dalle_pytorch_tpu.ops.ring_attention import ring_attention, ulysses_attend
from dalle_pytorch_tpu.parallel import activate_mesh, make_runtime
from dalle_pytorch_tpu.ops.jax_compat import shard_map


def sp_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("sp",))


def causal_oracle(q, k, v, scale, key_mask=None):
    mask = jnp.tril(jnp.ones((q.shape[2], q.shape[2]), bool))[None, None]
    if key_mask is not None:
        mask = mask & key_mask[:, None, None, :]
    return dense_attend(q * scale, k, v, mask)


@pytest.mark.parametrize("use_mask", [False, True])
def test_ring_attention_forward_parity(use_mask):
    mesh = sp_mesh()
    rng = np.random.RandomState(0)
    b, h, n, d = 2, 4, 64, 16
    q, k, v = (jnp.asarray(rng.randn(b, h, n, d), jnp.float32) for _ in range(3))
    scale = d**-0.5
    # keep key 0 visible so no causal row is fully masked (the dense oracle
    # averages V on fully-masked rows; ring's contract returns exact 0 there,
    # covered by test_ring_attention_noncausal_and_masked_rows)
    km = (
        jnp.asarray(rng.rand(b, n) > 0.2).at[:, 0].set(True)
        if use_mask
        else None
    )

    body = functools.partial(
        ring_attention, axis_name="sp", axis_size=8, causal=True, sm_scale=scale
    )
    spec = P(None, None, "sp", None)
    if use_mask:
        fn = jax.jit(
            shard_map(
                lambda q, k, v, m: body(q, k, v, key_mask=m),
                mesh=mesh,
                in_specs=(spec, spec, spec, P(None, "sp")),
                out_specs=spec,
                check_vma=False,
            )
        )
        out = fn(q, k, v, km)
    else:
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=False,
            )
        )
        out = fn(q, k, v)

    expected = causal_oracle(q, k, v, scale, km)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_attention_noncausal_and_masked_rows():
    """Non-causal ring matches dense; a fully-masked query row yields 0."""
    mesh = sp_mesh()
    rng = np.random.RandomState(1)
    b, h, n, d = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng.randn(b, h, n, d), jnp.float32) for _ in range(3))
    scale = d**-0.5
    km = jnp.zeros((b, n), bool)  # nothing attendable anywhere

    spec = P(None, None, "sp", None)
    fn = jax.jit(
        shard_map(
            lambda q, k, v, m: ring_attention(
                q, k, v, "sp", 8, causal=False, sm_scale=scale, key_mask=m
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, "sp")),
            out_specs=spec,
            check_vma=False,
        )
    )
    out = fn(q, k, v, km)
    np.testing.assert_array_equal(np.asarray(out), 0.0)

    km = jnp.ones((b, n), bool)
    out = fn(q, k, v, km)
    expected = dense_attend(q * scale, k, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_ring_attention_gradient_parity():
    mesh = sp_mesh()
    rng = np.random.RandomState(2)
    b, h, n, d = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng.randn(b, h, n, d), jnp.float32) for _ in range(3))
    w = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    scale = d**-0.5
    spec = P(None, None, "sp", None)

    ring = shard_map(
        functools.partial(
            ring_attention, axis_name="sp", axis_size=8, causal=True, sm_scale=scale
        ),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    g_ring = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) * w).sum(), argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(
        jax.grad(lambda q, k, v: (causal_oracle(q, k, v, scale) * w).sum(), argnums=(0, 1, 2))
    )(q, k, v)
    for a, e in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=3e-5)


def test_ulysses_parity_dense():
    mesh = sp_mesh()
    rng = np.random.RandomState(3)
    b, h, n, d = 2, 8, 40, 16
    q, k, v = (jnp.asarray(rng.randn(b, h, n, d), jnp.float32) for _ in range(3))
    scale = d**-0.5
    km = jnp.asarray(rng.rand(b, n) > 0.3)
    spec = P(None, None, "sp", None)

    def attend(q, k, v, km):
        mask = jnp.tril(jnp.ones((q.shape[2], q.shape[2]), bool))[None, None]
        mask = mask & km[:, None, None, :]
        return dense_attend(q * scale, k, v, mask)

    fn = jax.jit(
        shard_map(
            lambda q, k, v, m: ulysses_attend(q, k, v, "sp", 8, attend, key_mask=m),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, "sp")),
            out_specs=spec,
            check_vma=False,
        )
    )
    out = fn(q, k, v, km)
    expected = causal_oracle(q, k, v, scale, km)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


# --------------------------------------------------------------- model level


def tiny_dalle(sp_axis=None, attn_types=("full", "axial_row")):
    return DALLE(
        dim=32,
        depth=2,
        num_text_tokens=64,
        text_seq_len=8,
        num_image_tokens=32,
        image_fmap_size=4,
        heads=8,
        dim_head=8,
        attn_types=attn_types,
        shift_tokens=False,
        sp_axis=sp_axis,
    )


@pytest.mark.parametrize(
    "attn_types", [("full",), ("axial_row", "axial_col"), ("conv_like", "sparse")]
)
def test_dalle_sp_matches_single_device(attn_types):
    """Same params, same batch: sp-sharded loss & grads == unsharded loss &
    grads for every attention family (ring for full, Ulysses otherwise)."""
    base = tiny_dalle(None, attn_types)
    sp_model = tiny_dalle("sp", attn_types)

    rng = np.random.RandomState(4)
    text = jnp.asarray(rng.randint(1, 64, size=(2, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    params = base.init(jax.random.key(0), text, image)["params"]

    def loss_base(p):
        return base.apply({"params": p}, text, image, return_loss=True)

    def loss_sp(p):
        return sp_model.apply({"params": p}, text, image, return_loss=True)

    l0, g0 = jax.jit(jax.value_and_grad(loss_base))(params)

    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=4)
    with runtime.activate():
        l1, g1 = jax.jit(jax.value_and_grad(loss_sp))(params)

    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
    flat0 = jax.tree_util.tree_leaves(g0)
    flat1 = jax.tree_util.tree_leaves(g1)
    for a, e in zip(flat1, flat0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), atol=5e-4, rtol=5e-3
        )


def test_dalle_sp_with_text_mask():
    base = tiny_dalle(None, ("full", "axial_col"))
    sp_model = tiny_dalle("sp", ("full", "axial_col"))
    rng = np.random.RandomState(5)
    text = jnp.asarray(rng.randint(1, 64, size=(2, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    mask = jnp.asarray(rng.rand(2, 8) > 0.3)
    params = base.init(jax.random.key(0), text, image)["params"]

    l0 = jax.jit(
        lambda p: base.apply({"params": p}, text, image, mask=mask, return_loss=True)
    )(params)
    runtime = make_runtime(dp=1, fsdp=1, tp=2, sp=4)
    with runtime.activate():
        l1 = jax.jit(
            lambda p: sp_model.apply({"params": p}, text, image, mask=mask, return_loss=True)
        )(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)


def test_sp_train_step_end_to_end():
    """A full sharded train step over a dp×tp×sp mesh runs and reduces loss
    deterministically (make_train_step activates the mesh itself)."""
    import optax

    from dalle_pytorch_tpu.parallel import create_train_state, make_train_step

    runtime = make_runtime(dp=2, fsdp=1, tp=2, sp=2)
    model = tiny_dalle("sp")
    rng = np.random.RandomState(6)
    batch = {
        "text": jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32),
        "image": jnp.asarray(rng.randint(0, 32, size=(4, 16)), jnp.int32),
    }
    params = model.init(jax.random.key(0), batch["text"], batch["image"])["params"]
    opt = optax.adam(1e-3)
    state, shardings = create_train_state(params, opt, runtime)

    def loss_fn(p, batch, rng):
        return model.apply({"params": p}, batch["text"], batch["image"], return_loss=True)

    step = make_train_step(loss_fn, opt, runtime, shardings)
    losses = []
    for i in range(3):
        state, loss = step(state, batch, jax.random.key(i))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
