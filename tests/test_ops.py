"""Unit tests for core ops: rotary tables, static masks, layer primitives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import masks, rotary
from dalle_pytorch_tpu.ops.layers import (
    GMLPBlock,
    divide_max,
    layer_scale_init,
    shift_tokens,
    shift_tokens_decode,
    stable_softmax,
)


class TestRotary:
    def test_angle_table_shape(self):
        # dim_head=64 -> rot_dim=21 -> each part 2*(21//2)=20 wide, 3 parts
        table = rotary.dalle_rotary_table(64, text_len=9, image_fmap_size=4)
        assert table.shape == (9 + 16 - 1, 60)

    def test_apply_preserves_norm(self):
        # rotation is orthogonal on the rotated channels
        key = jax.random.PRNGKey(0)
        t = jax.random.normal(key, (2, 3, 8, 64))
        table = rotary.dalle_rotary_table(64, text_len=5, image_fmap_size=2)
        out = rotary.apply_rotary_emb(jnp.asarray(table[None, None]), t)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(t), axis=-1),
            rtol=1e-5,
        )

    def test_relative_property_1d(self):
        # <q(m), k(n)> after rotation depends only on m - n for 1-D angles
        freqs = rotary.lang_freqs(16)
        q = jax.random.normal(jax.random.PRNGKey(1), (16,))
        k = jax.random.normal(jax.random.PRNGKey(2), (16,))

        def dot(m, n):
            am = jnp.asarray(rotary.angles(np.array([m]), freqs)[0])
            an = jnp.asarray(rotary.angles(np.array([n]), freqs)[0])
            qm = rotary.apply_rotary_emb(am, q)
            kn = rotary.apply_rotary_emb(an, k)
            return float(jnp.dot(qm, kn))

        assert dot(3, 1) == pytest.approx(dot(7, 5), rel=1e-5)
        assert dot(3, 1) != pytest.approx(dot(3, 2), rel=1e-3)

    def test_rotate_half_pairs(self):
        x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        np.testing.assert_allclose(
            np.asarray(rotary.rotate_half(x)), [-2.0, 1.0, -4.0, 3.0]
        )


class TestMasks:
    text_len, f = 5, 4  # text includes <bos>; 4x4 image grid

    def total(self):
        return self.text_len + self.f * self.f

    def test_causal(self):
        m = masks.causal_mask(4)
        assert m[2, 2] and m[2, 0] and not m[2, 3]

    def test_all_patterns_are_causal_and_self_attending(self):
        for attn_type in ("full", "axial_row", "axial_col", "conv_like", "sparse"):
            m = masks.pattern_mask(attn_type, self.text_len, self.f)
            assert m.shape == (self.total(), self.total())
            assert not np.triu(m, 1).any(), f"{attn_type} must be causal"
            assert m.diagonal().all(), f"{attn_type} must attend to self"

    def test_image_attends_all_text(self):
        for attn_type in ("axial_row", "axial_col", "conv_like"):
            m = masks.pattern_mask(attn_type, self.text_len, self.f)
            assert m[self.text_len :, : self.text_len].all()

    def test_axial_row_structure(self):
        m = masks.axial_mask(self.text_len, self.f, axis=0)
        tl, f = self.text_len, self.f
        q = tl + 1 * f + 2  # image (row 1, col 2)
        assert m[q, tl + 1 * f + 0] and m[q, tl + 1 * f + 2]
        assert not m[q, tl + 1 * f + 3]  # later col in same row
        assert not m[q, tl + 0 * f + 2]  # different row
        assert not m[q, tl + 0 * f + 0]

    def test_axial_col_structure(self):
        m = masks.axial_mask(self.text_len, self.f, axis=1)
        tl, f = self.text_len, self.f
        q = tl + 2 * f + 1  # (row 2, col 1)
        assert m[q, tl + 0 * f + 1] and m[q, tl + 1 * f + 1]
        assert not m[q, tl + 3 * f + 1]  # later row same col
        assert not m[q, tl + 2 * f + 0]  # same row different col

    def test_conv_window(self):
        m = masks.conv_mask(self.text_len, self.f, kernel_size=3)
        tl, f = self.text_len, self.f
        q = tl + 2 * f + 2  # (2, 2)
        assert m[q, tl + 1 * f + 1]  # diag neighbor above-left
        assert m[q, tl + 2 * f + 1]  # left
        assert not m[q, tl + 2 * f + 3]  # right of q (index greater)
        assert not m[q, tl + 0 * f + 2]  # outside 3x3 window

    def test_block_sparse_global_text(self):
        total = self.total()
        m = masks.block_sparse_mask(
            total, block_size=4, text_seq_len=self.text_len - 1, num_random_blocks=1
        )
        # global text blocks: every query sees the first text block (causally)
        assert all(m[i, 0] for i in range(1, total))
        assert not np.triu(m, 1).any()

    def test_dilated_conv_window(self):
        m = masks.conv_mask(2, 8, kernel_size=3, dilation=2)
        tl, f = 2, 8
        q = tl + 4 * f + 4
        assert m[q, tl + 2 * f + 2]  # dilation-2 neighbor
        assert not m[q, tl + 3 * f + 3]  # odd offset not part of dilated grid


class TestLayers:
    def test_stable_softmax_matches_softmax(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16)) * 5
        np.testing.assert_allclose(
            np.asarray(stable_softmax(x)),
            np.asarray(jax.nn.softmax(x, axis=-1)),
            atol=1e-6,
        )

    def test_divide_max(self):
        x = jnp.asarray([[1.0, 2.0, 4.0]])
        np.testing.assert_allclose(np.asarray(divide_max(x)), [[0.25, 0.5, 1.0]])

    def test_layer_scale_init_schedule(self):
        assert layer_scale_init(1) == 0.1
        assert layer_scale_init(18) == 0.1
        assert layer_scale_init(19) == 1e-5
        assert layer_scale_init(24) == 1e-5
        assert layer_scale_init(25) == 1e-6

    def test_shift_tokens_semantics(self):
        b, d, f, text_len = 1, 8, 3, 3
        n = text_len + f * f - 1  # truncated final token, like training
        x = jax.random.normal(jax.random.PRNGKey(0), (b, n, d))
        out = shift_tokens(x, text_len, f)
        assert out.shape == x.shape
        x, out = np.asarray(x), np.asarray(out)
        half, q = d // 2, d // 4
        # text position 0: first half zeros
        np.testing.assert_allclose(out[0, 0, :half], 0.0)
        np.testing.assert_allclose(out[0, 0, half:], x[0, 0, half:])
        # text position 2: first half from position 1
        np.testing.assert_allclose(out[0, 2, :half], x[0, 1, :half])
        # image grid position (1, 1) = seq index text_len + 4 (f=3 grid):
        p = text_len + 4
        np.testing.assert_allclose(out[0, p, :q], x[0, p - f, :q])  # from above
        np.testing.assert_allclose(out[0, p, q : 2 * q], x[0, p - 1, q : 2 * q])  # left
        np.testing.assert_allclose(out[0, p, 2 * q :], x[0, p, 2 * q :])
        # image grid position (0, 0): top and left quarters zero
        p0 = text_len
        np.testing.assert_allclose(out[0, p0, : 2 * q], 0.0)

    def test_shift_tokens_decode_matches_batch(self):
        """The per-token decode shift must agree with the full-sequence shift."""
        b, d, f, text_len = 2, 8, 3, 4
        n = text_len + f * f
        x = jax.random.normal(jax.random.PRNGKey(1), (b, n, d))
        full = np.asarray(shift_tokens(x, text_len, f))
        zeros = jnp.zeros((b, 1, d))
        for pos in range(n):
            prev = x[:, pos - 1 : pos] if pos > 0 else zeros
            ra = x[:, pos - f : pos - f + 1] if pos - f >= 0 else zeros
            step = shift_tokens_decode(
                x[:, pos : pos + 1], jnp.asarray(pos), prev, ra, text_len, f
            )
            np.testing.assert_allclose(
                np.asarray(step)[:, 0], full[:, pos], atol=1e-6, err_msg=f"pos={pos}"
            )


class TestGMLPDecode:
    def test_decode_matches_full_forward(self):
        """One-token decode through the spatial-gating cache must reproduce
        the full-sequence forward at every position (round-1 VERDICT weak #4:
        decode used to silently see w[:1,:1] instead of the history row)."""
        b, n, dim = 2, 10, 16
        block = GMLPBlock(dim=dim, dim_ff=32, seq_len=n, causal=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (b, n, dim))
        params = block.init(jax.random.PRNGKey(1), x)["params"]
        full = np.asarray(block.apply({"params": params}, x))

        cache = block.init(jax.random.PRNGKey(1), x[:, :1], decode=True)["cache"]
        for pos in range(n):
            step, vars_ = block.apply(
                {"params": params, "cache": cache},
                x[:, pos : pos + 1],
                decode=True,
                mutable=["cache"],
            )
            cache = vars_["cache"]
            np.testing.assert_allclose(
                np.asarray(step)[:, 0], full[:, pos], atol=1e-5,
                err_msg=f"gMLP decode pos {pos}",
            )
