"""Fused single-token decode attention (ops/decode_attention.py): kernel
parity vs the unfused decode math, model-level decode-vs-forward logits
consistency, and dispatch conditions. Interpret mode on CPU.

The kernel is an opt-in path (measured slower than the XLA chain on v5e —
module docstring); model-level tests flip FUSED_DECODE_ENABLED on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.ops.decode_attention import fused_decode_attention
from dalle_pytorch_tpu.ops.rotary import _rotate_half_matrix


def _oracle(qkv, kc, vc, idx, cos, sin, P, km, h, d, rotary=True):
    """The unfused decode math (ops/attention.py:_decode_attend)."""
    b, L, _ = kc.shape
    q, k, v = (t.reshape(b, 1, h, d) for t in jnp.split(qkv, 3, axis=-1))
    if rotary:
        def rot(t):
            return t * cos[idx][None, None, None] + (t @ P) * sin[idx][None, None, None]
        q, k, v = rot(q), rot(k), rot(v)
    kcr = kc.reshape(b, L, h, d).at[:, idx].set(k[:, 0])
    vcr = vc.reshape(b, L, h, d).at[:, idx].set(v[:, 0])
    s = jnp.einsum("bnhd,blhd->bhnl", q * d**-0.5, kcr)
    allowed = (jnp.arange(L) <= idx)[None, None, None, :]
    if km is not None:
        allowed = allowed & km[:, None, None, :]
    s = jnp.where(allowed, s, -1e30)
    att = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhnl,blhd->bnhd", att, vcr).reshape(b, 1, h * d)
    return out, kcr.reshape(b, L, h * d), vcr.reshape(b, L, h * d)


@pytest.mark.parametrize("rotary", [True, False])
@pytest.mark.parametrize("masked", [True, False])
def test_kernel_matches_unfused_math(rotary, masked):
    b, L, h, d = 2, 32, 4, 64
    rng = np.random.RandomState(0)
    qkv = jnp.asarray(rng.randn(b, 1, 3 * h * d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, L, h * d) * 0.1, jnp.float32)
    vc = jnp.asarray(rng.randn(b, L, h * d) * 0.1, jnp.float32)
    cos = jnp.asarray(np.cos(rng.rand(L, d)), jnp.float32)
    sin = jnp.asarray(np.sin(rng.rand(L, d)), jnp.float32)
    P = jnp.asarray(_rotate_half_matrix(d), jnp.float32)
    km = None
    if masked:
        km_np = rng.rand(b, L) > 0.3
        km_np[:, 0] = True
        km = jnp.asarray(km_np)
    idx = 7

    out, k_row, v_row = fused_decode_attention(
        qkv, kc, vc, idx, cos, sin, P,
        None if km is None else km[..., None].astype(jnp.int32),
        heads=h, dim_head=d, use_rotary=rotary, interpret=True,
    )
    ref, kcr, vcr = _oracle(qkv, kc, vc, idx, cos, sin, P, km, h, d, rotary)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # the emitted rows are what the caller writes into the caches at idx
    np.testing.assert_allclose(
        np.asarray(k_row[:, 0]), np.asarray(kcr.reshape(b, L, h * d)[:, idx]),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(v_row[:, 0]), np.asarray(vcr.reshape(b, L, h * d)[:, idx]),
        atol=1e-6,
    )


def _kernel_dalle(**kw):
    """dim_head=64 so the fused kernel's head-group constraint holds."""
    cfg = dict(
        dim=128, depth=2, num_text_tokens=50, text_seq_len=6,
        num_image_tokens=32, image_fmap_size=3, heads=2, dim_head=64,
        attn_types=("full",), shift_tokens=False,
    )
    cfg.update(kw)
    return DALLE(**cfg)


def test_dalle_decode_dispatches_kernel_and_matches_forward(monkeypatch):
    """decode_step must route single-token steps through the fused kernel
    (spied, opt-in flag on) and reproduce the full-forward logits at every
    position."""
    import dalle_pytorch_tpu.ops.attention as A

    calls = []
    real = fused_decode_attention

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    import dalle_pytorch_tpu.ops.decode_attention as DK

    monkeypatch.setattr(DK, "FUSED_DECODE_ENABLED", True)
    monkeypatch.setattr(DK, "fused_decode_attention", spy)
    # the fused kernel serves the flat/4-D cache formats only; batch 2
    # defaults to the paged cache (ops/kv_policy.py), which correctly
    # bypasses it — pin the historical 4-D layout for the dispatch spy
    monkeypatch.setenv("DALLE_TPU_KV_FORMAT", "4d")

    dalle = _kernel_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 50, (2, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 9, (2, 9)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    full_logits = np.asarray(dalle.apply({"params": params}, text, image))

    from dalle_pytorch_tpu.models.sampling import init_decode_cache

    internal = np.concatenate(
        (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
    )
    cache = init_decode_cache(dalle, params, batch_size=2)
    for i in range(dalle.total_seq_len):
        step_logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            jnp.asarray(internal[:, i]),
            jnp.array(i, jnp.int32),
            method=DALLE.decode_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits), full_logits[:, i],
            atol=2e-3, rtol=1e-3,
            err_msg=f"fused decode/forward mismatch at position {i}",
        )
    assert calls, "fused decode kernel never dispatched"


def test_dalle_generation_through_kernel(monkeypatch):
    import dalle_pytorch_tpu.ops.decode_attention as DK

    monkeypatch.setattr(DK, "FUSED_DECODE_ENABLED", True)
    from dalle_pytorch_tpu.models.sampling import generate_image_tokens

    dalle = _kernel_dalle()
    rng = np.random.RandomState(1)
    text = jnp.asarray(rng.randint(1, 50, (2, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 9, (2, 9)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    toks = np.asarray(generate_image_tokens(dalle, params, text, jax.random.key(2)))
    assert toks.shape == (2, 9)
    assert (toks >= 0).all() and (toks < 32).all()


def test_small_head_dims_fall_back(monkeypatch):
    """dim_head=16 (hpb=8 > heads) must keep the unfused path."""
    import dalle_pytorch_tpu.ops.decode_attention as DK

    def boom(*a, **k):
        raise AssertionError("fused kernel dispatched for unsupported heads")

    monkeypatch.setattr(DK, "FUSED_DECODE_ENABLED", True)
    monkeypatch.setattr(DK, "fused_decode_attention", boom)
    dalle = _kernel_dalle(dim=64, heads=4, dim_head=16)
    rng = np.random.RandomState(2)
    text = jnp.asarray(rng.randint(1, 50, (1, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 9, (1, 9)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]

    from dalle_pytorch_tpu.models.sampling import generate_image_tokens

    toks = np.asarray(generate_image_tokens(dalle, params, text, jax.random.key(3)))
    assert toks.shape == (1, 9)


def test_masked_own_key_with_extreme_score():
    """A key-padding-masked current position with a huge self-score must not
    poison the softmax max (review finding: exp underflow zeroed the whole
    row where the unfused path attends correctly over live keys)."""
    b, L, h, d = 1, 16, 2, 64
    rng = np.random.RandomState(3)
    qkv = jnp.asarray(rng.randn(b, 1, 3 * h * d), jnp.float32)
    # make q . k_new enormous: q and k_new aligned and large
    big = jnp.ones((b, 1, h * d), jnp.float32) * 30.0
    qkv = jnp.concatenate([big, big, qkv[..., 2 * h * d:]], axis=-1)
    kc = jnp.asarray(rng.randn(b, L, h * d) * 0.1, jnp.float32)
    vc = jnp.asarray(rng.randn(b, L, h * d) * 0.1, jnp.float32)
    cos = jnp.asarray(np.cos(rng.rand(L, d)), jnp.float32)
    sin = jnp.asarray(np.sin(rng.rand(L, d)), jnp.float32)
    P = jnp.asarray(_rotate_half_matrix(d), jnp.float32)
    idx = 7
    km = np.ones((b, L), bool)
    km[:, idx] = False  # the current token's own key is padded out

    out, _, _ = fused_decode_attention(
        qkv, kc, vc, idx, cos, sin, P,
        jnp.asarray(km[..., None], jnp.int32),
        heads=h, dim_head=d, use_rotary=False, interpret=True,
    )
    ref, _, _ = _oracle(qkv, kc, vc, idx, cos, sin, P, jnp.asarray(km),
                        h, d, rotary=False)
    assert np.abs(np.asarray(out)).max() > 0, "output spuriously zeroed"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_lane_packed_decode_matches_forward_default_path(monkeypatch):
    """The TPU decode path's lane-packed sweeps (attention.py:
    _cache_attend, taken when 128 % dim_head == 0 and heads divide into
    full tiles) must reproduce the full-forward logits — independent of
    the opt-in fused kernel, which stays off here. Forced on via
    DALLE_TPU_LANE_PACK=1: the pack is TPU-gated by default (its
    regrouped contraction is ~1 ulp off the plain gemm at some head
    counts, and the CPU tier carries the fused-vs-split bit-parity
    gates; tests/test_ragged_attention.py)."""
    import dalle_pytorch_tpu.ops.decode_attention as DK

    monkeypatch.setenv("DALLE_TPU_LANE_PACK", "1")
    assert not DK.FUSED_DECODE_ENABLED  # default path under test
    dalle = _kernel_dalle()  # heads=2, dim_head=64 -> packed branch
    rng = np.random.RandomState(5)
    text = jnp.asarray(rng.randint(1, 50, (2, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 9, (2, 9)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    full_logits = np.asarray(dalle.apply({"params": params}, text, image))

    from dalle_pytorch_tpu.models.sampling import init_decode_cache

    internal = np.concatenate(
        (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
    )
    cache = init_decode_cache(dalle, params, batch_size=2)
    for i in range(dalle.total_seq_len):
        step_logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            jnp.asarray(internal[:, i]),
            jnp.array(i, jnp.int32),
            method=DALLE.decode_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits), full_logits[:, i],
            atol=2e-3, rtol=1e-3,
            err_msg=f"lane-packed decode/forward mismatch at position {i}",
        )
