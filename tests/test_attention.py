"""Attention family tests: efficient paths vs the dense-masked oracle,
pattern-correct information flow, and KV-cached decode parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops.attention import PatternAttention
from dalle_pytorch_tpu.ops.rotary import dalle_rotary_table

F = 4  # image grid
TEXT_LEN = 5  # includes <bos>
L = TEXT_LEN + F * F  # internal pattern length
N = L - 1  # model sequence (last token truncated)
DIM, HEADS, DIM_HEAD = 32, 2, 16


def make_attn(attn_type, **kw):
    return PatternAttention(
        dim=DIM,
        seq_len=L,
        attn_type=attn_type,
        heads=HEADS,
        dim_head=DIM_HEAD,
        image_fmap_size=F,
        block_size=4,
        num_random_blocks=1,
        **kw,
    )


def rotary_table():
    return jnp.asarray(dalle_rotary_table(DIM_HEAD, TEXT_LEN, F))


@pytest.fixture(scope="module")
def x():
    return jax.random.normal(jax.random.PRNGKey(0), (2, N, DIM))


@pytest.mark.parametrize("attn_type", ["axial_row", "axial_col", "conv_like"])
@pytest.mark.parametrize("use_rotary", [False, True])
def test_efficient_path_matches_dense_oracle(x, attn_type, use_rotary):
    attn = make_attn(attn_type)
    params = attn.init(jax.random.PRNGKey(1), x)
    rot = rotary_table() if use_rotary else None
    eff = attn.apply(params, x, rotary_pos_emb=rot)
    dense = attn.apply(params, x, rotary_pos_emb=rot, force_dense=True)
    np.testing.assert_allclose(np.asarray(eff), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize("attn_type", ["axial_row", "conv_like"])
def test_efficient_path_with_key_mask(x, attn_type):
    attn = make_attn(attn_type)
    params = attn.init(jax.random.PRNGKey(1), x)
    mask = jnp.asarray(np.random.RandomState(0).rand(2, L) > 0.3)
    mask = mask.at[:, 0].set(True)  # <bos> always visible
    eff = attn.apply(params, x, mask=mask)
    dense = attn.apply(params, x, mask=mask, force_dense=True)
    np.testing.assert_allclose(np.asarray(eff), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize(
    "attn_type", ["full", "axial_row", "axial_col", "conv_like", "sparse"]
)
def test_information_flow_matches_pattern(attn_type):
    """Perturbing input j changes output i only if the pattern allows i<-j."""
    attn = make_attn(attn_type)
    x0 = jax.random.normal(jax.random.PRNGKey(2), (1, N, DIM))
    params = attn.init(jax.random.PRNGKey(1), x0)
    base = np.asarray(attn.apply(params, x0))
    allowed = attn.pattern_mask()

    for j in [0, TEXT_LEN - 1, TEXT_LEN + 1, TEXT_LEN + F + 2]:
        x1 = x0.at[0, j].add(1.0)
        out = np.asarray(attn.apply(params, x1))
        changed = np.abs(out - base).max(axis=-1)[0] > 1e-6
        for i in range(N):
            if i == j:
                continue
            assert changed[i] == bool(allowed[i, j]), (
                f"{attn_type}: output {i} vs perturbed {j}: "
                f"changed={changed[i]} allowed={allowed[i, j]}"
            )


@pytest.mark.parametrize("attn_type", ["full", "axial_row", "conv_like", "sparse"])
@pytest.mark.parametrize("use_rotary", [False, True])
def test_decode_matches_full_forward(attn_type, use_rotary):
    attn = make_attn(attn_type)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, N, DIM))
    params = attn.init(jax.random.PRNGKey(1), x)
    rot = rotary_table() if use_rotary else None
    full = np.asarray(attn.apply(params, x, rotary_pos_emb=rot))

    cache = attn.init(jax.random.PRNGKey(1), x[:, :1], decode=True)["cache"]
    for pos in range(N):
        step, vars_ = attn.apply(
            {"params": params["params"], "cache": cache},
            x[:, pos : pos + 1],
            rotary_pos_emb=rot,
            decode=True,
            mutable=["cache"],
        )
        cache = vars_["cache"]
        np.testing.assert_allclose(
            np.asarray(step)[:, 0], full[:, pos], atol=3e-5,
            err_msg=f"{attn_type} decode pos {pos}",
        )


def test_stable_softmax_path(x):
    attn = make_attn("full", stable=True)
    params = attn.init(jax.random.PRNGKey(1), x)
    out = attn.apply(params, x)
    ref = attn.apply(params, x)  # determinism
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
    assert np.isfinite(np.asarray(out)).all()


def test_non_causal_full():
    """CLIP-style bidirectional attention: early output depends on later input."""
    attn = PatternAttention(
        dim=DIM, seq_len=8, attn_type="full", causal=False, heads=2, dim_head=16
    )
    x0 = jax.random.normal(jax.random.PRNGKey(4), (1, 8, DIM))
    params = attn.init(jax.random.PRNGKey(1), x0)
    base = np.asarray(attn.apply(params, x0))
    out = np.asarray(attn.apply(params, x0.at[0, 7].add(1.0)))
    assert np.abs(out[0, 0] - base[0, 0]).max() > 1e-6


@pytest.mark.parametrize("attn_type", ["axial_row", "axial_col", "conv_like", "sparse"])
def test_flash_pattern_matches_grouped_at_flash_shape(attn_type):
    """At flash-eligible shapes every pattern rides the packed flash kernel
    with its static mask as an in-kernel operand (measured faster than the
    grouped HBM-materialized forms at the flagship shape — note at
    _pattern_attend). The kernel path must agree with the grouped/dense
    oracle the parity tests pin to the reference."""
    f, text_len = 8, 64
    seq = text_len + f * f  # 128 — flash-eligible
    attn_kw = dict(
        dim=DIM, seq_len=seq, attn_type=attn_type, heads=HEADS,
        dim_head=DIM_HEAD, image_fmap_size=f, block_size=16,
        num_random_blocks=1,
    )
    x_big = jax.random.normal(jax.random.PRNGKey(2), (2, seq, DIM))
    flash = PatternAttention(**attn_kw, use_flash=True)
    grouped = PatternAttention(**attn_kw, use_flash=False)
    params = flash.init(jax.random.PRNGKey(1), x_big)
    out_flash = np.asarray(flash.apply(params, x_big))
    out_grouped = np.asarray(grouped.apply(params, x_big))
    np.testing.assert_allclose(out_flash, out_grouped, atol=3e-5, rtol=1e-4)
