"""Packaging sanity: an sdist/wheel built from pyproject must carry the
vendored BPE vocab and the native engine sources (the reference ships its
vocab via MANIFEST.in; this framework must stand alone, VERDICT round-1
item 5). Runs the same check the publish workflow performs."""

import subprocess
import sys
import zipfile
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_wheel_ships_vocab_and_native_sources(tmp_path):
    build = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-build-isolation",
         "-w", str(tmp_path), str(REPO)],
        capture_output=True, text=True,
    )
    assert build.returncode == 0, f"wheel build failed: {build.stderr[-500:]}"
    wheels = list(tmp_path.glob("*.whl"))
    assert wheels, "no wheel produced"
    names = zipfile.ZipFile(wheels[0]).namelist()
    for need in (
        "dalle_pytorch_tpu/data/bpe_simple_vocab_16e6.txt",
        "dalle_pytorch_tpu/native/bpe_tokenizer.cc",
        "dalle_pytorch_tpu/native/unicode_tables.h",
    ):
        assert need in names, f"wheel is missing {need}"
