"""Packaging sanity: an sdist/wheel built from pyproject must carry the
vendored BPE vocab and the native engine sources (the reference ships its
vocab via MANIFEST.in; this framework must stand alone, VERDICT round-1
item 5). Runs the same check the publish workflow performs."""

import shutil
import subprocess
import sys
import zipfile
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_wheel_ships_vocab_and_native_sources(tmp_path):
    # build from a clean copy of the tracked tree: an in-repo build would
    # leave (and later silently reuse) a stale build/lib that can mask a
    # broken package-data config
    src = tmp_path / "src"
    src.mkdir()
    archive = subprocess.run(
        ["git", "archive", "HEAD"], cwd=REPO, capture_output=True,
    )
    assert archive.returncode == 0, archive.stderr[-300:]
    subprocess.run(
        ["tar", "-x", "-C", str(src)], input=archive.stdout, check=True,
    )
    build = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-build-isolation",
         "-w", str(tmp_path), str(src)],
        capture_output=True, text=True,
    )
    shutil.rmtree(src, ignore_errors=True)
    assert build.returncode == 0, f"wheel build failed: {build.stderr[-500:]}"
    wheels = list(tmp_path.glob("*.whl"))
    assert wheels, "no wheel produced"
    names = zipfile.ZipFile(wheels[0]).namelist()
    for need in (
        "dalle_pytorch_tpu/data/bpe_simple_vocab_16e6.txt",
        "dalle_pytorch_tpu/native/bpe_tokenizer.cc",
        "dalle_pytorch_tpu/native/unicode_tables.h",
        "dalle_pytorch_tpu/models/ckpt_manifests/openai_dvae_encoder.json",
        "dalle_pytorch_tpu/models/ckpt_manifests/openai_dvae_decoder.json",
        "dalle_pytorch_tpu/models/ckpt_manifests/vqgan_f16_1024.json",
    ):
        assert need in names, f"wheel is missing {need}"
