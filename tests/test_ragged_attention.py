"""Unified ragged paged-attention (ops/ragged_attention.py) + the fused
engine iteration (serving/engine.py:_iteration_jit) — the ROADMAP-1
contracts pinned deterministically on CPU:

- width-1 numerics: ``cache_block_attend`` computes width-1 blocks as
  padded width-2 gemms (bit-consistent with wider blocks at every batch
  width), the lane-packed n==1 formulation is bitwise equal to the gemm
  on CPU, and the RESIDUAL caveat — a batch-1 width-1 block's M=1
  PROJECTION matvecs — is pinned exactly where it lives (why the split
  chunker merges 1-token tails while the fused path pads rows instead);
- kernel-vs-reference parity: the Pallas kernel (interpret mode) matches
  the jnp reference path over ragged descriptor sweeps — empty
  iteration, all-prefill, all-decode, mixed, single-row — and through a
  permuted (non-identity) page table;
- fused-vs-split ENGINE bit-identity: fused engines (lookahead on and
  off) sample tokens bit-identical to the split chunked AND monolithic
  engines, through preempt-and-requeue replay, chunk-granular
  prefill_fail resume, and mid-iteration deadline/cancel;
- the dispatch contract: a steady-state fused engine performs at most
  ONE device dispatch per iteration with a FLAT compile-signature set
  (``_iteration_jit._cache_size()`` delta zero across a mixed trace),
  and the committed trace contract (tools/trace_contracts.json) pins
  ``serving.iteration`` to exactly the steady + final-chunk signature
  pair with the cache donated
  (the lowered-aliasing half is machine-checked by the repo's
  ``lint --trace --check`` gate, tests/test_static_analysis.py).

Page size 2 (env override), as in tests/test_chunked_prefill.py, so the
tiny model exercises real page-boundary arithmetic.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE, init_decode_cache
from dalle_pytorch_tpu.models.sampling import (
    insert_decode_cache,
    set_decode_offsets,
)
from dalle_pytorch_tpu.ops import paged_kv
from dalle_pytorch_tpu.ops import ragged_attention as ra
from dalle_pytorch_tpu.ops.attention import PatternAttention, cache_block_attend
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
    check_accounting,
)
from dalle_pytorch_tpu.serving import engine as engine_mod
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters

REPO = Path(__file__).resolve().parent.parent


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=prompt(i), max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def run_requests(model, n=3, max_new=4, **cfg_kw):
    eng = make_engine(model, **cfg_kw)
    for i in range(n):
        assert eng.submit(req(i, max_new=max_new)) is None
    eng.run(max_steps=500)
    check_accounting(eng)
    return eng


def tokens_of(eng):
    return {
        rid: None if r.tokens is None else np.asarray(r.tokens)
        for rid, r in eng.results.items()
    }


def fresh_cache(dalle, params, b):
    return set_decode_offsets(
        init_decode_cache(dalle, params, b, cache_format="paged"),
        jnp.zeros((b,), jnp.int32),
    )


# ---------------------------------------------------- width-1 numerics


class TestWidthOneNumerics:
    def test_width1_block_bit_consistent_with_wider_blocks(self):
        """The resolved half of the PR 5 caveat: cache_block_attend pads
        width-1 blocks to width-2 gemms, so a width-1 block's row is
        bitwise equal to the same row inside any wider block, at any
        batch width."""
        q = jax.random.normal(jax.random.key(0), (2, 1, 2, 8), jnp.float32)
        kc = jax.random.normal(jax.random.key(1), (2, 10, 16), jnp.float32)
        allowed = jnp.ones((2, 1, 1, 10), bool)
        o1 = jax.jit(cache_block_attend)(q, kc, kc, allowed)
        q3 = jnp.concatenate([q, q, q], axis=1)
        o3 = jax.jit(cache_block_attend)(q3, kc, kc, allowed)
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o3[:, :1]))
        o1b = jax.jit(cache_block_attend)(q[:1], kc[:1], kc[:1], allowed[:1])
        np.testing.assert_array_equal(np.asarray(o1b), np.asarray(o1[:1]))

    @pytest.mark.parametrize("heads", [2, 16])
    def test_lane_pack_tpu_gated_and_close_to_gemm(self, heads, monkeypatch):
        """The n==1 lane-packed sweep (the TPU decode optimization) is
        TPU-gated: on the CPU parity tier, _cache_attend at lane-eligible
        shapes takes the SAME gemm as the fused rows, bitwise — measured
        necessity, because the packed contraction itself is only
        allclose-equal to the gemm (bitwise at h=2, ~5e-7 off at h=16,
        CPU 2026-08), which is exactly the divergence that broke
        fused-vs-split parity on the flagship serving shape before the
        gate."""
        b, d, W = 2, 64, 20  # d=64, h%(128//d)==0 -> pack-eligible
        h = heads
        q = jax.random.normal(jax.random.key(0), (b, 1, h, d), jnp.float32)
        kc = jax.random.normal(jax.random.key(1), (b, W, h * d), jnp.float32)
        vc = jax.random.normal(jax.random.key(2), (b, W, h * d), jnp.float32)
        allowed = jnp.broadcast_to(
            jnp.arange(W)[None, None, None, :] < 7, (b, 1, 1, W)
        )
        mod = PatternAttention(dim=h * d, seq_len=W, heads=h, dim_head=d)
        gemm = jax.jit(cache_block_attend)(q, kc, vc, allowed)
        # default (auto) on CPU: the branch is OFF -> bitwise the gemm
        default = jax.jit(
            lambda *a: PatternAttention._cache_attend(mod, *a)
        )(q, kc, vc, allowed)
        np.testing.assert_array_equal(np.asarray(default), np.asarray(gemm))
        # forced on: the packed math is the same attention within ulps
        monkeypatch.setenv("DALLE_TPU_LANE_PACK", "1")
        packed = jax.jit(
            lambda *a: PatternAttention._cache_attend(mod, *a)
        )(q, kc, vc, allowed)
        np.testing.assert_allclose(
            np.asarray(packed), np.asarray(gemm), atol=5e-6, rtol=5e-6
        )

    def test_width1_projection_caveat_pinned(self):
        """The RESIDUAL caveat, pinned where it lives: a batch-1 WIDTH-1
        prefill chunk diverges from monolithic prefill in the written
        K/V — its projection matmuls run as M=1 matvecs — while the same
        prompt split into width>=2 chunks is bit-identical. This is the
        measured reason the split chunker merges 1-token tails and the
        fused path pads rows to the iteration width instead. If this
        test ever fails because the (4, 1) chunking became bit-identical,
        XLA's matvec lowering changed — the merge rule can be retired."""
        dalle = small_dalle()
        rng = np.random.RandomState(0)
        text = jnp.asarray(rng.randint(1, 16, size=(1, 4)), jnp.int32)
        image = jnp.asarray(rng.randint(0, 12, size=(1, 4)), jnp.int32)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = dalle.remap_text(text)
        T = dalle.text_len_internal  # 5

        def run_chunks(widths):
            cache = fresh_cache(dalle, params, 1)
            s = 0
            for c in widths:
                _, mut = dalle.apply(
                    {"params": params, "cache": cache},
                    internal[:, s:s + c], jnp.int32(s),
                    return_logits=False,
                    method=DALLE.prefill_chunk, mutable=["cache"],
                )
                cache = mut["cache"]
                s += c
            assert s == T
            return cache

        def kv_leaves(cache):
            return [
                (p, x) for p, x in jax.tree_util.tree_leaves_with_path(cache)
                if getattr(p[-1], "key", None) == "cached_key_pages"
            ]

        mono = run_chunks((5,))
        wide = run_chunks((2, 3))
        tail1 = run_chunks((4, 1))
        for (p, m), (_, w) in zip(kv_leaves(mono), kv_leaves(wide)):
            np.testing.assert_array_equal(np.asarray(m), np.asarray(w))
        diverged = any(
            not bool(jnp.all(m == t))
            for (p, m), (_, t) in zip(kv_leaves(mono), kv_leaves(tail1))
        )
        assert diverged, (
            "a batch-1 width-1 chunk is now bit-identical to monolithic — "
            "the M=1 matvec caveat is gone; the split-path 1-token-tail "
            "merge (engine._next_chunk) can be retired"
        )
        # ... but it IS the same math: ~1 ulp, not a bug
        for (p, m), (_, t) in zip(kv_leaves(mono), kv_leaves(tail1)):
            np.testing.assert_allclose(
                np.asarray(m), np.asarray(t), atol=1e-5, rtol=1e-5
            )


# ------------------------------------------------- kernel-vs-reference


DESCRIPTOR_SWEEPS = [
    ("empty", [0, 0, 0], [0, 0, 0]),
    ("all_prefill", [0, 2, 5], [4, 3, 1]),
    ("all_decode", [7, 9, 11], [1, 1, 1]),
    ("mixed", [7, 0, 0], [1, 4, 0]),
    ("single_row", [3, 0, 0], [2, 0, 0]),
]


class TestKernelParity:
    def _pools(self, b=3, n_p=5, page=4, hd=16, seed=0):
        rng = np.random.RandomState(seed)
        k_pool = jnp.asarray(rng.randn(b, n_p, page, hd), jnp.float32) * 0.3
        v_pool = jnp.asarray(rng.randn(b, n_p, page, hd), jnp.float32) * 0.3
        return k_pool, v_pool

    @pytest.mark.parametrize(
        "label,start,length", DESCRIPTOR_SWEEPS,
        ids=[d[0] for d in DESCRIPTOR_SWEEPS],
    )
    def test_kernel_matches_reference(self, label, start, length):
        b, n, h, d, page, n_p = 3, 4, 2, 8, 4, 5
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32) * 0.3
        k_pool, v_pool = self._pools(b, n_p, page, h * d)
        table = paged_kv.identity_table(b, n_p)
        start = jnp.asarray(start, jnp.int32)
        length = jnp.asarray(length, jnp.int32)
        pos = start[:, None] + jnp.arange(n)[None]
        allowed = (
            jnp.arange(n_p * page)[None, None] <= pos[..., None]
        )[:, None]
        ref = ra.reference_attend(q, k_pool, v_pool, table, allowed)
        ker = ra.kernel_attend(
            q, k_pool, v_pool, table, start, length, interpret=True
        )
        assert bool(jnp.all(jnp.isfinite(ker))), "kernel produced non-finite"
        valid = (jnp.arange(n)[None] < length[:, None])[..., None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, ker, 0.0)),
            np.asarray(jnp.where(valid, ref, 0.0)),
            atol=2e-6, rtol=2e-6,
            err_msg=f"kernel diverged from reference for {label}",
        )

    def test_kernel_follows_permuted_page_table(self):
        """The page-table indirection is real: permuting each row's
        physical pages (and the table with them) must leave the kernel's
        output unchanged — the seam prefix sharing will use."""
        b, n, h, d, page, n_p = 2, 3, 2, 8, 4, 4
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32) * 0.3
        k_pool, v_pool = self._pools(b, n_p, page, h * d, seed=3)
        ident = paged_kv.identity_table(b, n_p)
        start = jnp.asarray([5, 0], jnp.int32)
        length = jnp.asarray([1, 3], jnp.int32)
        base = ra.kernel_attend(
            q, k_pool, v_pool, ident, start, length, interpret=True
        )
        perm = np.stack([
            np.random.RandomState(10 + r).permutation(n_p) for r in range(b)
        ])
        inv = np.argsort(perm, axis=1).astype(np.int32)  # logical -> physical
        bidx = np.arange(b)[:, None]
        k_perm = jnp.asarray(np.asarray(k_pool)[bidx, perm])
        v_perm = jnp.asarray(np.asarray(v_pool)[bidx, perm])
        # tables hold GLOBAL ids: row r's physical page p is r * n_p + p
        table = jnp.asarray(inv + bidx * n_p)
        out = ra.kernel_attend(
            q, k_perm, v_perm, table, start, length, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(base), atol=1e-6, rtol=1e-6
        )

    def test_append_limit_masks_rows(self):
        """paged_kv.append's per-row limit: rows past a row's valid count
        are never written — the ragged write mask."""
        pool = jnp.zeros((2, 3, 2, 4), jnp.float32)
        table = paged_kv.identity_table(2, 3)
        rows = jnp.ones((2, 3, 4), jnp.float32)
        out = paged_kv.append(
            pool, table, jnp.asarray([0, 2], jnp.int32), rows,
            limit=jnp.asarray([2, 0], jnp.int32),
        )
        flat = np.asarray(out).reshape(2, 6, 4)
        assert flat[0, :2].all() and not flat[0, 2:].any()
        assert not flat[1].any()


# ------------------------------------------- fused model-level parity


class TestFusedStepParity:
    @pytest.mark.parametrize("rotary", [True, False])
    def test_fused_rows_bit_identical_to_split_paths(self, rotary):
        """One mixed fused block — a decode row beside a prefill-chunk
        row beside an idle row — is bitwise the split paths: the decode
        row equals the vector decode_step, the prefill row equals a
        batch-1 prefill_chunk, the idle row touches nothing."""
        dalle = small_dalle(rotary_emb=rotary)
        rng = np.random.RandomState(0)
        text = jnp.asarray(rng.randint(1, 16, size=(3, 4)), jnp.int32)
        image = jnp.asarray(rng.randint(0, 12, size=(3, 4)), jnp.int32)
        params = dalle.init(jax.random.key(0), text[:2], image[:2])["params"]
        T = dalle.text_len_internal
        internal = dalle.remap_text(text)

        def prefilled_row0(b):
            cache = fresh_cache(dalle, params, b)
            c1 = fresh_cache(dalle, params, 1)
            _, mut = dalle.apply(
                {"params": params, "cache": c1}, internal[0:1],
                image_only=True, method=DALLE.prefill_step, mutable=["cache"],
            )
            return insert_decode_cache(cache, mut["cache"], 0)

        # split: vector decode step over the batched cache
        toks = jnp.array([7, 0, 0], jnp.int32)
        pos = jnp.array([T, 0, 0], jnp.int32)
        lg_split, mut = dalle.apply(
            {"params": params, "cache": prefilled_row0(3)}, toks, pos,
            image_only=True, method=DALLE.decode_step, mutable=["cache"],
        )
        split_after = mut["cache"]
        # split: batch-1 chunk for row 1's first 3 prompt positions
        c1 = fresh_cache(dalle, params, 1)
        _, mut1 = dalle.apply(
            {"params": params, "cache": c1}, internal[1:2, 0:3], jnp.int32(0),
            return_logits=False, method=DALLE.prefill_chunk, mutable=["cache"],
        )
        row1_split = mut1["cache"]

        # fused: the same mix in one ragged block
        toks_f = jnp.stack([
            jnp.array([7, 0, 0], jnp.int32),
            internal[1, 0:3],
            jnp.zeros(3, jnp.int32),
        ])
        lg_f, mutf = dalle.apply(
            {"params": params, "cache": prefilled_row0(3)},
            toks_f, jnp.array([T, 0, 0], jnp.int32),
            jnp.array([1, 3, 0], jnp.int32),
            jnp.array([False, False, False]),
            method=DALLE.fused_step, mutable=["cache"],
        )
        fused_after = mutf["cache"]

        np.testing.assert_array_equal(
            np.asarray(lg_f[0]), np.asarray(lg_split[0])
        )
        pristine = fresh_cache(dalle, params, 3)
        for (p, ls), (_, lf), (_, l1), (_, lp) in zip(
            jax.tree_util.tree_leaves_with_path(split_after),
            jax.tree_util.tree_leaves_with_path(fused_after),
            jax.tree_util.tree_leaves_with_path(row1_split),
            jax.tree_util.tree_leaves_with_path(pristine),
        ):
            row1 = l1[0]
            if getattr(p[-1], "key", None) == "page_table":
                # tables hold GLOBAL ids: the batch-1 cache's row-0 pages
                # sit one row offset below their batch-3 row-1 location
                row1 = row1 + l1.shape[1]
            assert bool(jnp.all(ls[0] == lf[0])), f"decode row diverged: {p}"
            assert bool(jnp.all(row1 == lf[1])), f"prefill row diverged: {p}"
            assert bool(jnp.all(lp[2] == lf[2])), f"idle row touched: {p}"


# ------------------------------------------------ fused engine parity


class TestFusedEngine:
    def test_fused_bit_identical_to_split_and_monolithic(self, model):
        """THE acceptance contract: fused engines — lookahead on and off
        — produce tokens bit-identical to the split chunked AND
        monolithic engines."""
        mono = tokens_of(run_requests(model))
        split = tokens_of(run_requests(model, prefill_chunk=2))
        for cfg in (
            dict(prefill_chunk=2, fused_iteration=True),
            dict(prefill_chunk=2, fused_iteration=True,
                 decode_lookahead=False),
            dict(prefill_chunk=3, fused_iteration=True),
        ):
            fused = tokens_of(run_requests(model, **cfg))
            for rid, toks in mono.items():
                np.testing.assert_array_equal(
                    fused[rid], toks, err_msg=f"{cfg} diverged for {rid}"
                )
                np.testing.assert_array_equal(split[rid], toks)

    def test_fused_requires_chunked_prefill(self, model):
        with pytest.raises(ValueError, match="fused_iteration"):
            make_engine(model, fused_iteration=True)

    def test_fused_preempt_replay_bit_identical(self, model):
        """Mid-iteration preemption: a page_exhaust eviction mid-decode
        replays bit-identically through the fused path (the row reset +
        (seed, position) keys survive the mode change)."""
        FAULTS.reset()
        counters.reset()
        clean = tokens_of(run_requests(
            model, prefill_chunk=2, fused_iteration=True
        ))
        FAULTS.configure("page_exhaust=1")
        eng = run_requests(model, prefill_chunk=2, fused_iteration=True)
        assert FAULTS.fired.get("page_exhaust") == 1
        assert any(r.preempt_count > 0 for r in eng.results.values())
        for rid, r in eng.results.items():
            assert r.outcome is Outcome.COMPLETED, (rid, r)
            np.testing.assert_array_equal(np.asarray(r.tokens), clean[rid])
        assert eng.pool.used == 0

    def test_fused_chunk_fault_resumes_from_last_chunk(self, model):
        FAULTS.reset()
        counters.reset()
        clean = tokens_of(run_requests(
            model, n=1, prefill_chunk=2, fused_iteration=True
        ))
        FAULTS.configure("prefill_fail=1")
        eng = run_requests(model, n=1, prefill_chunk=2, fused_iteration=True)
        res = eng.results["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert res.prefill_attempts == 1
        np.testing.assert_array_equal(np.asarray(res.tokens), clean["r0"])

    def test_fused_mid_prefill_deadline_frees_pages_that_iteration(self, model):
        """A deadline lands BETWEEN fused iterations: the prefilling row
        — which owns real batched-cache state in fused mode — is reset
        and its pages return the iteration the deadline sweeps."""
        eng = make_engine(model, prefill_chunk=2, fused_iteration=True,
                          token_budget=1, clock=FakeClock(step_dt=1.0))
        assert eng.submit(req(0, deadline=0.5)) is None
        eng.step()
        assert eng.pool.used > 0
        slot = next(s for s in eng.slots if s)
        assert slot.phase == "prefill" and 0 < slot.filled < eng.T
        eng.step()
        assert eng.pool.used == 0, "mid-prefill deadline did not free pages"
        res = eng.results["r0"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert res.tokens is None and res.ttft_s is None
        eng.run(max_steps=50)
        check_accounting(eng)

    def test_fused_cancel_mid_prefill(self, model):
        eng = make_engine(model, prefill_chunk=2, fused_iteration=True,
                          token_budget=1)
        assert eng.submit(req(0)) is None
        eng.step()
        eng.cancel("r0")
        eng.step()
        assert eng.pool.used == 0
        assert eng.results["r0"].outcome is Outcome.CANCELLED
        eng.run(max_steps=50)
        check_accounting(eng)

    def test_fused_one_dispatch_per_iteration_one_signature(self, model):
        """The dispatch contract, measured at the engine: after a warm
        request compiles both signature classes (steady + final-chunk),
        a MIXED multi-request trace performs at most one dispatch per
        iteration and compiles NOTHING new (``_iteration_jit``'s
        trace-cache size is flat — descriptor raggedness is data, not
        shape)."""
        eng = make_engine(model, prefill_chunk=2, fused_iteration=True)
        assert eng.submit(req(9, max_new=2)) is None
        eng.run(max_steps=200)
        sigs0 = engine_mod._iteration_jit._cache_size()
        d0, i0 = eng.dispatches, eng.iterations
        for i in range(3):
            assert eng.submit(req(i)) is None
        eng.run(max_steps=500)
        check_accounting(eng)
        assert engine_mod._iteration_jit._cache_size() == sigs0, (
            "a descriptor mix drifted the fused compile signature"
        )
        dispatches = eng.dispatches - d0
        iterations = eng.iterations - i0
        assert 0 < dispatches <= iterations, (dispatches, iterations)

    def test_fused_counters_accounted(self, model):
        counters.reset()
        eng = run_requests(model, prefill_chunk=2, fused_iteration=True)
        assert counters.get("serve.dispatches") == eng.dispatches > 0
        assert counters.get("serve.prefill_chunks") > 0
        assert counters.get("serve.decode_steps") > 0


# ----------------------------------------------------- trace contract


class TestTraceContract:
    def test_iteration_contract_single_signature_cache_donated(self):
        """The committed trace contract pins ``serving.iteration`` to
        EXACTLY two compile signatures — the steady mix and the
        final-chunk class (a host-known static that adds the per-row
        split-parity heads) — with the cache donated and at most one
        host-visible output (the sample readback). The registry<->contract 1:1 and the lowered
        donation-aliasing half are machine-checked by the repo's
        ``python tools/lint.py --trace --check`` gate
        (tests/test_static_analysis.py) — this pin keeps the contract's
        CONTENT from being weakened in a future re-emit."""
        contract = json.loads(
            (REPO / "tools" / "trace_contracts.json").read_text()
        )
        entry = contract["entries"]["serving.iteration"]
        assert entry["max_signatures"] == 2
        assert [s["label"] for s in entry["signatures"]] == [
            "steady", "final"
        ]
        assert entry["donate"] == ["cache"]
        # steady iterations read back the samples ONLY; final-chunk
        # iterations additionally surface the per-row terminal logits —
        # the prefix cache's full-hit payload (ISSUE 10), captured on the
        # already-warm final signature class so plain decode iterations
        # pay nothing for it
        assert entry["max_host_visible_outputs"] <= 2
        assert entry["max_host_callbacks"] == 0
        # the prefix-cache engine variant: same program logic over the
        # arena-extended cache, same two-signature budget
        arena = contract["entries"]["serving.iteration_prefix"]
        assert arena["max_signatures"] == 2
        assert arena["donate"] == ["cache"]
