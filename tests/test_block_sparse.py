"""Block-sparse pair-grid attention tests (ops/block_sparse_attention.py).

Four tiers, mirroring the module's contract:
- layout compilation: the BlockLayout's visit map, pair tables, and
  visited-block fraction against hand-checkable properties of the axial /
  conv / strided patterns, including ragged tails (n not a multiple of the
  block edge);
- kernel vs reference: interpret-mode pair-grid kernel pinned allclose —
  values and gradients — against the jnp path that shares
  ``cache_block_attend``'s einsums, per layout and with runtime key masks
  (the flash contract on dead rows: exact 0, asserted separately);
- dual balancing: ``dual_balanced_assignment`` keeps per-chip q-block
  counts within one block and visited-pair loads within one block's
  weight (the LPT bound) on the skewed axial layout;
- sp composition: the shard_map'd dual-balanced path (jnp and kernel
  chip-local compute) against the single-device reference, and the
  routed DALLE train-step loss-parity pin vs 1-device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.ops import masks as masks_lib
from dalle_pytorch_tpu.ops.block_sparse_attention import (
    block_sparse_attention,
    compile_block_layout,
    compile_sp_plan,
    dual_balanced_assignment,
    reference_attend,
    sp_block_sparse_attend,
)
from dalle_pytorch_tpu.ops.jax_compat import shard_map
from dalle_pytorch_tpu.parallel import make_runtime


def _axial(n_text=8, f=4, axis=0):
    return masks_lib.axial_mask(n_text, f, axis=axis)  # n = n_text + f*f


def _conv(n_text=8, f=4):
    return masks_lib.conv_mask(n_text, f, 3, 1)


def _strided(n=64):
    return masks_lib.block_sparse_mask(
        n, block_size=8, text_seq_len=15, causal=True, seed=0
    )


# axial_col needs a grid wider than the block edge for its column stride
# to leave dead blocks (at f == block every block catches a column member)
LAYOUT_CASES = [
    ("axial_row", _axial(axis=0), 4),
    ("axial_col", _axial(8, 8, axis=1), 4),
    ("conv_like", _conv(), 4),
    ("strided", _strided(), 8),
]
LAYOUT_IDS = [c[0] for c in LAYOUT_CASES]


# ------------------------------------------------------------ layout compile


@pytest.mark.parametrize("name,mask,block", LAYOUT_CASES, ids=LAYOUT_IDS)
def test_layout_visit_map_matches_mask(name, mask, block):
    n = mask.shape[0]
    layout = compile_block_layout(mask, block, block)
    assert layout.n == n
    assert layout.n_pad % block == 0
    for qb in range(layout.nq):
        for kb in range(layout.nk):
            blk = layout.mask[
                qb * block : (qb + 1) * block, kb * block : (kb + 1) * block
            ]
            expect = 0 if not blk.any() else (2 if blk.all() else 1)
            assert layout.visit[qb, kb] == expect
    # every sparse pattern must actually skip blocks vs the dense-causal
    # grid — the premise of the whole kernel
    assert layout.n_pairs < layout.dense_pairs
    assert 0.0 < layout.visited_block_frac < 1.0


def test_layout_ragged_tail_pads_dead():
    mask = _axial()  # n = 24
    layout = compile_block_layout(mask, 16, 16)  # n_pad = 32, ragged tail
    assert layout.n_pad == 32
    # padded rows/cols are never attendable
    assert not layout.mask[24:, :].any()
    assert not layout.mask[:, 24:].any()


def test_engage_frac_separates_flagship_patterns():
    """The routing threshold at flagship geometry (text 256, fmap 32,
    block 128): axial_col's live stride (fmap=32) is finer than the block
    edge, so every causal pair stays live and the pair grid must decline;
    axial_row/conv_like skip enough pairs to engage. ENGAGE_FRAC drifting
    past either side silently turns into kernel-overhead-for-nothing or a
    lost block-skip win."""
    from dalle_pytorch_tpu.ops.block_sparse_attention import ENGAGE_FRAC

    def frac(pattern):
        mask = masks_lib.pattern_mask(pattern, 256, 32)
        return compile_block_layout(mask, 128, 128).visited_block_frac

    assert frac("axial_col") == 1.0
    assert frac("axial_col") > ENGAGE_FRAC
    assert frac("axial_row") <= ENGAGE_FRAC
    assert frac("conv_like") <= ENGAGE_FRAC


@pytest.mark.parametrize("name,mask,block", LAYOUT_CASES, ids=LAYOUT_IDS)
def test_layout_tables_cover_every_block(name, mask, block):
    """Every q block appears in the fwd table (its output must finalize)
    and every k block in the kv table (its dk/dv must be written), with
    exactly one first and one last flag per contiguous group."""
    layout = compile_block_layout(mask, block, block)
    for tab, idx_row, n_blocks in (
        (layout.fwd_table, 0, layout.nq),
        (layout.kv_table, 1, layout.nk),
    ):
        groups = tab[idx_row]
        assert set(groups.tolist()) == set(range(n_blocks))
        # contiguous groups: first/last flags frame each run
        change = np.flatnonzero(np.diff(groups) != 0)
        firsts = np.concatenate(([0], change + 1))
        lasts = np.concatenate((change, [groups.size - 1]))
        assert np.array_equal(np.flatnonzero(tab[3] == 1), firsts)
        assert np.array_equal(np.flatnonzero(tab[4] == 1), lasts)


# ------------------------------------------------------- kernel vs reference


def _rand_qkv(rng, b, h, n, d):
    return (
        jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("name,mask,block", LAYOUT_CASES, ids=LAYOUT_IDS)
def test_kernel_matches_reference(name, mask, block):
    rng = np.random.default_rng(0)
    n = mask.shape[0]
    b, h, d = 1, 2, 32
    layout = compile_block_layout(mask, block, block)
    q, k, v = _rand_qkv(rng, b, h, n, d)
    o_k = block_sparse_attention(q, k, v, layout, interpret=True)
    o_r = reference_attend(q, k, v, layout)
    np.testing.assert_allclose(o_k, o_r, atol=2e-5, rtol=1e-5)


def test_kernel_ragged_tail_matches_reference():
    rng = np.random.default_rng(1)
    mask = _axial()  # n = 24, block 16 -> n_pad 32
    layout = compile_block_layout(mask, 16, 16)
    q, k, v = _rand_qkv(rng, 1, 2, 24, 32)
    o_k = block_sparse_attention(q, k, v, layout, interpret=True)
    o_r = reference_attend(q, k, v, layout)
    np.testing.assert_allclose(o_k, o_r, atol=2e-5, rtol=1e-5)


def test_kernel_key_mask_and_dead_rows():
    """Runtime key mask streams through the kernel; rows whose every
    visible key is masked return exactly 0 (the flash contract — the
    dense softmax's uniform average is NOT reproduced), so parity is
    asserted on live rows and the zero on dead ones."""
    rng = np.random.default_rng(2)
    mask = _axial()
    n, b, h, d = 24, 2, 2, 32
    layout = compile_block_layout(mask, 4, 4)
    q, k, v = _rand_qkv(rng, b, h, n, d)
    km = np.ones((b, n), bool)
    km[0, :1] = False  # kills text row 0 (attends only bos)
    km[1, 20:] = False
    kmj = jnp.asarray(km)
    o_k = block_sparse_attention(q, k, v, layout, key_mask=kmj, interpret=True)
    o_r = reference_attend(q, k, v, layout, key_mask=kmj)
    live = (mask[None] & km[:, None, :]).any(-1)  # (b, n)
    lr = jnp.asarray(live)[:, None, :, None]
    assert not bool(live.all())  # the dead-row case is actually exercised
    np.testing.assert_allclose(
        jnp.where(lr, o_k, 0.0), jnp.where(lr, o_r, 0.0), atol=2e-5, rtol=1e-5
    )
    assert float(jnp.max(jnp.abs(jnp.where(lr, 0.0, o_k)))) == 0.0


@pytest.mark.parametrize(
    "name,mask,block", LAYOUT_CASES[:2] + LAYOUT_CASES[3:], ids=LAYOUT_IDS[:2] + LAYOUT_IDS[3:]
)
def test_kernel_gradients_match_reference(name, mask, block):
    rng = np.random.default_rng(3)
    n = mask.shape[0]
    b, h, d = 1, 2, 32
    layout = compile_block_layout(mask, block, block)
    q, k, v = _rand_qkv(rng, b, h, n, d)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gk = jax.grad(
        loss(lambda q, k, v: block_sparse_attention(q, k, v, layout, interpret=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: reference_attend(q, k, v, layout)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=5e-4, rtol=5e-3)


# ------------------------------------------------------------- dual balance


def test_dual_balanced_assignment_bounds():
    """Skewed axial weights: block counts within one of each other (the
    cap) and pair loads within one block's weight (the LPT bound)."""
    layout = compile_block_layout(_axial(), 4, 4)
    weights = (layout.visit > 0).sum(axis=1)
    assert weights.max() > weights.min()  # the pattern IS skewed
    for chips in (2, 3, 4):
        assign = dual_balanced_assignment(weights, chips)
        counts = np.bincount(assign, minlength=chips)
        loads = np.bincount(assign, weights=weights, minlength=chips)
        assert counts.max() - counts.min() <= 1
        assert loads.max() - loads.min() <= weights.max()


def test_sp_plan_balances_pairs_within_one_block():
    layout = compile_block_layout(_axial(), 4, 4)
    row_weight = (layout.visit > 0).sum(axis=1).max()
    for sp in (2, 4):
        plan = compile_sp_plan(layout, sp)
        # every q row dealt exactly once and recoverable by inv_perm
        seen = np.sort(plan.row_table.ravel())
        assert set(range(layout.n_pad)) <= set(seen.tolist())
        spread = plan.pair_counts.max() - plan.pair_counts.min()
        assert spread <= row_weight


# -------------------------------------------------------------- sp parity


def _sp_setup(sp, use_kernel):
    rng = np.random.default_rng(4)
    mask = _axial(axis=1)
    n, b, h, d = 24, 2, 2, 16
    layout = compile_block_layout(mask, 4, 4)
    plan = compile_sp_plan(layout, sp)
    q, k, v = _rand_qkv(rng, b, h, n, d)
    km = np.ones((b, n), bool)
    km[0, 5:9] = False
    kmj = jnp.asarray(km)
    mesh = Mesh(np.asarray(jax.devices()[:sp]).reshape(sp), ("sp",))
    qspec = P(None, None, "sp", None)

    def body(q, k, v, km):
        return sp_block_sparse_attend(
            q, k, v, plan, "sp", sp, sm_scale=d**-0.5, key_mask=km,
            use_kernel=use_kernel, interpret=True,
        )

    f = shard_map(
        body, mesh=mesh, in_specs=(qspec,) * 3 + (P(None, "sp"),),
        out_specs=qspec, check_vma=False,
    )
    return f, layout, q, k, v, kmj


@pytest.mark.parametrize("use_kernel", [False, True])
def test_sp_attend_matches_reference(use_kernel):
    f, layout, q, k, v, km = _sp_setup(4, use_kernel)
    o_sp = f(q, k, v, km)
    o_r = reference_attend(q, k, v, layout, key_mask=km)
    tol = dict(atol=2e-5, rtol=1e-5)
    if use_kernel:
        # kernel dead-row contract differs from the dense softmax; this
        # layout + mask keeps every row live (bos column stays visible)
        live = (np.asarray(layout.mask[:24, :24])[None] & np.asarray(km)[:, None]).any(-1)
        assert bool(live.all())
    np.testing.assert_allclose(o_sp, o_r, **tol)


def test_sp_attend_gradients_match_reference():
    f, layout, q, k, v, km = _sp_setup(4, False)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gs = jax.grad(loss(lambda q, k, v: f(q, k, v, km)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: reference_attend(q, k, v, layout, key_mask=km)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(gs, gr):
        np.testing.assert_allclose(a, b_, atol=5e-4, rtol=5e-3)


# --------------------------------------------------------- routed train step


def _tiny_dalle(sp_axis, attn_types):
    return DALLE(
        dim=32, num_text_tokens=64, text_seq_len=8, depth=2, heads=8,
        dim_head=8, num_image_tokens=32, image_fmap_size=4,
        attn_types=attn_types, rotary_emb=False, sp_axis=sp_axis,
    )


def test_dalle_sp_sparse_loss_matches_single_device():
    """The routed dual-balanced sp path: DALLE train-step loss on the sp
    mesh pinned against the 1-device run for sparse attention types."""
    base = _tiny_dalle(None, ("axial_row", "sparse"))
    sp_model = _tiny_dalle("sp", ("axial_row", "sparse"))
    rng = np.random.RandomState(7)
    text = jnp.asarray(rng.randint(1, 64, size=(2, 8)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    params = base.init(jax.random.key(0), text, image)["params"]

    l0 = jax.jit(
        lambda p: base.apply({"params": p}, text, image, return_loss=True)
    )(params)
    runtime = make_runtime(dp=2, fsdp=1, tp=1, sp=4)
    with runtime.activate():
        l1 = jax.jit(
            lambda p: sp_model.apply(
                {"params": p}, text, image, return_loss=True
            )
        )(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
