"""Multi-process worker + shared training routine for test_multiprocess.py.

The reference actually executes as one OS process per GPU with NCCL
rendezvous (deepspeed_backend.py:36-64, README launcher docs); this is the
TPU-native equivalent — one process per host, ``jax.distributed``
rendezvous, a global dp x fsdp mesh spanning both processes' devices.

Run as a script by the test (``python tests/multiprocess_worker.py
--process_id i ...``), each process pinned to 4 virtual CPU devices, and
also imported by the test for the single-process baseline: the training
math lives in ``run_training`` so the 2-process run and the in-pytest
8-device run execute literally the same code.

Exercises the process_count > 1 paths that single-process tests cannot:
  - ``init_distributed`` rendezvous (parallel/mesh.py)
  - global-array creation from process-local callbacks
  - cross-process ``barrier`` / ``average_all`` / ``to_host`` collectives
  - ``DataLoader`` per-host disjoint sample sharding (data/loader.py)
  - root-only checkpoint write, readable by all after the barrier
    (the reference's root-gated save, train_dalle.py + vae.py barriers)
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

TINY = dict(
    dim=64,
    depth=2,
    num_text_tokens=32,
    text_seq_len=8,
    num_image_tokens=16,
    image_fmap_size=4,
    heads=4,
    dim_head=16,
    attn_types=("full",),
)
BATCH = 16
STEPS = 3


def run_training(runtime):
    """Identical math on any runtime: tiny DALLE, dp/fsdp-sharded Adam,
    STEPS steps on a deterministic global batch.

    -> (losses, update_norm_fingerprint, host_params) where host_params is
    the full (allgathered) post-training parameter tree on every process.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dalle_pytorch_tpu.models import DALLE
    from dalle_pytorch_tpu.parallel import create_train_state, make_train_step

    dalle = DALLE(**TINY)
    rng = np.random.RandomState(0)
    text_np = rng.randint(1, TINY["num_text_tokens"], size=(BATCH, TINY["text_seq_len"])).astype(np.int32)
    image_np = rng.randint(0, TINY["num_image_tokens"], size=(BATCH, TINY["image_fmap_size"] ** 2)).astype(np.int32)

    def loss_fn(p, batch, rng):
        return dalle.apply(
            {"params": p}, batch["text"], batch["image"], return_loss=True
        )

    params = dalle.init(
        jax.random.key(0), jnp.asarray(text_np[:1]), jnp.asarray(image_np[:1])
    )["params"]
    opt = optax.chain(optax.clip_by_global_norm(0.5), optax.adam(1e-3))
    state, shardings = create_train_state(params, opt, runtime)
    step = make_train_step(loss_fn, opt, runtime, shardings)

    # global batch: every process holds the same full numpy batch; each
    # process's devices pull their own shards through the callback
    dsh = runtime.data_sharding

    def globalize(x):
        return jax.make_array_from_callback(x.shape, dsh, lambda idx: x[idx])

    batch = {"text": globalize(text_np), "image": globalize(image_np)}

    p0 = runtime.to_host(state.params)
    losses = []
    fingerprint = None
    for i in range(STEPS):
        state, loss = step(state, batch, jax.random.key(i))
        losses.append(float(loss))
        if i == 0:
            delta = jax.tree_util.tree_map(
                lambda a, b: np.asarray(a, np.float32) - np.asarray(b, np.float32),
                runtime.to_host(state.params), p0,
            )
            fingerprint = float(jnp.sqrt(sum(
                jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(delta)
            )))
    return losses, fingerprint, runtime.to_host(state.params)


def loader_shard_indices(data_dir: str, process_index: int, process_count: int):
    """The per-host sample shard the DataLoader would consume this epoch —
    and prove the pipeline yields by pulling the first batch."""
    from dalle_pytorch_tpu.data import DataLoader, TextImageDataset

    ds = TextImageDataset(
        data_dir, text_len=8, image_size=16, truncate_captions=True
    )
    loader = DataLoader(
        ds, batch_size=4, shuffle=True, seed=7,
        process_index=process_index, process_count=process_count,
    )
    first = next(iter(loader))
    assert first["text"].shape == (4, 8) and first["image"].shape == (4, 16, 16, 3)
    return sorted(loader._indices())


def main(argv=None):
    import argparse
    import os

    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--local_devices", type=int, default=4)
    ap.add_argument("--data_dir", required=True)
    ap.add_argument("--ckpt", required=True)
    args = ap.parse_args(argv)

    # platform setup must precede the first backend-initializing jax call.
    # Preserve inherited XLA_FLAGS (site configs may carry memory/threading
    # flags the in-pytest baseline also sees) but override the device count —
    # the pytest parent pins 8, this worker needs its own local_devices.
    kept = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={args.local_devices}"]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)
    jax.config.update(
        "jax_compilation_cache_dir", str(REPO / "tests" / ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    sys.path.insert(0, str(REPO))
    from dalle_pytorch_tpu.parallel import init_distributed, make_runtime
    from dalle_pytorch_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    init_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes
    assert jax.local_device_count() == args.local_devices
    n_global = args.num_processes * args.local_devices

    runtime = make_runtime(fsdp=2)  # dp x fsdp over all global devices
    assert runtime.world_size == n_global

    losses, fingerprint, host_params = run_training(runtime)

    # root-only checkpoint write; everyone reads it back after the barrier
    if runtime.is_root_worker():
        save_checkpoint(args.ckpt, {"params": host_params}, meta={"world": n_global})
    runtime.barrier("post-save")
    import numpy as np

    loaded, meta = load_checkpoint(args.ckpt, target={"params": host_params})
    ckpt_ok = meta.get("world") == n_global and all(
        np.allclose(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(loaded["params"]),
            jax.tree_util.tree_leaves(host_params),
        )
    )

    avg = runtime.average_all(float(runtime.process_index))
    shard = loader_shard_indices(
        args.data_dir, runtime.process_index, runtime.process_count
    )

    print("MPRESULT " + json.dumps({
        "process_id": args.process_id,
        "world_size": runtime.world_size,
        "losses": losses,
        "fingerprint": fingerprint,
        "ckpt_ok": bool(ckpt_ok),
        "average_all": avg,
        "loader_shard": shard,
    }), flush=True)


if __name__ == "__main__":
    main()
