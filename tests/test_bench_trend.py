"""Bench trend gate (tools/bench_trend.py, ISSUE 19): the committed
``BENCH_r*.json`` history parses into per-metric series, the gate exits
0 on that history, and the SEEDED regression fixture
(tests/fixtures_bench/regression_new.jsonl) proves the red path — a
regressed latency folded in as the newest point exits nonzero. Pure
stdlib + subprocess; no jax."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
TOOL = REPO / "tools" / "bench_trend.py"
FIXTURE = REPO / "tests" / "fixtures_bench" / "regression_new.jsonl"

sys.path.insert(0, str(REPO / "tools"))
import bench_trend  # noqa: E402


# ------------------------------------------------------------ unit layer


class TestParsing:
    def test_parse_records_skips_non_metric_lines(self):
        text = "\n".join([
            "not json",
            json.dumps({"assert": "zero compiles"}),
            json.dumps({"metric": "m", "value": "not-a-number"}),
            json.dumps({"metric": "m", "value": 1.5, "unit": "ms"}),
        ])
        recs = bench_trend.parse_records(text)
        assert recs == [{"metric": "m", "value": 1.5, "unit": "ms"}]

    def test_load_history_file_reads_tail_shape(self, tmp_path):
        p = tmp_path / "BENCH_r99.json"
        tail = json.dumps({"metric": "m", "value": 2.0}) + "\n"
        p.write_text(json.dumps({"n": 99, "rc": 0, "tail": tail}))
        assert bench_trend.load_history_file(str(p)) == [
            {"metric": "m", "value": 2.0}
        ]

    def test_load_history_file_reads_raw_jsonl(self, tmp_path):
        p = tmp_path / "new.jsonl"
        p.write_text(json.dumps({"metric": "m", "value": 3.0}) + "\n")
        assert bench_trend.load_history_file(str(p)) == [
            {"metric": "m", "value": 3.0}
        ]

    def test_repeated_metric_within_file_keeps_last(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text(
            json.dumps({"metric": "m", "value": 1.0}) + "\n"
            + json.dumps({"metric": "m", "value": 2.0}) + "\n"
        )
        series = bench_trend.collect_series([str(p)])
        assert series["m"] == [("a.jsonl", 2.0, None)]


class TestDirection:
    @pytest.mark.parametrize("metric,unit,want", [
        ("serve_ttft_p95_cold", None, "lower"),
        ("gen_latency_p50_image1024_tokens_1chip", "ms", "lower"),
        ("train_mfu_dalle_depth12", None, "higher"),
        ("serve_decode_tokens_per_sec", None, "higher"),
        ("serve_spec_accept_per_step", None, "higher"),
        ("jit_recompiles_in_trace", None, "lower"),
        ("mystery_number", None, None),
        ("mystery_number", "s", "lower"),
    ])
    def test_direction(self, metric, unit, want):
        assert bench_trend.direction(metric, unit) == want


class TestEvaluate:
    def _series(self, values, metric="x_latency_ms"):
        return {metric: [(f"r{i}", v, "ms") for i, v in enumerate(values)]}

    def test_ok_within_tolerance(self):
        rows = bench_trend.evaluate(self._series([10.0, 10.0, 11.0]), 0.5)
        assert rows[0]["status"] == "ok"
        assert rows[0]["baseline"] == 10.0

    def test_regression_past_tolerance(self):
        rows = bench_trend.evaluate(self._series([10.0, 10.0, 16.0]), 0.5)
        assert rows[0]["status"] == "regressed"

    def test_median_baseline_resists_outlier(self):
        # a single historical spike must not raise the baseline enough
        # to mask a real regression
        rows = bench_trend.evaluate(
            self._series([10.0, 10.0, 100.0, 16.0]), 0.5
        )
        assert rows[0]["baseline"] == 10.0
        assert rows[0]["status"] == "regressed"

    def test_higher_is_better_direction(self):
        series = {"x_mfu": [("r0", 0.5, None), ("r1", 0.2, None)]}
        rows = bench_trend.evaluate(series, 0.25)
        assert rows[0]["status"] == "regressed"
        series = {"x_mfu": [("r0", 0.5, None), ("r1", 0.45, None)]}
        assert bench_trend.evaluate(series, 0.25)[0]["status"] == "ok"

    def test_single_point_and_unknown_direction_ungated(self):
        rows = bench_trend.evaluate(self._series([10.0]), 0.5)
        assert rows[0]["status"] == "ungated"
        rows = bench_trend.evaluate(
            {"mystery": [("r0", 1.0, None), ("r1", 99.0, None)]}, 0.5
        )
        assert rows[0]["status"] == "ungated"


# ------------------------------------------------- gate (CLI) layer


def run_tool(*args):
    return subprocess.run(
        [sys.executable, str(TOOL), *args],
        capture_output=True, text=True, cwd=str(REPO),
    )


class TestGate:
    def test_check_exits_zero_on_committed_history(self):
        proc = run_tool("--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["regressed"] == 0
        assert summary["gated"] >= 1  # the gate is not vacuous

    def test_seeded_regression_fixture_fails_red(self):
        proc = run_tool("--new", str(FIXTURE), "--check")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "REGRESSION gen_latency_p50" in proc.stderr
        rows = [
            json.loads(l) for l in proc.stdout.strip().splitlines()
        ]
        regressed = [
            r for r in rows if r.get("status") == "regressed"
        ]
        assert len(regressed) == 1
        assert regressed[0]["latest_source"] == "regression_new.jsonl"

    def test_without_check_regression_still_exits_zero(self):
        # report-only mode never gates: the pre-flight opts in with
        # --check
        proc = run_tool("--new", str(FIXTURE))
        assert proc.returncode == 0
