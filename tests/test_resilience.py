"""Fault-tolerance suite (docs/DESIGN.md §9) — every resilience behavior
exercised deterministically on CPU through the fault registry:

- retry/backoff policy and its injectable clock,
- preemption handling with REAL signals (SIGTERM → flag → emergency save),
- two-phase-committed checkpoint dirs: torn/corrupt dirs are never
  restored, fallback picks the newest verified step,
- the NaN step-guard: a non-finite step leaves state bit-identical to the
  prior state, a finite step is bit-identical to the unguarded step,
- download/shard retry + quarantine with counter accounting,
- the acceptance scenario: SIGTERM mid-run + corrupted newest checkpoint
  + 2 transient download failures + 1 NaN loss, and the resumed run's
  final params/opt_state equal an unfaulted run's exactly.
"""

import io
import json
import math
import os
import signal
import sys
import tarfile
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from PIL import Image

from dalle_pytorch_tpu.parallel import create_train_state, make_runtime, make_train_step
from dalle_pytorch_tpu.utils import (
    FAULTS,
    PreemptionHandler,
    RetryPolicy,
    counters,
    download,
    latest_verified_step,
    load_sharded_checkpoint,
    retry,
    save_sharded_checkpoint,
    verify_step_dir,
)
from dalle_pytorch_tpu.utils.faults import FaultRegistry
from dalle_pytorch_tpu.utils.resilience import (
    verify_dir_manifest,
    write_dir_manifest,
)

TOOLS = Path(__file__).resolve().parent.parent / "tools"


# ------------------------------------------------------------ fault registry


class TestFaultRegistry:
    def test_take_counts_down(self):
        r = FaultRegistry()
        r.arm("download", 2)
        assert [r.take("download") for _ in range(4)] == [True, True, False, False]
        assert r.fired["download"] == 2

    def test_env_spec(self):
        r = FaultRegistry("download=2, shard_open=1,nan_at_step=5")
        assert r.value("nan_at_step") == 5
        assert r.take("nan_at_step") is False  # value site, never consumed
        assert r.take("shard_open") and not r.take("shard_open")
        assert r.active()

    def test_unarmed_is_inert(self):
        r = FaultRegistry()
        assert not r.active() and not r.take("download")
        r.maybe_raise("download", OSError("nope"))  # no-op

    def test_maybe_raise(self):
        r = FaultRegistry()
        r.arm("download", 1)
        with pytest.raises(OSError):
            r.maybe_raise("download", OSError("boom"))
        r.maybe_raise("download", OSError("boom"))  # consumed

    def test_bad_spec(self):
        with pytest.raises(ValueError):
            FaultRegistry("download")

    def test_unknown_site_in_spec_rejected(self):
        """A typo'd site name in DALLE_TPU_FAULTS must fail the run, not
        silently inject nothing (the drill would 'pass' untested)."""
        with pytest.raises(ValueError, match="unknown fault site"):
            FaultRegistry("page_exhaustt=1")

    def test_serving_sites_are_known(self):
        from dalle_pytorch_tpu.utils.faults import KNOWN_SITES

        r = FaultRegistry(
            "page_exhaust=1,prefill_fail=1,decode_stall=1,request_cancel=1"
        )
        for site in ("page_exhaust", "prefill_fail", "decode_stall",
                     "request_cancel"):
            assert site in KNOWN_SITES
            assert r.take(site) and not r.take(site)


class TestFileManifest:
    """Single-file sidecar manifests — what generate.py's checkpoint gate
    stands on (the single-file analog of the step-dir two-phase commit)."""

    def test_save_checkpoint_writes_sidecar_and_verifies(self, tmp_path):
        from dalle_pytorch_tpu.utils.checkpoint import (
            check_checkpoint_file, save_checkpoint,
        )
        from dalle_pytorch_tpu.utils.resilience import verify_file_manifest

        path = tmp_path / "m.ckpt"
        save_checkpoint(str(path), {"w": np.ones(3)}, {"k": 1})
        assert (tmp_path / "m.ckpt.manifest.json").exists()
        ok, reason = verify_file_manifest(str(path))
        assert ok, reason
        check_checkpoint_file(str(path))  # no raise

    def test_corruption_is_typed_error(self, tmp_path):
        from dalle_pytorch_tpu.utils.checkpoint import (
            CheckpointError, check_checkpoint_file, save_checkpoint,
        )

        path = tmp_path / "m.ckpt"
        save_checkpoint(str(path), {"w": np.ones(3)}, {})
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0xFF
        path.write_bytes(bytes(data))
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            check_checkpoint_file(str(path))

    def test_truncation_is_typed_error(self, tmp_path):
        from dalle_pytorch_tpu.utils.checkpoint import (
            CheckpointError, check_checkpoint_file, save_checkpoint,
        )

        path = tmp_path / "m.ckpt"
        save_checkpoint(str(path), {"w": np.ones(64)}, {})
        path.write_bytes(path.read_bytes()[:-16])  # torn write
        with pytest.raises(CheckpointError, match="size mismatch"):
            check_checkpoint_file(str(path))

    def test_missing_file_is_typed_error(self, tmp_path):
        from dalle_pytorch_tpu.utils.checkpoint import (
            CheckpointError, check_checkpoint_file,
        )

        with pytest.raises(CheckpointError, match="missing"):
            check_checkpoint_file(str(tmp_path / "nope.ckpt"))

    def test_pre_manifest_file_warns_but_loads(self, tmp_path, capsys):
        """Checkpoints saved before the sidecar existed stay loadable
        (warn, don't refuse) unless the caller requires verification."""
        from dalle_pytorch_tpu.utils.checkpoint import (
            CheckpointError, check_checkpoint_file, save_checkpoint,
        )

        path = tmp_path / "m.ckpt"
        save_checkpoint(str(path), {"w": np.ones(3)}, {})
        (tmp_path / "m.ckpt.manifest.json").unlink()
        check_checkpoint_file(str(path))  # warns, no raise
        assert "no manifest sidecar" in capsys.readouterr().err
        with pytest.raises(CheckpointError, match="no manifest"):
            check_checkpoint_file(str(path), require_manifest=True)


# -------------------------------------------------------------------- retry


class TestRetry:
    def test_succeeds_after_transients(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        out = retry(flaky, RetryPolicy(attempts=3, base_delay=1.0, jitter=0.0),
                    sleep=slept.append)
        assert out == "ok" and calls["n"] == 3
        assert slept == [1.0, 2.0]  # exponential, jitter disabled

    def test_exhaustion_reraises_last(self):
        def dead():
            raise OSError("always")

        slept = []
        with pytest.raises(OSError, match="always"):
            retry(dead, RetryPolicy(attempts=2, base_delay=0.0), sleep=slept.append)
        assert slept == []  # base_delay 0 -> no sleeps

    def test_jitter_bounds_and_cap(self):
        import random

        slept = []

        def dead():
            raise OSError("x")

        with pytest.raises(OSError):
            retry(
                dead,
                RetryPolicy(attempts=4, base_delay=1.0, max_delay=2.0, jitter=0.5),
                sleep=slept.append,
                rng=random.Random(0),
            )
        caps = [1.0, 2.0, 2.0]  # min(max_delay, base * 2**i)
        assert len(slept) == 3
        for got, cap in zip(slept, caps):
            assert cap * 0.5 <= got <= cap

    def test_on_retry_hook_and_non_retryable(self):
        seen = []

        def boom():
            raise ValueError("not retryable")

        with pytest.raises(ValueError):
            retry(boom, RetryPolicy(attempts=3, retry_on=(OSError,)),
                  on_retry=lambda i, e: seen.append(i))
        assert seen == []  # ValueError escaped immediately

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("DALLE_TPU_DOWNLOAD_RETRIES", "7")
        monkeypatch.setenv("DALLE_TPU_DOWNLOAD_BACKOFF", "0.125")
        p = RetryPolicy(attempts=3, base_delay=1.0).from_env("DALLE_TPU_DOWNLOAD")
        assert p.attempts == 7 and p.base_delay == 0.125

    def test_zero_attempts_still_tries_once(self):
        # an operator setting <PREFIX>_RETRIES=0 means "no retries", not
        # "never call the function"
        assert retry(lambda: "ok", RetryPolicy(attempts=0)) == "ok"
        with pytest.raises(OSError, match="once"):
            retry(lambda: (_ for _ in ()).throw(OSError("once")),
                  RetryPolicy(attempts=0))


# -------------------------------------------------------------- preemption


class TestPreemptionHandler:
    def test_sigterm_sets_flag_and_restores(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionHandler() as p:
            assert not p.triggered
            os.kill(os.getpid(), signal.SIGTERM)
            assert p.triggered and p.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_second_signal_raises(self):
        with PreemptionHandler(signals=(signal.SIGTERM,)) as p:
            os.kill(os.getpid(), signal.SIGTERM)
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGTERM)
            assert p.triggered


# ------------------------------------------------------------- manifests


class TestDirManifest:
    def _dir(self, tmp_path):
        d = tmp_path / "step_00000001"
        d.mkdir()
        (d / "a.bin").write_bytes(b"payload-a")
        (d / "sub").mkdir()
        (d / "sub" / "b.bin").write_bytes(b"payload-b")
        return d

    def test_roundtrip(self, tmp_path):
        d = self._dir(tmp_path)
        write_dir_manifest(d, extra={"step": 1})
        ok, reason = verify_dir_manifest(d)
        assert ok, reason
        m = json.loads((d / "MANIFEST.json").read_text())
        assert set(m["files"]) == {"a.bin", "sub/b.bin"} and m["step"] == 1

    def test_no_commit_marker_is_torn(self, tmp_path):
        d = self._dir(tmp_path)
        write_dir_manifest(d)
        (d / "COMMITTED").unlink()
        ok, reason = verify_dir_manifest(d)
        assert not ok and "commit marker" in reason

    def test_bit_corruption_detected(self, tmp_path):
        d = self._dir(tmp_path)
        write_dir_manifest(d)
        (d / "a.bin").write_bytes(b"payload-X")  # same size, different bytes
        ok, reason = verify_dir_manifest(d)
        assert not ok and "checksum" in reason

    def test_missing_and_truncated_files(self, tmp_path):
        d = self._dir(tmp_path)
        write_dir_manifest(d)
        (d / "a.bin").write_bytes(b"pay")  # truncated
        ok, reason = verify_dir_manifest(d)
        assert not ok and "size" in reason
        (d / "a.bin").unlink()
        ok, reason = verify_dir_manifest(d)
        assert not ok and "missing" in reason


# ---------------------------------------------------- tiny training harness


def _toy_setup(nan_inject_step=None, lr=0.1):
    """1-device runtime + quadratic toy model; returns (state, step_fn,
    make_batch). Deterministic, fast, and donation-correct like the real
    trainer's step."""
    runtime = make_runtime(devices=jax.devices()[:1])
    params = {"w": jnp.eye(4) * 0.5}

    def loss_fn(p, batch, rng):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.adam(lr)
    state, shardings = create_train_state(params, opt, runtime)
    step_fn = make_train_step(
        loss_fn, opt, runtime, shardings, nan_inject_step=nan_inject_step
    )
    return state, step_fn


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "x": jnp.asarray(rng.randn(8, 4).astype(np.float32)),
            "y": jnp.asarray(rng.randn(8, 4).astype(np.float32)),
        }
        for _ in range(n)
    ]


def _host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _run_loop(state, step_fn, batches, *, start=0, ckpt_dir=None,
              save_every=None, preempt=None, on_step=None, abort_after=5):
    """Mirror train_dalle.py's loop semantics on the toy harness: verdict of
    the previous step decides scheduler/retry BEFORE the next dispatch; a
    NaN-skipped batch is re-fed so the applied-update sequence matches an
    unfaulted run; periodic verified saves carry the next batch index; a
    preemption flag triggers an emergency save and an early return.

    -> (state, stopped_early)."""
    prev_loss = None
    nan_run = 0
    retry_batch = None
    last = None
    i = start
    while True:
        if prev_loss is not None:
            if math.isfinite(float(prev_loss)):
                nan_run = 0
            else:
                nan_run += 1
                assert nan_run < abort_after, "persistent NaN — abort"
                retry_batch = last
            prev_loss = None
        if retry_batch is not None:
            batch, retry_batch = retry_batch, None
        else:
            if i >= len(batches):
                break
            batch = batches[i]
            i += 1
        last = batch
        state, loss = step_fn(state, batch, jax.random.key(0))
        prev_loss = loss
        if ckpt_dir and save_every and int(state.step) % save_every == 0:
            save_sharded_checkpoint(
                ckpt_dir, int(state.step), state, meta={"next": i}
            )
        if on_step is not None:
            on_step(int(state.step))
        if preempt is not None and preempt.triggered:
            save_sharded_checkpoint(
                ckpt_dir, int(state.step), state,
                meta={"next": i, "emergency": True},
            )
            return state, True
    return state, False


# ------------------------------------------------------------- NaN guard


class TestNaNGuard:
    def test_skip_leaves_state_bit_identical(self):
        state, step_fn = _toy_setup(nan_inject_step=0)
        (batch,) = _batches(1)
        before = _host(state)
        state, loss = step_fn(state, batch, jax.random.key(0))
        assert not math.isfinite(float(loss))  # host sees the raw NaN
        after = _host(state)
        for a, b in zip(
            jax.tree_util.tree_leaves(before.params),
            jax.tree_util.tree_leaves(after.params),
        ):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(before.opt_state),
            jax.tree_util.tree_leaves(after.opt_state),
        ):
            np.testing.assert_array_equal(a, b)
        assert int(after.step) == 1  # attempts still count
        assert int(after.skipped) == 1 and int(after.consec_skipped) == 1

    def test_finite_loss_nonfinite_grad_rejected_and_signaled(self):
        """The guard keys on loss AND grad norm; the returned loss must be
        NaN for a grad-only rejection so the host's retry/abort verdict
        agrees with the device's select."""
        runtime = make_runtime(devices=jax.devices()[:1])

        def loss_fn(p, batch, rng):
            # value 0 (finite); d/dw sqrt(sum(w*0)) = 0/(2*sqrt(0)) -> NaN
            return jnp.sqrt(jnp.sum(p["w"] * batch["x"][:4, :4] * 0.0))

        opt = optax.adam(0.1)
        params = {"w": np.eye(4, dtype=np.float32) * 0.5}
        state, shardings = create_train_state(params, opt, runtime)
        before = _host(state)
        fn = make_train_step(loss_fn, opt, runtime, shardings)
        (batch,) = _batches(1)
        state, loss = fn(state, batch, jax.random.key(0))
        assert not math.isfinite(float(loss))  # rejection signal
        assert int(state.skipped) == 1 and int(state.consec_skipped) == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(before.params),
            jax.tree_util.tree_leaves(_host(state.params)),
        ):
            np.testing.assert_array_equal(a, b)

    def test_finite_step_matches_unguarded_bitwise(self):
        runtime = make_runtime(devices=jax.devices()[:1])

        def loss_fn(p, batch, rng):
            return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

        opt = optax.adam(0.1)
        (batch,) = _batches(1)
        results = {}
        for guard in (True, False):
            # fresh host params each round: the donated buffers from the
            # first round's step are gone
            params = {"w": np.eye(4, dtype=np.float32) * 0.5}
            state, shardings = create_train_state(params, opt, runtime)
            fn = make_train_step(loss_fn, opt, runtime, shardings, nan_guard=guard)
            state, loss = fn(state, batch, jax.random.key(0))
            results[guard] = (_host(state), float(loss))
        assert results[True][1] == results[False][1]
        for a, b in zip(
            jax.tree_util.tree_leaves(results[True][0]),
            jax.tree_util.tree_leaves(results[False][0]),
        ):
            np.testing.assert_array_equal(a, b)

    def test_consec_counter_resets_and_retry_recovers_parity(self):
        """1 injected NaN + batch retry ends bit-identical to an unfaulted
        run (the trainer's skip-and-refeed policy)."""
        batches = _batches(4)

        clean_state, clean_fn = _toy_setup()
        clean_state, _ = _run_loop(clean_state, clean_fn, batches)

        faulted_state, faulted_fn = _toy_setup(nan_inject_step=2)
        faulted_state, _ = _run_loop(faulted_state, faulted_fn, batches)

        assert int(faulted_state.skipped) == 1
        assert int(faulted_state.consec_skipped) == 0  # reset by recovery
        assert int(faulted_state.step) == int(clean_state.step) + 1
        for a, b in zip(
            jax.tree_util.tree_leaves(_host(faulted_state.params)),
            jax.tree_util.tree_leaves(_host(clean_state.params)),
        ):
            np.testing.assert_array_equal(a, b)

    def test_trailing_nan_on_last_batch_is_still_retried(self):
        """A non-finite verdict on the run's FINAL step must not be
        silently dropped: the loop drains the pending verdict and retries
        before finishing (the epoch-boundary case in train_dalle.py)."""
        batches = _batches(3)
        clean_state, clean_fn = _toy_setup()
        clean_state, _ = _run_loop(clean_state, clean_fn, batches)

        # input step 2 == the dispatch of the last batch
        f_state, f_fn = _toy_setup(nan_inject_step=2)
        f_state, _ = _run_loop(f_state, f_fn, batches)
        assert int(f_state.skipped) == 1
        assert int(f_state.step) == int(clean_state.step) + 1
        for a, b in zip(
            jax.tree_util.tree_leaves(_host(f_state.params)),
            jax.tree_util.tree_leaves(_host(clean_state.params)),
        ):
            np.testing.assert_array_equal(a, b)


# ------------------------------------------- checkpoint verify + fallback


class TestVerifiedCheckpoints:
    def test_corrupt_newest_falls_back_to_verified(self, tmp_path):
        state, step_fn = _toy_setup()
        batches = _batches(3)
        root = str(tmp_path / "cp")
        for k, batch in enumerate(batches, start=1):
            state, _ = step_fn(state, batch, jax.random.key(0))
            if k == 3:
                FAULTS.arm("ckpt_corrupt", 1)  # poison the NEWEST save
            save_sharded_checkpoint(root, k, state, meta={"k": k})
        assert FAULTS.fired.get("ckpt_corrupt") == 1
        assert not (Path(root) / "aux.json.tmp").exists()  # atomic sidecar

        ok, _ = verify_step_dir(str(Path(root) / "step_00000003"))
        assert not ok
        assert latest_verified_step(root) == 2

        restored, meta, step = load_sharded_checkpoint(root, _host(state))
        assert step == 2 and meta == {"k": 2}  # per-step meta, not newest

    def test_torn_dir_without_commit_is_skipped(self, tmp_path):
        state, step_fn = _toy_setup()
        (batch,) = _batches(1)
        state, _ = step_fn(state, batch, jax.random.key(0))
        root = str(tmp_path / "cp")
        save_sharded_checkpoint(root, 1, state, meta={"k": 1})
        # simulate a crash mid-save: orbax wrote files, no commit marker
        torn = Path(root) / "step_00000002"
        torn.mkdir()
        (torn / "half_written.bin").write_bytes(b"\0" * 64)

        restored, meta, step = load_sharded_checkpoint(root, _host(state))
        assert step == 1 and meta == {"k": 1}
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]), np.asarray(state.params["w"])
        )

    def test_explicit_corrupt_step_refuses(self, tmp_path):
        state, step_fn = _toy_setup()
        (batch,) = _batches(1)
        state, _ = step_fn(state, batch, jax.random.key(0))
        root = str(tmp_path / "cp")
        FAULTS.arm("ckpt_corrupt", 1)
        save_sharded_checkpoint(root, 1, state)
        with pytest.raises(AssertionError, match="verification"):
            load_sharded_checkpoint(root, _host(state), step=1)

    def test_all_torn_refuses(self, tmp_path):
        root = tmp_path / "cp"
        torn = root / "step_00000001"
        torn.mkdir(parents=True)
        (torn / "x.bin").write_bytes(b"x")
        with pytest.raises(AssertionError, match="no verified"):
            load_sharded_checkpoint(str(root), {"w": np.zeros(2)})

    def test_rotation_counts_only_committed_dirs(self, tmp_path):
        """A torn dir must not push the last good fallback out of the
        keep_n window — and gets pruned as junk."""
        state, step_fn = _toy_setup()
        root = tmp_path / "cp"
        (batch,) = _batches(1)
        state, _ = step_fn(state, batch, jax.random.key(0))
        save_sharded_checkpoint(str(root), 1, state, keep_n=2)
        # crash-mid-save debris newer than the good step
        torn = root / "step_00000002"
        torn.mkdir()
        (torn / "half.bin").write_bytes(b"\0" * 32)
        state, _ = step_fn(state, batch, jax.random.key(0))
        save_sharded_checkpoint(str(root), 3, state, keep_n=2)
        kept = sorted(p.name for p in root.glob("step_*"))
        assert kept == ["step_00000001", "step_00000003"]  # torn junk gone

    def test_verify_ckpt_cli(self, tmp_path, capsys):
        sys.path.insert(0, str(TOOLS))
        try:
            import verify_ckpt
        finally:
            sys.path.pop(0)

        state, step_fn = _toy_setup()
        root = str(tmp_path / "cp")
        for k, batch in enumerate(_batches(2), start=1):
            state, _ = step_fn(state, batch, jax.random.key(0))
            save_sharded_checkpoint(root, k, state)
        assert verify_ckpt.main([root]) == 0

        # corrupt the newest -> exit 1, report names the failure
        victim = max(
            (p for p in (Path(root) / "step_00000002").rglob("*")
             if p.is_file() and p.name not in ("MANIFEST.json", "COMMITTED")),
            key=lambda p: p.stat().st_size,
        )
        victim.write_bytes(b"\xff" * victim.stat().st_size)
        assert verify_ckpt.main([root]) == 1
        out = capsys.readouterr().out
        assert "FAIL  step_00000002" in out and "newest verified: step_00000001" in out

        assert verify_ckpt.main([str(tmp_path / "absent")]) == 2


# --------------------------------------------------------- kill-and-resume


class TestKillAndResume:
    def test_emergency_save_then_resume_is_bit_identical(self, tmp_path):
        """Real SIGTERM mid-run -> emergency step-granular save -> a fresh
        'process' resumes and ends bit-identical to an uninterrupted run."""
        batches = _batches(6, seed=1)
        root = str(tmp_path / "cp")

        clean_state, clean_fn = _toy_setup()
        clean_state, _ = _run_loop(clean_state, clean_fn, batches)

        state, step_fn = _toy_setup()
        with PreemptionHandler() as preempt:
            kill = lambda step: step == 3 and os.kill(os.getpid(), signal.SIGTERM)
            state, stopped = _run_loop(
                state, step_fn, batches,
                ckpt_dir=root, preempt=preempt, on_step=kill,
            )
        assert stopped and latest_verified_step(root) == 3

        # "restart": fresh state + step_fn, restore, continue from meta
        state2, step_fn2 = _toy_setup()
        restored, meta, step = load_sharded_checkpoint(root, _host(state2))
        assert step == 3 and meta["emergency"]
        resumed, _ = _run_loop(restored, step_fn2, batches, start=meta["next"])

        assert int(resumed.step) == int(clean_state.step)
        for a, b in zip(
            jax.tree_util.tree_leaves(_host(resumed)),
            jax.tree_util.tree_leaves(_host(clean_state)),
        ):
            np.testing.assert_array_equal(a, b)

    def test_acceptance_all_faults_same_final_state(self, tmp_path):
        """The ISSUE's acceptance scenario, end to end: 2 transient download
        failures fetching the dataset, 1 injected NaN loss (skipped on
        device, batch retried), SIGTERM mid-run (emergency save), and the
        newest checkpoint dir corrupted post-commit — the resumed run falls
        back to the last verified periodic save, replays, and its final
        params/opt_state equal the unfaulted run's bit for bit."""
        # -- data arrives via download() with 2 injected transient failures
        src = tmp_path / "remote" / "data.npy"
        src.parent.mkdir()
        rng = np.random.RandomState(7)
        np.save(src, rng.randn(6, 2, 8, 4).astype(np.float32))
        FAULTS.arm("download", 2)
        local = download(
            str(src), root=str(tmp_path / "cache"),
            policy=RetryPolicy(attempts=3, base_delay=0.0),
        )
        assert FAULTS.fired["download"] == 2
        data = np.load(local)
        batches = [
            {"x": jnp.asarray(d[0]), "y": jnp.asarray(d[1])} for d in data
        ]
        root = str(tmp_path / "cp")

        # -- reference: unfaulted run over the same data
        clean_state, clean_fn = _toy_setup()
        clean_state, _ = _run_loop(clean_state, clean_fn, batches)

        # -- faulted run: NaN at step 2, SIGTERM at step 5, and the
        #    emergency save itself corrupted (post-commit bit rot)
        state, step_fn = _toy_setup(nan_inject_step=2)
        with PreemptionHandler() as preempt:
            def on_step(step):
                if step == 5:
                    FAULTS.arm("ckpt_corrupt", 1)
                    os.kill(os.getpid(), signal.SIGTERM)

            state, stopped = _run_loop(
                state, step_fn, batches,
                ckpt_dir=root, save_every=2, preempt=preempt, on_step=on_step,
            )
        assert stopped
        assert int(state.skipped) == 1  # the injected NaN was rejected
        assert FAULTS.fired.get("ckpt_corrupt") == 1

        # the corrupted emergency dir must NOT be restorable; fallback is
        # the step-4 periodic save
        assert latest_verified_step(root) == 4

        # -- "relaunch": resume exactly like train_dalle.py's startup probe
        state2, step_fn2 = _toy_setup(nan_inject_step=2)  # env still armed
        restored, meta, step = load_sharded_checkpoint(root, _host(state2))
        assert step == 4 and not meta.get("emergency")
        resumed, stopped = _run_loop(
            restored, step_fn2, batches, start=meta["next"]
        )
        assert not stopped

        # one extra dispatch (the retried NaN batch); applied updates equal
        assert int(resumed.step) == int(clean_state.step) + 1
        assert int(resumed.skipped) == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(_host(resumed.params)),
            jax.tree_util.tree_leaves(_host(clean_state.params)),
        ):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(_host(resumed.opt_state)),
            jax.tree_util.tree_leaves(_host(clean_state.opt_state)),
        ):
            np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- download resilience


class TestDownloadResilience:
    def test_transient_failures_then_success(self, tmp_path):
        src = tmp_path / "w.bin"
        src.write_bytes(b"\x01\x02\x03")
        FAULTS.arm("download", 2)
        out = download(
            str(src), root=str(tmp_path / "cache"),
            policy=RetryPolicy(attempts=3, base_delay=0.0),
        )
        assert Path(out).read_bytes() == b"\x01\x02\x03"
        assert FAULTS.fired["download"] == 2
        assert counters.get("download.retries") == 2

    def test_stale_tmp_cleaned_on_entry(self, tmp_path):
        src = tmp_path / "w.bin"
        src.write_bytes(b"fresh")
        cache = tmp_path / "cache"
        cache.mkdir()
        stale = cache / "w.bin.tmp"
        stale.write_bytes(b"wedged half-download from a crashed run")
        out = download(str(src), root=str(cache))
        assert Path(out).read_bytes() == b"fresh" and not stale.exists()

    def test_exhaustion_raises_and_leaves_no_tmp(self, tmp_path):
        src = tmp_path / "w.bin"
        src.write_bytes(b"data")
        FAULTS.arm("download", 9)
        with pytest.raises(OSError):
            download(
                str(src), root=str(tmp_path / "cache"),
                policy=RetryPolicy(attempts=2, base_delay=0.0),
            )
        assert counters.get("download.failures") == 1
        assert not list((tmp_path / "cache").glob("*.tmp"))

    def test_timeout_reaches_urlopen(self, tmp_path, monkeypatch):
        seen = {}

        class FakeResp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        def fake_urlopen(url, timeout=None):
            seen["timeout"] = timeout
            return FakeResp(b"remote-bytes")

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        out = download(
            "http://example.invalid/f.bin", root=str(tmp_path / "cache"),
            timeout=7.5,
        )
        assert seen["timeout"] == 7.5
        assert Path(out).read_bytes() == b"remote-bytes"

    def test_timeout_none_means_no_limit(self, tmp_path, monkeypatch):
        seen = {}

        class FakeResp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        monkeypatch.setattr(
            urllib.request, "urlopen",
            lambda url, timeout=None: (seen.update(timeout=timeout), FakeResp(b"x"))[1],
        )
        download("http://example.invalid/h.bin", root=str(tmp_path / "cache"),
                 timeout=None)
        assert seen["timeout"] is None

    def test_timeout_env_override(self, tmp_path, monkeypatch):
        seen = {}

        class FakeResp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        monkeypatch.setattr(
            urllib.request, "urlopen",
            lambda url, timeout=None: (seen.update(timeout=timeout), FakeResp(b"x"))[1],
        )
        monkeypatch.setenv("DALLE_TPU_DOWNLOAD_TIMEOUT", "3")
        download("http://example.invalid/g.bin", root=str(tmp_path / "cache"))
        assert seen["timeout"] == 3.0


# --------------------------------------------------------- shard resilience


class _StubTokenizer:
    vocab_size = 64

    def tokenize(self, text, length, truncate_text=False):
        ids = [(ord(c) % 63) + 1 for c in text[:length]]
        return np.asarray([ids + [0] * (length - len(ids))], dtype=np.int32)


def _make_shard(path, n=2, start=0, with_bad=False):
    with tarfile.open(path, "w") as tf:
        def add(name, data):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))

        for i in range(start, start + n):
            img = Image.new("RGB", (24, 24), (10 * i, 20, 30))
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            add(f"sample{i:04d}.png", buf.getvalue())
            add(f"sample{i:04d}.txt", f"caption {i}".encode())
        if with_bad:
            add("bad0001.png", b"garbage bytes")
            add("bad0001.txt", b"broken image")


class TestShardResilience:
    def _ds(self, spec, attempts=2):
        from dalle_pytorch_tpu.data.webdata import TarImageTextDataset

        return TarImageTextDataset(
            spec, text_len=8, image_size=16, tokenizer=_StubTokenizer(),
            retry_policy=RetryPolicy(attempts=attempts, base_delay=0.0),
        )

    def test_transient_open_retries_then_streams(self, tmp_path):
        _make_shard(tmp_path / "s.tar", n=2)
        ds = self._ds(str(tmp_path / "s.tar"))
        FAULTS.arm("shard_open", 1)
        assert len(list(ds)) == 2
        assert counters.get("webdata.shard_open_retries") == 1
        assert counters.get("webdata.shards_quarantined") == 0

    def test_dead_shard_quarantined_and_not_rehammered(self, tmp_path):
        _make_shard(tmp_path / "shard-0000.tar", n=2, start=0)
        _make_shard(tmp_path / "shard-0001.tar", n=2, start=2)
        ds = self._ds(str(tmp_path / "shard-{0000..0001}.tar"))
        FAULTS.arm("shard_open", 2)  # kills every attempt at the 1st shard
        assert len(list(ds)) == 2  # second shard still streamed
        assert counters.get("webdata.shards_quarantined") == 1
        # epoch 2: quarantined shard skipped WITHOUT new open attempts
        # (the retry counter tallies actual RETRIES: 2 attempts = 1 retry)
        assert len(list(ds)) == 2
        assert counters.get("webdata.quarantined_skips") == 1
        assert counters.get("webdata.shard_open_retries") == 1

    def test_decode_errors_are_counted(self, tmp_path):
        _make_shard(tmp_path / "s.tar", n=2, with_bad=True)
        ds = self._ds(str(tmp_path / "s.tar"))
        assert len(list(ds)) == 2  # bad sample dropped, stream continued
        assert counters.get("webdata.decode_errors") == 1

    def test_midshard_fault_aborts_shard_but_keeps_stream(self, tmp_path):
        _make_shard(tmp_path / "shard-0000.tar", n=2, start=0)
        _make_shard(tmp_path / "shard-0001.tar", n=2, start=2)
        ds = self._ds(str(tmp_path / "shard-{0000..0001}.tar"))
        FAULTS.arm("shard_read", 1)
        got = len(list(ds))
        assert got == 2  # first shard aborted mid-read, second intact
        assert counters.get("webdata.shard_aborts") == 1
