"""Quantized KV pages (ISSUE 14, ROADMAP 3): int8 paged pools with
per-(token, head) scales in parallel scale pools, quantized at append
and dequantized at READ time — pinned deterministically on CPU:

- policy: the ``kv_quant`` knob resolves through all three channels
  (explicit argument, ``quant_override`` context, ``DALLE_TPU_KV_QUANT``
  env) and an invalid value fails TYPED in each, at resolution time;
- quantizer unit behavior: symmetric amax/127 scales (zeros quantize
  with scale 1), deterministic/idempotent bytes, round-trip error
  bounded, and append->gather->dequant through real page-boundary
  arithmetic equals the direct formula;
- kernel parity: the Pallas ragged kernel's in-register dequant matches
  the jnp reference path (interpret mode) over mixed descriptors and
  through a PERMUTED (non-identity) page table;
- engine parity tiers: quantized-vs-quantized is BITWISE across
  monolithic/chunked/fused/speculative engines (exact AND genuinely
  misdrafting truncated drafters — the reject-suffix rewind overwrites
  bytes and scales identically), preempt-and-requeue replay, and the
  prefix-cache cold/warm hit (incl. the forged-probe collide drill and
  COW divergence on a shared quantized terminal page); quantized-vs-f32
  is the PINNED token-agreement floor
  (kv_policy.KV_QUANT_TOKEN_AGREEMENT_MIN), never a bitwise claim;
- capacity: per-slot KV bytes from the REAL cache leaves give int8
  >= 1.8x the pages of the unquantized format at a fixed budget, the
  ``serve.kv_quant.*`` gauges are registered and published, and the
  committed trace contract pins the quant serving entries to the same
  signature budgets as their unquantized twins;
- bench record shape: ``bench.bench_serve_quant`` on the tiny parity
  model carries the capacity ratio, agreement fraction, and
  zero-compile fields.

Page size 2 (env override), as in tests/test_serving.py, so the tiny
model's T=5 prompt spans 3 pages with a partial terminal page and
decode crosses page boundaries mid-flight.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.models.sampling import (
    init_decode_cache,
    set_decode_offsets,
)
from dalle_pytorch_tpu.ops import kv_policy, paged_kv
from dalle_pytorch_tpu.ops import ragged_attention as ra
from dalle_pytorch_tpu.ops.kv_policy import (
    KV_QUANT_TOKEN_AGREEMENT_MIN,
    InvalidKVFormatError,
)
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
    check_accounting,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges

REPO = Path(__file__).resolve().parent.parent


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, rid=None, p=None, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=rid or f"r{i}",
        prompt=prompt(i) if p is None else p,
        max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def run_tokens(model, reqs, **cfg_kw):
    eng = make_engine(model, **cfg_kw)
    for r in reqs:
        assert eng.submit(r) is None
    eng.run(max_steps=1500)
    check_accounting(eng)
    assert all(
        r.outcome is Outcome.COMPLETED for r in eng.results.values()
    ), {k: v.outcome for k, v in eng.results.items()}
    return {rid: np.asarray(r.tokens) for rid, r in eng.results.items()}


# the quantized engine-mode axis: every mode must be BITWISE equal to
# every other (quant-vs-quant is the standing contract). spec-trunc uses
# a GENUINELY misdrafting depth-1-of-2 drafter, so its runs contain real
# reject-suffix rewinds — bitwise tokens prove the rewind restored the
# pre-draft quantized bytes AND scales (later logits read the rewound
# K/V through the dequant formula).
QUANT_MODES = [
    pytest.param(dict(), id="mono"),
    pytest.param(dict(prefill_chunk=2), id="chunked"),
    pytest.param(dict(prefill_chunk=2, fused_iteration=True), id="fused"),
    pytest.param(
        dict(prefill_chunk=2, fused_iteration=True, spec_decode=True,
             spec_k=2),
        id="spec-exact",
    ),
    pytest.param(
        dict(prefill_chunk=2, fused_iteration=True, spec_decode=True,
             spec_k=2, spec_draft_depth=1),
        id="spec-trunc",
    ),
]


# --------------------------------------------------------------- policy


class TestQuantPolicy:
    def test_invalid_argument_typed(self):
        with pytest.raises(InvalidKVFormatError) as e:
            kv_policy.resolve_quant("int4")
        assert "int8" in str(e.value) and "int4" in str(e.value)

    def test_invalid_env_typed(self, monkeypatch):
        monkeypatch.setenv("DALLE_TPU_KV_QUANT", "fp8")
        with pytest.raises(InvalidKVFormatError) as e:
            kv_policy.choose_kv_quant()
        assert "DALLE_TPU_KV_QUANT" in str(e.value)

    def test_invalid_override_typed(self):
        with pytest.raises(InvalidKVFormatError):
            with kv_policy.quant_override("bogus"):
                pass

    def test_channel_precedence(self, monkeypatch):
        monkeypatch.setenv("DALLE_TPU_KV_QUANT", "none")
        with kv_policy.quant_override("int8"):
            assert kv_policy.choose_kv_quant() == "int8"
        assert kv_policy.choose_kv_quant() == "none"
        monkeypatch.setenv("DALLE_TPU_KV_QUANT", "int8")
        assert kv_policy.choose_kv_quant() == "int8"
        assert kv_policy.resolve_quant("none") == "none"

    def test_engine_config_invalid_typed(self, model):
        with pytest.raises(InvalidKVFormatError):
            make_engine(model, kv_quant="int4")


# ------------------------------------------------------------ quantizer


class TestQuantizeRows:
    def test_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        rows = jnp.asarray(rng.randn(2, 7, 16), jnp.float32)
        q, s = paged_kv.quantize_rows(rows, heads=2)
        assert q.dtype == jnp.int8 and s.dtype == paged_kv.SCALE_DTYPE
        assert q.shape == rows.shape and s.shape == (2, 7, 2)
        deq = paged_kv.dequant(q, s, jnp.float32)
        # symmetric 127-level quantization: error <= scale/2 per element
        err = np.abs(np.asarray(deq) - np.asarray(rows))
        bound = np.repeat(np.asarray(s), 8, axis=-1) / 2 + 1e-7
        assert np.all(err <= bound)

    def test_zero_rows_scale_one(self):
        q, s = paged_kv.quantize_rows(jnp.zeros((1, 3, 8)), heads=2)
        np.testing.assert_array_equal(np.asarray(q), 0)
        np.testing.assert_array_equal(np.asarray(s), 1.0)

    def test_deterministic_idempotent(self):
        """The bitwise-parity keystone: quantizing the same rows always
        yields identical bytes and scales — a rewind's overwrite or a
        replay's re-append reproduces pool content exactly."""
        rng = np.random.RandomState(1)
        rows = jnp.asarray(rng.randn(1, 5, 16), jnp.float32)
        q1, s1 = jax.jit(paged_kv.quantize_rows, static_argnums=1)(rows, 2)
        q2, s2 = jax.jit(paged_kv.quantize_rows, static_argnums=1)(rows, 2)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    def test_append_gather_dequant_through_pages(self):
        """Quantized rows appended across page boundaries gather and
        dequantize back to exactly the direct formula's values."""
        b, n, h, d, page, n_p = 2, 5, 2, 4, 2, 4
        rng = np.random.RandomState(2)
        rows = jnp.asarray(rng.randn(b, n, h * d), jnp.float32)
        q, s = paged_kv.quantize_rows(rows, h)
        pool = jnp.zeros((b, n_p, page, h * d), jnp.int8)
        spool = jnp.zeros((b, n_p, page, h), paged_kv.SCALE_DTYPE)
        table = paged_kv.identity_table(b, n_p)
        idx = jnp.asarray([0, 1], jnp.int32)  # ragged offsets
        pool = paged_kv.append(pool, table, idx, q)
        spool = paged_kv.append(spool, table, idx, s)
        view = paged_kv.dequant(
            paged_kv.gather(pool, table), paged_kv.gather(spool, table),
            jnp.float32,
        )
        direct = paged_kv.dequant(q, s, jnp.float32)
        for r in range(b):
            lo = int(idx[r])
            np.testing.assert_array_equal(
                np.asarray(view[r, lo:lo + n]), np.asarray(direct[r])
            )

    def test_rewind_overwrite_restores_bytes_and_scales(self):
        """The spec-decode reject-suffix seam at the pool level: draft
        garbage written past the accepted frontier, then the anchored
        re-append (the rewind) overwrites it — bytes AND scales end
        exactly equal to a run that never drafted."""
        b, h, d, page, n_p = 1, 2, 4, 2, 4
        rng = np.random.RandomState(3)
        real = jnp.asarray(rng.randn(b, 4, h * d), jnp.float32)
        garbage = jnp.asarray(rng.randn(b, 3, h * d) * 9.0, jnp.float32)

        def fresh():
            return (
                jnp.zeros((b, n_p, page, h * d), jnp.int8),
                jnp.zeros((b, n_p, page, h), paged_kv.SCALE_DTYPE),
            )

        table = paged_kv.identity_table(b, n_p)

        def put(pools, rows, at):
            pool, spool = pools
            q, s = paged_kv.quantize_rows(rows, h)
            idx = jnp.full((b,), at, jnp.int32)
            return (
                paged_kv.append(pool, table, idx, q),
                paged_kv.append(spool, table, idx, s),
            )

        clean = put(fresh(), real, 0)
        drafted = put(fresh(), real[:, :1], 0)
        drafted = put(drafted, garbage, 1)      # the rejected suffix
        drafted = put(drafted, real[:, 1:], 1)  # the anchored rewind
        np.testing.assert_array_equal(
            np.asarray(clean[0]), np.asarray(drafted[0])
        )
        np.testing.assert_array_equal(
            np.asarray(clean[1]), np.asarray(drafted[1])
        )


# -------------------------------------------------------- kernel parity


class TestKernelParityQuant:
    def _quant_pools(self, b, n_p, page, h, d, seed=0):
        rng = np.random.RandomState(seed)
        hd = h * d
        k = jnp.asarray(rng.randn(b, n_p * page, hd), jnp.float32) * 0.3
        v = jnp.asarray(rng.randn(b, n_p * page, hd), jnp.float32) * 0.3
        kq, ks = paged_kv.quantize_rows(k, h)
        vq, vs = paged_kv.quantize_rows(v, h)
        shape = (b, n_p, page)
        return (
            kq.reshape(*shape, hd), vq.reshape(*shape, hd),
            ks.reshape(*shape, h), vs.reshape(*shape, h),
        )

    @pytest.mark.parametrize("label,start,length", [
        ("mixed", [0, 3, 9], [4, 2, 1]),
        ("all_decode", [5, 7, 9], [1, 1, 1]),
        ("with_idle", [0, 0, 6], [4, 0, 2]),
    ], ids=["mixed", "all_decode", "with_idle"])
    def test_kernel_matches_reference_quant(self, label, start, length):
        b, n, h, d, page, n_p = 3, 4, 2, 8, 4, 5
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32) * 0.3
        kq, vq, ks, vs = self._quant_pools(b, n_p, page, h, d)
        table = paged_kv.identity_table(b, n_p)
        start = jnp.asarray(start, jnp.int32)
        length = jnp.asarray(length, jnp.int32)
        pos = start[:, None] + jnp.arange(n)[None]
        allowed = (
            jnp.arange(n_p * page)[None, None] <= pos[..., None]
        )[:, None]
        ref = ra.reference_attend(
            q, kq, vq, table, allowed, k_scales=ks, v_scales=vs
        )
        ker = ra.kernel_attend(
            q, kq, vq, table, start, length, interpret=True,
            k_scales=ks, v_scales=vs,
        )
        assert bool(jnp.all(jnp.isfinite(ker)))
        valid = (jnp.arange(n)[None] < length[:, None])[..., None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, ker, 0.0)),
            np.asarray(jnp.where(valid, ref, 0.0)),
            atol=2e-6, rtol=2e-6,
        )

    def test_kernel_permuted_table_streams_scales_too(self):
        """A non-identity GLOBAL table (pages living in other rows'
        storage — the prefix-cache shape): the kernel must dereference
        the SAME entry for content and scale pages, or a shared page
        would dequantize under a stranger's scales."""
        b, n, h, d, page, n_p = 2, 3, 2, 8, 4, 4
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32) * 0.3
        kq, vq, ks, vs = self._quant_pools(b, n_p, page, h, d, seed=5)
        perm = rng.permutation(b * n_p).reshape(b, n_p)
        table = jnp.asarray(perm, jnp.int32)
        start = jnp.asarray([2, 8], jnp.int32)
        length = jnp.asarray([3, 1], jnp.int32)
        pos = start[:, None] + jnp.arange(n)[None]
        allowed = (
            jnp.arange(n_p * page)[None, None] <= pos[..., None]
        )[:, None]
        ref = ra.reference_attend(
            q, kq, vq, table, allowed, k_scales=ks, v_scales=vs
        )
        ker = ra.kernel_attend(
            q, kq, vq, table, start, length, interpret=True,
            k_scales=ks, v_scales=vs,
        )
        valid = (jnp.arange(n)[None] < length[:, None])[..., None, None]
        np.testing.assert_allclose(
            np.asarray(jnp.where(valid, ker, 0.0)),
            np.asarray(jnp.where(valid, ref, 0.0)),
            atol=2e-6, rtol=2e-6,
        )


# -------------------------------------------------------- engine parity


class TestEngineQuantParity:
    def test_all_modes_bitwise_equal(self, model):
        """Quant-vs-quant is BITWISE across every engine mode — incl.
        the genuinely misdrafting truncated drafter, whose runs contain
        real reject-suffix rewinds over quantized pages."""
        reqs = lambda: [req(i) for i in range(3)]
        base = run_tokens(model, reqs(), kv_quant="int8")
        for mode in (
            dict(prefill_chunk=2),
            dict(prefill_chunk=2, fused_iteration=True),
            dict(prefill_chunk=2, fused_iteration=True, spec_decode=True,
                 spec_k=2),
            dict(prefill_chunk=2, fused_iteration=True, spec_decode=True,
                 spec_k=2, spec_draft_depth=1),
        ):
            got = run_tokens(model, reqs(), kv_quant="int8", **mode)
            for rid in base:
                np.testing.assert_array_equal(
                    base[rid], got[rid],
                    err_msg=f"{rid} diverged under {mode}",
                )

    def test_truncated_drafter_actually_misdrafts(self, model):
        """The spec-trunc mode above only exercises the rewind if the
        depth-1 drafter genuinely mispredicts — pin that it does."""
        counters.reset()
        run_tokens(
            model, [req(i) for i in range(3)], kv_quant="int8",
            prefill_chunk=2, fused_iteration=True, spec_decode=True,
            spec_k=2, spec_draft_depth=1,
        )
        assert counters.get("serve.spec.rejected") > 0, (
            "depth-1 drafter rejected nothing — the rewind seam was "
            "not exercised"
        )

    def test_quant_vs_f32_agreement_floor(self, model):
        reqs = lambda: [req(i) for i in range(3)]
        f32 = run_tokens(model, reqs(), prefill_chunk=2)
        q = run_tokens(model, reqs(), prefill_chunk=2, kv_quant="int8")
        agree = float(np.mean([
            np.mean(f32[rid] == q[rid]) for rid in f32
        ]))
        assert agree >= KV_QUANT_TOKEN_AGREEMENT_MIN, agree

    def test_preempt_replay_bit_identical(self, model):
        """An injected page_exhaust forces an eviction mid-decode on the
        quantized engine; the evicted request re-prefills (re-quantizes)
        from scratch and its tokens are BIT-identical to the unpreempted
        quantized run."""
        FAULTS.reset()
        counters.reset()
        clean = run_tokens(model, [req(i) for i in range(3)],
                           kv_quant="int8")
        FAULTS.configure("page_exhaust=1")
        eng = make_engine(model, kv_quant="int8")
        for i in range(3):
            assert eng.submit(req(i)) is None
        eng.run(max_steps=1500)
        check_accounting(eng)
        FAULTS.reset()
        assert counters.get("serve.preempted") >= 1
        for rid, r in eng.results.items():
            assert r.outcome is Outcome.COMPLETED, (rid, r.outcome)
            np.testing.assert_array_equal(
                np.asarray(r.tokens), clean[rid],
                err_msg=f"{rid} diverged across quantized preemption",
            )
        assert eng.pool.used == 0

    def test_cold_warm_prefix_hit_bitwise(self, model):
        """Warm full hits against quantized arena pages are bitwise
        equal to the quantized cold run (content-addressed int8 bytes +
        scales mapped read-only through the table)."""
        eng = make_engine(
            model, prefill_chunk=2, prefix_cache=True, kv_quant="int8"
        )
        for i in range(3):
            assert eng.submit(req(i, rid=f"r{i}.c")) is None
        eng.run(max_steps=1500)
        h0 = eng.prefix.stats.hits
        for i in range(3):
            assert eng.submit(req(i, rid=f"r{i}.w")) is None
        eng.run(max_steps=1500)
        check_accounting(eng)
        assert eng.prefix.stats.hits > h0, "warm round never hit"
        for i in range(3):
            np.testing.assert_array_equal(
                np.asarray(eng.results[f"r{i}.c"].tokens),
                np.asarray(eng.results[f"r{i}.w"].tokens),
                err_msg=f"r{i} warm quantized hit diverged from cold",
            )

    def test_forged_probe_rejected_falls_back_cold_bitwise(self, model):
        """The forged-scale/collide probe: a prefix_hash_collide-forged
        lookup is rejected by token verification and the request runs
        cold, bit-identical — forged addresses can never map another
        prompt's quantized bytes or scales."""
        FAULTS.reset()
        counters.reset()
        eng = make_engine(
            model, prefill_chunk=2, prefix_cache=True, kv_quant="int8"
        )
        assert eng.submit(req(0, rid="cold")) is None
        eng.run(max_steps=1500)
        FAULTS.arm("prefix_hash_collide", 1)
        assert eng.submit(req(0, rid="probed")) is None
        eng.run(max_steps=1500)
        check_accounting(eng)
        FAULTS.reset()
        assert counters.get("serve.fault_prefix_hash_collide") == 1
        np.testing.assert_array_equal(
            np.asarray(eng.results["cold"].tokens),
            np.asarray(eng.results["probed"].tokens),
        )

    def test_cow_divergence_leaves_quantized_arena_untouched(self, model):
        """Two same-prompt requests take full hits on the same quantized
        chain (partial terminal page COW'd at map time) and decode to
        DIFFERENT continuations; a third same-prompt request afterwards
        still hits and matches the first bit-for-bit — the shared arena
        bytes and scales were never written through the COW copies."""
        eng = make_engine(
            model, max_batch=2, prefill_chunk=2, prefix_cache=True,
            kv_quant="int8",
        )
        assert eng.submit(req(0, rid="pub", seed=5)) is None
        eng.run(max_steps=1500)
        c0 = counters.get("serve.prefix.cow_copies")
        assert eng.submit(req(0, rid="a", seed=6)) is None
        assert eng.submit(req(0, rid="b", seed=7)) is None
        eng.run(max_steps=1500)
        assert counters.get("serve.prefix.cow_copies") > c0, (
            "terminal page was not COW'd — the divergence never "
            "touched the seam under test"
        )
        assert eng.submit(req(0, rid="a2", seed=6)) is None
        eng.run(max_steps=1500)
        check_accounting(eng)
        assert not np.array_equal(
            np.asarray(eng.results["a"].tokens),
            np.asarray(eng.results["b"].tokens),
        ), "seeds 6/7 sampled identical streams — divergence not exercised"
        np.testing.assert_array_equal(
            np.asarray(eng.results["a"].tokens),
            np.asarray(eng.results["a2"].tokens),
            err_msg="later hit diverged — COW leaked into the arena",
        )

    def test_kv_bytes_per_slot_capacity_and_gauges(self, model):
        gauges.reset()
        base = make_engine(model)
        quant = make_engine(model, kv_quant="int8")
        assert quant.kv_quant == "int8" and base.kv_quant == "none"
        ratio = base.kv_bytes_per_slot / quant.kv_bytes_per_slot
        assert ratio >= 1.8, ratio
        # gauges registered (DTL041) and published at construction
        from dalle_pytorch_tpu.utils import telemetry_names as tn

        assert tn.is_registered("serve.kv_quant.bytes_per_slot", "gauge")
        assert tn.is_registered("serve.kv_quant.pages", "gauge")
        assert gauges.get("serve.kv_quant.bytes_per_slot") == float(
            quant.kv_bytes_per_slot
        )

    def test_quant_cache_leaves_dtypes(self, model):
        dalle, params = model
        cache = init_decode_cache(
            dalle, params, 2, cache_format="paged", kv_quant="int8"
        )
        leaves = {
            getattr(p[-1], "key", None): x
            for p, x in jax.tree_util.tree_leaves_with_path(cache)
        }
        assert leaves["cached_key_pages"].dtype == jnp.int8
        assert leaves["cached_value_pages"].dtype == jnp.int8
        assert (
            leaves["cached_key_scale_pages"].dtype == paged_kv.SCALE_DTYPE
        )
        h = dalle.heads
        assert leaves["cached_key_scale_pages"].shape[-1] == h
        # scale pools are POOL-shaped: same (b, n_pages, page) prefix
        assert (
            leaves["cached_key_scale_pages"].shape[:3]
            == leaves["cached_key_pages"].shape[:3]
        )


# --------------------------------------------------- contracts + bench


class TestContractsAndBench:
    def test_trace_contract_pins_quant_entries(self):
        """The committed trace contract carries the quantized serving
        entries at the SAME signature budgets as their unquantized twins
        (1 decode / 2 iteration signatures, cache donated) — and the
        quant decode entry's donated (aliased) cache bytes are well
        under the unquantized entry's: DTL141's standing guard that
        quantized KV stays roughly half-size."""
        import re

        contract = json.loads(
            (REPO / "tools" / "trace_contracts.json").read_text()
        )
        entries = contract["entries"]
        dq = entries["serving.decode_quant"]
        iq = entries["serving.iteration_quant"]
        assert dq["max_signatures"] == 1
        assert iq["max_signatures"] == 2
        assert dq["donate"] == ["cache"], "quant decode must donate its cache"
        assert iq["donate"] == ["cache"]

        def cache_bytes(entry):
            # signature keys carry each tree arg as tree#..(<n>L,<b>B);
            # arg order is (model, params, cache, ...) so the SECOND
            # tree is the donated cache
            trees = re.findall(
                r"tree#\w+\(\d+L,(\d+)B\)",
                entry["signatures"][0]["key"],
            )
            assert len(trees) >= 2, entry["signatures"][0]["key"]
            return int(trees[1])

        base_cache = cache_bytes(entries["serving.decode"])
        quant_cache = cache_bytes(dq)
        assert quant_cache * 1.8 <= base_cache, (
            f"quant cache {quant_cache}B not <= ~half of the "
            f"unquantized {base_cache}B — the DTL141 half-size guard"
        )
        # the total HBM budget shrinks by exactly the cache savings
        assert dq["max_hbm_bytes"] < entries["serving.decode"][
            "max_hbm_bytes"
        ]

    def test_bench_serve_quant_record(self, model):
        import bench

        rec = bench.bench_serve_quant(True, model=model, seed=0)
        for k in ("kv_bytes_per_slot_unquant", "kv_bytes_per_slot_int8",
                  "kv_pages_per_budget_ratio", "token_agreement_vs_unquant",
                  "token_agreement_floor", "compiles_in_trace_int8",
                  "jit_recompiles_in_trace_int8",
                  "roofline_tokens_per_sec_batch8",
                  "roofline_tokens_per_sec_batch8_kv_int8"):
            assert k in rec, k
        assert rec["metric"].startswith("serve_kv_quant")
        assert rec["kv_pages_per_budget_ratio"] >= 1.8
        assert (
            rec["token_agreement_vs_unquant"]
            >= rec["token_agreement_floor"]
        )
        assert rec["compiles_in_trace_int8"] in (0, -1)
        assert all(
            v in (0, -1)
            for v in rec["jit_recompiles_in_trace_int8"].values()
        )
        # bytes halve (or better): the f32 parity-tier model quantizes
        # 4-byte elements down to 1 + scale overhead
        assert (
            rec["kv_bytes_per_slot_int8"] * 2
            <= rec["kv_bytes_per_slot_unquant"]
        )
        # the recomputed int8 stream bound sits ABOVE the bf16 bound
        assert (
            rec["roofline_tokens_per_sec_batch8_kv_int8"]
            > rec["roofline_tokens_per_sec_batch8"]
        )
