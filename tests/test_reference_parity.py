"""Byte-level parity against the REFERENCE tokenizer itself.

Loads the reference's SimpleTokenizer (/root/reference/dalle_pytorch/
tokenizer.py, OpenAI's CLIP BPE) standalone — its unused yttm/ftfy imports
stubbed — and checks that this framework's Python AND native C++ tokenizers
produce identical ids and decodes. This is the strongest compatibility
statement available in-environment: same vocab file, same ids, token for
token. (The full reference package needs torch-ecosystem pips that are not
installed, so model-level numeric parity is covered by our own oracles
instead.)
"""

import importlib.machinery
import importlib.util
import sys
import types
import unicodedata
from pathlib import Path

import numpy as np
import pytest

REF_TOKENIZER = Path("/root/reference/dalle_pytorch/tokenizer.py")

pytestmark = pytest.mark.skipif(
    not REF_TOKENIZER.exists(), reason="reference checkout not available"
)


@pytest.fixture(scope="module")
def ref_tokenizer():
    """The reference SimpleTokenizer, with its module-level yttm/ftfy
    imports stubbed (neither is installed; ftfy's fix_text is stubbed to the
    same NFC normalization our no-ftfy fallback uses, so both pipelines
    clean text identically)."""

    def stub(name):
        if name in sys.modules:
            return sys.modules[name]
        m = types.ModuleType(name)
        m.__spec__ = importlib.machinery.ModuleSpec(name, loader=None)
        sys.modules[name] = m
        return m

    stub("youtokentome")
    ftfy = stub("ftfy")
    ftfy.fix_text = lambda s: unicodedata.normalize("NFC", s)

    spec = importlib.util.spec_from_file_location("ref_tokenizer", REF_TOKENIZER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.SimpleTokenizer()


@pytest.fixture(scope="module")
def ours():
    from dalle_pytorch_tpu.data.tokenizers import SimpleTokenizer

    return SimpleTokenizer()


CORPUS = [
    "a red square",
    "A man riding a horse on the beach at sunset.",
    "Hello, World! It's a test... isn't it?",
    "naïve café résumé über straße",
    "numbers 0 1 23 456 7890 ² ³ ½",
    "emoji 🎨🌈🦄 and CJK 中文字符串 and kana テスト",
    "don't can't we'll I'm you've they're he'd",
    "punctuation!!! ??? ... ---- ###$$$%%%",
    "html &amp; entities &lt;tag&gt;",
    "Ωμέγα ελληνικά кириллица العربية עברית",
    "  collapse   whitespace\tand\nnewlines ",
    "a" * 200,
]


def test_vocab_size_matches(ref_tokenizer, ours):
    assert ours.vocab_size == ref_tokenizer.vocab_size == 49408


@pytest.mark.parametrize("text", CORPUS, ids=range(len(CORPUS)))
def test_encode_matches_reference(ref_tokenizer, ours, text):
    assert ours.encode(text) == ref_tokenizer.encode(text)


def test_native_engine_matches_reference(ref_tokenizer):
    from dalle_pytorch_tpu.data.native_bpe import (
        NativeSimpleTokenizer,
        native_available,
    )

    if not native_available():
        pytest.skip("no C++ toolchain")
    nt = NativeSimpleTokenizer()
    for text in CORPUS:
        assert nt.encode(text) == ref_tokenizer.encode(text), repr(text)


def test_decode_matches_reference(ref_tokenizer, ours):
    for text in CORPUS:
        ids = ref_tokenizer.encode(text)
        if 0 in ids:
            continue  # ours treats id 0 as the shared pad and drops it
        # reference decode takes a tensor-like of ids and strips nothing else
        ref_out = ref_tokenizer.decode(np.asarray(ids))
        assert ours.decode(ids) == ref_out


def test_tokenize_contract_matches_reference(ref_tokenizer, ours):
    """Same 0-padded (b, context) output and same too-long behavior
    (reference tokenizer.py:137-152)."""
    texts = ["a red square", "tiny"]
    ref = ref_tokenizer.tokenize(texts, context_length=16).numpy()
    got = ours.tokenize(texts, context_length=16)
    np.testing.assert_array_equal(got, ref)
    with pytest.raises(RuntimeError):
        ours.tokenize(["word " * 200], context_length=8)
    with pytest.raises(RuntimeError):
        ref_tokenizer.tokenize(["word " * 200], context_length=8)


def test_fuzz_against_reference(ref_tokenizer, ours):
    rng = np.random.RandomState(7)
    pools = [
        list(range(0x20, 0x7F)),
        list(range(0xA0, 0x250)),
        list(range(0x370, 0x400)),
        list(range(0x4E00, 0x4E40)),
        [0x1F600 + i for i in range(30)],
        [0x20, 0x27, 0x73, 0x74, 0x2E, 0x31],
    ]
    for _ in range(150):
        n = rng.randint(1, 50)
        text = "".join(
            chr(int(rng.choice(pools[rng.randint(len(pools))]))) for _ in range(n)
        )
        # keep inputs NFC so the cleaning pipelines (stubbed ftfy vs our
        # fallback) cannot diverge on normalization
        text = unicodedata.normalize("NFC", text)
        assert ours.encode(text) == ref_tokenizer.encode(text), repr(text)