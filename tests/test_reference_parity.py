"""Numeric and byte-level parity against the REFERENCE implementation itself.

The full reference package needs torch-ecosystem pips that are not installed,
but two of its modules load standalone (with their unused external imports
stubbed), giving direct ground-truth oracles:

- tokenizer.py: this framework's Python AND native C++ tokenizers must
  produce identical ids and decodes — same vocab file, token for token;
- attention.py (torch CPU): the dense-causal, conv-like-sparse and axial
  attention modules must produce the same outputs as our ``PatternAttention``
  when the projection weights are transplanted — semantics verified against
  the reference's own einsums/masking, not just our internal oracles.
"""

import importlib.machinery
import importlib.util
import sys
import types
import unicodedata
from pathlib import Path

import numpy as np
import pytest

REF_TOKENIZER = Path("/root/reference/dalle_pytorch/tokenizer.py")

pytestmark = pytest.mark.skipif(
    not REF_TOKENIZER.exists(), reason="reference checkout not available"
)


class _StubScope:
    """Installs import stubs for packages that are genuinely absent (checked
    via find_spec, so an installed-but-unimported package is never shadowed)
    and removes every module it added on close — stubs stay scoped to this
    test module."""

    def __init__(self):
        self.created = []

    def stub(self, name, force=False, **attrs):
        if not force:
            if name in sys.modules:
                return sys.modules[name]
            try:
                if importlib.util.find_spec(name) is not None:
                    return None  # real package available; leave imports alone
            except (ImportError, ValueError):
                pass
        if name in sys.modules:
            return sys.modules[name]
        m = types.ModuleType(name)
        m.__spec__ = importlib.machinery.ModuleSpec(name, loader=None)
        for k, v in attrs.items():
            setattr(m, k, v)
        sys.modules[name] = m
        self.created.append(name)
        return m

    def track(self, name):
        """Register an externally-created sys.modules entry for teardown."""
        self.created.append(name)

    def close(self):
        for name in reversed(self.created):
            sys.modules.pop(name, None)
        # submodules imported under a stubbed package (dalle_pytorch.*)
        for name in [n for n in list(sys.modules) if n.startswith("dalle_pytorch.") or n == "dalle_pytorch"]:
            if name in self.created or any(c == "dalle_pytorch" for c in self.created):
                sys.modules.pop(name, None)


@pytest.fixture(scope="module")
def stub_scope():
    scope = _StubScope()
    yield scope
    scope.close()


@pytest.fixture(scope="module")
def ref_tokenizer(stub_scope):
    """The reference SimpleTokenizer, with its module-level yttm/ftfy
    imports stubbed when those packages are genuinely absent (ftfy's
    fix_text falls back to the same NFC normalization our no-ftfy fallback
    uses, so both pipelines clean text identically; with a real ftfy
    installed, both sides use it and parity still holds)."""
    stub_scope.stub("youtokentome")
    stub_scope.stub("ftfy", fix_text=lambda s: unicodedata.normalize("NFC", s))

    spec = importlib.util.spec_from_file_location("ref_tokenizer", REF_TOKENIZER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.SimpleTokenizer()


@pytest.fixture(scope="module")
def ours():
    from dalle_pytorch_tpu.data.tokenizers import SimpleTokenizer

    return SimpleTokenizer()


CORPUS = [
    "a red square",
    "A man riding a horse on the beach at sunset.",
    "Hello, World! It's a test... isn't it?",
    "naïve café résumé über straße",
    "numbers 0 1 23 456 7890 ² ³ ½",
    "emoji 🎨🌈🦄 and CJK 中文字符串 and kana テスト",
    "don't can't we'll I'm you've they're he'd",
    "punctuation!!! ??? ... ---- ###$$$%%%",
    "html &amp; entities &lt;tag&gt;",
    "Ωμέγα ελληνικά кириллица العربية עברית",
    "  collapse   whitespace\tand\nnewlines ",
    "a" * 200,
]


def test_vocab_size_matches(ref_tokenizer, ours):
    assert ours.vocab_size == ref_tokenizer.vocab_size == 49408


@pytest.mark.parametrize("text", CORPUS, ids=range(len(CORPUS)))
def test_encode_matches_reference(ref_tokenizer, ours, text):
    assert ours.encode(text) == ref_tokenizer.encode(text)


def test_native_engine_matches_reference(ref_tokenizer):
    from dalle_pytorch_tpu.data.native_bpe import (
        NativeSimpleTokenizer,
        native_available,
    )

    if not native_available():
        pytest.skip("no C++ toolchain")
    nt = NativeSimpleTokenizer()
    for text in CORPUS:
        assert nt.encode(text) == ref_tokenizer.encode(text), repr(text)


def test_decode_matches_reference(ref_tokenizer, ours):
    for text in CORPUS:
        ids = ref_tokenizer.encode(text)
        if 0 in ids:
            continue  # ours treats id 0 as the shared pad and drops it
        # reference decode takes a tensor-like of ids and strips nothing else
        ref_out = ref_tokenizer.decode(np.asarray(ids))
        assert ours.decode(ids) == ref_out


def test_tokenize_contract_matches_reference(ref_tokenizer, ours):
    """Same 0-padded (b, context) output and same too-long behavior
    (reference tokenizer.py:137-152)."""
    texts = ["a red square", "tiny"]
    ref = ref_tokenizer.tokenize(texts, context_length=16).numpy()
    got = ours.tokenize(texts, context_length=16)
    np.testing.assert_array_equal(got, ref)
    with pytest.raises(RuntimeError):
        ours.tokenize(["word " * 200], context_length=8)
    with pytest.raises(RuntimeError):
        ref_tokenizer.tokenize(["word " * 200], context_length=8)


class TestAttentionParity:
    """Transplant reference attention weights into PatternAttention and
    require matching outputs (reference attention.py:39-321)."""

    @pytest.fixture(scope="class")
    def ref_attention_mod(self, stub_scope):
        torch = pytest.importorskip("torch")

        # never invoked in these tests (no rotary embeddings passed)
        stub_scope.stub("rotary_embedding_torch", apply_rotary_emb=lambda f, t: t)
        spec = importlib.util.spec_from_file_location(
            "ref_attention", "/root/reference/dalle_pytorch/attention.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _compare(self, ref_mod_cls, our_kwargs, seq_len, n, ref_kwargs=None,
                 with_mask=False, atol=2e-4, internal_plus_one=False):
        """``internal_plus_one``: the reference sparse classes treat their
        internal pattern length as seq_len + 1 (bos included — they compute
        text_len = seq_len + 1 - img_seq, attention.py:116) and our
        Transformer mirrors that by building PatternAttention with
        seq_len + 1 (models/transformer.py:_attn_seq_len)."""
        import jax.numpy as jnp
        import torch

        from dalle_pytorch_tpu.ops.attention import PatternAttention

        dim, heads, dim_head = 32, 2, 8
        torch.manual_seed(0)
        ref = ref_mod_cls(
            dim=dim, seq_len=seq_len, heads=heads, dim_head=dim_head,
            **(ref_kwargs or {}),
        ).eval()
        our_seq_len = seq_len + 1 if internal_plus_one else seq_len

        rng = np.random.RandomState(0)
        x = rng.randn(2, n, dim).astype(np.float32)
        ref_mask = our_mask = None
        if with_mask:
            if internal_plus_one:
                # the sparse classes consume a TEXT-ONLY padding mask
                # (mask[:, :text_len], attention.py:123) and image keys are
                # always visible; ours takes a full (b, n) key mask
                text_len = our_seq_len - 16  # image_fmap 4
                tm = rng.rand(2, text_len) > 0.3
                tm[:, 0] = True
                ref_mask = tm
                our_mask = np.concatenate(
                    [tm, np.ones((2, n - text_len), bool)], axis=1
                )
            else:
                ref_mask = our_mask = (
                    (rng.rand(2, n) > 0.3) | (np.arange(n)[None] == 0)
                )

        with torch.no_grad():
            ref_out = ref(
                torch.tensor(x),
                mask=None if ref_mask is None else torch.tensor(ref_mask),
            ).numpy()

        params = {
            "to_qkv": {"kernel": ref.to_qkv.weight.detach().numpy().T},
            "to_out": {
                "kernel": ref.to_out[0].weight.detach().numpy().T,
                "bias": ref.to_out[0].bias.detach().numpy(),
            },
        }
        ours = PatternAttention(
            dim=dim, seq_len=our_seq_len, heads=heads, dim_head=dim_head,
            use_flash=False, **our_kwargs,
        )
        out = ours.apply(
            {"params": params}, jnp.asarray(x),
            mask=None if our_mask is None else jnp.asarray(our_mask),
        )
        got = np.asarray(out)
        if with_mask and internal_plus_one:
            # reference quirk: the sparse classes apply the padding mask ONLY
            # to image->text attention — their text self-attention ignores it
            # entirely (attention.py:141-149 vs :185-188). Ours applies the
            # key mask uniformly (the saner semantics; the path is vestigial
            # since padding is handled by per-position pad tokens). Compare
            # the image rows, where both implement the mask identically.
            text_len = our_seq_len - 16
            got, ref_out = got[:, text_len:], ref_out[:, text_len:]
        np.testing.assert_allclose(got, ref_out, atol=atol)

    @pytest.mark.parametrize("with_mask", [False, True])
    def test_full_causal(self, ref_attention_mod, with_mask):
        self._compare(
            ref_attention_mod.Attention,
            dict(attn_type="full", causal=True),
            seq_len=24, n=24, ref_kwargs=dict(causal=True),
            with_mask=with_mask,
        )

    def test_full_causal_stable_softmax(self, ref_attention_mod):
        self._compare(
            ref_attention_mod.Attention,
            dict(attn_type="full", causal=True, stable=True),
            seq_len=24, n=24, ref_kwargs=dict(causal=True, stable=True),
        )

    @pytest.mark.parametrize("n", [20, 18])
    @pytest.mark.parametrize("with_mask", [False, True])
    def test_conv_like(self, ref_attention_mod, n, with_mask):
        """Conv-like window attention incl. a partially-generated image
        (n < seq_len; the reference pads internally, attention.py:121-124)."""
        self._compare(
            ref_attention_mod.SparseConvCausalAttention,
            dict(attn_type="conv_like", image_fmap_size=4, kernel_size=3),
            seq_len=20, n=n,
            ref_kwargs=dict(image_size=4, kernel_size=3),
            with_mask=with_mask, internal_plus_one=True,
        )

    @pytest.mark.parametrize("axis, attn_type", [(0, "axial_row"), (1, "axial_col")])
    @pytest.mark.parametrize("with_mask", [False, True])
    def test_axial(self, ref_attention_mod, axis, attn_type, with_mask):
        self._compare(
            ref_attention_mod.SparseAxialCausalAttention,
            dict(attn_type=attn_type, image_fmap_size=4),
            seq_len=20, n=20,
            ref_kwargs=dict(image_size=4, axis=axis),
            with_mask=with_mask, internal_plus_one=True,
        )


@pytest.fixture(scope="module")
def ref_dalle_mod(stub_scope):
    """The reference dalle_pytorch.dalle_pytorch module (DALLE + CLIP),
    loaded as a package with its unavailable externals stubbed.

    Stub notes: dalle_pytorch.vae is replaced (its module-level taming/
    omegaconf imports are not installed; the VAE is unused when image token
    ids are passed directly), rotary/g-mlp stubs are never invoked
    (rotary_emb=False, no 'mlp' layers), and axial_positional_embedding is
    re-implemented with lucidrains' summed-axial semantics — image position
    embeddings are therefore parity-by-construction while everything else
    is genuinely cross-checked."""
    torch = pytest.importorskip("torch")
    from torch import nn

    return _load_ref_dalle(stub_scope, torch, nn)


def _load_ref_dalle(stub_scope, torch, nn):

        class AxialPositionalEmbedding(nn.Module):
            def __init__(self, dim, axial_shape, axial_dims=None):
                super().__init__()
                self.shape = axial_shape
                self.weights = nn.ParameterList([
                    nn.Parameter(torch.randn(1, axial_shape[0], 1, dim) * 0.02),
                    nn.Parameter(torch.randn(1, 1, axial_shape[1], dim) * 0.02),
                ])

            def forward(self, x):
                r, c = self.shape
                emb = (self.weights[0] + self.weights[1]).reshape(1, r * c, -1)
                return emb[:, : x.shape[1]].to(x)

        stub_scope.stub(
            "axial_positional_embedding",
            AxialPositionalEmbedding=AxialPositionalEmbedding,
        )
        rot = stub_scope.stub(
            "rotary_embedding_torch",
            RotaryEmbedding=object, broadcat=None, apply_rotary_emb=lambda f, t: t,
        )
        if rot is not None and not hasattr(rot, "RotaryEmbedding"):
            # stub created earlier by the attention fixture; extend it
            rot.RotaryEmbedding, rot.broadcat = object, None
        stub_scope.stub("g_mlp_pytorch", gMLPBlock=object)
        if "dalle_pytorch" not in sys.modules:
            pkg = types.ModuleType("dalle_pytorch")
            pkg.__path__ = ["/root/reference/dalle_pytorch"]
            pkg.__spec__ = importlib.machinery.ModuleSpec(
                "dalle_pytorch", loader=None, is_package=True
            )
            sys.modules["dalle_pytorch"] = pkg
            stub_scope.track("dalle_pytorch")
        # force: the real vae.py needs taming/omegaconf, and the VAE is
        # unused when image token ids are passed directly
        stub_scope.stub(
            "dalle_pytorch.vae", force=True,
            OpenAIDiscreteVAE=object, VQGanVAE=object,
        )
        import importlib as _il

        return _il.import_module("dalle_pytorch.dalle_pytorch")


def _T(a):
    """Torch Linear/Conv kernel -> flax layout transpose."""
    return np.ascontiguousarray(a.T)


def _np_state_dict(mod, skip_prefix=None):
    return {
        k: v.detach().numpy()
        for k, v in mod.state_dict().items()
        if skip_prefix is None or not k.startswith(skip_prefix)
    }


def _ref_layer_pair(sd, a, f, shifted):
    """Map one reference (attn, ff) layer pair into our param subtrees; the
    same mapping carries gradients (pure reindexing). ``shifted``: DALLE's
    transformer wraps blocks in PreShiftToken (one extra fn level on both
    sides); CLIP's does not."""
    T = _T
    mid = ".fn.fn.fn" if shifted else ".fn.fn"

    def wrap(inner):
        return {"fn": inner} if shifted else inner

    attn = {
        "scale": sd[f"{a}.scale"].reshape(-1),
        "fn": {
            "LayerNorm_0": {
                "scale": sd[f"{a}.fn.norm.weight"],
                "bias": sd[f"{a}.fn.norm.bias"],
            },
            "fn": wrap({
                "to_qkv": {"kernel": T(sd[f"{a}{mid}.to_qkv.weight"])},
                "to_out": {
                    "kernel": T(sd[f"{a}{mid}.to_out.0.weight"]),
                    "bias": sd[f"{a}{mid}.to_out.0.bias"],
                },
            }),
        },
    }
    ff = {
        "scale": sd[f"{f}.scale"].reshape(-1),
        "fn": {
            "LayerNorm_0": {
                "scale": sd[f"{f}.fn.norm.weight"],
                "bias": sd[f"{f}.fn.norm.bias"],
            },
            "fn": wrap({
                "Dense_0": {
                    "kernel": T(sd[f"{f}{mid}.net.0.weight"]),
                    "bias": sd[f"{f}{mid}.net.0.bias"],
                },
                "Dense_1": {
                    "kernel": T(sd[f"{f}{mid}.net.3.weight"]),
                    "bias": sd[f"{f}{mid}.net.3.bias"],
                },
            }),
        },
    }
    return attn, ff


class TestDALLEModelParity:
    """Full-model parity: load the reference DALLE (torch CPU), transplant
    EVERY weight into our DALLE, and require the same logits, loss, and
    gradients (see the ref_dalle_mod fixture for the stub notes)."""

    def _transplant(self, sd, depth, fmap, dim, reversible=False):
        """Reference state dict (numpy) -> our DALLE param tree. The same
        mapping carries gradients (same shapes, linear transforms)."""
        T = _T

        def layer(i):
            if reversible:  # ReversibleSequence wraps blocks as f/g streams
                a = f"transformer.layers.blocks.{i}.f.net"
                f = f"transformer.layers.blocks.{i}.g.net"
            else:
                a = f"transformer.layers.layers.{i}.0"
                f = f"transformer.layers.layers.{i}.1"
            return _ref_layer_pair(sd, a, f, shifted=True)

        transformer = {}
        for i in range(depth):
            a, f = layer(i)
            transformer[f"attn_{i}"] = a
            transformer[f"ff_{i}"] = f
        return {
            "text_emb": {"embedding": sd["text_emb.weight"]},
            "image_emb": {"embedding": sd["image_emb.weight"]},
            "text_pos_emb": {"embedding": sd["text_pos_emb.weight"]},
            "image_pos_emb": {
                "row_emb": sd["image_pos_emb.weights.0"].reshape(fmap, 1, dim),
                "col_emb": sd["image_pos_emb.weights.1"].reshape(1, fmap, dim),
            },
            "final_norm": {
                "scale": sd["to_logits.0.weight"],
                "bias": sd["to_logits.0.bias"],
            },
            "to_logits": {
                "kernel": T(sd["to_logits.1.weight"]),
                "bias": sd["to_logits.1.bias"],
            },
            "transformer": transformer,
        }

    @pytest.mark.parametrize(
        "attn_types, reversible",
        [
            (("full",), False),
            (("full", "axial_row"), False),
            (("conv_like", "axial_col"), False),
            (("full", "axial_row"), True),
        ],
    )
    def test_full_model_logits_loss_and_grads(
        self, ref_dalle_mod, attn_types, reversible
    ):
        import jax
        import jax.numpy as jnp
        import torch
        from torch import nn

        from dalle_pytorch_tpu.models import DALLE

        dim, depth, heads, dim_head, fmap = 32, 2, 2, 8, 4
        text_seq, n_text, n_image = 8, 64, 32

        class FakeVAE(nn.Module):
            def __init__(self):
                super().__init__()
                self.num_layers = 2
                self.image_size = 16
                self.num_tokens = n_image
                self.dummy = nn.Parameter(torch.zeros(1))

            def get_codebook_indices(self, img):  # pragma: no cover
                raise AssertionError("tokens are passed directly")

        torch.manual_seed(0)
        # train mode (all dropout is 0, so outputs are unaffected): the
        # reference's reversible Deterministic wrapper only records the RNG
        # state it replays in backward when module.training is set
        # (reversible.py:36-47)
        ref = ref_dalle_mod.DALLE(
            dim=dim, vae=FakeVAE(), num_text_tokens=n_text, text_seq_len=text_seq,
            depth=depth, heads=heads, dim_head=dim_head, attn_types=attn_types,
            rotary_emb=False, shift_tokens=True, reversible=reversible,
        ).train()

        rng = np.random.RandomState(0)
        text_np = rng.randint(1, n_text, size=(2, text_seq))
        text_np[0, -2:] = 0  # exercise the per-position pad-token remap
        image_np = rng.randint(0, n_image, size=(2, 16))
        text_t = torch.tensor(text_np, dtype=torch.long)
        image_t = torch.tensor(image_np, dtype=torch.long)

        with torch.no_grad():
            ref_logits = ref(text_t, image=image_t).numpy()
        ref_loss_t = ref(text_t, image=image_t, return_loss=True)
        ref_loss_t.backward()  # reference gradients for the parity below
        ref_loss = float(ref_loss_t.detach())

        sd = _np_state_dict(ref, skip_prefix="vae.")
        params = self._transplant(sd, depth, fmap, dim, reversible=reversible)

        ours = DALLE(
            dim=dim, depth=depth, num_text_tokens=n_text, text_seq_len=text_seq,
            num_image_tokens=n_image, image_fmap_size=fmap, heads=heads,
            dim_head=dim_head, attn_types=attn_types, rotary_emb=False,
            shift_tokens=True, use_flash=False, reversible=reversible,
        )
        text_j = jnp.asarray(text_np, jnp.int32)
        image_j = jnp.asarray(image_np, jnp.int32)
        our_logits = np.asarray(ours.apply({"params": params}, text_j, image_j))
        our_loss, our_grads = jax.value_and_grad(
            lambda p: ours.apply({"params": p}, text_j, image_j, return_loss=True)
        )(jax.tree_util.tree_map(jnp.asarray, params))

        # masked entries use different fill values (-finfo.max vs our
        # NEG_INF); compare the live entries and the loss
        live = ~ours.logits_mask_np()[None]
        np.testing.assert_allclose(
            our_logits[np.broadcast_to(live, our_logits.shape)],
            ref_logits[np.broadcast_to(live, ref_logits.shape)],
            atol=3e-4,
        )
        np.testing.assert_allclose(float(our_loss), ref_loss, atol=1e-4)

        # FULL gradient parity: the reference .grad tensors form a tree with
        # the same shapes as the weights, so the same transplant mapping
        # carries them into our param layout for leaf-by-leaf comparison
        ref_grads_sd = {
            k: (p.grad.detach().numpy() if p.grad is not None else None)
            for k, p in ref.named_parameters()
            if not k.startswith("vae.")
        }
        assert all(g is not None for g in ref_grads_sd.values())
        ref_grads = self._transplant(ref_grads_sd, depth, fmap, dim, reversible=reversible)
        flat_ours = jax.tree_util.tree_leaves_with_path(our_grads)
        flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
        assert len(flat_ours) == len(flat_ref)
        for (pa, a), (pb, b) in zip(flat_ours, flat_ref):
            assert pa == pb
            np.testing.assert_allclose(
                np.asarray(a), b, atol=2e-4,
                err_msg=f"gradient mismatch at {jax.tree_util.keystr(pa)}",
            )


class TestCLIPParity:
    """Reference CLIP (dalle_pytorch.py:229-305) vs ours with transplanted
    weights: similarity scores, contrastive loss, masked-mean pooling."""

    @pytest.mark.parametrize("with_mask", [False, True])
    def test_similarity_and_loss(self, ref_dalle_mod, with_mask):
        import jax.numpy as jnp
        import torch

        from dalle_pytorch_tpu.models import CLIP

        kw = dict(dim_text=32, dim_image=32, dim_latent=16, num_text_tokens=50,
                  text_enc_depth=2, text_seq_len=8, text_heads=2,
                  visual_enc_depth=2, visual_heads=2, visual_image_size=16,
                  visual_patch_size=8)
        torch.manual_seed(0)
        ref = ref_dalle_mod.CLIP(**kw).eval()

        rng = np.random.RandomState(0)
        text_np = rng.randint(0, 50, size=(3, 8))
        img_np = rng.rand(3, 3, 16, 16).astype(np.float32)  # NCHW for torch
        mask_np = (rng.rand(3, 8) > 0.3) if with_mask else None
        if mask_np is not None:
            mask_np[:, 0] = True

        t_text = torch.tensor(text_np, dtype=torch.long)
        t_img = torch.tensor(img_np)
        t_mask = None if mask_np is None else torch.tensor(mask_np)
        with torch.no_grad():
            ref_sim = ref(t_text, t_img, text_mask=t_mask).numpy()
            ref_loss = float(ref(t_text, t_img, text_mask=t_mask, return_loss=True))

        sd = _np_state_dict(ref)
        T = _T
        text_tf, visual_tf = {}, {}
        for i in range(2):
            for tf, prefix in ((text_tf, "text_transformer"),
                               (visual_tf, "visual_transformer")):
                a, f = _ref_layer_pair(
                    sd, f"{prefix}.layers.layers.{i}.0",
                    f"{prefix}.layers.layers.{i}.1", shifted=False,
                )
                tf[f"attn_{i}"], tf[f"ff_{i}"] = a, f
        params = {
            "text_emb": {"embedding": sd["text_emb.weight"]},
            "text_pos_emb": {"embedding": sd["text_pos_emb.weight"]},
            "text_transformer": text_tf,
            "to_text_latent": {"kernel": T(sd["to_text_latent.weight"])},
            "to_visual_embedding": {
                "kernel": T(sd["to_visual_embedding.weight"]),
                "bias": sd["to_visual_embedding.bias"],
            },
            "visual_pos_emb": {"embedding": sd["visual_pos_emb.weight"]},
            "visual_transformer": visual_tf,
            "to_visual_latent": {"kernel": T(sd["to_visual_latent.weight"])},
            "temperature": sd["temperature"],
        }

        ours = CLIP(**kw)
        j_img = jnp.asarray(np.transpose(img_np, (0, 2, 3, 1)))  # NHWC here
        j_mask = None if mask_np is None else jnp.asarray(mask_np)
        our_sim = np.asarray(
            ours.apply({"params": params}, jnp.asarray(text_np), j_img, j_mask)
        )
        our_loss = float(
            ours.apply(
                {"params": params}, jnp.asarray(text_np), j_img, j_mask,
                return_loss=True,
            )
        )
        np.testing.assert_allclose(our_sim, ref_sim, atol=2e-4)
        np.testing.assert_allclose(our_loss, ref_loss, atol=1e-4)


class TestDiscreteVAEParity:
    """Reference DiscreteVAE (dalle_pytorch.py:74-225) vs ours with
    transplanted conv stacks: the deterministic paths — encoder logits /
    codebook indices and decode — must match (the gumbel-sampled training
    forward is stochastic and is pinned by our own KL/recon tests)."""

    def _transplant(self, sd, num_layers, num_res):
        def conv(prefix):
            return {
                "kernel": np.ascontiguousarray(
                    np.transpose(sd[f"{prefix}.weight"], (2, 3, 1, 0))
                ),
                "bias": sd[f"{prefix}.bias"],
            }

        def convT(prefix):
            # torch ConvTranspose2d weight is (in, out, H, W) and applies the
            # kernel SPATIALLY FLIPPED relative to flax's ConvTranspose
            # (fractionally-strided correlation): transpose to (H, W, in,
            # out) then flip both spatial dims (verified: unflipped diverges
            # ~5e-2, flipped matches to ~3e-4)
            k = np.transpose(sd[f"{prefix}.weight"], (2, 3, 0, 1))
            return {
                "kernel": np.ascontiguousarray(k[::-1, ::-1]),
                "bias": sd[f"{prefix}.bias"],
            }

        def res(prefix):
            return {
                "Conv_0": conv(f"{prefix}.net.0"),
                "Conv_1": conv(f"{prefix}.net.2"),
                "Conv_2": conv(f"{prefix}.net.4"),
            }

        p = {"codebook": {"embedding": sd["codebook.weight"]}}
        for i in range(num_layers):
            p[f"enc_convs_{i}"] = conv(f"encoder.{i}.0")
            p[f"dec_convs_{i}"] = convT(f"decoder.{1 + num_res + i}.0")
        for j in range(num_res):
            p[f"enc_res_{j}"] = res(f"encoder.{num_layers + j}")
            p[f"dec_res_{j}"] = res(f"decoder.{1 + j}")
        p["enc_out"] = conv(f"encoder.{num_layers + num_res}")
        p["dec_in"] = conv("decoder.0")
        p["dec_out"] = conv(f"decoder.{1 + num_res + num_layers}")
        return p

    def test_encode_decode_match(self, ref_dalle_mod):
        import jax.numpy as jnp
        import torch

        from dalle_pytorch_tpu.models import DiscreteVAE

        kw = dict(image_size=16, num_tokens=24, codebook_dim=20, num_layers=2,
                  num_resnet_blocks=1, hidden_dim=12)
        torch.manual_seed(0)
        ref = ref_dalle_mod.DiscreteVAE(**kw).eval()

        rng = np.random.RandomState(0)
        img_np = rng.rand(2, 3, 16, 16).astype(np.float32)  # NCHW
        t_img = torch.tensor(img_np)
        with torch.no_grad():
            ref_logits = ref(t_img, return_logits=True).numpy()  # (b, T, h, w)
            ref_idx = ref.get_codebook_indices(t_img).numpy()
            ref_dec = ref.decode(torch.tensor(ref_idx)).numpy()  # NCHW

        sd = _np_state_dict(ref)
        params = self._transplant(sd, num_layers=2, num_res=1)
        ours = DiscreteVAE(**kw)

        j_img = jnp.asarray(np.transpose(img_np, (0, 2, 3, 1)))  # NHWC here
        our_idx = np.asarray(
            ours.apply({"params": params}, j_img,
                       method=DiscreteVAE.get_codebook_indices)
        )
        our_logits = np.asarray(
            ours.apply({"params": params}, j_img, return_logits=True)
        )  # NHWC: (b, h, w, T)
        our_dec = np.asarray(
            ours.apply({"params": params}, jnp.asarray(ref_idx),
                       method=DiscreteVAE.decode)
        )  # NHWC

        np.testing.assert_allclose(
            our_logits, np.transpose(ref_logits, (0, 2, 3, 1)), atol=2e-4
        )
        # indices come from argmax over identical logits; identical up to
        # float ties, which random weights make measure-zero
        np.testing.assert_array_equal(
            our_idx, ref_idx.reshape(our_idx.shape)
        )
        np.testing.assert_allclose(
            our_dec, np.transpose(ref_dec, (0, 2, 3, 1)), atol=2e-4
        )


class TestSchedulerParity:
    """The reference drives torch's stateful schedulers
    (train_dalle.py:429-441, train_vae.py:150-151); our host-side
    controllers must trace the same lr trajectories."""

    def test_reduce_lr_on_plateau_matches_torch(self):
        torch = pytest.importorskip("torch")

        from dalle_pytorch_tpu.utils import ReduceLROnPlateau

        lr0 = 3e-4
        kw = dict(factor=0.5, patience=3, cooldown=2, min_lr=1e-6,
                  threshold=1e-4)
        ours = ReduceLROnPlateau(lr0, **kw)
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=lr0)
        ref = torch.optim.lr_scheduler.ReduceLROnPlateau(
            opt, mode="min", **kw
        )

        rng = np.random.RandomState(0)
        # plateaus with improvement bursts, plus a hand-built prefix whose
        # improvements land INSIDE a cooldown window (steps 5-6 fall in the
        # cooldown opened by the step-4 reduction) — the case where torch
        # decrements the cooldown counter on improving steps and a naive
        # elif-ordered implementation diverges
        metrics = [5.0] * 5 + [4.0, 3.0] + [3.0] * 8
        level = 5.0
        for seg in range(8):
            if seg % 3 == 2:
                level *= 0.7  # improvement burst
            metrics += list(level + rng.rand(7) * 1e-6)
        for i, m in enumerate(metrics):
            our_lr = ours.step(float(m))
            ref.step(float(m))
            ref_lr = opt.param_groups[0]["lr"]
            assert our_lr == pytest.approx(ref_lr, rel=1e-9), (
                f"lr diverged at step {i}: ours {our_lr} vs torch {ref_lr}"
            )

    def test_exponential_decay_matches_torch(self):
        torch = pytest.importorskip("torch")

        from dalle_pytorch_tpu.utils import ExponentialDecay

        lr0, gamma = 1e-3, 0.98
        ours = ExponentialDecay(lr0, gamma=gamma)
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=lr0)
        ref = torch.optim.lr_scheduler.ExponentialLR(opt, gamma=gamma)
        for i in range(25):
            our_lr = ours.step()
            ref.step()
            assert our_lr == pytest.approx(opt.param_groups[0]["lr"], rel=1e-9)


def test_fuzz_against_reference(ref_tokenizer, ours):
    rng = np.random.RandomState(7)
    pools = [
        list(range(0x20, 0x7F)),
        list(range(0xA0, 0x250)),
        list(range(0x370, 0x400)),
        list(range(0x4E00, 0x4E40)),
        [0x1F600 + i for i in range(30)],
        [0x20, 0x27, 0x73, 0x74, 0x2E, 0x31],
    ]
    for _ in range(150):
        n = rng.randint(1, 50)
        text = "".join(
            chr(int(rng.choice(pools[rng.randint(len(pools))]))) for _ in range(n)
        )
        # keep inputs NFC so the cleaning pipelines (stubbed ftfy vs our
        # fallback) cannot diverge on normalization
        text = unicodedata.normalize("NFC", text)
        assert ours.encode(text) == ref_tokenizer.encode(text), repr(text)