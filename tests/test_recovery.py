"""Crash-recovery tests (docs/DESIGN.md §8.3): the durable request
journal, the persistent prefix-cache snapshot, replica resurrection, and
the chaos-soak subprocess gate — every mechanism pinned deterministically
on CPU.

The recovery contracts under test:

* journal replay is IDEMPOTENT (outcome records close replayed ids) and
  BIT-IDENTICAL (tokens depend only on (seed, position) fold-ins);
* a torn journal tail is detected, dropped, and counted — never parsed,
  never fatal; mid-file corruption is the typed ``JournalCorrupt``;
* a prefix snapshot is verify-on-load: manifest, shape, and recomputed
  chain digests — ANY failure rejects the WHOLE snapshot and the engine
  falls back cold (``snapshot_corrupt`` drill);
* a restored snapshot serves real prefix HITS bit-identical to cold;
* a killed replica respawns (DEAD → RESPAWNING → HEALTHY) and serves
  again, bit-identically; failed respawns back off and exhaust typed;
  a drained replica stays retired.

Same tiny model + page-size-2 override as tests/test_serving.py so the
terminal prompt page is partial (the snapshot must round-trip the COW
full-hit path too).
"""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    JournalCorrupt,
    Outcome,
    ReplicaState,
    Request,
    RequestJournal,
    Router,
    RouterConfig,
    replay_unfinished,
    request_from_record,
    request_to_record,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters
from dalle_pytorch_tpu.utils.resilience import (
    RetryPolicy,
    verify_file_manifest,
    write_dir_manifest,
)


@pytest.fixture(scope="module")
def model():
    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield
    FAULTS.reset()


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=prompt(i), max_new_tokens=max_new, **kw
    )


def reference_tokens(model, requests):
    """Fault-free oracle: the same requests on a clean chunked engine."""
    dalle, params = model
    eng = Engine(dalle, params, EngineConfig(max_batch=2, prefill_chunk=2))
    for r in requests:
        assert eng.submit(r) is None
    return {
        rid: np.asarray(res.tokens)
        for rid, res in eng.run(max_steps=2000).items()
    }


# ------------------------------------------------------------- journal


class TestJournal:
    def test_record_roundtrip(self):
        r = req(7, deadline=12.5, priority=2)
        back = request_from_record(request_to_record(r, now=1.0))
        assert back.request_id == r.request_id
        assert np.array_equal(back.prompt, r.prompt)
        assert back.max_new_tokens == r.max_new_tokens
        assert back.deadline == r.deadline
        assert back.priority == r.priority
        assert back.seed == r.seed

    def test_deadline_rebased_onto_restarted_clock(self):
        """A journaled deadline is an instant on the DEAD process's
        monotonic clock; replay must rebase the remaining budget onto
        the restarted clock, not reuse the stale absolute value."""
        r = req(0, deadline=30.0)  # admitted at t=10 -> 20s remaining
        rec = request_to_record(r, now=10.0)
        assert rec["deadline_remaining"] == 20.0
        rebased = request_from_record(rec, now=1000.0)
        assert rebased.deadline == 1020.0
        # without a clock (same-process tests) the absolute value holds
        assert request_from_record(rec).deadline == 30.0
        # deadline-free requests stay deadline-free either way
        rec2 = request_to_record(req(1), now=10.0)
        assert request_from_record(rec2, now=1000.0).deadline is None

    def test_unfinished_is_idempotent(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.append_admitted(req(1), now=0.1)
        j.append_outcome("r0", "completed", now=1.0)
        j.close()
        unfinished = RequestJournal.unfinished(p)
        assert [r.request_id for r in unfinished] == ["r1"]
        # replaying re-appends r1; once its outcome lands, nothing is left
        j2 = RequestJournal(p)
        replayed = replay_unfinished(p, lambda r: j2.append_admitted(r, 2.0))
        assert replayed == ["r1"]
        j2.append_outcome("r1", "completed", now=3.0)
        j2.close()
        assert RequestJournal.unfinished(p) == []
        assert RequestJournal.outcomes(p) == {
            "r0": "completed", "r1": "completed",
        }

    def test_torn_tail_dropped_and_counted(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.append_admitted(req(1), now=0.1)
        j.close()
        # crash mid-append: the tail record loses its last bytes
        data = open(p, "rb").read()
        open(p, "wb").write(data[:-7])
        torn0 = counters.get("serve.journal.torn")
        records, torn = RequestJournal.load(p)
        assert torn == 1
        assert counters.get("serve.journal.torn") == torn0 + 1
        assert [r["request_id"] for r in records] == ["r0"]
        # the torn admission is simply not in the replay set
        assert [r.request_id for r in RequestJournal.unfinished(p)] == ["r0"]

    def test_journal_torn_fault_drill(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.append_admitted(req(1), now=0.1)
        j.close()
        FAULTS.arm("journal_torn", 1)
        fault0 = counters.get("serve.fault_journal_torn")
        records, torn = RequestJournal.load(p)
        assert torn == 1
        assert [r["request_id"] for r in records] == ["r0"]
        assert counters.get("serve.fault_journal_torn") == fault0 + 1
        # the budget is spent: the next load sees the intact file
        records, torn = RequestJournal.load(p)
        assert torn == 0 and len(records) == 2

    def test_torn_tail_counted_once_across_recovery_reads(self, tmp_path):
        """One real torn tail moves serve.journal.torn by exactly ONE
        through a full recovery (reconcile reads outcomes, replay reads
        unfinished, tools re-scan) — secondary reads never re-count."""
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.append_outcome("r0", "completed", now=0.5)
        j.append_admitted(req(1), now=1.0)
        j.close()
        data = open(p, "rb").read()
        open(p, "wb").write(data[:-7])
        torn0 = counters.get("serve.journal.torn")
        seen = {}
        replayed = replay_unfinished(
            p, lambda r: None, reconcile=seen.__setitem__,
        )
        assert replayed == [] and seen == {"r0": "completed"}
        assert counters.get("serve.journal.torn") == torn0 + 1
        # inspection reads leave the counter alone
        RequestJournal.verify(p)
        RequestJournal.outcomes(p)
        RequestJournal.unfinished(p, count=False)
        assert counters.get("serve.journal.torn") == torn0 + 1

    def test_midfile_corruption_raises_typed(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.append_admitted(req(1), now=0.1)
        j.append_admitted(req(2), now=0.2)
        j.close()
        lines = open(p).read().splitlines()
        lines[0] = lines[0][:10]  # bit rot on a NON-tail record
        open(p, "w").write("\n".join(lines) + "\n")
        with pytest.raises(JournalCorrupt):
            RequestJournal.load(p)
        ok, reason = RequestJournal.verify(p)
        assert not ok and "unparseable" in reason

    def test_seal_writes_manifest_and_verify(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.append_admitted(req(0), now=0.0)
        j.seal()
        assert verify_file_manifest(p)[0]
        ok, reason = RequestJournal.verify(p)
        assert ok and reason == "ok"
        # an unsealed (crashed) journal still verifies, flagged as such
        j2 = RequestJournal(p)
        j2.append_admitted(req(1), now=1.0)
        j2.close()
        ok, reason = RequestJournal.verify(p)
        assert ok and "unsealed" in reason


# ------------------------------------------------- prefix-cache snapshot


def run_prefix_engine(model, requests, snapshot_dir=None, load_from=None,
                      **eng_kw):
    """One prefix-enabled engine run; optionally snapshot after, or
    verify-load a snapshot before. Returns (engine, results, restored)."""
    dalle, params = model
    eng = Engine(dalle, params, EngineConfig(
        max_batch=2, prefill_chunk=2, prefix_cache=True, **eng_kw
    ))
    restored = None
    if load_from is not None:
        restored = eng.load_prefix_snapshot(load_from)
    for r in requests:
        assert eng.submit(r) is None
    results = eng.run(max_steps=2000)
    eng.verify_invariants(idle=True)
    if snapshot_dir is not None:
        eng.save_prefix_snapshot(snapshot_dir)
    return eng, results, restored


class TestSnapshot:
    def test_roundtrip_warm_hit_bit_identical(self, model, tmp_path):
        snap = str(tmp_path / "prefix_snapshot")
        cold_req = req(0, seed=11)
        _, cold_res, _ = run_prefix_engine(
            model, [cold_req], snapshot_dir=snap
        )
        # a fresh engine restores the snapshot; the same prompt under a
        # NEW seed must be a full-prefix hit and bit-match its own cold
        # reference (prefix reuse shares K/V, never token streams)
        warm_req = Request(
            request_id="warm", prompt=prompt(0), max_new_tokens=4, seed=77,
        )
        ref = reference_tokens(model, [Request(
            request_id="warm", prompt=prompt(0), max_new_tokens=4, seed=77,
        )])
        restored0 = counters.get("serve.snapshot.restored")
        eng, res, restored = run_prefix_engine(
            model, [warm_req], load_from=snap
        )
        assert restored is True
        assert counters.get("serve.snapshot.restored") == restored0 + 1
        assert eng.prefix.stats.hits >= 1, "restored snapshot never hit"
        assert res["warm"].outcome is Outcome.COMPLETED
        assert np.array_equal(np.asarray(res["warm"].tokens), ref["warm"])

    def test_snapshot_corrupt_rejects_to_cold(self, model, tmp_path):
        snap = str(tmp_path / "prefix_snapshot")
        run_prefix_engine(model, [req(0, seed=11)], snapshot_dir=snap)
        FAULTS.arm("snapshot_corrupt", 1)
        rejected0 = counters.get("serve.snapshot.rejected")
        fault0 = counters.get("serve.fault_snapshot_corrupt")
        ref = reference_tokens(model, [req(3, seed=33)])
        eng, res, restored = run_prefix_engine(
            model, [req(3, seed=33)], load_from=snap
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1
        assert counters.get("serve.fault_snapshot_corrupt") == fault0 + 1
        # cold fallback still serves, bit-identically
        assert res["r3"].outcome is Outcome.COMPLETED
        assert np.array_equal(np.asarray(res["r3"].tokens), ref["r3"])

    def test_uncommitted_dir_rejected(self, model, tmp_path):
        snap = tmp_path / "prefix_snapshot"
        run_prefix_engine(model, [req(0, seed=11)], snapshot_dir=str(snap))
        (snap / "COMMITTED").unlink()  # the torn-save shape
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=str(snap)
        )
        assert restored is False

    def test_duplicate_and_incoherent_snapshots_reject_typed(
        self, model, tmp_path
    ):
        """Snapshots that would crash the restore phase (duplicate chain
        nodes, payload arrays missing, foreign cache dtype) must reject
        typed at verify-on-load — never raise mid-build."""
        from dalle_pytorch_tpu.serving.prefix_cache import (
            verify_snapshot_records,
        )

        snap = tmp_path / "prefix_snapshot"
        run_prefix_engine(model, [req(0, seed=11)], snapshot_dir=str(snap))
        index = json.loads((snap / "index.json").read_text())
        # duplicate chain node: insert would die on dedup-on-insert
        ok, reason = verify_snapshot_records(
            [index["nodes"][0], dict(index["nodes"][0])],
            int(index["page_size"]),
        )
        assert not ok and "duplicate" in reason
        # foreign cache dtype: a cast restore would fake warm parity
        tampered = dict(index)
        tampered["dtypes"] = dict(index["dtypes"])
        tampered["dtypes"]["pages_l0"] = "float16"
        (snap / "index.json").write_text(
            json.dumps(tampered, sort_keys=True)
        )
        write_dir_manifest(str(snap))
        rejected0 = counters.get("serve.snapshot.rejected")
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=str(snap)
        )
        assert restored is False
        # payload array missing (has_ring promised, ring arrays absent)
        import numpy as onp
        with onp.load(snap / "arrays.npz") as z:
            kept = {k: z[k] for k in z.files if not k.startswith("ring")}
        onp.savez(snap / "arrays.npz", **kept)
        (snap / "index.json").write_text(json.dumps(index, sort_keys=True))
        write_dir_manifest(str(snap))
        _, _, restored = run_prefix_engine(
            model, [req(2, seed=23)], load_from=str(snap)
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 2

    def test_chain_digest_catches_re_manifested_tamper(self, model, tmp_path):
        """The manifest covers bytes; the chain digests cover MEANING: a
        tampered index whose manifest was regenerated still fails the
        mandatory recompute."""
        snap = tmp_path / "prefix_snapshot"
        run_prefix_engine(model, [req(0, seed=11)], snapshot_dir=str(snap))
        index = json.loads((snap / "index.json").read_text())
        index["nodes"][0]["tokens"][0] += 1
        (snap / "index.json").write_text(json.dumps(index, sort_keys=True))
        write_dir_manifest(str(snap))  # "clean" manifest over bad data
        rejected0 = counters.get("serve.snapshot.rejected")
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=str(snap)
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1


# ------------------------------------- quantized-arena snapshot (ISSUE 14)


class TestQuantSnapshot:
    """Snapshot round-trips for QUANTIZED arenas: int8 page bytes and
    f32 scale arrays persist dtype-exact, verify-on-load rejects a
    scale/page length mismatch, a foreign-dtype cast restore, and a
    re-manifested payload tamper (typed reject-to-cold, never a
    mid-restore crash), and a cross-format restore misses at the
    format tag. Restored warm hits are bit-identical to the quantized
    cold run."""

    def _snap(self, model, tmp_path):
        snap = str(tmp_path / "prefix_snapshot")
        _, cold_res, _ = run_prefix_engine(
            model, [req(0, seed=11)], snapshot_dir=snap, kv_quant="int8"
        )
        return snap, cold_res

    def test_roundtrip_dtype_exact_warm_hit_bit_identical(
        self, model, tmp_path
    ):
        snap, _ = self._snap(model, tmp_path)
        index = json.loads(
            (tmp_path / "prefix_snapshot" / "index.json").read_text()
        )
        # the persisted dtypes are the quantized reality, dtype-exact:
        # int8 content pools AND f32 scale pools, under a non-empty
        # format tag
        page_dtypes = sorted({
            v for k, v in index["dtypes"].items() if k.startswith("pages_")
        })
        assert "int8" in page_dtypes and "float32" in page_dtypes
        assert index["kv_format"].startswith("kv:int8:")
        scale_leaves = [
            p for p in index["leaf_paths"] if "scale_pages" in p
        ]
        assert len(scale_leaves) >= 2, index["leaf_paths"]
        # every record carries its payload content digest
        assert all("content_sha256" in r for r in index["nodes"])
        warm_req = Request(
            request_id="warm", prompt=prompt(0), max_new_tokens=4, seed=77,
        )
        ref_eng = Engine(model[0], model[1], EngineConfig(
            max_batch=2, prefill_chunk=2, kv_quant="int8",
        ))
        assert ref_eng.submit(Request(
            request_id="warm", prompt=prompt(0), max_new_tokens=4, seed=77,
        )) is None
        ref = np.asarray(ref_eng.run(max_steps=2000)["warm"].tokens)
        eng, res, restored = run_prefix_engine(
            model, [warm_req], load_from=snap, kv_quant="int8"
        )
        assert restored is True
        assert eng.prefix.stats.hits >= 1, "restored quant arena never hit"
        np.testing.assert_array_equal(np.asarray(res["warm"].tokens), ref)

    def test_cross_format_restore_rejected(self, model, tmp_path):
        snap, _ = self._snap(model, tmp_path)
        rejected0 = counters.get("serve.snapshot.rejected")
        # a quantized snapshot offered to an UNQUANTIZED engine must
        # reject typed (format tag mismatch), never cast int8 bytes
        # into f32 pools as "verified" warm K/V
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=snap
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1

    def test_foreign_dtype_cast_rejected(self, model, tmp_path):
        snap, _ = self._snap(model, tmp_path)
        sp = tmp_path / "prefix_snapshot"
        index = json.loads((sp / "index.json").read_text())
        scale_key = next(
            f"pages_l{j}" for j, p in enumerate(index["leaf_paths"])
            if "scale_pages" in p
        )
        index["dtypes"][scale_key] = "float16"
        (sp / "index.json").write_text(json.dumps(index, sort_keys=True))
        write_dir_manifest(str(sp))
        rejected0 = counters.get("serve.snapshot.rejected")
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=snap, kv_quant="int8"
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1

    def test_scale_length_mismatch_rejected(self, model, tmp_path):
        snap, _ = self._snap(model, tmp_path)
        sp = tmp_path / "prefix_snapshot"
        index = json.loads((sp / "index.json").read_text())
        scale_key = next(
            f"pages_l{j}" for j, p in enumerate(index["leaf_paths"])
            if "scale_pages" in p
        )
        with np.load(sp / "arrays.npz") as z:
            arrays = {k: z[k] for k in z.files}
        arrays[scale_key] = arrays[scale_key][:-1]  # drop one node's scales
        np.savez(sp / "arrays.npz", **arrays)
        write_dir_manifest(str(sp))
        rejected0 = counters.get("serve.snapshot.rejected")
        _, _, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=snap, kv_quant="int8"
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1

    def test_content_digest_catches_re_manifested_scale_tamper(
        self, model, tmp_path
    ):
        """The manifest covers files, the chain digest covers tokens —
        a flipped SCALE byte behind a regenerated manifest is caught by
        the per-node content digest (forged scales would dequantize
        shared pages to wrong values while every token check passes)."""
        snap, _ = self._snap(model, tmp_path)
        sp = tmp_path / "prefix_snapshot"
        index = json.loads((sp / "index.json").read_text())
        scale_key = next(
            f"pages_l{j}" for j, p in enumerate(index["leaf_paths"])
            if "scale_pages" in p
        )
        with np.load(sp / "arrays.npz") as z:
            arrays = {k: z[k] for k in z.files}
        tampered = arrays[scale_key].copy()
        tampered.reshape(-1)[0] ^= 0xFF  # one scale byte flips
        arrays[scale_key] = tampered
        np.savez(sp / "arrays.npz", **arrays)
        write_dir_manifest(str(sp))  # "clean" manifest over forged scales
        rejected0 = counters.get("serve.snapshot.rejected")
        ref = reference_tokens(model, [req(1, seed=22)])
        eng, res, restored = run_prefix_engine(
            model, [req(1, seed=22)], load_from=snap, kv_quant="int8"
        )
        assert restored is False
        assert counters.get("serve.snapshot.rejected") == rejected0 + 1
        # reject-to-cold still serves; agreement with the f32 oracle is
        # not asserted here (quant engine) — completion + typed reject is
        assert res["r1"].outcome is Outcome.COMPLETED
        assert ref  # oracle computed; the engine ran cold past the reject


# ------------------------------------------------------------- respawn


def make_router(model, n=2, clock=None, journal=None, router_kw=None,
                **eng_kw):
    dalle, params = model
    eng_kw.setdefault("max_batch", 2)
    eng_kw.setdefault("prefill_chunk", 2)
    kw = {"n_replicas": n, "respawn": True}
    kw.update(router_kw or {})
    return Router(
        dalle, params, RouterConfig(**kw), EngineConfig(**eng_kw),
        clock=clock or FakeClock(step_dt=0.1), journal=journal,
    )


class TestRespawn:
    def test_killed_replica_respawns_and_serves_bit_identical(self, model):
        requests = [req(i, seed=40 + i) for i in range(4)]
        ref = reference_tokens(model, requests)
        router = make_router(model, n=2)
        respawns0 = counters.get("router.respawns")
        for r in requests:
            assert router.submit(r) is None
        steps, killed = 0, False
        while router.step():
            steps += 1
            assert steps < 3000
            if not killed and steps == 3:
                FAULTS.arm("replica_crash", 1)
                killed = True
        # idle steps let the backoff expire and the rebuild fire (it may
        # already have fired mid-run — the baseline predates the kill)
        for _ in range(40):
            router.step()
        router.verify_invariants()
        assert counters.get("router.respawns") == respawns0 + 1
        states = router.replica_states()
        assert set(states.values()) == {ReplicaState.HEALTHY.value}, states
        for r in requests:
            res = router.results[r.request_id]
            assert res.outcome is Outcome.COMPLETED
            assert np.array_equal(
                np.asarray(res.tokens), ref[r.request_id]
            ), f"{r.request_id} diverged across kill/failover"
        # the resurrected replica accepts and serves new work
        post = req(9, seed=99)
        assert router.submit(post) is None
        res = router.run(max_steps=2000)["r9"]
        assert res.outcome is Outcome.COMPLETED
        router.verify_invariants()

    def test_respawning_holds_queue_until_fleet_returns(self, model):
        """A 1-replica fleet whose replica dies does NOT flush queued
        work typed while a respawn is pending — the work waits and
        completes after resurrection."""
        router = make_router(model, n=1)
        router.kill(0, reason="test_crash")
        assert router.replica_states()[0] == ReplicaState.RESPAWNING.value
        r = req(0, seed=5)
        assert router.submit(r) is None  # queued, not no_replica-rejected
        res = router.run(max_steps=3000)["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert counters.get("router.respawns") >= 1
        router.verify_invariants()

    def test_respawn_fail_backs_off_then_exhausts_typed(self, model):
        router = make_router(
            model, n=1,
            router_kw={
                "max_respawns": 2,
                "respawn_backoff": RetryPolicy(
                    attempts=3, base_delay=0.2, max_delay=5.0,
                    jitter=0.0, retry_on=(),
                ),
            },
        )
        FAULTS.arm("replica_respawn_fail", 5)
        fault0 = counters.get("router.fault_replica_respawn_fail")
        router.kill(0, reason="test_crash")
        for _ in range(200):
            router.step()
        assert router.replica_states()[0] == ReplicaState.DEAD.value
        assert counters.get("router.fault_replica_respawn_fail") == fault0 + 2
        info = router.stats()["replicas"][0]
        assert "respawns exhausted" in info["death_reason"]
        # a permanently dead fleet rejects typed, immediately
        result = router.submit(req(0))
        assert result is not None
        assert result.outcome is Outcome.REJECTED

    def test_drain_of_respawning_replica_retires_it(self, model):
        """drain() on a RESPAWNING replica must cancel the pending
        respawn and retire it — never re-activate the abandoned stale
        engine (whose in-flight work already failed over)."""
        router = make_router(model, n=2)
        for i in range(2):
            assert router.submit(req(i, seed=80 + i)) is None
        router.step()  # work in flight on replica 0 or 1
        victim = max(
            router._replicas, key=lambda r: len(r.inflight)
        ).id
        router.kill(victim, reason="test_crash")
        assert router.replica_states()[victim] == (
            ReplicaState.RESPAWNING.value
        )
        router.drain(victim)
        assert router.replica_states()[victim] == ReplicaState.DEAD.value
        assert router.stats()["replicas"][victim]["death_reason"] == (
            "drained"
        )
        # the retirement sticks (no respawn fires) and the fleet stays
        # consistent: invariants clean, all work completes on siblings
        results = router.run(max_steps=3000)
        for _ in range(40):
            router.step()
        router.verify_invariants()
        assert router.replica_states()[victim] == ReplicaState.DEAD.value
        assert all(
            res.outcome is Outcome.COMPLETED for res in results.values()
        )

    def test_drained_replica_is_retired_not_respawned(self, model):
        router = make_router(model, n=2)
        router.drain(0)
        for _ in range(30):
            router.step()
        states = router.replica_states()
        assert states[0] == ReplicaState.DEAD.value
        assert router.stats()["replicas"][0]["death_reason"] == "drained"
        # still dead after plenty of backoff time: drains are retirement
        for _ in range(60):
            router.step()
        assert router.replica_states()[0] == ReplicaState.DEAD.value


# ------------------------------------------- process restart (journal)


class TestRestartReplay:
    def test_restart_replays_unfinished_with_warm_hit(self, model, tmp_path):
        jpath = str(tmp_path / "journal.jsonl")
        snap = str(tmp_path / "prefix_snapshot")
        cold = req(0, seed=60)
        # the crash-set request reuses prompt(0): its post-restart
        # replay must hit the RESTORED arena
        crash = Request(
            request_id="crash", prompt=prompt(0), max_new_tokens=4, seed=61,
        )
        ref = reference_tokens(model, [Request(
            request_id="crash", prompt=prompt(0), max_new_tokens=4, seed=61,
        )])
        router = make_router(
            model, n=1, journal=RequestJournal(jpath), prefix_cache=True,
        )
        assert router.submit(cold) is None
        router.run(max_steps=2000)
        router._replicas[0].engine.save_prefix_snapshot(snap)
        assert router.submit(crash) is None
        router.step()  # in flight...
        router._journal.close()  # ...and the process dies

        router2 = make_router(
            model, n=1, journal=RequestJournal(jpath), prefix_cache=True,
        )
        eng2 = router2._replicas[0].engine
        assert eng2.load_prefix_snapshot(snap)
        replayed = replay_unfinished(jpath, router2.submit)
        assert replayed == ["crash"]
        res = router2.run(max_steps=2000)["crash"]
        router2.verify_invariants()
        assert res.outcome is Outcome.COMPLETED
        assert np.array_equal(np.asarray(res.tokens), ref["crash"])
        assert eng2.prefix.stats.hits >= 1, (
            "replayed request missed the restored snapshot"
        )
        # idempotency: the finished request does not replay again
        router2._journal.seal()
        assert RequestJournal.unfinished(jpath) == []

    def test_shutdown_flushes_snapshot_and_leaves_queue_journaled(
        self, model, tmp_path
    ):
        """The SIGTERM path with work IN FLIGHT: shutdown() must finish
        in-flight requests, save the prefix snapshot (the drained
        replica's index is intact and eligible), seal the journal, and
        leave still-queued requests journaled-unfinished for the next
        incarnation — never flushed typed, never snapshot-skipped."""
        jpath = str(tmp_path / "journal.jsonl")
        snap = tmp_path / "prefix_snapshot"
        router = make_router(
            model, n=1, journal=RequestJournal(jpath),
            prefix_cache=True, max_batch=1,
        )
        for i in range(3):
            assert router.submit(req(i, seed=70 + i)) is None
        router.step()  # r0 in flight, r1/r2 queued at the router
        router.shutdown(snapshot_dir=str(snap))
        # in-flight work finished and was journaled terminal
        assert router.results["r0"].outcome is Outcome.COMPLETED
        # the drained (DEAD) replica's non-empty index WAS snapshotted
        assert (snap / "COMMITTED").exists()
        index = json.loads((snap / "index.json").read_text())
        assert len(index["nodes"]) >= 1
        # journal sealed; queued work stays unfinished (not flushed)
        ok, reason = RequestJournal.verify(jpath)
        assert ok and reason == "ok"
        assert sorted(
            r.request_id for r in RequestJournal.unfinished(jpath)
        ) == ["r1", "r2"]
        assert "r1" not in router.results and "r2" not in router.results
        # the next incarnation restores warm and replays both
        router2 = make_router(
            model, n=1, journal=RequestJournal(jpath), prefix_cache=True,
        )
        assert router2._replicas[0].engine.load_prefix_snapshot(str(snap))
        replayed = replay_unfinished(jpath, router2.submit)
        assert sorted(replayed) == ["r1", "r2"]
        results = router2.run(max_steps=2000)
        assert all(
            results[rid].outcome is Outcome.COMPLETED
            for rid in ("r1", "r2")
        )
        router2.verify_invariants()

    def test_live_requests_export(self, model):
        dalle, params = model
        eng = Engine(dalle, params, EngineConfig(
            max_batch=1, prefill_chunk=2, queue_limit=4,
        ))
        for i in range(3):
            assert eng.submit(req(i, seed=i)) is None
        eng.step()  # r0 admitted, r1/r2 queued
        live = eng.live_requests()
        assert [r.request_id for r in live] == ["r1", "r2", "r0"]
        router = make_router(model, n=1, max_batch=1)
        for i in range(3):
            assert router.submit(req(i, seed=i)) is None
        router.step()
        ids = [r.request_id for r in router.live_requests()]
        assert set(ids) == {"r0", "r1", "r2"}
        router.run(max_steps=2000)
        assert router.live_requests() == []


# --------------------------------------------------- chaos soak gates


def test_chaos_mini_soak_subprocess_gate():
    """The fast-tier chaos gate: a seeded, bounded randomized fault
    schedule (all serving sites + replica kill/respawn/process restart)
    must end with 100% typed outcomes and bit-identical survivors."""
    out = subprocess.run(
        [sys.executable, "tools/chaos_soak.py",
         "--iters", "40", "--requests", "4",
         "--restart-every", "18", "--snap-every", "9", "--seed", "0"],
        capture_output=True, text=True, cwd=".",
    )
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    summary = json.loads(out.stdout)
    assert summary["ok"] is True
    assert summary["completed_bit_identical"] is True
    assert summary["restarts"] >= 1
    assert sum(summary["outcomes"].values()) == summary["submitted"]


@pytest.mark.slow
def test_chaos_soak_long_subprocess_gate():
    out = subprocess.run(
        [sys.executable, "tools/chaos_soak.py",
         "--iters", "400", "--requests", "12",
         "--restart-every", "60", "--snap-every", "20", "--seed", "1"],
        capture_output=True, text=True, cwd=".",
    )
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    summary = json.loads(out.stdout)
    assert summary["ok"] is True
    assert summary["outcomes"].get("completed", 0) >= 1
