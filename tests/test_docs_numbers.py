"""Docs must cite the benchmark record, not a remembered round.

The round-4 advisor found README / DESIGN / PARITY citing three different
rounds' serving numbers. The fix: docs/BENCH_LATEST.jsonl is the single
source of truth and tools/sync_bench_docs.py regenerates the marked doc
blocks from it — this test fails the suite when the blocks drift."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_doc_numbers_match_bench_record():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "sync_bench_docs.py"), "--check"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"docs drifted from docs/BENCH_LATEST.jsonl:\n{proc.stdout}{proc.stderr}"
    )
