"""Always-on optimized-XLA smoke subset.

conftest.py runs the whole suite under JAX_DISABLE_MOST_OPTIMIZATIONS=1
(a measured ~35% compile-time win for the compile-dominated suite), which
means every other parity test exercises the UNOPTIMIZED XLA pipeline while
bench.py/serving run fully optimized — a miscompile or numerical
divergence introduced by XLA's optimization passes (exactly the bug class
the parity suite exists to catch) would pass CI undetected (ADVICE.md
round 5). This file is the counterweight: one decode-parity and one
attention-parity case re-run with the optimization pipeline ENABLED, every
run, kept tiny so they stay in tier-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE, generate_image_tokens
from dalle_pytorch_tpu.ops.attention import PatternAttention


@pytest.fixture
def optimized_xla():
    """Flip the process-wide config to the optimized pipeline for one test;
    clear compiled-program caches on both edges so nothing compiled under
    the other setting is reused."""
    prev = jax.config.read("jax_disable_most_optimizations")
    jax.config.update("jax_disable_most_optimizations", False)
    jax.clear_caches()
    try:
        yield
    finally:
        jax.config.update("jax_disable_most_optimizations", prev)
        jax.clear_caches()


def test_decode_parity_with_optimizations_enabled(optimized_xla):
    """KV-cached decode (prefill + scan, the serving path) vs the full
    forward pass, under the optimized XLA pipeline: the logits argmax chain
    that picks every sampled token must agree with the parallel forward."""
    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full", "axial_row"),
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]

    full_logits = np.asarray(dalle.apply({"params": params}, text, image))
    internal = np.concatenate(
        (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
    )
    from dalle_pytorch_tpu.models import init_decode_cache

    cache = init_decode_cache(dalle, params, 2)
    for i in range(dalle.total_seq_len):
        step_logits, mutated = dalle.apply(
            {"params": params, "cache": cache},
            jnp.asarray(internal[:, i]),
            jnp.array(i, jnp.int32),
            method=DALLE.decode_step,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits), full_logits[:, i], atol=2e-3, rtol=1e-3,
            err_msg=f"optimized-XLA decode/forward mismatch at position {i}",
        )
    # the end-to-end sampler also runs (prefill + segmented scan compile
    # under the optimized pipeline) and stays in-vocab
    toks = np.asarray(generate_image_tokens(dalle, params, text, jax.random.key(1)))
    assert ((toks >= 0) & (toks < dalle.num_image_tokens)).all()


def test_serving_decode_parity_with_optimizations_enabled(optimized_xla):
    """The serving path's pinned contract — chunked prefill bit-identical
    to monolithic — re-run with the optimization pipeline ENABLED: the
    continuous-batching engine's prefill/decode programs (the ones
    bench.py and production serving actually compile) must sample the
    same tokens either way (ADVICE.md round 5: every other serving test
    runs unoptimized)."""
    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, Request,
    )

    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]

    def serve(prefill_chunk):
        eng = Engine(
            dalle, params,
            EngineConfig(max_batch=2, prefill_chunk=prefill_chunk),
            clock=FakeClock(step_dt=1.0),
        )
        for i in range(2):
            assert eng.submit(Request(
                request_id=f"o{i}",
                prompt=rng.__class__(100 + i).randint(
                    1, 16, size=(4,)).astype(np.int32),
                max_new_tokens=4, seed=i,
            )) is None
        eng.run(max_steps=200)
        for r in eng.results.values():
            assert r.outcome is Outcome.COMPLETED, r
        return {k: np.asarray(r.tokens) for k, r in eng.results.items()}

    mono = serve(prefill_chunk=None)
    chunked = serve(prefill_chunk=2)
    assert mono.keys() == chunked.keys()
    for rid in mono:
        np.testing.assert_array_equal(
            mono[rid], chunked[rid],
            err_msg=f"{rid}: optimized-XLA serving chunked/monolithic "
                    "divergence",
        )


@pytest.mark.parametrize("attn_type", ["axial_row", "conv_like"])
def test_attention_parity_with_optimizations_enabled(optimized_xla, attn_type):
    """Grouped FLOP-efficient attention vs the dense-masked oracle under
    the optimized XLA pipeline."""
    attn = PatternAttention(
        dim=32, seq_len=21, attn_type=attn_type, heads=2, dim_head=16,
        image_fmap_size=4,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 20, 32))
    params = attn.init(jax.random.PRNGKey(1), x)
    eff = attn.apply(params, x)
    dense = attn.apply(params, x, force_dense=True)
    np.testing.assert_allclose(np.asarray(eff), np.asarray(dense), atol=2e-5)
