"""Adaptive control loop (serving/control.py + engine wiring, ISSUE 19).

Two layers. The PURE layer pins the Controller as a deterministic
function: ladder steps with explicit hysteresis, noise gates, the
``control_stall`` raise, and same-inputs -> same-decision-sequence. The
ENGINE layer pins the contracts that make runtime adaptation safe at
all: a controller-on engine (forced-low accept via the misdrafting
depth-1 drafter) steps the effective spec_k DOWN while producing tokens
BIT-IDENTICAL to the controller-off engine (every knob channel is data
to the jits — exact-match acceptance absorbs any verify width, budget
swaps keep the chunk width), with ZERO new jit signatures; and the
``control_stall`` drill degrades to static defaults with 100% typed
accounting, never touching decode progress.

Page size 2 (env override) as in tests/test_spec_decode.py, so verify
blocks cross page boundaries mid-block.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.serving import (
    ControlConfig,
    Controller,
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
    check_accounting,
)
from dalle_pytorch_tpu.serving import engine as engine_mod
from dalle_pytorch_tpu.serving.control import ControlStall
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


@pytest.fixture(scope="module")
def deep_model():
    """Depth-4 stack whose depth-1 early-exit drafter genuinely
    misdrafts (~0.3 accept rate on this geometry) — the forced-low
    accept signal the spec ladder reacts to."""
    dalle = DALLE(
        dim=32, depth=4, num_text_tokens=32, text_seq_len=6,
        num_image_tokens=64, image_fmap_size=4, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 32, size=(1, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 64, size=(1, 16)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


def vit(**kw):
    """A full vitals snapshot (every key always present)."""
    base = {
        "iterations": 0.0, "spec_accept_rate": 0.0, "spec_drafted": 0.0,
        "prefix_hit_frac": 0.0, "decode_gap_s": 0.0, "stage_lag": 0.0,
        "deadline_miss_rate": 0.0, "occupancy": 0.0, "roofline_frac": 0.0,
    }
    base.update(kw)
    return base


def make_controller(**kw):
    cfg = kw.pop("config", ControlConfig())
    defaults = dict(
        spec_k_ceiling=3, budget_default=6, chunk=2,
        watermark_default=0.85, prefix_enabled=True,
    )
    defaults.update(kw)
    return Controller(cfg, **defaults)


# ---------------------------------------------------- pure ladder tests


class TestLadder:
    def test_spec_steps_down_and_floors_at_one(self):
        c = make_controller()
        low = vit(spec_drafted=10.0, spec_accept_rate=0.1)
        for want in (2, 1, 1, 1):
            d = c.evaluate(0, low)
            assert d.knobs["spec_k"] == float(want)
        assert "spec_down" not in c.log[-1].reasons  # floored: no change

    def test_spec_steps_back_up_to_ceiling(self):
        c = make_controller()
        c.evaluate(0, vit(spec_drafted=10.0, spec_accept_rate=0.1))
        assert c.knobs["spec_k"] == 2.0
        for want in (3, 3):
            d = c.evaluate(1, vit(spec_drafted=10.0, spec_accept_rate=0.95))
            assert d.knobs["spec_k"] == float(want)  # never past ceiling

    def test_spec_noise_gate(self):
        c = make_controller(config=ControlConfig(spec_min_drafts=8))
        d = c.evaluate(0, vit(spec_drafted=4.0, spec_accept_rate=0.0))
        assert d.knobs["spec_k"] == 3.0 and not d.changed

    def test_spec_hysteresis_band_holds(self):
        c = make_controller()
        # between low and high: no movement either way
        d = c.evaluate(0, vit(spec_drafted=10.0, spec_accept_rate=0.6))
        assert d.knobs["spec_k"] == 3.0 and not d.changed

    def test_budget_tightens_under_gap_and_floors(self):
        c = make_controller()
        high = vit(decode_gap_s=1.0)
        for want in (4, 3, 3):  # floor = max(chunk, 6*0.5) = 3
            d = c.evaluate(0, high)
            assert d.knobs["budget"] == float(want)

    def test_budget_relaxes_back_to_default(self):
        c = make_controller()
        c.evaluate(0, vit(decode_gap_s=1.0))
        for want in (6, 6):  # +chunk, capped at the default
            d = c.evaluate(1, vit(decode_gap_s=0.0))
            assert d.knobs["budget"] == float(want)

    def test_budget_hysteresis_band_holds(self):
        cfg = ControlConfig(gap_high_s=1.0, gap_low_frac=0.5)
        c = make_controller(config=cfg)
        c.evaluate(0, vit(decode_gap_s=2.0))
        assert c.knobs["budget"] == 4.0
        # in (low, high]: hold
        d = c.evaluate(1, vit(decode_gap_s=0.8))
        assert d.knobs["budget"] == 4.0 and not d.changed

    def test_watermark_clamp_and_restore(self):
        c = make_controller()
        d = c.evaluate(0, vit(deadline_miss_rate=0.5))
        assert d.knobs["watermark"] == 0.5 and "watermark_clamp" in d.reasons
        d = c.evaluate(1, vit(deadline_miss_rate=0.2))  # in the band: hold
        assert d.knobs["watermark"] == 0.5 and not d.changed
        d = c.evaluate(2, vit(deadline_miss_rate=0.0))
        assert d.knobs["watermark"] == 0.85
        assert "watermark_restore" in d.reasons

    def test_prefix_shed_and_restore(self):
        c = make_controller()
        d = c.evaluate(0, vit(occupancy=0.95))
        assert d.knobs["prefix_pages_target"] == 0.0
        assert "prefix_shed" in d.reasons
        d = c.evaluate(1, vit(occupancy=0.6))  # in the band: hold
        assert d.knobs["prefix_pages_target"] == 0.0 and not d.changed
        d = c.evaluate(2, vit(occupancy=0.1))
        assert d.knobs["prefix_pages_target"] is None
        assert "prefix_restore" in d.reasons

    def test_disabled_knobs_never_move(self):
        c = make_controller(spec_k_ceiling=None, budget_default=None,
                            prefix_enabled=False)
        d = c.evaluate(0, vit(spec_drafted=10.0, spec_accept_rate=0.0,
                              decode_gap_s=5.0, occupancy=1.0))
        assert d.knobs["spec_k"] is None
        assert d.knobs["budget"] is None
        assert d.knobs["prefix_pages_target"] is None

    def test_stall_fault_raises_typed(self):
        c = make_controller()
        FAULTS.arm("control_stall", 1)
        with pytest.raises(ControlStall):
            c.evaluate(0, vit())
        assert FAULTS.fired.get("control_stall") == 1
        c.evaluate(1, vit())  # disarmed: back to normal

    def test_reset_restores_defaults(self):
        c = make_controller()
        c.evaluate(0, vit(spec_drafted=10.0, spec_accept_rate=0.0,
                          decode_gap_s=5.0, deadline_miss_rate=1.0))
        assert c.knobs != c.defaults()
        c.reset()
        assert c.knobs == c.defaults()

    def test_log_is_bounded(self):
        c = make_controller(config=ControlConfig(max_log=8))
        for i in range(20):
            c.evaluate(i, vit())
        assert len(c.log) == 8
        assert c.log[-1].iteration == 19

    def test_deterministic_decision_sequence(self):
        # same snapshot sequence into two fresh controllers -> identical
        # decision sequences, field for field
        snaps = [
            vit(spec_drafted=10.0, spec_accept_rate=r, decode_gap_s=g,
                deadline_miss_rate=m, occupancy=o)
            for r, g, m, o in [
                (0.1, 1.0, 0.0, 0.5), (0.2, 0.0, 0.5, 0.95),
                (0.9, 0.1, 0.0, 0.1), (0.95, 2.0, 0.3, 0.99),
            ]
        ]
        a, b = make_controller(), make_controller()
        for i, s in enumerate(snaps):
            a.evaluate(i, s)
            b.evaluate(i, s)
        assert [(d.iteration, d.knobs, d.reasons, d.changed)
                for d in a.log] == [
            (d.iteration, d.knobs, d.reasons, d.changed) for d in b.log
        ]


# ------------------------------------------------------ engine-level


SPEC = dict(
    max_batch=2, prefill_chunk=2, fused_iteration=True,
    spec_decode=True, spec_k=3, spec_draft_depth=1,
)


def prompt(i):
    return np.random.RandomState(100 + i).randint(
        1, 32, size=(6,)
    ).astype(np.int32)


def run_engine(model, *, n=4, max_new=10, **cfg_kw):
    dalle, params = model
    kw = dict(SPEC)
    kw.update(cfg_kw)
    eng = Engine(
        dalle, params, EngineConfig(**kw), clock=FakeClock(step_dt=1.0)
    )
    for i in range(n):
        eng.submit(Request(
            request_id=f"r{i}", prompt=prompt(i),
            max_new_tokens=max_new, seed=i,
        ))
    results = eng.run(max_steps=800)
    return eng, results


def tokens_of(results):
    return {rid: list(map(int, r.tokens)) for rid, r in results.items()}


class TestEngineControl:
    def test_spec_k_steps_down_under_forced_low_accept(self, deep_model):
        eng, results = run_engine(
            deep_model, controller=True,
            control=ControlConfig(interval=4),
        )
        assert all(
            r.outcome is Outcome.COMPLETED for r in results.values()
        )
        # the misdrafter's ~0.3 windowed accept rate sits below
        # spec_accept_low: the effective width must have stepped down
        # from the pre-traced ceiling
        assert eng._eff_spec_k < eng.config.spec_k
        reasons = [r for d in eng.controller.log for r in d.reasons]
        assert "spec_down" in reasons
        assert counters.get("serve.control.decisions") == len(
            eng.controller.log
        )
        assert counters.get("serve.control.adjustments") >= 1
        assert gauges.get("serve.control.spec_k") == float(eng._eff_spec_k)
        check_accounting(eng)

    def test_controller_on_tokens_bit_identical_to_off(self, deep_model):
        _, off = run_engine(deep_model)
        sig_count = engine_mod._spec_iteration_jit._cache_size()
        eng, on = run_engine(
            deep_model, controller=True,
            control=ControlConfig(interval=2),
        )
        # adaptation really happened AND the tokens are the same bits:
        # the verify width is data, exact-match acceptance absorbs it
        assert eng._eff_spec_k < eng.config.spec_k
        assert tokens_of(on) == tokens_of(off)
        # ...through the pre-traced signatures only (no recompile)
        assert engine_mod._spec_iteration_jit._cache_size() == sig_count

    def test_decision_sequence_replays_bit_deterministically(
        self, deep_model
    ):
        a, _ = run_engine(
            deep_model, controller=True, control=ControlConfig(interval=2)
        )
        b, _ = run_engine(
            deep_model, controller=True, control=ControlConfig(interval=2)
        )
        assert len(a.controller.log) >= 2
        assert [
            (d.iteration, d.vitals, d.knobs, d.reasons, d.changed,
             d.stalled)
            for d in a.controller.log
        ] == [
            (d.iteration, d.vitals, d.knobs, d.reasons, d.changed,
             d.stalled)
            for d in b.controller.log
        ]

    def test_control_stall_drill_typed_accounting(self, deep_model):
        FAULTS.arm("control_stall", 1)
        eng, results = run_engine(
            deep_model, controller=True,
            control=ControlConfig(interval=2),
        )
        # the stall consumed the armed fault, was typed and counted, and
        # degraded the knobs to static defaults at that evaluation
        assert FAULTS.fired.get("control_stall") == 1
        assert counters.get("serve.fault_control_stall") == 1
        assert counters.get("serve.control.stalls") == 1
        stalled = [d for d in eng.controller.log if d.stalled]
        assert len(stalled) == 1
        assert stalled[0].knobs == eng.controller.defaults()
        # 100% typed accounting: every submitted request has a typed
        # outcome, decode progress never depended on the controller
        assert len(results) == 4
        assert all(
            r.outcome is Outcome.COMPLETED for r in results.values()
        )
        check_accounting(eng)

    def test_vitals_gauges_published_during_run(self, deep_model):
        run_engine(deep_model, controller=True, vitals=True)
        published = set(gauges.snapshot("serve.vitals."))
        for name in (
            "serve.vitals.spec_accept_rate",
            "serve.vitals.decode_gap_s",
            "serve.vitals.occupancy",
            "serve.vitals.deadline_miss_rate",
            "serve.vitals.stage_lag",
            "serve.vitals.prefix_hit_frac",
            "serve.vitals.roofline_frac",
        ):
            assert name in published, name
        assert gauges.get("serve.vitals.decode_gap_s") == pytest.approx(1.0)

    def test_vitals_off_publishes_nothing(self, deep_model):
        run_engine(deep_model)
        assert gauges.snapshot("serve.vitals.") == {}

    def test_controller_off_knobs_never_move(self, deep_model):
        eng, _ = run_engine(deep_model)
        assert eng.controller is None and eng.vitals is None
        assert eng._eff_spec_k == eng.config.spec_k
        assert eng._eff_watermark == eng.config.high_watermark
