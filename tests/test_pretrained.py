"""OpenAIDiscreteVAE wrapper tests: torch-pickle ingestion without the
source package, weight conversion (OIHW->HWIO), and numerics parity of the
re-owned flax graphs against a torch-side structural replica of the dVAE
blocks (reference vae.py:103-133 and the published dall_e package layout)."""

import math
import sys
import types
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as tF  # noqa: E402

from dalle_pytorch_tpu.models.pretrained import (  # noqa: E402
    OpenAIDecoder,
    OpenAIDiscreteVAE,
    OpenAIEncoder,
    convert_openai_decoder,
    convert_openai_encoder,
    load_torch_checkpoint,
    map_pixels,
    unmap_pixels,
)

N_HID, VOCAB, BLKS = 8, 16, 2


def _fake_dall_e_classes():
    """Define torch modules structurally identical to the published dVAE
    under a throwaway module name, so pickles of them are unloadable without
    the tolerant unpickler (like real dall_e pickles on a box without
    dall_e installed)."""
    mod = types.ModuleType("fake_dall_e")

    class Conv2d(tnn.Module):
        def __init__(self, n_in, n_out, kw):
            super().__init__()
            self.kw = kw
            self.w = tnn.Parameter(
                torch.randn(n_out, n_in, kw, kw) / math.sqrt(n_in * kw**2)
            )
            self.b = tnn.Parameter(torch.zeros(n_out))

        def forward(self, x):
            return tF.conv2d(x, self.w, self.b, padding=(self.kw - 1) // 2)

    class EncoderBlock(tnn.Module):
        def __init__(self, n_in, n_out, n_layers):
            super().__init__()
            n_hid = n_out // 4
            self.post_gain = 1 / n_layers**2
            self.id_path = (
                Conv2d(n_in, n_out, 1) if n_in != n_out else tnn.Identity()
            )
            self.res_path = tnn.Sequential(OrderedDict([
                ("relu_1", tnn.ReLU()), ("conv_1", Conv2d(n_in, n_hid, 3)),
                ("relu_2", tnn.ReLU()), ("conv_2", Conv2d(n_hid, n_hid, 3)),
                ("relu_3", tnn.ReLU()), ("conv_3", Conv2d(n_hid, n_hid, 3)),
                ("relu_4", tnn.ReLU()), ("conv_4", Conv2d(n_hid, n_out, 1)),
            ]))

        def forward(self, x):
            return self.id_path(x) + self.post_gain * self.res_path(x)

    class DecoderBlock(tnn.Module):
        def __init__(self, n_in, n_out, n_layers):
            super().__init__()
            n_hid = n_out // 4
            self.post_gain = 1 / n_layers**2
            self.id_path = (
                Conv2d(n_in, n_out, 1) if n_in != n_out else tnn.Identity()
            )
            self.res_path = tnn.Sequential(OrderedDict([
                ("relu_1", tnn.ReLU()), ("conv_1", Conv2d(n_in, n_hid, 1)),
                ("relu_2", tnn.ReLU()), ("conv_2", Conv2d(n_hid, n_hid, 3)),
                ("relu_3", tnn.ReLU()), ("conv_3", Conv2d(n_hid, n_hid, 3)),
                ("relu_4", tnn.ReLU()), ("conv_4", Conv2d(n_hid, n_out, 3)),
            ]))

        def forward(self, x):
            return self.id_path(x) + self.post_gain * self.res_path(x)

    class Encoder(tnn.Module):
        def __init__(self, n_hid=N_HID, vocab=VOCAB, n_blk=BLKS):
            super().__init__()
            n_layers = 4 * n_blk
            groups = []
            for g, mult in enumerate((1, 2, 4, 8), start=1):
                prev = mult // 2 if g > 1 else 1
                blocks = [
                    (f"block_{i + 1}",
                     EncoderBlock((prev if i == 0 else mult) * n_hid,
                                  mult * n_hid, n_layers))
                    for i in range(n_blk)
                ]
                if g < 4:
                    blocks.append(("pool", tnn.MaxPool2d(kernel_size=2)))
                groups.append((f"group_{g}", tnn.Sequential(OrderedDict(blocks))))
            self.blocks = tnn.Sequential(OrderedDict([
                ("input", Conv2d(3, n_hid, 7)),
                *groups,
                ("output", tnn.Sequential(OrderedDict([
                    ("relu", tnn.ReLU()), ("conv", Conv2d(8 * n_hid, vocab, 1)),
                ]))),
            ]))

        def forward(self, x):
            return self.blocks(x)

    class Decoder(tnn.Module):
        def __init__(self, n_init=8, n_hid=N_HID, vocab=VOCAB, n_blk=BLKS):
            super().__init__()
            n_layers = 4 * n_blk
            groups = []
            for g, mult in enumerate((8, 4, 2, 1), start=1):
                prev = n_init if g == 1 else mult * 2 * n_hid
                blocks = [
                    (f"block_{i + 1}",
                     DecoderBlock(prev if i == 0 else mult * n_hid,
                                  mult * n_hid, n_layers))
                    for i in range(n_blk)
                ]
                if g < 4:
                    blocks.append(
                        ("upsample", tnn.Upsample(scale_factor=2, mode="nearest"))
                    )
                groups.append((f"group_{g}", tnn.Sequential(OrderedDict(blocks))))
            self.blocks = tnn.Sequential(OrderedDict([
                ("input", Conv2d(vocab, n_init, 1)),
                *groups,
                ("output", tnn.Sequential(OrderedDict([
                    ("relu", tnn.ReLU()), ("conv", Conv2d(n_hid, 6, 1)),
                ]))),
            ]))

        def forward(self, x):
            return self.blocks(x)

    for cls in (Conv2d, EncoderBlock, DecoderBlock, Encoder, Decoder):
        cls.__module__ = "fake_dall_e"
        cls.__qualname__ = cls.__name__
        setattr(mod, cls.__name__, cls)
    return mod


@pytest.fixture()
def fake_dall_e():
    mod = _fake_dall_e_classes()
    sys.modules["fake_dall_e"] = mod
    yield mod
    sys.modules.pop("fake_dall_e", None)


def _save_and_strip(model, path):
    """torch.save the full module, then make its classes unimportable."""
    torch.save(model, path)
    sys.modules.pop("fake_dall_e", None)


def test_encoder_parity_via_pickle(fake_dall_e, tmp_path):
    torch.manual_seed(0)
    tenc = fake_dall_e.Encoder().eval()
    x = torch.rand(2, 3, 16, 16)
    with torch.no_grad():
        ref = tenc(x).numpy()  # (b, vocab, f, f)

    p = tmp_path / "encoder.pkl"
    _save_and_strip(tenc, str(p))
    sd = load_torch_checkpoint(str(p))
    assert "blocks.input.w" in sd and "blocks.output.conv.b" in sd

    params = convert_openai_encoder(sd)
    enc = OpenAIEncoder(n_hid=N_HID, vocab_size=VOCAB, n_blk_per_group=BLKS)
    out = enc.apply({"params": params}, jnp.asarray(x.numpy().transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(
        np.asarray(out), ref.transpose(0, 2, 3, 1), atol=2e-5, rtol=2e-5
    )


def test_decoder_parity_via_pickle(fake_dall_e, tmp_path):
    torch.manual_seed(1)
    tdec = fake_dall_e.Decoder().eval()
    z = torch.zeros(2, VOCAB, 2, 2)
    z[:, 3] = 1.0
    with torch.no_grad():
        ref = tdec(z).numpy()

    p = tmp_path / "decoder.pkl"
    _save_and_strip(tdec, str(p))
    params = convert_openai_decoder(load_torch_checkpoint(str(p)))
    dec = OpenAIDecoder(
        n_init=8, n_hid=N_HID, vocab_size=VOCAB, n_blk_per_group=BLKS
    )
    out = dec.apply({"params": params}, jnp.asarray(z.numpy().transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(
        np.asarray(out), ref.transpose(0, 2, 3, 1), atol=2e-5, rtol=2e-5
    )


def test_state_dict_pickle_also_loads(fake_dall_e, tmp_path):
    tenc = fake_dall_e.Encoder()
    p = tmp_path / "sd.pt"
    torch.save({"state_dict": tenc.state_dict()}, str(p))
    sd = load_torch_checkpoint(str(p))
    assert "blocks.input.w" in sd


def test_wrapper_surface():
    """DiscreteVAE duck-type: fmap/seq-len props, encode->decode shapes,
    frozen __call__."""
    vae = OpenAIDiscreteVAE(image_size=16, num_layers=3, num_tokens=VOCAB, n_hid=N_HID)
    assert vae.fmap_size == 2 and vae.image_seq_len == 4

    img = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3), jnp.float32)
    params = {
        **vae.init(jax.random.key(0), img, method="get_codebook_indices")["params"],
        **vae.init(
            jax.random.key(0), jnp.zeros((2, 4), jnp.int32), method="decode"
        )["params"],
    }
    idx = vae.apply({"params": params}, img, method="get_codebook_indices")
    assert idx.shape == (2, 4) and idx.dtype == jnp.int32
    assert (np.asarray(idx) >= 0).all() and (np.asarray(idx) < VOCAB).all()

    pix = vae.apply({"params": params}, idx, method="decode")
    assert pix.shape == (2, 16, 16, 3)
    arr = np.asarray(pix)
    assert np.isfinite(arr).all() and arr.min() >= 0 and arr.max() <= 1

    with pytest.raises(NotImplementedError):
        vae.apply({"params": params}, img)


def test_pixel_remap_roundtrip():
    x = jnp.linspace(0, 1, 11)
    np.testing.assert_allclose(
        np.asarray(unmap_pixels(map_pixels(x))), np.asarray(x), atol=1e-6
    )
    # eps remap matches reference vae.py:47-51
    np.testing.assert_allclose(float(map_pixels(jnp.asarray(0.0))), 0.1)
    np.testing.assert_allclose(float(map_pixels(jnp.asarray(1.0))), 0.9)
