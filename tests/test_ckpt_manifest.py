"""Pretrained-checkpoint manifest contract (VERDICT r3 ask #1).

The vendored JSONs under dalle_pytorch_tpu/models/ckpt_manifests/ freeze the
key/shape inventory of the published checkpoints the reference's default
``train_dalle.py`` path consumes: OpenAI's dVAE encoder.pkl / decoder.pkl
(reference vae.py:29-30) and taming's f=16/1024 last.ckpt + model.yaml
(reference vae.py:150-174). They are derived from the public architectures
by tools/gen_ckpt_manifests.py — independently of this package's flax
modules — so these tests genuinely cross-check the converters:

- every manifest key must be consumed by the converter (none skipped),
- the converted tree must cover the flax module's parameter tree exactly
  (same paths, same post-transpose shapes, nothing missing, nothing extra).

A converter that silently drops or mis-maps any key in the real layout now
fails HERE instead of at a user's first real-checkpoint load. The env-gated
golden test at the bottom additionally runs the real published weights
end-to-end when DALLE_TPU_REAL_CKPTS points at them.
"""

import importlib.resources
import json
import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models.pretrained import (
    OpenAIDiscreteVAE,
    OpenAIEncoder,
    OpenAIDecoder,
    convert_openai_decoder,
    convert_openai_encoder,
)
from dalle_pytorch_tpu.models.vqgan import VQGanVAE, convert_vqgan_checkpoint

# the manifests are package data (shipped in the wheel), so an installed
# copy is the source of truth — these tests work against a wheel install
# exactly as against the repo tree
MANIFEST_DIR = importlib.resources.files("dalle_pytorch_tpu.models") / "ckpt_manifests"


def load_manifest(name):
    return json.loads((MANIFEST_DIR / name).read_text())


def synthetic_sd(manifest):
    """Deterministic small-valued arrays in the manifest's shapes."""
    rng = np.random.RandomState(0)
    return {
        k: rng.randn(*spec["shape"]).astype(spec["dtype"]) * 0.02
        for k, spec in manifest.items()
    }


def flat_shapes(tree):
    out = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        out[jax.tree_util.keystr(path)] = tuple(leaf.shape)
    return out


def test_manifests_match_generator():
    """The vendored JSONs must stay in sync with the architecture walk in
    tools/gen_ckpt_manifests.py (regeneration is the provenance record)."""
    import sys

    tools = Path(__file__).resolve().parent.parent / "tools"
    if not tools.exists():
        pytest.skip("generator lives in the repo tree, not the wheel")
    sys.path.insert(0, str(tools))
    import gen_ckpt_manifests as gen

    assert load_manifest("openai_dvae_encoder.json") == gen.openai_dvae_manifest("encoder")
    assert load_manifest("openai_dvae_decoder.json") == gen.openai_dvae_manifest("decoder")
    vq = load_manifest("vqgan_f16_1024.json")
    assert vq["state_dict"] == gen.vqgan_manifest()
    assert vq["config"] == gen.VQGAN_F16_1024_CONFIG


@pytest.mark.parametrize("kind", ["encoder", "decoder"])
def test_openai_converter_consumes_exact_manifest(kind):
    manifest = load_manifest(f"openai_dvae_{kind}.json")
    sd = synthetic_sd(manifest)
    convert = convert_openai_encoder if kind == "encoder" else convert_openai_decoder
    converted = convert(sd)

    # every manifest key consumed: each (w, b) pair lands as one flax leaf
    n_leaves = len(jax.tree_util.tree_leaves(converted))
    assert n_leaves == len(manifest), (
        f"{len(manifest) - n_leaves} manifest keys were not consumed"
    )

    if kind == "encoder":
        module, x = OpenAIEncoder(), jnp.zeros((1, 256, 256, 3))
    else:
        module, x = OpenAIDecoder(), jnp.zeros((1, 32, 32, 8192))
    expected = jax.eval_shape(module.init, jax.random.key(0), x)["params"]

    got, want = flat_shapes(converted), flat_shapes(expected)
    assert got == want, (
        f"converted tree != flax tree\nmissing: {sorted(set(want) - set(got))[:10]}"
        f"\nextra: {sorted(set(got) - set(want))[:10]}"
        f"\nshape-diff: {[k for k in got.keys() & want.keys() if got[k] != want[k]][:10]}"
    )


def test_openai_wrapper_runs_on_manifest_weights():
    """The full OpenAIDiscreteVAE surface must run on a manifest-shaped
    checkpoint (the exact code path load_openai_vae takes)."""
    params = {
        "enc": convert_openai_encoder(synthetic_sd(load_manifest("openai_dvae_encoder.json"))),
        "dec": convert_openai_decoder(synthetic_sd(load_manifest("openai_dvae_decoder.json"))),
    }
    vae = OpenAIDiscreteVAE()
    img = jnp.zeros((1, 64, 64, 3))  # any multiple of 8 works for the graph
    idx = vae.apply({"params": params}, img, method="get_codebook_indices")
    assert idx.shape == (1, 64)
    out = vae.apply({"params": params}, idx, method="decode")
    assert out.shape == (1, 64, 64, 3)
    assert bool(jnp.isfinite(out).all())


def test_vqgan_converter_consumes_exact_manifest():
    m = load_manifest("vqgan_f16_1024.json")
    sd = synthetic_sd(m["state_dict"])
    # the real last.ckpt carries LPIPS/discriminator weights under loss.*
    # (and GumbelVQ ckpts a temperature scheduler) — the converter must skip
    # them without error
    sd["loss.discriminator.main.0.weight"] = np.zeros((64, 3, 4, 4), np.float32)
    sd["loss.perceptual_loss.lin0.model.1.weight"] = np.zeros((1, 64, 1, 1), np.float32)
    converted = convert_vqgan_checkpoint(sd)

    n_leaves = len(jax.tree_util.tree_leaves(converted))
    assert n_leaves == len(m["state_dict"]), (
        f"{len(m['state_dict']) - n_leaves} model keys were not consumed"
    )

    cfg, dd = m["config"], m["config"]["ddconfig"]
    vae = VQGanVAE(
        image_size=dd["resolution"], ch=dd["ch"], ch_mult=tuple(dd["ch_mult"]),
        num_res_blocks=dd["num_res_blocks"],
        attn_resolutions=tuple(dd["attn_resolutions"]),
        z_channels=dd["z_channels"], n_embed=cfg["n_embed"],
        embed_dim=cfg["embed_dim"],
    )
    img = jnp.zeros((1, dd["resolution"], dd["resolution"], 3))
    seq = jnp.zeros((1, vae.image_seq_len), jnp.int32)
    enc_params = jax.eval_shape(
        lambda k: vae.init(k, img, method="get_codebook_indices"), jax.random.key(0)
    )["params"]
    dec_params = jax.eval_shape(
        lambda k: vae.init(k, seq, method="decode"), jax.random.key(0)
    )["params"]
    # merge the two entry points' param trees (they overlap on quantize)
    merged = dict(dec_params)
    for k, v in enc_params.items():
        merged[k] = v

    got, want = flat_shapes(converted), flat_shapes(merged)
    assert got == want, (
        f"converted tree != flax tree\nmissing: {sorted(set(want) - set(got))[:10]}"
        f"\nextra: {sorted(set(got) - set(want))[:10]}"
        f"\nshape-diff: {[k for k in got.keys() & want.keys() if got[k] != want[k]][:10]}"
    )


def test_vqgan_wrapper_runs_on_manifest_weights():
    m = load_manifest("vqgan_f16_1024.json")
    converted = convert_vqgan_checkpoint(synthetic_sd(m["state_dict"]))
    vae = VQGanVAE()  # defaults ARE the f16/1024 published config
    img = jnp.zeros((1, 32, 32, 3))  # graph is resolution-agnostic
    idx = vae.apply({"params": converted}, img, method="get_codebook_indices")
    assert idx.shape == (1, 4)
    out = vae.apply({"params": converted}, idx, method="decode")
    assert out.shape == (1, 32, 32, 3)
    assert bool(jnp.isfinite(out).all())


# ------------------------------------------------------------ real weights

REAL = os.environ.get("DALLE_TPU_REAL_CKPTS")


@pytest.mark.skipif(
    not REAL, reason="set DALLE_TPU_REAL_CKPTS=<dir with encoder.pkl/"
    "decoder.pkl[/last.ckpt]> to run the published-weight golden test"
)
def test_real_openai_checkpoints_golden():
    from dalle_pytorch_tpu.models.pretrained import (
        load_openai_vae,
        load_torch_checkpoint,
    )

    real = Path(REAL)
    # 1. inventory must equal the vendored manifest exactly
    for fname, mname in (
        ("encoder.pkl", "openai_dvae_encoder.json"),
        ("decoder.pkl", "openai_dvae_decoder.json"),
    ):
        sd = load_torch_checkpoint(str(real / fname))
        manifest = load_manifest(mname)
        assert {k: list(v.shape) for k, v in sd.items()} == {
            k: v["shape"] for k, v in manifest.items()
        }, f"{fname} inventory drifted from the vendored manifest"

    # 2. golden roundtrip: a smooth synthetic fixture must reconstruct
    vae, params = load_openai_vae(
        enc_path=str(real / "encoder.pkl"), dec_path=str(real / "decoder.pkl")
    )
    yy, xx = np.mgrid[:256, :256] / 255.0
    img = np.stack([yy, xx, 0.5 * (yy + xx)], -1)[None].astype(np.float32)
    idx = vae.apply({"params": params}, jnp.asarray(img), method="get_codebook_indices")
    assert idx.shape == (1, 1024)
    # token histogram sanity: a smooth gradient uses many distinct codes
    assert np.unique(np.asarray(idx)).size > 16
    recon = vae.apply({"params": params}, idx, method="decode")
    err = float(jnp.abs(recon - img).mean())
    assert err < 0.1, f"reconstruction error {err:.3f} too high for real weights"


@pytest.mark.skipif(
    not REAL or not (Path(REAL or ".") / "last.ckpt").exists(),
    reason="needs DALLE_TPU_REAL_CKPTS with taming last.ckpt + model.yaml",
)
def test_real_vqgan_checkpoint_golden():
    from dalle_pytorch_tpu.models.pretrained import load_torch_checkpoint
    from dalle_pytorch_tpu.models.vqgan import load_vqgan_vae

    real = Path(REAL)
    sd = load_torch_checkpoint(str(real / "last.ckpt"))
    manifest = load_manifest("vqgan_f16_1024.json")["state_dict"]
    model_keys = {k: list(v.shape) for k, v in sd.items() if not k.startswith("loss.")}
    assert model_keys == {k: v["shape"] for k, v in manifest.items()}, (
        "last.ckpt inventory drifted from the vendored manifest"
    )

    vae, params = load_vqgan_vae(
        config_path=str(real / "model.yaml"), model_path=str(real / "last.ckpt")
    )
    yy, xx = np.mgrid[:256, :256] / 255.0
    img = np.stack([yy, xx, 0.5 * (yy + xx)], -1)[None].astype(np.float32)
    idx = vae.apply({"params": params}, jnp.asarray(img), method="get_codebook_indices")
    assert idx.shape == (1, 256)
    assert np.unique(np.asarray(idx)).size > 8
    recon = vae.apply({"params": params}, idx, method="decode")
    err = float(jnp.abs(recon - img).mean())
    assert err < 0.15, f"reconstruction error {err:.3f} too high for real weights"


def test_generator_cli_is_idempotent(tmp_path, monkeypatch):
    """Running tools/gen_ckpt_manifests.py must regenerate byte-identical
    JSONs (the vendored files are exactly what the generator emits)."""
    import sys

    tools = Path(__file__).resolve().parent.parent / "tools"
    if not tools.exists():
        pytest.skip("generator lives in the repo tree, not the wheel")
    sys.path.insert(0, str(tools))
    import gen_ckpt_manifests as gen

    monkeypatch.setattr(gen, "OUT_DIR", tmp_path)
    gen.write_manifests()
    for name in (
        "openai_dvae_encoder.json",
        "openai_dvae_decoder.json",
        "vqgan_f16_1024.json",
    ):
        fresh = (tmp_path / name).read_text()
        vendored = (MANIFEST_DIR / name).read_text()
        assert fresh == vendored, f"{name} drifted from the generator output"
