"""Weight-only int8 serving (utils/quantize.py + ops/layers.py:QuantDense).

The reference has no quantized path; these tests pin the beyond-parity
contract: the quantized twin reproduces the full-precision model's live
logits within int8 tolerance, KV-cached decode runs end to end, MoE/gMLP
blocks pass through unquantized, and training a serve_quant model fails
loudly."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO)) if str(REPO) not in sys.path else None

from dalle_pytorch_tpu.models import DALLE  # noqa: E402
from dalle_pytorch_tpu.models.sampling import generate_image_tokens  # noqa: E402
from dalle_pytorch_tpu.utils.quantize import (  # noqa: E402
    quantize_dalle,
    quantize_kernel,
)


def small_dalle(**kw):
    cfg = dict(
        dim=64, depth=3, num_text_tokens=50, text_seq_len=6,
        num_image_tokens=32, image_fmap_size=4, heads=4, dim_head=16,
        attn_types=("full", "axial_row"),
    )
    cfg.update(kw)
    return DALLE(**cfg)


@pytest.fixture(scope="module")
def trained():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 50, size=(2, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params, text, image


def test_quantize_kernel_roundtrip():
    w = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    q, s = quantize_kernel(w)
    assert q.dtype == np.int8 and s.shape == (16,)
    err = np.abs(q.astype(np.float32) * s - w)
    # per-channel symmetric int8: error bounded by half a quantization step
    assert (err <= s / 2 + 1e-7).all()


def test_zero_column_kernel_is_safe():
    w = np.zeros((8, 4), np.float32)
    q, s = quantize_kernel(w)
    assert (q == 0).all() and (s == 1.0).all()


def test_quantized_logits_match_full_precision(trained):
    dalle, params, text, image = trained
    full = dalle.apply({"params": params}, text, image)
    dq, pq = quantize_dalle(dalle, params, batch_size=2)
    quant = dq.apply({"params": pq}, text, image)

    live = np.asarray(full) > -1e30
    assert (live == (np.asarray(quant) > -1e30)).all()
    a, b = np.asarray(full)[live], np.asarray(quant)[live]
    rel = np.abs(a - b) / (np.abs(a).mean() + 1e-9)
    assert rel.max() < 0.15, f"int8 logits diverge: max rel {rel.max():.3f}"


def test_quantized_decode_runs(trained):
    dalle, params, text, _ = trained
    dq, pq = quantize_dalle(dalle, params, batch_size=1)
    toks = generate_image_tokens(dq, pq, text[:1], jax.random.key(0))
    toks = np.asarray(toks)
    assert toks.shape == (1, dalle.image_seq_len)
    assert (toks >= 0).all() and (toks < dalle.num_image_tokens).all()


def test_param_bytes_halved(trained):
    dalle, params, text, image = trained

    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))

    bf16 = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x, params
    )
    _, pq = quantize_dalle(dalle.clone(dtype=jnp.bfloat16), bf16)
    # kernels dominate: int8 tree must be well under the bf16 tree
    assert nbytes(pq) < 0.62 * nbytes(bf16)


def test_moe_and_mlp_blocks_stay_unquantized():
    dalle = small_dalle(
        attn_types=("full",), ff_experts=2, moe_every=2, rotary_emb=True
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 50, size=(2, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    dq, pq = quantize_dalle(dalle, params, batch_size=2)
    flat = jax.tree_util.tree_leaves_with_path(pq)
    moe_leaves = [
        (p, x) for p, x in flat
        if any(k in jax.tree_util.keystr(p) for k in ("experts_in", "experts_out", "gate"))
    ]
    assert moe_leaves, "expected MoE params in the tree"
    assert all(x.dtype != jnp.int8 for _, x in moe_leaves)
    out, _ = dq.apply(
        {"params": pq}, text, image, mutable=["moe_aux"]
    )
    assert bool(np.isfinite(np.asarray(out)[np.asarray(out) > -1e30]).all())


def test_training_quant_model_raises(trained):
    dalle, params, text, image = trained
    dq, pq = quantize_dalle(dalle, params, batch_size=2)
    with pytest.raises(ValueError, match="inference-only"):
        dq.apply({"params": pq}, text, image, return_loss=True)


def test_sharding_rules_cover_real_and_quant_paths(trained):
    """The Megatron tp layout must hit the ACTUAL flax paths — the
    feed-forward projections live under anonymous `fn` wrappers
    (ff_0/fn/fn/fn/Dense_0), and int8 serving renames them to
    QuantDense_i/kernel_q."""
    from jax.sharding import Mesh, PartitionSpec as P

    from dalle_pytorch_tpu.parallel.sharding import partition_spec

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    cases = {
        "transformer/ff_0/fn/fn/fn/Dense_0/kernel": ((64, 512), P("fsdp", "tp")),
        "transformer/ff_0/fn/fn/fn/Dense_1/kernel": ((256, 64), P("tp", "fsdp")),
        "transformer/ff_0/fn/fn/fn/QuantDense_0/kernel_q": ((64, 512), P("fsdp", "tp")),
        "transformer/ff_0/fn/fn/fn/QuantDense_1/kernel_q": ((256, 64), P("tp", "fsdp")),
        "transformer/attn_0/fn/fn/fn/to_qkv/kernel_q": ((64, 192), P("fsdp", "tp")),
        "transformer/attn_0/fn/fn/fn/to_out/kernel_q": ((64, 64), P("tp", "fsdp")),
        "to_logits/kernel_q": ((64, 128), P("fsdp", "tp")),
    }
    for path, (shape, want) in cases.items():
        got = partition_spec(path, shape, mesh)
        assert got == want, f"{path}: {got} != {want}"


def test_real_tree_ff_kernels_get_megatron_specs(trained):
    """Walk the ACTUAL parameter tree (not hand-written path strings): every
    feed-forward and attention projection kernel must carry the Megatron
    tp layout under an fsdp x tp mesh — this is what guards against flax
    auto-naming drift silently downgrading kernels to the fsdp fallback."""
    from jax.sharding import Mesh, PartitionSpec as P

    from dalle_pytorch_tpu.parallel.sharding import params_shardings

    dalle, params, _, _ = trained
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    shardings = params_shardings(params, mesh)
    flat = {
        jax.tree_util.keystr(p): s.spec
        for p, s in jax.tree_util.tree_leaves_with_path(shardings)
    }

    up = [k for k in flat if k.endswith("['Dense_0']['kernel']")]
    down = [k for k in flat if k.endswith("['Dense_1']['kernel']")]
    qkv = [k for k in flat if k.endswith("['to_qkv']['kernel']")]
    out = [k for k in flat if k.endswith("['to_out']['kernel']")]
    assert up and down and qkv and out, sorted(flat)[:10]
    for k in up + qkv:
        assert flat[k] == P("fsdp", "tp"), (k, flat[k])
    for k in down + out:
        assert flat[k] == P("tp", "fsdp"), (k, flat[k])
    assert flat["['to_logits']['kernel']"] == P("fsdp", "tp")


def test_embeddings_quantized(trained):
    dalle, params, text, image = trained
    dq, pq = quantize_dalle(dalle, params, batch_size=2)
    for emb in ("text_emb", "image_emb"):
        assert pq[emb]["embedding_q"].dtype == jnp.int8
        assert pq[emb]["scale"].shape == (pq[emb]["embedding_q"].shape[0],)
    # per-row dequant error bounded by half a step
    src = np.asarray(params["text_emb"]["embedding"], np.float32)
    deq = np.asarray(pq["text_emb"]["embedding_q"], np.float32) * np.asarray(
        pq["text_emb"]["scale"]
    )[:, None]
    step = np.asarray(pq["text_emb"]["scale"])[:, None]
    assert (np.abs(deq - src) <= step / 2 + 1e-7).all()
