"""VQGanVAE tests: taming state-dict conversion and numerics parity of the
re-owned flax encoder/decoder/quantizer against a torch-side structural
replica of taming's modules (reference vae.py:135-220)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as tF  # noqa: E402

from dalle_pytorch_tpu.models.pretrained import load_torch_checkpoint  # noqa: E402
from dalle_pytorch_tpu.models.vqgan import (  # noqa: E402
    VQGanVAE,
    _ddconfig_from_yaml,
    convert_vqgan_checkpoint,
)

# small but structurally faithful config: 2 levels (one downsample), attn at
# the final 8x8 resolution, GroupNorm(32)-compatible channels
CFG = dict(
    image_size=16, ch=32, ch_mult=(1, 2), num_res_blocks=1,
    attn_resolutions=(8,), z_channels=64, n_embed=24, embed_dim=64,
)


def _tnorm(c):
    return tnn.GroupNorm(32, c, eps=1e-6, affine=True)


def _tswish(x):
    return x * torch.sigmoid(x)


class TRes(tnn.Module):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm1 = _tnorm(cin)
        self.conv1 = tnn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = _tnorm(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.nin_shortcut = tnn.Conv2d(cin, cout, 1)

    def forward(self, x):
        h = self.conv1(_tswish(self.norm1(x)))
        h = self.conv2(_tswish(self.norm2(h)))
        if hasattr(self, "nin_shortcut"):
            x = self.nin_shortcut(x)
        return x + h


class TAttn(tnn.Module):
    def __init__(self, c):
        super().__init__()
        self.norm = _tnorm(c)
        self.q = tnn.Conv2d(c, c, 1)
        self.k = tnn.Conv2d(c, c, 1)
        self.v = tnn.Conv2d(c, c, 1)
        self.proj_out = tnn.Conv2d(c, c, 1)

    def forward(self, x):
        h_ = self.norm(x)
        b, c, hh, ww = h_.shape
        q = self.q(h_).reshape(b, c, hh * ww).permute(0, 2, 1)
        k = self.k(h_).reshape(b, c, hh * ww)
        w = torch.bmm(q, k) * c**-0.5
        w = torch.softmax(w, dim=2)
        v = self.v(h_).reshape(b, c, hh * ww)
        h = torch.bmm(v, w.permute(0, 2, 1)).reshape(b, c, hh, ww)
        return x + self.proj_out(h)


class TLevel(tnn.Module):
    pass


class TEncoder(tnn.Module):
    def __init__(self, ch, ch_mult, nrb, attn_res, resolution, z):
        super().__init__()
        self.conv_in = tnn.Conv2d(3, ch, 3, padding=1)
        self.down = tnn.ModuleList()
        curr = resolution
        cin = ch
        for i, m in enumerate(ch_mult):
            lvl = TLevel()
            cout = ch * m
            lvl.block = tnn.ModuleList()
            lvl.attn = tnn.ModuleList()
            for _ in range(nrb):
                lvl.block.append(TRes(cin, cout))
                cin = cout
                if curr in attn_res:
                    lvl.attn.append(TAttn(cout))
            if i != len(ch_mult) - 1:
                ds = TLevel()
                ds.conv = tnn.Conv2d(cout, cout, 3, stride=2)
                lvl.downsample = ds
                curr //= 2
            self.down.append(lvl)
        self.mid = TLevel()
        self.mid.block_1 = TRes(cin, cin)
        self.mid.attn_1 = TAttn(cin)
        self.mid.block_2 = TRes(cin, cin)
        self.norm_out = _tnorm(cin)
        self.conv_out = tnn.Conv2d(cin, z, 3, padding=1)

    def forward(self, x):
        h = self.conv_in(x)
        for i, lvl in enumerate(self.down):
            for j, blk in enumerate(lvl.block):
                h = blk(h)
                if len(lvl.attn) > 0:
                    h = lvl.attn[j](h)
            if hasattr(lvl, "downsample"):
                h = lvl.downsample.conv(tF.pad(h, (0, 1, 0, 1)))
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        return self.conv_out(_tswish(self.norm_out(h)))


class TDecoder(tnn.Module):
    def __init__(self, ch, ch_mult, nrb, attn_res, resolution, z):
        super().__init__()
        n = len(ch_mult)
        block_in = ch * ch_mult[-1]
        self.curr0 = resolution // 2 ** (n - 1)
        self.attn_res = attn_res
        self.conv_in = tnn.Conv2d(z, block_in, 3, padding=1)
        self.mid = TLevel()
        self.mid.block_1 = TRes(block_in, block_in)
        self.mid.attn_1 = TAttn(block_in)
        self.mid.block_2 = TRes(block_in, block_in)
        self.up = tnn.ModuleList()
        cin = block_in
        curr = self.curr0
        ups = []
        for i in reversed(range(n)):
            lvl = TLevel()
            cout = ch * ch_mult[i]
            lvl.block = tnn.ModuleList()
            lvl.attn = tnn.ModuleList()
            for _ in range(nrb + 1):
                lvl.block.append(TRes(cin, cout))
                cin = cout
                if curr in attn_res:
                    lvl.attn.append(TAttn(cout))
            if i != 0:
                us = TLevel()
                us.conv = tnn.Conv2d(cout, cout, 3, padding=1)
                lvl.upsample = us
                curr *= 2
            ups.insert(0, lvl)
        for lvl in ups:
            self.up.append(lvl)
        self.norm_out = _tnorm(cin)
        self.conv_out = tnn.Conv2d(cin, 3, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(z)
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        for lvl in reversed(self.up):
            for j, blk in enumerate(lvl.block):
                h = blk(h)
                if len(lvl.attn) > 0:
                    h = lvl.attn[j](h)
            if hasattr(lvl, "upsample"):
                h = lvl.upsample.conv(
                    tF.interpolate(h, scale_factor=2, mode="nearest")
                )
        return self.conv_out(_tswish(self.norm_out(h)))


class TQuantize(tnn.Module):
    def __init__(self, n_embed, embed_dim):
        super().__init__()
        self.embedding = tnn.Embedding(n_embed, embed_dim)


class TVQGan(tnn.Module):
    def __init__(self, **c):
        super().__init__()
        args = (c["ch"], c["ch_mult"], c["num_res_blocks"],
                c["attn_resolutions"], c["image_size"], c["z_channels"])
        self.encoder = TEncoder(*args)
        self.decoder = TDecoder(*args)
        self.quant_conv = tnn.Conv2d(c["z_channels"], c["embed_dim"], 1)
        self.post_quant_conv = tnn.Conv2d(c["embed_dim"], c["z_channels"], 1)
        self.quantize = TQuantize(c["n_embed"], c["embed_dim"])


@pytest.fixture(scope="module")
def models():
    torch.manual_seed(0)
    tm = TVQGan(**CFG).eval()
    sd = {k: v.numpy() for k, v in tm.state_dict().items()}
    params = convert_vqgan_checkpoint(sd)
    fm = VQGanVAE(**CFG)
    return tm, fm, params


def test_encode_indices_parity(models):
    tm, fm, params = models
    torch.manual_seed(1)
    img = torch.rand(2, 3, 16, 16)
    with torch.no_grad():
        h = tm.quant_conv(tm.encoder(2 * img - 1))  # (b, e, f, f)
        flat = h.permute(0, 2, 3, 1).reshape(2, -1, CFG["embed_dim"])
        e = tm.quantize.embedding.weight
        d = (flat**2).sum(-1, keepdim=True) - 2 * flat @ e.T + (e**2).sum(-1)
        ref_idx = d.argmin(-1).numpy()

    idx = fm.apply(
        {"params": params},
        jnp.asarray(img.numpy().transpose(0, 2, 3, 1)),
        method="get_codebook_indices",
    )
    assert idx.shape == (2, fm.image_seq_len)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)


def test_decode_parity(models):
    tm, fm, params = models
    rng = np.random.RandomState(2)
    idx = rng.randint(0, CFG["n_embed"], size=(2, fm.image_seq_len))
    with torch.no_grad():
        z = tm.quantize.embedding(torch.tensor(idx))
        f = int(math.isqrt(fm.image_seq_len))
        z = z.reshape(2, f, f, -1).permute(0, 3, 1, 2)
        dec = tm.decoder(tm.post_quant_conv(z))
        ref = ((dec.clamp(-1, 1) + 1) * 0.5).numpy().transpose(0, 2, 3, 1)

    out = fm.apply({"params": params}, jnp.asarray(idx), method="decode")
    assert out.shape == (2, 16, 16, 3)
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-5, rtol=5e-5)


def test_roundtrip_via_saved_checkpoint(models, tmp_path):
    """Full taming-style {'state_dict': ...} ckpt file -> loader -> encode
    shapes (the ingestion path generate.py/train_dalle.py will use)."""
    tm, fm, _ = models
    p = tmp_path / "last.ckpt"
    torch.save({"state_dict": tm.state_dict()}, str(p))
    sd = load_torch_checkpoint(str(p))
    params = convert_vqgan_checkpoint(sd)
    img = jnp.zeros((1, 16, 16, 3))
    idx = fm.apply({"params": params}, img, method="get_codebook_indices")
    assert idx.shape == (1, fm.image_seq_len)


def test_gumbel_variant_surface():
    """GumbelVQ flavor: proj-conv encode, embed-table decode, z->z convs."""
    cfg = dict(CFG, gumbel=True, z_channels=64, embed_dim=64)
    vae = VQGanVAE(**cfg)
    from dalle_pytorch_tpu.models.factory import deep_merge

    img = jnp.asarray(np.random.RandomState(3).rand(2, 16, 16, 3), jnp.float32)
    seq = jnp.zeros((2, vae.image_seq_len), jnp.int32)
    params = deep_merge(
        vae.init(jax.random.key(0), img, method="get_codebook_indices")["params"],
        vae.init(jax.random.key(0), seq, method="decode")["params"],
    )
    idx = vae.apply({"params": params}, img, method="get_codebook_indices")
    assert idx.shape == (2, vae.image_seq_len)
    out = vae.apply({"params": params}, idx, method="decode")
    assert out.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_f16_default_cuts_sequence():
    """The default published f=16 model gives image seq 256 (vs the dVAE's
    1024) — the reference's headline perf lever (README.md:189)."""
    vae = VQGanVAE()
    assert vae.num_layers == 4
    assert vae.fmap_size == 16
    assert vae.image_seq_len == 256


def test_yaml_config_parsing(tmp_path):
    y = tmp_path / "model.yaml"
    y.write_text(
        """
model:
  target: taming.models.vqgan.VQModel
  params:
    embed_dim: 256
    n_embed: 1024
    ddconfig:
      double_z: false
      z_channels: 256
      resolution: 256
      in_channels: 3
      out_ch: 3
      ch: 128
      ch_mult: [1, 1, 2, 2, 4]
      num_res_blocks: 2
      attn_resolutions: [16]
      dropout: 0.0
"""
    )
    dd, n_embed, embed_dim, gumbel = _ddconfig_from_yaml(str(y))
    assert dd["ch"] == 128 and n_embed == 1024 and embed_dim == 256
    assert not gumbel


def test_dalle_checkpoint_with_frozen_vae_roundtrip(models, tmp_path):
    """Frozen VAE weights are NOT bundled in DALLE checkpoints; the loader
    reconstitutes them from local paths (or the download cache)."""
    from dalle_pytorch_tpu.models import DALLE
    from dalle_pytorch_tpu.models.factory import (
        dalle_from_checkpoint,
        save_dalle_checkpoint,
    )

    tm, fm, fparams = models
    # write the taming-style artifacts the loader will ingest
    ckpt = tmp_path / "last.ckpt"
    torch.save({"state_dict": tm.state_dict()}, str(ckpt))
    cfg_yaml = tmp_path / "model.yaml"
    cfg_yaml.write_text(
        f"""
model:
  target: taming.models.vqgan.VQModel
  params:
    embed_dim: {CFG['embed_dim']}
    n_embed: {CFG['n_embed']}
    ddconfig:
      z_channels: {CFG['z_channels']}
      resolution: {CFG['image_size']}
      ch: {CFG['ch']}
      ch_mult: {list(CFG['ch_mult'])}
      num_res_blocks: {CFG['num_res_blocks']}
      attn_resolutions: {list(CFG['attn_resolutions'])}
"""
    )

    dalle = DALLE(
        dim=32, depth=1, num_text_tokens=32, text_seq_len=4,
        num_image_tokens=fm.num_tokens, image_fmap_size=fm.fmap_size,
        heads=2, dim_head=16,
    )
    text = jnp.zeros((1, 4), jnp.int32)
    image = jnp.zeros((1, fm.image_seq_len), jnp.int32)
    dparams = dalle.init(jax.random.key(0), text, image)["params"]

    path = tmp_path / "dalle.ckpt"
    save_dalle_checkpoint(str(path), dalle, dparams, vae=fm, vae_params=fparams)
    # frozen weights must not have been serialized into the checkpoint
    assert path.stat().st_size < 2_000_000

    dalle2, _, vae2, vae_params2, _ = dalle_from_checkpoint(
        str(path),
        vae_weight_paths={
            "vqgan_config_path": str(cfg_yaml),
            "vqgan_model_path": str(ckpt),
        },
    )
    assert type(vae2).__name__ == "VQGanVAE"
    assert vae2.n_embed == CFG["n_embed"]
    idx = vae2.apply(
        {"params": vae_params2}, jnp.zeros((1, 16, 16, 3)),
        method="get_codebook_indices",
    )
    assert idx.shape == (1, vae2.image_seq_len)
