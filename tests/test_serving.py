"""Serving-engine failure-path tests — every robustness behavior of the
continuous-batching engine pinned deterministically on CPU: typed admission
rejects, deadline expiry mid-decode, preempt-and-requeue with BIT-IDENTICAL
replay, cancellation and page reclamation, watermark degradation, livelock
aging, the preemption cap, and the combined-fault overload scenario where
100% of submitted requests must end in a typed outcome.

Page size 2 (env override) so tiny models cross page boundaries mid-decode
— the page-growth allocation is the natural preemption trigger and the
``page_exhaust`` fault site sits exactly there.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE, init_decode_cache, insert_decode_cache
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    EngineUnsupportedModel,
    FakeClock,
    Outcome,
    PagePool,
    RejectReason,
    Request,
    Scheduler,
    check_accounting,
    pages_for,
)
from dalle_pytorch_tpu.serving.scheduler import Entry
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    """One (dalle, params) for the whole module: every engine test shares
    the jit cache, so the suite compiles the prefill/decode programs once."""
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    """Page size 2: the tiny model's decode then genuinely grows pages
    mid-flight (text_len_internal=5 -> 3 prompt pages; positions 6+ cross
    into growth territory)."""
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=prompt(i), max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def outcome_accounting_holds(engine):
    check_accounting(engine)
    outcomes = engine.stats()["outcomes"]
    assert sum(outcomes.values()) == engine.stats()["submitted"]
    assert counters.get("serve.submitted") == engine.stats()["submitted"]
    return outcomes


# ----------------------------------------------------- scheduler (pure)


class TestScheduler:
    def test_page_pool_alloc_free(self):
        pool = PagePool(4)
        assert pool.alloc("a", 3) and pool.free == 1
        assert not pool.alloc("b", 2)  # all-or-nothing
        assert pool.alloc("b", 1) and pool.free == 0
        assert pool.free_all("a") == 3 and pool.free == 3
        assert pool.free_all("a") == 0  # idempotent

    def test_pages_for(self):
        assert pages_for(0, 2) == 0
        assert pages_for(1, 2) == 1
        assert pages_for(5, 2) == 3

    def test_priority_order_and_fifo_tiebreak(self):
        s = Scheduler(queue_limit=8)
        for i, pri in enumerate([0, 2, 1, 2]):
            s.submit(Entry(request=req(i, priority=pri), submit_time=0.0, seq=i))
        assert [s.pop().request_id for _ in range(4)] == ["r1", "r3", "r2", "r0"]

    def test_preemption_ages_priority(self):
        """The livelock guard: each eviction boosts effective priority, so
        an evicted request eventually outranks fresh same-priority work."""
        s = Scheduler(queue_limit=8, preempt_priority_boost=1)
        evicted = Entry(request=req(0, priority=0), submit_time=0.0, seq=0,
                        preempt_count=2)
        fresh = Entry(request=req(1, priority=1), submit_time=0.0, seq=1)
        assert s.effective_priority(evicted) == 2 > s.effective_priority(fresh)
        s.requeue(evicted)
        s.submit(fresh)
        assert s.pop() is evicted

    def test_bounded_queue(self):
        s = Scheduler(queue_limit=1)
        assert s.submit(Entry(request=req(0), submit_time=0.0, seq=0))
        assert not s.submit(Entry(request=req(1), submit_time=0.0, seq=1))
        # a requeued (admitted-once) entry neither gets bounced by the
        # bound nor occupies it against fresh arrivals
        popped = s.pop()
        s.requeue(popped)
        assert s.submit(Entry(request=req(2), submit_time=0.0, seq=2))
        assert len(s) == 2


# ------------------------------------------------------------ admission


class TestAdmission:
    def test_demand_exceeds_pool_rejected_typed(self, model):
        eng = make_engine(model, page_budget=2)  # worst case needs 4 pages
        res = eng.submit(req(0))
        assert res is not None and res.outcome is Outcome.REJECTED
        assert res.reject_reason is RejectReason.DEMAND_EXCEEDS_POOL
        assert eng.results["r0"] is res
        outcome_accounting_holds(eng)

    def test_queue_full_rejected_typed(self, model):
        eng = make_engine(model, queue_limit=1)
        assert eng.submit(req(0)) is None
        res = eng.submit(req(1))
        assert res is not None and res.reject_reason is RejectReason.QUEUE_FULL
        eng.run(max_steps=200)
        outcomes = outcome_accounting_holds(eng)
        assert outcomes["completed"] == 1 and outcomes["rejected"] == 1

    def test_duplicate_request_id_raises(self, model):
        eng = make_engine(model)
        assert eng.submit(req(0)) is None
        with pytest.raises(ValueError, match="duplicate"):
            eng.submit(req(0))

    def test_max_new_tokens_bounds(self, model):
        eng = make_engine(model)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(req(0, max_new=99))

    def test_gmlp_model_typed_unsupported(self):
        dalle = small_dalle(attn_types=("mlp", "full"))
        with pytest.raises(EngineUnsupportedModel, match="gMLP"):
            Engine(dalle, params=None)


# --------------------------------------------------- deadlines & cancel


class TestDeadlinesCancel:
    def test_deadline_expiry_mid_decode_frees_pages(self, model):
        clock = FakeClock(step_dt=1.0)
        eng = make_engine(model, clock=clock)
        # admits at t=0; each decode iteration costs 1s; expires mid-decode
        assert eng.submit(req(0, max_new=4, deadline=1.5)) is None
        eng.run(max_steps=200)
        res = eng.results["r0"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert res.tokens is not None and 0 < len(res.tokens) < 4  # partial
        assert eng.pool.used == 0
        outcome_accounting_holds(eng)

    def test_deadline_expired_in_queue(self, model):
        clock = FakeClock(step_dt=1.0)
        eng = make_engine(model, max_batch=1, clock=clock)
        assert eng.submit(req(0, max_new=4)) is None
        assert eng.submit(req(1, max_new=4, deadline=2.0)) is None  # waits
        eng.run(max_steps=200)
        assert eng.results["r0"].outcome is Outcome.COMPLETED
        res = eng.results["r1"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert res.tokens is None  # never prefilled
        outcome_accounting_holds(eng)

    def test_cancellation_frees_pages(self, model):
        eng = make_engine(model)
        assert eng.submit(req(0, max_new=4)) is None
        eng.step()  # admit + first decode
        assert eng.pool.used > 0
        eng.cancel("r0")
        eng.run(max_steps=200)
        res = eng.results["r0"]
        assert res.outcome is Outcome.CANCELLED
        assert res.tokens is not None and len(res.tokens) < 4
        assert eng.pool.used == 0
        outcome_accounting_holds(eng)

    def test_cancel_queued_request(self, model):
        eng = make_engine(model, max_batch=1)
        assert eng.submit(req(0)) is None
        assert eng.submit(req(1)) is None
        eng.step()  # r0 admitted, r1 queued
        eng.cancel("r1")
        eng.run(max_steps=200)
        assert eng.results["r1"].outcome is Outcome.CANCELLED
        assert eng.results["r1"].tokens is None
        assert eng.results["r0"].outcome is Outcome.COMPLETED
        outcome_accounting_holds(eng)

    def test_request_cancel_fault_site(self, model):
        FAULTS.arm("request_cancel", 1)
        eng = make_engine(model)
        for i in range(2):
            assert eng.submit(req(i)) is None
        eng.run(max_steps=200)
        outcomes = outcome_accounting_holds(eng)
        assert outcomes["cancelled"] == 1
        assert FAULTS.fired.get("request_cancel") == 1

    def test_decode_stall_fault_pushes_past_deadline(self, model):
        FAULTS.arm("decode_stall", 1)
        clock = FakeClock(step_dt=0.0)  # ONLY the stall advances time
        eng = make_engine(model, clock=clock, stall_penalty_s=10.0)
        assert eng.submit(req(0, deadline=5.0)) is None
        eng.run(max_steps=200)
        assert eng.results["r0"].outcome is Outcome.DEADLINE_EXCEEDED
        assert FAULTS.fired.get("decode_stall") == 1
        outcome_accounting_holds(eng)


# ------------------------------------------------- preempt-and-requeue


class TestPreemption:
    def run_trace(self, model, fault_spec=None, **cfg_kw):
        FAULTS.reset()
        counters.reset()
        if fault_spec:
            FAULTS.configure(fault_spec)
        eng = make_engine(model, **cfg_kw)
        for i in range(3):
            assert eng.submit(req(i)) is None
        eng.run(max_steps=500)
        return eng

    def test_preempt_requeue_bit_identical(self, model):
        """THE acceptance criterion: an injected page_exhaust forces an
        eviction; the evicted request re-prefills from scratch and its
        final tokens are BIT-identical to the unpreempted run (pure
        (seed, position) sampling keys + row-independent fixed-width
        decode), and every page returns to the pool."""
        clean = self.run_trace(model)
        clean_tokens = {
            rid: np.asarray(r.tokens) for rid, r in clean.results.items()
        }
        faulted = self.run_trace(model, fault_spec="page_exhaust=1")
        assert FAULTS.fired.get("page_exhaust") == 1
        assert counters.get("serve.preempted") >= 1
        assert any(r.preempt_count > 0 for r in faulted.results.values())
        for rid, r in faulted.results.items():
            assert r.outcome is Outcome.COMPLETED, (rid, r)
            np.testing.assert_array_equal(
                np.asarray(r.tokens), clean_tokens[rid],
                err_msg=f"{rid} tokens diverged across preemption",
            )
        assert faulted.pool.used == 0
        outcome_accounting_holds(faulted)

    def test_preempt_cap_is_typed_failure(self, model):
        eng = self.run_trace(
            model, fault_spec="page_exhaust=1", max_preemptions=0
        )
        outcomes = outcome_accounting_holds(eng)
        assert outcomes["preempt_cap"] == 1
        capped = [
            r for r in eng.results.values()
            if r.outcome is Outcome.PREEMPT_CAP
        ]
        assert capped[0].preempt_count == 1
        assert eng.pool.used == 0

    def test_victim_is_lowest_priority_youngest(self, model):
        """Eviction order: the low-priority request dies for the
        high-priority one's pages, and aging boosts it on requeue."""
        FAULTS.arm("page_exhaust", 1)
        eng = make_engine(model, max_batch=2)
        assert eng.submit(req(0, priority=5)) is None
        assert eng.submit(req(1, priority=0)) is None
        eng.run(max_steps=500)
        assert eng.results["r0"].preempt_count == 0
        assert eng.results["r1"].preempt_count == 1
        assert all(
            r.outcome is Outcome.COMPLETED for r in eng.results.values()
        )
        outcome_accounting_holds(eng)

    def test_natural_exhaustion_under_tight_pool(self, model):
        """No faults: a page budget below the runnable batch's aggregate
        demand makes decode-time growth collide for real; the engine must
        still complete everything via preempt-and-requeue."""
        # worst case per request = pages_for(5 + 3, 2) = 4, prompt = 3.
        # Budget 7 admits two requests (3 + 3 held, 1 free — each passed
        # the worst-vs-free gate at ITS admission instant) whose combined
        # growth then wants 2 more pages than exist: optimistic admission
        # cannot see the collision coming, preemption absorbs it.
        eng = self.run_trace(model, page_budget=7)
        outcomes = outcome_accounting_holds(eng)
        assert outcomes["completed"] == 3
        assert counters.get("serve.preempted") >= 1
        assert eng.pool.used == 0


# ------------------------------------------------ degradation & overload


class TestDegradationOverload:
    def test_watermark_clamp_reported(self, model):
        eng = make_engine(
            model, max_batch=2,
            high_watermark=0.0,  # any occupancy counts as pressure
            degraded_max_new_tokens=2,
        )
        assert eng.submit(req(0, max_new=4)) is None
        assert eng.submit(req(1, max_new=4)) is None
        eng.run(max_steps=200)
        # first admission happens at 0 occupancy -> unclamped; the second
        # sees the first's pages resident -> clamped, and SAYS so
        r0, r1 = eng.results["r0"], eng.results["r1"]
        clamped = [r for r in (r0, r1) if r.clamped_max_new_tokens is not None]
        full = [r for r in (r0, r1) if r.clamped_max_new_tokens is None]
        assert len(clamped) == 1 and len(full) == 1
        assert clamped[0].outcome is Outcome.COMPLETED
        assert len(clamped[0].tokens) == 2 == clamped[0].clamped_max_new_tokens
        assert len(full[0].tokens) == 4
        assert counters.get("serve.clamped") == 1
        outcome_accounting_holds(eng)

    def test_combined_faults_overload_all_accounted(self, model):
        """The combined acceptance scenario: aggregate demand far over the
        pool, a bounded queue, deadlines, and injected prefill_fail +
        page_exhaust (the DALLE_TPU_FAULTS env spec format). No hang, no
        allocation failure, and every submitted request ends in exactly one
        typed outcome with counters summing to 100%."""
        FAULTS.configure("page_exhaust=1,prefill_fail=1")
        clock = FakeClock(step_dt=1.0)
        eng = make_engine(
            model, clock=clock, max_batch=2, page_budget=7, queue_limit=3,
            prefill_attempts=2,
        )
        immediate = []
        for i in range(8):
            r = eng.submit(req(
                i, max_new=4,
                deadline=None if i % 2 else 40.0,
                priority=i % 3,
            ))
            if r is not None:
                immediate.append(r)
        eng.run(max_steps=1000)
        outcomes = outcome_accounting_holds(eng)
        assert sum(outcomes.values()) == 8
        assert outcomes["rejected"] == len(immediate) > 0  # bounded queue bit
        # the transient prefill failure was retried, not surfaced
        assert counters.get("serve.prefill_retries") == 1
        assert FAULTS.fired.get("prefill_fail") == 1
        assert FAULTS.fired.get("page_exhaust") == 1
        assert eng.pool.used == 0
        for r in eng.results.values():
            assert r.outcome in (
                Outcome.COMPLETED, Outcome.REJECTED,
                Outcome.DEADLINE_EXCEEDED, Outcome.PREEMPT_CAP,
            ), r

    def test_prefill_fail_exhausts_attempts_typed(self, model):
        FAULTS.arm("prefill_fail", 5)
        eng = make_engine(model, prefill_attempts=2)
        assert eng.submit(req(0)) is None
        eng.run(max_steps=200)
        res = eng.results["r0"]
        assert res.outcome is Outcome.PREFILL_FAILED
        assert res.prefill_attempts == 2
        assert eng.pool.used == 0
        outcome_accounting_holds(eng)

    def test_gauges_published(self, model):
        gauges.reset()
        eng = make_engine(model)
        assert eng.submit(req(0)) is None
        eng.step()
        snap = gauges.snapshot("serve.")
        assert set(snap) == {
            "serve.pool_occupancy", "serve.running", "serve.prefilling",
            "serve.queued",
            # KV storage-format footprint (ISSUE 14): published from
            # construction on, whatever the kv_quant setting
            "serve.kv_quant.bytes_per_slot", "serve.kv_quant.pages",
        }
        assert snap["serve.running"] == 1
        assert snap["serve.kv_quant.bytes_per_slot"] == float(
            eng.kv_bytes_per_slot
        )
        eng.run(max_steps=200)
        assert gauges.get("serve.pool_occupancy") == 0.0


# --------------------------------------------- decode-path correctness


class TestEngineDecodeParity:
    def test_tokens_independent_of_batch_width_composition(self, model):
        """Row independence at the engine level: the same request produces
        identical tokens alone in a max_batch=1 engine and sharing a
        max_batch=2 engine with unrelated traffic — the property the
        bit-identical preemption replay stands on."""
        dalle, params = model

        def run(max_batch, n_extra):
            eng = Engine(
                dalle, params, EngineConfig(max_batch=max_batch),
                clock=FakeClock(step_dt=0.1),
            )
            assert eng.submit(req(0, max_new=4)) is None
            for i in range(n_extra):
                assert eng.submit(req(10 + i, max_new=4)) is None
            eng.run(max_steps=500)
            return np.asarray(eng.results["r0"].tokens)

        alone = run(1, 0)
        shared = run(2, 3)
        np.testing.assert_array_equal(alone, shared)

    def test_ragged_nonrotary_step_matches_per_sequence(self):
        """Vector-position decode_step with LEARNED positional tables
        (rotary_emb=False — the train_dalle.py CLI default): the merged
        ragged step must match each sequence's own scalar-position step,
        which is what lets generate.py route non-rotary checkpoints
        through the engine."""
        dalle = small_dalle(rotary_emb=False)
        rng = np.random.RandomState(0)
        text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
        image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = np.concatenate(
            (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
        )

        def replay(row, upto):
            cache = init_decode_cache(dalle, params, 1, cache_format="paged")
            for i in range(upto):
                _, mutated = dalle.apply(
                    {"params": params, "cache": cache},
                    jnp.asarray(internal[row: row + 1, i]),
                    jnp.array(i, jnp.int32),
                    method=DALLE.decode_step, mutable=["cache"],
                )
                cache = mutated["cache"]
            return cache

        offs = (6, 8)
        caches = [replay(r, o) for r, o in enumerate(offs)]
        from dalle_pytorch_tpu.models import merge_decode_caches

        merged = merge_decode_caches(caches)
        tok = jnp.asarray([internal[r, o] for r, o in enumerate(offs)], jnp.int32)
        ragged_logits, _ = dalle.apply(
            {"params": params, "cache": merged},
            tok, jnp.asarray(offs, jnp.int32),
            method=DALLE.decode_step, mutable=["cache"],
        )
        for r, o in enumerate(offs):
            ref, _ = dalle.apply(
                {"params": params, "cache": caches[r]},
                tok[r: r + 1], jnp.array(o, jnp.int32),
                method=DALLE.decode_step, mutable=["cache"],
            )
            np.testing.assert_allclose(
                np.asarray(ragged_logits[r: r + 1]), np.asarray(ref),
                atol=1e-5, rtol=1e-5,
                err_msg=f"non-rotary ragged step diverged (seq {r})",
            )

    def test_insert_decode_cache_rejects_unvectorized(self, model):
        dalle, params = model
        batched = init_decode_cache(dalle, params, 2, cache_format="paged")
        sub = init_decode_cache(dalle, params, 1, cache_format="paged")
        # scalar shift_index leaf -> must be refused with guidance
        with pytest.raises(ValueError, match="set_decode_offsets"):
            insert_decode_cache(batched, sub, 0)

    def test_insert_decode_cache_rejects_unpaged(self, model):
        dalle, params = model
        batched = init_decode_cache(dalle, params, 2, cache_format="flat")
        with pytest.raises(ValueError, match="paged"):
            insert_decode_cache(batched, batched, 0)


# ------------------------------------------- donation + compile budget


def _leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestDonation:
    """The serving jits donate their cache (ISSUE 8 satellite): donation
    must change HBM residency, never tokens — pinned here bit-exactly
    against non-donating rewraps of the same functions — and the shared
    pristine template must survive it (jax deletes donated buffers on
    CPU too, so any template reuse would crash loudly in this suite).
    The static regression guard is DTL12x in `tools/lint.py --trace`."""

    def test_prefill_and_decode_bit_identical_to_undonated(self, model):
        from functools import partial

        from dalle_pytorch_tpu.models.sampling import set_decode_offsets
        from dalle_pytorch_tpu.serving import engine as eng

        dalle, params = model
        pre_nd = partial(
            jax.jit, static_argnums=(0, 5)
        )(eng._prefill_jit.__wrapped__)
        dec_nd = partial(
            jax.jit, static_argnums=(0, 6)
        )(eng._decode_jit.__wrapped__)
        fresh = set_decode_offsets(
            init_decode_cache(dalle, params, 1, cache_format="paged"),
            jnp.zeros((1,), jnp.int32),
        )
        text = jnp.asarray(prompt(0), jnp.int32)[None, :]
        internal = dalle.remap_text(text)
        T = dalle.text_len_internal
        k = max(int((1 - 0.9) * dalle.total_tokens), 1)
        key = jax.random.fold_in(jax.random.key(7), T)

        donated_in = jax.tree_util.tree_map(jnp.copy, fresh)
        c_d, t_d, img_d = eng._prefill_jit(
            dalle, params, donated_in, internal, key, k, 1.0
        )
        c_n, t_n, img_n = pre_nd(dalle, params, fresh, internal, key, k, 1.0)
        assert int(t_d[0]) == int(t_n[0])
        _leaves_equal(c_d, c_n)
        _leaves_equal(img_d, img_n)

        # one vector-position decode step, donated vs not, equal caches in
        batched = set_decode_offsets(
            init_decode_cache(dalle, params, 2, cache_format="paged"),
            jnp.zeros((2,), jnp.int32),
        )
        batched = insert_decode_cache(batched, c_d, 0)
        batched2 = jax.tree_util.tree_map(jnp.copy, batched)
        tok = jnp.asarray([int(t_d[0]), 0], jnp.int32)
        pos = jnp.asarray([T, 0], jnp.int32)
        keys = jnp.stack([
            jax.random.fold_in(jax.random.key(7), T + 1),
            jax.random.key(0),
        ])
        cd2, sd = eng._decode_jit(dalle, params, batched, tok, pos, keys, k, 1.0)
        cn2, sn = dec_nd(dalle, params, batched2, tok, pos, keys, k, 1.0)
        assert int(sd[0]) == int(sn[0])
        _leaves_equal(cd2, cn2)

    def test_fresh_template_survives_sequential_prefills(self, model):
        """Two requests prefilled back-to-back from the same engine: both
        monolithic prefills start from the SAME pristine template, which
        the donating jit must therefore never consume directly."""
        engine = make_engine(model)
        assert engine.submit(req(0, max_new=3)) is None
        engine.run(max_steps=200)
        assert engine.submit(req(1, max_new=3)) is None
        engine.run(max_steps=200)
        check_accounting(engine)
        assert engine.results["r0"].outcome is Outcome.COMPLETED
        assert engine.results["r1"].outcome is Outcome.COMPLETED
        assert not any(
            x.is_deleted() for x in jax.tree_util.tree_leaves(engine._fresh1)
        ), "donation consumed the shared pristine prefill template"

    def test_decode_jit_compiles_once_steady_state(self, model):
        """The DTL11x acceptance property at runtime: a multi-request
        engine run (admissions landing mid-decode, completions freeing
        slots) feeds `_decode_jit` EXACTLY one compile signature; an
        injected shape-drifting call compiles a second one — the drift
        the committed compile-signature contract turns into a lint
        failure (tests/fixtures_lint: DTL111)."""
        from dalle_pytorch_tpu.serving import engine as eng

        dalle, params = model
        # max_batch=5 is used nowhere else in this module: the signature
        # is fresh, so the compile-count delta is exact, not <=
        engine = Engine(dalle, params, EngineConfig(max_batch=5),
                        clock=FakeClock(step_dt=0.1))
        before = eng._decode_jit._cache_size()
        for i in range(8):
            assert engine.submit(req(i, max_new=4)) is None
        engine.run(max_steps=800)
        check_accounting(engine)
        assert all(
            r.outcome is Outcome.COMPLETED for r in engine.results.values()
        )
        assert eng._decode_jit._cache_size() - before == 1, (
            "steady-state decode recompiled: the engine fed _decode_jit "
            "more than one (shape, dtype, static) signature"
        )
        # inject shape drift: a second engine at a different batch width
        # is a second signature — exactly what DTL111/DTL113 would flag
        # if the registry/engine started producing it
        drift = Engine(dalle, params, EngineConfig(max_batch=6),
                       clock=FakeClock(step_dt=0.1))
        assert drift.submit(req(90, max_new=2)) is None
        drift.run(max_steps=200)
        assert eng._decode_jit._cache_size() - before == 2


# ----------------------------------------------------- release gates


@pytest.mark.slow
def test_serve_smoke_tool():
    """The release gate must pass clean AND absorb an env-armed transient
    prefill fault (the DALLE_TPU_FAULTS inheritance path through a real
    subprocess)."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for extra_env in ({}, {"DALLE_TPU_FAULTS": "prefill_fail=1"}):
        env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
        out = subprocess.run(
            [sys.executable, "tools/serve_smoke.py"],
            capture_output=True, text=True, timeout=600, env=env, cwd=repo,
        )
        assert out.returncode == 0, (extra_env, out.stderr[-2000:])
        assert "serve smoke OK" in out.stderr


@pytest.mark.slow
def test_bench_serve_record():
    """bench.py --serve must emit a record carrying the request-latency
    percentiles and the robustness counters."""
    import json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--serve"],
        capture_output=True, text=True, timeout=900, env=env, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    serve = [r for r in recs if r["metric"].startswith("serve_request_latency")]
    assert len(serve) == 1
    r = serve[0]
    for k in ("p95_ms", "p99_ms", "rejected", "preempted", "deadline_exceeded",
              "pool_occupancy_mean", "pool_occupancy_max", "arrival_seed",
              # telemetry-era keys: histogram-sourced splits + the
              # measured on/off overhead (ISSUE 4 acceptance)
              "queue_p50_ms", "queue_p95_ms", "prefill_p50_ms",
              "decode_step_p50_ms", "completed_tokens_per_sec",
              "tokens_per_sec_telemetry_on", "telemetry_overhead_frac",
              "telemetry_ring_dropped",
              # chunked-prefill era: TTFT percentiles ride the same
              # histogram mechanism as the other splits
              "ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
              # compile accounting (ISSUE 8): recompiles are first-class
              "compiles_warm", "compiles_in_trace",
              "jit_signatures_warm", "jit_recompiles_in_trace"):
        assert k in r, k
    # the timed trace must be recompile-free in every serving jit —
    # the runtime twin of the DTL11x compile-signature contract
    assert all(v == 0 for v in r["jit_recompiles_in_trace"].values()), r[
        "jit_recompiles_in_trace"
    ]
    assert r["completed"] + r["rejected"] + r["deadline_exceeded"] <= r["n_requests"]
    assert r["value"] > 0
    assert r["tokens_per_sec_telemetry_on"] > 0
    assert r["latency_source"].startswith("telemetry_histogram")
    # the interference scenario record rides the same --serve invocation;
    # its emission implies the in-bench acceptance assert held (chunked
    # max decode gap < monolithic)
    inter = [r for r in recs if r["metric"].startswith("serve_interference")]
    assert len(inter) == 1
    assert inter[0]["value"] > 0
    assert inter[0]["value"] < inter[0]["monolithic_max_gap_ms"]
    assert inter[0]["n_chunks"] > 1
    # the zipf-of-prefixes record rides the same invocation; emission
    # implies the in-bench acceptance held (hit rate > 0.5, cached TTFT
    # p50 < cold, bit-identical template tokens, zero in-trace compiles)
    pre = [r for r in recs if r["metric"].startswith("serve_prefix")]
    assert len(pre) == 1
    assert pre[0]["hit_rate"] > 0.5
    assert pre[0]["ttft_cached_p50_ms"] < pre[0]["ttft_cold_p50_ms"]
    assert pre[0]["pages_deduped"] > 0
