"""Parallel runtime tests on the virtual 8-device CPU mesh (the TPU-native
analog of the reference's DummyBackend fake, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.parallel import (
    create_train_state,
    make_runtime,
    make_train_step,
    params_shardings,
)


def small_dalle():
    return DALLE(
        dim=64,
        depth=2,
        num_text_tokens=24,
        text_seq_len=8,
        num_image_tokens=16,
        image_fmap_size=4,
        heads=4,
        dim_head=16,
        attn_types=("full", "axial_row"),
    )


def make_batch(dalle, b=8, seed=0):
    rng = np.random.RandomState(seed)
    text = jnp.asarray(rng.randint(1, 20, size=(b, dalle.text_seq_len)), jnp.int32)
    image = jnp.asarray(
        rng.randint(0, dalle.num_image_tokens, size=(b, dalle.image_seq_len)), jnp.int32
    )
    return {"text": text, "image": image}


def dalle_loss_fn(dalle):
    def loss_fn(params, batch, rng):
        return dalle.apply(
            {"params": params}, batch["text"], batch["image"], return_loss=True
        )

    return loss_fn


class TestMeshRuntime:
    def test_default_runtime_all_dp(self):
        rt = make_runtime()
        assert rt.world_size == 8
        assert rt.mesh.shape["dp"] == 8
        assert rt.data_spec == P(("dp",))
        assert rt.is_root_worker()
        rt.check_batch_size(8)
        with pytest.raises(AssertionError):
            rt.check_batch_size(4)

    def test_mixed_mesh_shapes(self):
        rt = make_runtime(fsdp=2, tp=2)
        assert rt.mesh.shape == {
            "dp": 2, "fsdp": 2, "tp": 2, "sp": 1, "pp": 1, "ep": 1,
        }
        assert rt.data_spec == P(("dp", "fsdp"))

    def test_bad_mesh_rejected(self):
        with pytest.raises(AssertionError):
            make_runtime(dp=3, fsdp=2)


class TestSharding:
    def test_tp_rules_applied(self):
        dalle = small_dalle()
        batch = make_batch(dalle)
        params = dalle.init(jax.random.key(0), batch["text"], batch["image"])["params"]
        rt = make_runtime(fsdp=2, tp=2)
        shardings = params_shardings(params, rt.mesh)

        flat = {
            "/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
        }
        qkv = next(v for k, v in flat.items() if k.endswith("to_qkv/kernel"))
        assert qkv.spec == P("fsdp", "tp")
        out = next(v for k, v in flat.items() if k.endswith("to_out/kernel"))
        assert out.spec == P("tp", "fsdp")
        emb = next(v for k, v in flat.items() if k.endswith("text_emb/embedding"))
        assert emb.spec == P("fsdp", "tp")

    def test_indivisible_rule_degrades(self):
        """A rule axis that doesn't divide the tensor is dropped, not fatal."""
        from dalle_pytorch_tpu.parallel.sharding import partition_spec

        rt = make_runtime(fsdp=2, tp=4)
        # neither 5 % 2 nor 7 % 4 divide -> both rule axes dropped
        spec = partition_spec("x/to_qkv/kernel", (5, 7), rt.mesh)
        assert spec == P(None, None)
        # one dividing axis is kept
        spec = partition_spec("x/to_qkv/kernel", (6, 7), rt.mesh)
        assert spec == P("fsdp", None)


class TestTrainStep:
    def _run(self, runtime, n_steps=3):
        dalle = small_dalle()
        batch = make_batch(dalle)
        params = dalle.init(jax.random.key(0), batch["text"], batch["image"])["params"]
        opt = optax.chain(optax.clip_by_global_norm(0.5), optax.adam(1e-3))
        state, shardings = create_train_state(params, opt, runtime)
        step = make_train_step(dalle_loss_fn(dalle), opt, runtime, shardings)
        losses = []
        for i in range(n_steps):
            state, loss = step(state, batch, jax.random.key(i))
            losses.append(float(loss))
        return losses

    def test_dp_matches_single_device(self):
        """The same model/batch must produce the same losses on a 1-device
        and an 8-device data-parallel mesh."""
        single = self._run(make_runtime(devices=jax.devices()[:1]))
        dp8 = self._run(make_runtime())
        np.testing.assert_allclose(single, dp8, rtol=2e-4)

    def test_fsdp_tp_matches_dp(self):
        """ZeRO-style param sharding + tensor parallelism must be numerically
        equivalent to pure data parallelism: same loss, same gradients.

        The assertion is on loss + gradients, not a multi-step trajectory:
        different meshes legally reorder floating-point reductions (~1e-7
        relative), and Adam's early steps amplify any such perturbation
        (update ~ g/sqrt(g^2) is sign-like for small g), so step-3 losses
        across meshes can drift to ~1e-3 with bit-different-but-correct
        gradients."""
        from dalle_pytorch_tpu.parallel import shard_pytree

        dalle = small_dalle()
        batch = make_batch(dalle)
        params = dalle.init(jax.random.key(0), batch["text"], batch["image"])[
            "params"
        ]
        loss_fn = dalle_loss_fn(dalle)

        def value_grad(runtime):
            sh = params_shardings(params, runtime.mesh)
            p = shard_pytree(params, sh)
            with runtime.activate():
                l, g = jax.jit(
                    jax.value_and_grad(lambda p: loss_fn(p, batch, None)),
                    in_shardings=(sh,),
                    out_shardings=(None, sh),
                )(p)
            return float(l), jax.tree_util.tree_map(np.asarray, g)

        l_dp, g_dp = value_grad(make_runtime())
        l_mx, g_mx = value_grad(make_runtime(dp=2, fsdp=2, tp=2))
        np.testing.assert_allclose(l_dp, l_mx, rtol=1e-5)
        for a, e in zip(
            jax.tree_util.tree_leaves(g_mx), jax.tree_util.tree_leaves(g_dp)
        ):
            np.testing.assert_allclose(a, e, atol=1e-5, rtol=1e-3)

    def test_loss_decreases(self):
        losses = self._run(make_runtime(fsdp=4, tp=2), n_steps=10)
        assert losses[-1] < losses[0]

    def test_params_actually_sharded(self):
        dalle = small_dalle()
        batch = make_batch(dalle)
        params = dalle.init(jax.random.key(0), batch["text"], batch["image"])["params"]
        rt = make_runtime(fsdp=2, tp=2)
        opt = optax.adam(1e-3)
        state, _ = create_train_state(params, opt, rt)
        qkv = state.params["transformer"]["attn_0"]["fn"]["fn"]["fn"]["to_qkv"]["kernel"]
        # sharded over fsdp x tp: each device holds 1/4 of the elements
        shard = qkv.addressable_shards[0]
        assert shard.data.size == qkv.size // 4
        # adam moments inherit the sharding (ZeRO)
        mu = state.opt_state[0].mu["transformer"]["attn_0"]["fn"]["fn"]["fn"]["to_qkv"]["kernel"]
        assert mu.addressable_shards[0].data.size == mu.size // 4
