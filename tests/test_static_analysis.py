"""dalle-tpu-lint framework tests (tools/lint/, docs/DESIGN.md §11).

Two layers:

1. **Fixture corpus** (tests/fixtures_lint/): known-bad snippets, AST-
   parsed only (never imported), with exact finding codes AND lines
   pinned per checker — each one a violation the checker would have
   caught at review time that runtime tests would miss. Includes one
   inline-suppressed case and one baselined case, pinning both escape
   hatches.
2. **The repo gate**: ``python tools/lint.py --check`` over the whole
   package must exit 0 — the same pre-flight tools/serve_smoke.py and
   tools/telemetry_smoke.py run. A lint finding anywhere in the tree
   fails the fast tier here, not at the next release drill.

The AST stage is stdlib-only and never imports the package it checks,
so those tests run in milliseconds with no jax involvement. The TRACE
stage (tools/lint/trace/, ``--trace``, DTL1xx) is the exception by
design: its fixture registry jits are traced (never executed) with jax
on CPU, and the repo gate audits the real package's entry points
against tools/trace_contracts.json.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint import (  # noqa: E402  (tools/lint package, stdlib-only)
    FaultConfig,
    LayerRule,
    LintConfig,
    NamesConfig,
    default_config,
    run_lint,
)

FX = "tests/fixtures_lint"


def fixture_config(**kw) -> LintConfig:
    base = dict(
        repo_root=str(REPO),
        scan_roots=(),
        exclude=(),
        layer_rules=(),
        faults=None,
        names=None,
        baseline_path=None,
    )
    base.update(kw)
    return LintConfig(**base)


def codes_lines(findings):
    return sorted((f.code, f.line) for f in findings)


# ------------------------------------------------------------- purity


class TestPurity:
    def run(self, baseline=None):
        cfg = fixture_config(baseline_path=baseline)
        return run_lint(cfg, paths=[f"{FX}/fx_purity.py"],
                        checkers=["purity"])

    def test_exact_codes_and_lines(self):
        res = self.run()
        assert codes_lines(res.findings) == [
            ("DTL011", 19),   # if on traced value
            ("DTL011", 66),   # while on traced value (baselined case, no
                              # baseline loaded in this run)
            ("DTL011", 73),   # twin branch 1
            ("DTL011", 75),   # twin branch 2
            ("DTL012", 29),   # float() on propagated taint
            ("DTL012", 30),   # .item()
            ("DTL013", 36),   # time.time() in the jitted fn
            ("DTL013", 41),   # np.random reached from a jitted fn
            ("DTL014", 37),   # mutable module-global closure
        ], [f.render() for f in res.findings]

    def test_colliding_anchors_get_occurrence_suffixes(self):
        """Two same-shape violations in one function must carry DISTINCT
        baseline keys — otherwise one baseline entry would silently
        grandfather every future violation of that shape there."""
        res = self.run()
        keys = sorted(f.key for f in res.findings
                      if "twin_branches" in f.anchor)
        assert keys == [
            f"{FX}/fx_purity.py::DTL011::twin_branches:If",
            f"{FX}/fx_purity.py::DTL011::twin_branches:If#2",
        ]

    def test_static_args_and_none_checks_are_clean(self):
        res = self.run()
        lines = {f.line for f in res.findings}
        assert 26 not in lines   # `if n > 2` — n is static_argnums
        assert 51 not in lines   # `if mask is None` — structure check

    def test_inline_suppression(self):
        res = self.run()
        sup = [f for f in res.suppressed]
        assert [(f.code, f.line) for f in sup] == [("DTL011", 59)]
        assert not any(f.line == 59 for f in res.findings)

    def test_baseline_grandfathers_and_reports_stale(self):
        res = self.run(baseline=f"{FX}/fx_baseline.json")
        assert ("DTL011", 66) not in codes_lines(res.findings)
        assert [(f.code, f.line) for f in res.baselined] == [("DTL011", 66)]
        assert res.stale_baseline == []


# ----------------------------------------------------------- layering


class TestLayering:
    def test_host_only_rule_flags_lazy_imports_too(self):
        cfg = fixture_config(layer_rules=(
            LayerRule(name="fx-host-only",
                      files=(f"{FX}/fx_layering_host.py",),
                      forbid=("jax", "flax"), why="fixture"),
        ))
        res = run_lint(cfg, paths=[f"{FX}/fx_layering_host.py"],
                       checkers=["layering"])
        assert codes_lines(res.findings) == [
            ("DTL021", 4), ("DTL021", 8),
        ], [f.render() for f in res.findings]

    def test_ops_must_not_import_serving(self):
        cfg = fixture_config(layer_rules=(
            LayerRule(name="fx-ops",
                      files=(f"{FX}/fx_layering_ops.py",),
                      forbid=("dalle_pytorch_tpu.serving",), why="fixture"),
        ))
        res = run_lint(cfg, paths=[f"{FX}/fx_layering_ops.py"],
                       checkers=["layering"])
        assert codes_lines(res.findings) == [
            ("DTL021", 4),   # from x.serving import engine
            ("DTL021", 5),   # from x.serving.types import Request
            ("DTL021", 8),   # from x import serving — the from-parent
                             # spelling lands in the alias list
        ], [f.render() for f in res.findings]

    def test_relative_imports_resolve_against_package(self):
        # the REAL repo rule: utils/telemetry.py's `from .faults import`
        # resolves to dalle_pytorch_tpu.utils.faults and must NOT trip
        # the host-only rule, while any jax import would
        res = run_lint(default_config(str(REPO)),
                       paths=["dalle_pytorch_tpu/utils/telemetry.py"],
                       checkers=["layering"])
        assert res.clean, [f.render() for f in res.findings]


# -------------------------------------------------------- fault sites


class TestFaultSites:
    def run(self):
        cfg = fixture_config(faults=FaultConfig(
            registry_path=f"{FX}/fx_faults_registry.py",
            exercise_roots=(f"{FX}/fx_faults_tests.py",),
        ))
        return run_lint(cfg, paths=[f"{FX}/fx_faults.py"],
                        checkers=["fault-sites"], full=True)

    def test_unknown_dead_and_undrilled_sites(self):
        res = self.run()
        by_code = {}
        for f in res.findings:
            by_code.setdefault(f.code, []).append(f)
        # two unregistered literals at their exact take-site lines
        assert [(f.line, f.anchor) for f in by_code["DTL031"]] == [
            (21, "typo_site"), (23, "typo_site_2"),
        ]
        # dead_site is registered + drilled but never taken
        assert [f.anchor for f in by_code["DTL032"]] == ["dead_site"]
        # undrilled_site is registered + taken but never exercised —
        # the corpus docstring MENTIONING "undrilled_site=1" does not
        # count (documentation of a drill is not a drill)
        assert [f.anchor for f in by_code["DTL033"]] == ["undrilled_site"]

    def test_narrowed_scan_skips_registry_completeness(self):
        cfg = fixture_config(faults=FaultConfig(
            registry_path=f"{FX}/fx_faults_registry.py",
            exercise_roots=(f"{FX}/fx_faults_tests.py",),
        ))
        res = run_lint(cfg, paths=[f"{FX}/fx_faults.py"],
                       checkers=["fault-sites"])  # full defaults to False
        assert {f.code for f in res.findings} == {"DTL031"}


# ----------------------------------------------------- telemetry names


class TestTelemetryNames:
    def run(self, full=True):
        cfg = fixture_config(names=NamesConfig(
            registry_path=f"{FX}/fx_names_registry.py",
            doc_path=f"{FX}/fx_names_doc.md",
        ))
        return run_lint(cfg, paths=[f"{FX}/fx_names.py"],
                        checkers=["telemetry-names"], full=full)

    def test_typo_kind_mismatch_and_bad_fstring_head(self):
        res = self.run(full=False)
        assert codes_lines(res.findings) == [
            ("DTL041", 9),    # fx.typo: unregistered
            ("DTL041", 10),   # fx.known used as gauge: kind mismatch
            ("DTL041", 16),   # f"fx.bogus.{...}": head matches nothing
        ], [f.render() for f in res.findings]

    def test_span_duration_histograms_are_derived(self):
        res = self.run(full=False)
        assert 12 not in {f.line for f in res.findings}  # fx.request_s ok

    def test_doc_crosscheck(self):
        res = self.run(full=True)
        dtl042 = [f for f in res.findings if f.code == "DTL042"]
        # fx.wait pins whole-token doc matching: it PREFIXES the
        # documented `fx.wait_s` and must still count as undocumented
        assert [f.anchor for f in dtl042] == ["fx.undocumented", "fx.wait"]


# ------------------------------------------------------------- locks


class TestLocks:
    def run(self):
        return run_lint(fixture_config(), paths=[f"{FX}/fx_locks.py"],
                        checkers=["locks"])

    def test_unguarded_read_and_write(self):
        res = self.run()
        assert codes_lines(res.findings) == [
            ("DTL051", 24),   # write outside the lock
            ("DTL051", 27),   # torn read outside the lock
            ("DTL051", 37),   # malformed table fails LOUD, not silent
            ("DTL051", 43),   # typo'd guarded field __init__ never sets
        ], [f.render() for f in res.findings]

    def test_exemptions(self):
        res = self.run()
        lines = {f.line for f in res.findings}
        assert 11 not in lines and 12 not in lines  # __init__ exempt
        assert 30 not in lines                      # *_locked convention
        assert 21 not in lines                      # locked lambda is fine
        assert [(f.code, f.line) for f in res.suppressed] == [
            ("DTL051", 33),
        ]


# ---------------------------------------------------- repo-level gates


class TestRepoGate:
    def test_lint_check_exits_zero_on_the_repo(self):
        """THE acceptance gate: the whole package is finding-free (or
        explicitly baselined) under all five checkers."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"), "--check"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, (
            f"lint --check failed:\n{proc.stdout}\n{proc.stderr}"
        )

    def test_json_mode_emits_parseable_findings(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"), "--json",
             f"{FX}/fx_locks.py"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0  # report mode never gates
        recs = [json.loads(line) for line in proc.stdout.splitlines()]
        assert {r["code"] for r in recs} == {"DTL051"}
        assert all(r["key"].startswith(f"{FX}/fx_locks.py::") for r in recs)

    def test_check_mode_fails_on_findings(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"), "--check",
             f"{FX}/fx_locks.py"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "DTL051" in proc.stdout

    def test_check_mode_fails_on_stale_baseline(self, tmp_path):
        """The baseline can only shrink: a key whose finding was fixed
        fails the full-scan gate until it is pruned (a lingering dead
        key could mask a future same-shape violation)."""
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps([
            {"key": "gone/file.py::DTL011::fixed_long_ago:If",
             "note": "stale"},
        ]))
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"), "--check",
             "--baseline", str(bl)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stderr
        assert "stale baseline entry" in proc.stderr

    def test_guarded_by_tables_are_declared(self):
        """The seeded lock-discipline contracts exist where PR 6's
        thread-safety lives: Router, the metrics registries, the
        telemetry ring."""
        import ast

        want = {
            "dalle_pytorch_tpu/serving/router.py": {"Router"},
            "dalle_pytorch_tpu/utils/metrics.py": {
                "Counters", "Gauges", "Histograms", "Histogram",
            },
            "dalle_pytorch_tpu/utils/telemetry.py": {"Telemetry"},
        }
        for path, classes in want.items():
            tree = ast.parse((REPO / path).read_text())
            declared = {
                cls.name
                for cls in ast.walk(tree) if isinstance(cls, ast.ClassDef)
                if any(
                    isinstance(n, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                        for t in n.targets
                    )
                    for n in cls.body
                )
            }
            assert classes <= declared, (path, declared)

    def test_fault_registry_is_one_to_one(self):
        """Every KNOWN_SITES entry has a production take-site and a
        test/tool drill — the cross-reference the checker enforces
        (finding nothing IS the assertion)."""
        res = run_lint(default_config(str(REPO)),
                       checkers=["fault-sites"])
        assert res.clean, [f.render() for f in res.findings]

    def test_telemetry_names_match_registry_and_docs(self):
        res = run_lint(default_config(str(REPO)),
                       checkers=["telemetry-names"])
        assert res.clean, [f.render() for f in res.findings]


# ------------------------------------------------------- trace stage


_TRACE_CACHE: dict = {}


def trace_fixture_raw():
    """Audit the fixture registry once per session (the audit imports jax
    and traces every fixture jit — cached so each pinned-code test below
    reads the same result instead of re-tracing)."""
    if "raw" not in _TRACE_CACHE:
        from lint.trace import run_trace  # imports jax (fixture jits)

        _TRACE_CACHE["raw"] = run_trace(
            str(REPO),
            f"{FX}/fx_trace_registry.py",
            f"{FX}/fx_trace_contract.json",
        )
    return _TRACE_CACHE["raw"]


def trace_fixture_result(baseline=None):
    """Fold the fixture trace findings through the SHARED suppression/
    baseline machinery (run_lint extra_findings) — the same path the CLI
    composes the two stages on."""
    findings, reports = trace_fixture_raw()
    cfg = fixture_config(baseline_path=baseline)
    res = run_lint(cfg, paths=[f"{FX}/fx_trace_registry.py"], checkers=[],
                   full=True, extra_findings=findings)
    return res, reports


class TestTrace:
    """Fixture corpus for the --trace stage (tools/lint/trace/): >=2
    seeded violations per DTL1xx checker family at pinned codes and
    anchors, plus the suppression/baseline escapes and the
    contract-file round trip."""

    def test_exact_codes_and_anchors(self):
        res, _ = trace_fixture_result()
        got = sorted((f.code, f.anchor) for f in res.findings)
        assert got == [
            ("DTL101", "fx.uncommitted"),          # registered, uncommitted
            ("DTL102", "fx.ghost"),                # contract-only: stale
            ("DTL111", "fx.drift:w6"),             # unlisted signature
            ("DTL112", "fx.drift:float32[12]"),    # stale signature
            ("DTL113", "fx.drift"),                # over signature budget
            ("DTL121", "fx.not_donated:x"),        # declared, not donated
            ("DTL121", "fx.undeclared:undeclared"),  # donated, undeclared
            ("DTL122", "fx.plain"),                # declared on non-jit
            ("DTL122", "fx.unaliased"),            # donated, unaliased
            ("DTL131", "fx.chatty"),               # 2 callbacks > 0
            ("DTL132", "fx.chatty"),               # 3 visible outputs > 1
            ("DTL141", "fx.fat"),                  # HBM over budget
            ("DTL141", "fx.fat2"),                 # HBM over budget
        ], [f.render() for f in res.findings]

    def test_inline_suppression(self):
        # fx.fat3 exceeds its byte budget exactly like fx.fat/fat2 but
        # carries `# dtl: disable=DTL141` on its def line — the shared
        # escape hatch works for trace findings too
        res, _ = trace_fixture_result()
        assert [(f.code, f.anchor) for f in res.suppressed] == [
            ("DTL141", "fx.fat3"),
        ]

    def test_findings_anchor_on_def_lines(self):
        res, _ = trace_fixture_result()
        src = (REPO / FX / "fx_trace_registry.py").read_text().splitlines()
        want = next(
            i for i, line in enumerate(src, 1)
            if line.startswith("def _not_donated")
        )
        f = next(x for x in res.findings if x.anchor == "fx.not_donated:x")
        assert f.line == want and f.path == f"{FX}/fx_trace_registry.py"

    def test_clean_entry_stays_clean(self):
        # fx.donate_ok donates, aliases, and matches its contract exactly
        res, _ = trace_fixture_result()
        assert not any("fx.donate_ok" in f.anchor for f in res.findings)

    def test_baseline_grandfathers_with_stable_key(self, tmp_path):
        bl = tmp_path / "trace_baseline.json"
        bl.write_text(json.dumps([{
            "key": f"{FX}/fx_trace_registry.py::DTL113::fx.drift",
            "note": "fixture: grandfathered signature-budget overrun",
        }]))
        res, _ = trace_fixture_result(baseline=str(bl))
        assert ("DTL113", "fx.drift") not in [
            (f.code, f.anchor) for f in res.findings
        ]
        assert [(f.code, f.anchor) for f in res.baselined] == [
            ("DTL113", "fx.drift"),
        ]
        assert res.stale_baseline == []

    def test_emit_contract_round_trip(self):
        """A contract regenerated from the current registry must clear
        every budget/signature finding — what survives is exactly the
        donation drift between what the registry DECLARES and what the
        traced programs DO (that divergence is in the code, not the
        contract, so re-emitting cannot paper over it)."""
        from lint.trace import check_reports, emit_contract

        _, reports = trace_fixture_raw()
        fresh = emit_contract(reports)
        findings = check_reports(
            reports, fresh, "fresh.json", str(REPO)
        )
        got = sorted((f.code, f.anchor) for f in findings)
        assert got == [
            ("DTL121", "fx.not_donated:x"),
            ("DTL121", "fx.undeclared:undeclared"),
            ("DTL122", "fx.plain"),
            ("DTL122", "fx.unaliased"),
        ], got

    def test_trace_baseline_key_not_stale_for_ast_only_scan(self, tmp_path):
        """A baselined DTL1xx (trace-stage) key must NOT be judged stale
        by a scan that never ran the trace stage — otherwise one
        legitimately grandfathered trace finding would fail every plain
        `--check` run (including the smoke gates' stage-1 AST
        pre-flight). It IS judged when the trace stage ran (an empty
        extra_findings list means 'ran, found nothing')."""
        from lint import Finding  # noqa: F401  (core import side)

        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps([{
            "key": f"{FX}/fx_trace_registry.py::DTL141::fx.gone",
            "note": "trace finding fixed long ago",
        }]))
        cfg = fixture_config(baseline_path=str(bl))
        # AST-only (trace stage did not run): unseen, not stale
        res = run_lint(cfg, paths=[f"{FX}/fx_purity.py"], checkers=[],
                       full=True, extra_findings=None)
        assert res.stale_baseline == []
        # trace stage ran and produced nothing matching: NOW it is stale
        res = run_lint(cfg, paths=[f"{FX}/fx_purity.py"], checkers=[],
                       full=True, extra_findings=[])
        assert res.stale_baseline == [
            f"{FX}/fx_trace_registry.py::DTL141::fx.gone"
        ]

    def test_trace_suppression_survives_narrowed_ast_paths(self):
        """Trace findings anchor in files the AST stage may not have
        scanned (narrowed paths); their inline suppressions must load on
        demand instead of silently going live."""
        from lint import Finding

        src = (REPO / FX / "fx_trace_registry.py").read_text().splitlines()
        line = next(
            i for i, l in enumerate(src, 1) if l.startswith("def _fat3")
        )
        fake = Finding(
            code="DTL141", path=f"{FX}/fx_trace_registry.py", line=line,
            message="synthetic overrun", anchor="fx.fat3",
        )
        res = run_lint(
            fixture_config(), paths=[f"{FX}/fx_purity.py"], checkers=[],
            extra_findings=[fake],
        )
        assert res.findings == []
        assert [(f.code, f.anchor) for f in res.suppressed] == [
            ("DTL141", "fx.fat3"),
        ]

    def test_hbm_report_shape(self):
        """The per-entry report carries the per-jit HBM decomposition
        the DESIGN.md §11 operator workflow reads."""
        _, reports = trace_fixture_raw()
        rep = next(r for r in reports if r["name"] == "fx.donate_ok")
        sig = rep["signatures"][0]
        assert sig["arg_bytes"] == 64          # two f32[8]
        assert sig["out_bytes"] == 36          # f32[8] + scalar
        assert sig["aliased_bytes"] == 32      # donated x aliases out[0]
        assert sig["hbm_bytes"] == 68
        assert rep["max_host_visible_outputs"] == 1


class TestTraceCLI:
    """--trace through the real CLI: composition with the AST stage in
    one exit code, and THE acceptance gate on the repo contract."""

    def test_fixture_registry_fails_check(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--trace", "--check",
             "--trace-registry", f"{FX}/fx_trace_registry.py",
             "--contract", f"{FX}/fx_trace_contract.json",
             f"{FX}/fx_trace_registry.py"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stderr
        for code in ("DTL111", "DTL121", "DTL122", "DTL131", "DTL132",
                     "DTL141"):
            assert code in proc.stdout, (code, proc.stdout)
        # the suppressed fx.fat3 overrun must NOT be a live finding
        assert "fx.fat3" not in proc.stdout

    def test_repo_trace_gate_exits_zero(self):
        """THE acceptance gate: every registered entry point of the real
        package matches tools/trace_contracts.json — signatures closed,
        donation aliased, readbacks bounded, HBM inside budget."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--trace", "--check"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, (
            f"lint --trace --check failed:\n{proc.stdout}\n{proc.stderr}"
        )

    def test_emit_contract_matches_committed(self):
        """The committed contract is exactly what --emit-contract derives
        from the current registry — no drift between file and tree."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--trace", "--emit-contract"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        emitted = json.loads(proc.stdout)
        committed = json.loads(
            (REPO / "tools" / "trace_contracts.json").read_text()
        )
        assert emitted == committed


# --------------------------------------------------- lock-order cycles


class TestLockOrder:
    """DTL052 fixture corpus (tests/fixtures_lint/fx_lock_order.py):
    order-inversion cycles, the non-reentrant self-deadlock, the RLock
    reentry exemption, and the two escape hatches."""

    def run(self, baseline=None):
        cfg = fixture_config(baseline_path=baseline)
        return run_lint(cfg, paths=[f"{FX}/fx_lock_order.py"],
                        checkers=["locks"])

    def test_exact_codes_and_lines(self):
        res = self.run()
        assert codes_lines(res.findings) == [
            ("DTL052", 23),   # CycleAB: a->b vs b->a inversion
            ("DTL052", 38),   # SelfDeadlock: plain-Lock re-acquire
            ("DTL052", 78),   # CycleBaselined (no baseline in this run)
        ], [f.render() for f in res.findings]

    def test_anchors_name_the_cycle(self):
        res = self.run()
        assert sorted(f.anchor for f in res.findings) == [
            "CycleAB:_a->_b",
            "CycleBaselined:_e->_f",
            "SelfDeadlock:_m->_m",
        ]

    def test_rlock_reentry_is_sanctioned(self):
        # ReentrantOK nests an RLock under itself — the Router pattern —
        # and must stay clean
        res = self.run()
        assert not any("ReentrantOK" in f.anchor for f in res.findings)

    def test_closure_acquisition_is_not_an_edge(self):
        # a nested def DEFINED under a lock executes later without it:
        # ClosureNotAnEdge's worker must not create a phantom g->h edge
        # (its h->g order elsewhere is the only real one — no cycle)
        res = self.run()
        assert not any("ClosureNotAnEdge" in f.anchor
                       for f in res.findings)

    def test_inline_suppression(self):
        res = self.run()
        assert [(f.code, f.line) for f in res.suppressed] == [
            ("DTL052", 59),
        ]
        assert not any("CycleSuppressed" in f.anchor for f in res.findings)

    def test_baseline_grandfathers(self, tmp_path):
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps([{
            "key": f"{FX}/fx_lock_order.py::DTL052::CycleBaselined:_e->_f",
            "note": "fixture: grandfathered lock-order cycle",
        }]))
        res = self.run(baseline=str(bl))
        assert [(f.code, f.anchor) for f in res.baselined] == [
            ("DTL052", "CycleBaselined:_e->_f"),
        ]
        assert not any("CycleBaselined" in f.anchor for f in res.findings)

    def test_repo_lock_classes_are_cycle_free(self):
        """The production lock owners (Router, metrics, telemetry) must
        stay acyclic — finding nothing IS the assertion."""
        res = run_lint(default_config(str(REPO)), checkers=["locks"])
        assert res.clean, [f.render() for f in res.findings]


# ------------------------------------------------------- shard stage


_SHARD_CACHE: dict = {}


def shard_fixture_raw():
    """Audit the fixture shard registry once per session (lowers every
    fixture jit over the 2-device host mesh and compiles the one
    partitioned entry — cached so each pinned-code test below reads the
    same result instead of re-lowering)."""
    if "raw" not in _SHARD_CACHE:
        from lint.shard import run_shard  # imports jax (fixture jits)

        _SHARD_CACHE["raw"] = run_shard(
            str(REPO),
            f"{FX}/fx_shard_registry.py",
            f"{FX}/fx_shard_contract.json",
        )
    return _SHARD_CACHE["raw"]


def shard_fixture_result(baseline=None):
    findings, reports = shard_fixture_raw()
    cfg = fixture_config(baseline_path=baseline)
    res = run_lint(cfg, paths=[f"{FX}/fx_shard_registry.py"], checkers=[],
                   full=True, extra_findings=findings, stages={"shard"})
    return res, reports


class TestShard:
    """Fixture corpus for the --shard stage (tools/lint/shard/): >=2
    seeded violations per DTL15x checker family at pinned codes and
    anchors, plus the suppression/baseline escapes and the
    contract-file round trip."""

    def test_exact_codes_and_anchors(self):
        res, _ = shard_fixture_result()
        got = sorted((f.code, f.anchor) for f in res.findings)
        assert got == [
            ("DTL151", "fx.noisy:all-reduce"),        # over budget
            ("DTL151", "fx.unlisted:collective-permute"),  # unlisted kind
            ("DTL152", "fx.drifted:lowered"),         # rules vs lowered
            ("DTL152", "fx.stale_contract:contract"),  # contract drift
            ("DTL153", "fx.replicated:w1"),           # declared sharded,
            ("DTL153", "fx.replicated:w2"),           # lowered replicated
            ("DTL154", "fx.resharder"),               # 2 constraints > 0
            ("DTL154", "fx.resharder2"),              # 3 constraints > 1
            ("DTL155", "fx.ghost"),                   # contract-only: stale
            ("DTL155", "fx.uncommitted"),             # registered, uncommitted
        ], [f.render() for f in res.findings]

    def test_findings_anchor_on_def_lines(self):
        res, _ = shard_fixture_result()
        f = next(x for x in res.findings if x.anchor == "fx.resharder")
        assert f.line == 87 and f.path == f"{FX}/fx_shard_registry.py"
        ghost = next(x for x in res.findings if x.anchor == "fx.ghost")
        assert ghost.path == f"{FX}/fx_shard_contract.json"

    def test_inline_suppression(self):
        # fx.sneaky is over its all-reduce budget exactly like fx.noisy
        # but carries `# dtl: disable=DTL151` on its def line
        res, _ = shard_fixture_result()
        assert [(f.code, f.anchor) for f in res.suppressed] == [
            ("DTL151", "fx.sneaky:all-reduce"),
        ]

    def test_clean_entries_stay_clean(self):
        # fx.clean (lowered) and fx.partitioned (compiled, with its one
        # contracted GSPMD all-reduce) match the contract exactly
        res, reports = shard_fixture_result()
        for name in ("fx.clean", "fx.partitioned"):
            assert not any(name in f.anchor for f in res.findings)
        part = next(r for r in reports if r["name"] == "fx.partitioned")
        assert part["level"] == "partitioned"
        assert part["collectives"] == {"all-reduce": 1}

    def test_baseline_grandfathers_with_stable_key(self, tmp_path):
        bl = tmp_path / "shard_baseline.json"
        bl.write_text(json.dumps([{
            "key": f"{FX}/fx_shard_registry.py::DTL154::fx.resharder2",
            "note": "fixture: grandfathered reshard-budget overrun",
        }]))
        res, _ = shard_fixture_result(baseline=str(bl))
        assert ("DTL154", "fx.resharder2") not in [
            (f.code, f.anchor) for f in res.findings
        ]
        assert [(f.code, f.anchor) for f in res.baselined] == [
            ("DTL154", "fx.resharder2"),
        ]
        assert res.stale_baseline == []

    def test_emit_contract_round_trip(self):
        """A contract regenerated from the current registry must clear
        every budget/1:1 finding — what survives is exactly the
        code-level drift: DTL152's rules-vs-lowered disagreement and
        DTL153's accidental replication live in the code, not the
        contract, so re-emitting cannot paper over them."""
        from lint.shard import check_reports, emit_contract

        _, reports = shard_fixture_raw()
        fresh = emit_contract(reports)
        findings = check_reports(reports, fresh, "fresh.json", str(REPO))
        got = sorted((f.code, f.anchor) for f in findings)
        assert got == [
            ("DTL152", "fx.drifted:lowered"),
            ("DTL153", "fx.replicated:w1"),
            ("DTL153", "fx.replicated:w2"),
        ], got

    def test_shard_baseline_key_unseen_unless_shard_ran(self, tmp_path):
        """A baselined DTL15x key must NOT be judged stale by a scan
        that never ran the shard stage — a trace-only `--trace --check`
        run (stages={'trace'}) treats it as unseen, a shard run
        (stages={'shard'}) judges it."""
        bl = tmp_path / "bl.json"
        key = f"{FX}/fx_shard_registry.py::DTL151::fx.gone"
        bl.write_text(json.dumps([{"key": key, "note": "fixed long ago"}]))
        cfg = fixture_config(baseline_path=str(bl))
        res = run_lint(cfg, paths=[f"{FX}/fx_purity.py"], checkers=[],
                       full=True, extra_findings=[], stages={"trace"})
        assert res.stale_baseline == []
        res = run_lint(cfg, paths=[f"{FX}/fx_purity.py"], checkers=[],
                       full=True, extra_findings=[], stages={"shard"})
        assert res.stale_baseline == [key]

    def test_serving_entries_commit_zero_collectives(self):
        """The committed repo contract IS the 'no collectives in
        serving' baseline ROADMAP item 1 will renegotiate: every
        serving.* entry must budget an empty collective map, and the
        seven train.* mesh-kind entries must all be present (sp lowers
        twice — ring path and dual-balanced block-sparse path)."""
        committed = json.loads(
            (REPO / "tools" / "shard_contracts.json").read_text()
        )
        entries = committed["entries"]
        kinds = {n.split(".", 1)[1] for n in entries if n.startswith("train.")}
        assert kinds == {"dp", "fsdp", "tp", "sp", "sp_sparse", "pp", "ep"}
        serving = [n for n in entries if n.startswith("serving.")]
        assert len(serving) >= 10
        for name in serving:
            assert entries[name]["collectives"] == {}, name
        # the sharded mesh kinds actually shard: fsdp/tp commit sharded
        # param specs and nonzero collective budgets
        for kind in ("fsdp", "tp"):
            e = entries[f"train.{kind}"]
            assert e["param_specs"], kind
            assert e["collectives"], kind


class TestShardCLI:
    """--shard through the real CLI: composition in one exit code, and
    THE acceptance gate on the repo contract."""

    def test_fixture_registry_fails_check(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--shard", "--check",
             "--shard-registry", f"{FX}/fx_shard_registry.py",
             "--shard-contract", f"{FX}/fx_shard_contract.json",
             f"{FX}/fx_shard_registry.py"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stderr
        for code in ("DTL151", "DTL152", "DTL153", "DTL154", "DTL155"):
            assert code in proc.stdout, (code, proc.stdout)
        # the suppressed fx.sneaky overrun must NOT be a live finding
        assert "fx.sneaky" not in proc.stdout

    def test_repo_shard_gate_exits_zero(self):
        """THE acceptance gate: make_train_step under all six mesh kinds
        and every registered serving jit match
        tools/shard_contracts.json — collective budgets closed, specs
        agreed, nothing accidentally replicated, reshard sites
        budgeted."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--shard", "--check"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, (
            f"lint --shard --check failed:\n{proc.stdout}\n{proc.stderr}"
        )

    def test_emit_contract_matches_committed(self):
        """The committed shard contract is exactly what --emit-contract
        derives from the current registry — the pinned round trip."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "lint.py"),
             "--shard", "--emit-contract"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        emitted = json.loads(proc.stdout)
        committed = json.loads(
            (REPO / "tools" / "shard_contracts.json").read_text()
        )
        assert emitted == committed

    def test_emit_contract_requires_exactly_one_stage(self):
        for args in (["--emit-contract"],
                     ["--trace", "--shard", "--emit-contract"]):
            proc = subprocess.run(
                [sys.executable, str(REPO / "tools" / "lint.py"), *args],
                capture_output=True, text=True, cwd=REPO,
            )
            assert proc.returncode == 2, (args, proc.stdout)
            assert "exactly one of" in proc.stderr
