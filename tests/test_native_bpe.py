"""Parity tests: native C++ BPE engine vs the Python SimpleTokenizer.

The native engine (native/bpe_tokenizer.cc) re-owns the reference's native
tokenizer dependencies (HF tokenizers / youtokentome, SURVEY.md §2.3) and
must be byte-exact with the Python implementation on every input: same ids,
same decode, same tokenize() contract.
"""

import numpy as np
import pytest

from dalle_pytorch_tpu.data.native_bpe import (
    NativeSimpleTokenizer,
    native_available,
)
from dalle_pytorch_tpu.data.tokenizers import SimpleTokenizer

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain for the native engine"
)

CORPUS = [
    "a red square",
    "A man riding a horse on the beach at sunset.",
    "Hello, World! It's a test... isn't it?",
    "naïve café — résumé über straße",
    "numbers 0 1 23 456 7890 and ² ³ ½ Ⅳ",
    "emoji 🎨🌈🦄 and CJK 中文字符串 and kana テスト ひらがな",
    "<|startoftext|>prompt<|endoftext|>",
    "mixed<|endoftext|>inline special",
    "don't can't we'll I'm you've they're he'd 'quoted'",
    "  collapse   whitespace\tand\nnewlines\r\nplease ",
    "punctuation!!! ??? ... ---- ###$$$%%%",
    "!!<|startoftext|>not-special-mid-punct-run",
    "price: $12.50 (50% off!) e.g. i.e. etc.",
    "html &amp; entities &lt;tag&gt;",
    "Ωμέγα ελληνικά кириллица العربية עברית हिन्दी",
    "snake_case camelCase SCREAMING dots.and.dots",
    "a" * 300,
    "ab " * 100,
    "",
    "   ",
    "'", "''", "'s", "x's", "'sx", "'ll", "o'clock",
    # regression pins for regex-IGNORECASE case-closure quirks:
    "'ſ",    # long s: matches the 's contraction under IGNORECASE
    "ͅ",     # combining ypogegrammeni: matches NO alternative, skipped
    "aͅb", "it'ſ done",
]


@pytest.fixture(scope="module")
def pair():
    return NativeSimpleTokenizer(), SimpleTokenizer()


def test_vocab_size(pair):
    nt, pt = pair
    assert nt.vocab_size == pt.vocab_size == 49408


@pytest.mark.parametrize("text", CORPUS, ids=range(len(CORPUS)))
def test_encode_parity(pair, text):
    nt, pt = pair
    assert nt.encode(text) == pt.encode(text)


def test_decode_parity_and_roundtrip(pair):
    nt, pt = pair
    for text in CORPUS:
        ids = pt.encode(text)
        assert nt.decode(ids) == pt.decode(ids)


def test_decode_skips_pads(pair):
    nt, pt = pair
    ids = pt.encode("a blue circle")
    padded = [0] + ids[:2] + [49152, 49200] + ids[2:] + [0, 0]
    pads = {49152, 49200}
    assert nt.decode(padded, pad_tokens=pads) == pt.decode(padded, pad_tokens=pads)


def test_randomized_fuzz_parity(pair):
    """Random unicode strings: the scanner and merge loop must agree
    everywhere, not just on curated samples."""
    nt, pt = pair
    rng = np.random.RandomState(0)
    pools = [
        list(range(0x20, 0x7F)),                  # ascii
        list(range(0xA0, 0x250)),                 # latin supplement/extended
        list(range(0x370, 0x400)),                # greek
        list(range(0x4E00, 0x4E80)),              # CJK
        [0x1F600 + i for i in range(40)],         # emoji
        [0x20, 0x27, 0x2E, 0x31, 0x32],           # space/quote/dot/digits
        [0x27, 0x73, 0x17F, 0x345, 0x6C, 0x74],   # contraction/case-fold traps
        list(range(0x00, 0x20)),                  # control chars
        list(range(0x2000, 0x2030)),              # unicode spaces/format chars
    ]
    for _ in range(200):
        n = rng.randint(1, 60)
        cps = [
            int(rng.choice(pools[rng.randint(len(pools))])) for _ in range(n)
        ]
        text = "".join(chr(c) for c in cps)
        assert nt.encode(text) == pt.encode(text), repr(text)


def test_tokenize_contract(pair):
    nt, _ = pair
    out = nt.tokenize(["a red square", "tiny"], context_length=16)
    assert out.shape == (2, 16) and out.dtype == np.int32
    assert out[1, -1] == 0  # zero padded
    with pytest.raises(RuntimeError):
        nt.tokenize(["word " * 200], context_length=8)
    trunc = nt.tokenize(["word " * 200], context_length=8, truncate_text=True)
    assert trunc.shape == (1, 8)


def test_concurrent_encode_thread_safety(pair):
    """The data loader prefetches on a thread; concurrent encodes against
    one engine (shared token cache behind a mutex) must stay byte-exact."""
    import threading

    nt, pt = pair
    texts = [f"caption number {i} with a {w} object" for i in range(50)
             for w in ("red", "blue", "shiny")]
    expected = [pt.encode(t) for t in texts]
    results = {}

    def worker(tid):
        out = [nt.encode(t) for t in texts]
        results[tid] = out

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4, f"worker thread(s) died: only {sorted(results)}"
    for tid, out in results.items():
        assert out == expected, f"thread {tid} diverged"


def test_get_tokenizer_prefers_native(monkeypatch):
    import dalle_pytorch_tpu.data.tokenizers as tok

    monkeypatch.setattr(tok, "_default", None)
    t = tok.get_tokenizer()
    assert isinstance(t, NativeSimpleTokenizer)
    monkeypatch.setattr(tok, "_default", None)
    monkeypatch.setenv("DALLE_TPU_NO_NATIVE", "1")
    t = tok.get_tokenizer()
    assert isinstance(t, SimpleTokenizer)
    monkeypatch.setattr(tok, "_default", None)
