"""Speculative decoding through the fused iteration (ISSUE 11, ROADMAP
2) — multi-token decode steps pinned deterministically on CPU:

- paged_kv-level rewind: a verify block writes its FULL width (masked
  append / per-row ``limit``), and rollback is the next block landing on
  the accepted frontier and overwriting the rejected suffix — pinned
  against sequential appends for accept-all, reject-all, and mixed
  per-row acceptance (idle rows untouched);
- the exact-acceptance parity contract: speculative greedy output is
  BIT-IDENTICAL to non-speculative decode on the f32 CPU tier — exact
  drafter (accept rate 1.0) and a genuinely misdrafting truncated-depth
  drafter (rejections exercised), across split/monolithic/fused
  engines, through preempt-and-replay and prefix-cache warm hits;
- the degraded-drafter drill: ``spec_verify_abort`` falls back to plain
  decode for one iteration through the SAME jit signature, output still
  bit-identical, every request in a typed outcome (100% accounting);
- the dispatch/signature contract: a steady speculative trace keeps
  ``_spec_iteration_jit``'s trace cache FLAT (descriptor raggedness —
  verify widths, mixes, the abort fallback — is data, not shape), at
  most one dispatch per iteration, and commits >1 token per verify step
  with the exact drafter (the memory-bound multi-token claim at CPU
  scale); the committed trace contract pins ``serving.iteration_spec``
  to the steady + final signature pair with the cache donated, and the
  PR 10 follow-on page-copy jits (``serving.page_copy[_across]``) to
  one donated fixed-shape signature each;
- TokenBudget: the decode lane is charged the full VERIFY width (device
  work), while progress is accounted in ACCEPTED tokens.

Page size 2 (env override), as in tests/test_ragged_attention.py, so
verify blocks genuinely cross page boundaries mid-block.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.ops import paged_kv
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    Request,
    check_accounting,
)
from dalle_pytorch_tpu.serving import engine as engine_mod
from dalle_pytorch_tpu.serving.scheduler import TokenBudget
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges, histograms

REPO = Path(__file__).resolve().parent.parent

# the speculative serving mode: spec rides THROUGH the fused iteration
SPEC = dict(prefill_chunk=2, fused_iteration=True, spec_decode=True)


def small_dalle(**kw):
    defaults = dict(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture(scope="module")
def model():
    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(scope="module")
def deep_model():
    """A depth-4 stack whose depth-1 early-exit drafter genuinely
    MISDRAFTS — the engine config that exercises rollback (the tiny
    depth-2 model's truncated drafter agrees too often to reject)."""
    dalle = small_dalle(
        depth=4, num_text_tokens=32, text_seq_len=6,
        num_image_tokens=64, image_fmap_size=4,
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 32, size=(1, 6)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 64, size=(1, 16)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0, width=4, vocab=16):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, vocab, size=(width,)).astype(np.int32)


def req(i, max_new=4, rid=None, p=None, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=rid or f"r{i}",
        prompt=prompt(i) if p is None else p,
        max_new_tokens=max_new, **kw
    )


def make_engine(model, clock=None, **cfg_kw):
    dalle, params = model
    cfg_kw.setdefault("max_batch", 2)
    return Engine(
        dalle, params, EngineConfig(**cfg_kw),
        clock=clock or FakeClock(step_dt=1.0),
    )


def run_requests(model, n=3, max_new=4, reqs=None, **cfg_kw):
    eng = make_engine(model, **cfg_kw)
    for r in reqs if reqs is not None else [req(i, max_new=max_new)
                                            for i in range(n)]:
        assert eng.submit(r) is None
    eng.run(max_steps=800)
    check_accounting(eng)
    return eng


def tokens_of(eng):
    return {
        rid: None if r.tokens is None else np.asarray(r.tokens)
        for rid, r in eng.results.items()
    }


def completed_tokens(eng):
    out = tokens_of(eng)
    for rid, r in eng.results.items():
        assert r.outcome is Outcome.COMPLETED, (rid, r.outcome)
    return out


# ------------------------------------------------ paged_kv rewind pins


class TestPagedRewind:
    """The rollback substrate: a verify block writes its full width
    through the masked ``append``; rejection is the NEXT block anchored
    at the accepted frontier overwriting the rejected suffix. Pinned
    bit-exactly against sequential single-token appends."""

    def _pool(self, b=2, n_p=4, page=2, feat=3):
        pool = jnp.zeros((b, n_p, page, feat), jnp.float32)
        table = paged_kv.identity_table(b, n_p)
        return pool, table

    def _rows(self, b, n, feat=3, seed=0):
        rng = np.random.RandomState(seed)
        return jnp.asarray(rng.randn(b, n, feat), jnp.float32)

    def _sequential(self, pool, table, idx, rows):
        """Reference: append the same rows one position at a time."""
        for j in range(rows.shape[1]):
            pool = paged_kv.append(
                pool, table, idx + j, rows[:, j:j + 1],
                limit=jnp.ones((table.shape[0],), jnp.int32),
            )
        return pool

    def test_accept_all_block_equals_sequential(self):
        pool, table = self._pool()
        idx = jnp.asarray([1, 3], jnp.int32)
        rows = self._rows(2, 3)
        blk = paged_kv.append(
            pool, table, idx, rows, limit=jnp.asarray([3, 3], jnp.int32)
        )
        seq = self._sequential(pool, table, idx, rows)
        np.testing.assert_array_equal(np.asarray(blk), np.asarray(seq))

    def test_reject_all_rewind_overwrites_suffix(self):
        """Verify block A commits only its input token (accepted == 1);
        the corrective block B lands at idx+1 and must overwrite A's
        rejected positions — final pool equals sequential A[0], B."""
        pool, table = self._pool()
        idx = jnp.asarray([0, 2], jnp.int32)
        A = self._rows(2, 3, seed=1)
        B = self._rows(2, 3, seed=2)
        lim = jnp.asarray([3, 3], jnp.int32)
        specpool = paged_kv.append(pool, table, idx, A, limit=lim)
        specpool = paged_kv.append(specpool, table, idx + 1, B, limit=lim)
        seq = self._sequential(pool, table, idx, A[:, :1])
        seq = self._sequential(seq, table, idx + 1, B)
        np.testing.assert_array_equal(np.asarray(specpool), np.asarray(seq))

    def test_mixed_acceptance_per_row_and_idle_rows(self):
        """Row 0 accepts 2 of 3, row 1 accepts all, row 2 is IDLE
        (limit 0 — its pool rows must pass through untouched)."""
        pool, table = self._pool(b=3)
        marker = pool.at[2].set(7.0)  # idle row's pre-existing content
        idx = jnp.asarray([0, 1, 0], jnp.int32)
        A = self._rows(3, 3, seed=3)
        B = self._rows(3, 3, seed=4)
        specpool = paged_kv.append(
            marker, table, idx, A, limit=jnp.asarray([3, 3, 0], jnp.int32)
        )
        # row 0 accepted 2 -> next block at idx+2; row 1 accepted all 3
        # -> next at idx+3; row 2 still idle
        nxt = jnp.asarray([2, 4, 0], jnp.int32)
        specpool = paged_kv.append(
            specpool, table, nxt, B, limit=jnp.asarray([3, 3, 0], jnp.int32)
        )
        # reference: full A sequentially, then B overwriting the suffix
        ref = self._sequential(marker, table, idx, A)
        ref = self._sequential(ref, table, nxt, B)
        # idle row: marker content must survive both appends
        np.testing.assert_array_equal(
            np.asarray(specpool[2]), np.asarray(marker[2])
        )
        np.testing.assert_array_equal(
            np.asarray(specpool[:2]), np.asarray(ref[:2])
        )

    def test_block_crosses_page_boundary(self):
        """A verify block spanning a page boundary (page size 2, width 3
        from offset 1) lands bit-identically to sequential appends."""
        pool, table = self._pool(b=1, n_p=4, page=2)
        idx = jnp.asarray([1], jnp.int32)
        rows = self._rows(1, 3, seed=5)
        blk = paged_kv.append(
            pool, table, idx, rows, limit=jnp.asarray([3], jnp.int32)
        )
        seq = self._sequential(pool, table, idx, rows)
        np.testing.assert_array_equal(np.asarray(blk), np.asarray(seq))


# --------------------------------------------- engine-level bit parity


class TestSpecParity:
    def test_spec_bit_identical_exact_drafter(self, model):
        """THE acceptance contract: speculative engines (spec_k 2 and 3,
        full-depth exact drafter) produce tokens bit-identical to the
        split chunked, monolithic, and plain fused engines."""
        mono = completed_tokens(run_requests(model))
        split = completed_tokens(run_requests(model, prefill_chunk=2))
        fused = completed_tokens(run_requests(
            model, prefill_chunk=2, fused_iteration=True
        ))
        for spec_k in (2, 3):
            spec = completed_tokens(run_requests(model, **SPEC,
                                                 spec_k=spec_k))
            for rid, toks in mono.items():
                np.testing.assert_array_equal(split[rid], toks)
                np.testing.assert_array_equal(fused[rid], toks)
                np.testing.assert_array_equal(
                    spec[rid], toks,
                    err_msg=f"spec_k={spec_k} diverged for {rid}",
                )

    def test_exact_drafter_accepts_everything(self, model):
        """The full-depth drafter IS the target model, so exact-match
        acceptance must accept every draft (accept rate 1.0) — and the
        engine must therefore commit >1 token per verify step."""
        eng = run_requests(model, **SPEC, spec_k=3,
                           max_new=small_dalle().image_seq_len)
        assert eng._spec_drafted > 0
        assert eng._spec_accepted == eng._spec_drafted
        h = histograms.get("serve.spec_accepted_per_step")
        assert h is not None and h.count > 0

    def test_truncated_drafter_rejects_and_stays_bit_identical(
        self, deep_model
    ):
        """The depth-1 early-exit drafter of a depth-4 stack genuinely
        misdrafts — rollback is exercised (accepted < drafted) and the
        committed stream STILL matches plain decode bitwise."""
        split = completed_tokens(run_requests(
            deep_model, n=2, max_new=16, prefill_chunk=2,
            reqs=[req(i, max_new=16, p=prompt(i, width=6, vocab=32))
                  for i in range(2)],
        ))
        eng = run_requests(
            deep_model, n=2, max_new=16, **SPEC, spec_k=3,
            spec_draft_depth=1,
            reqs=[req(i, max_new=16, p=prompt(i, width=6, vocab=32))
                  for i in range(2)],
        )
        assert eng._spec_drafted > 0
        assert eng._spec_accepted < eng._spec_drafted, (
            "depth-1 drafter never rejected — the rollback path was "
            "not exercised"
        )
        spec = completed_tokens(eng)
        for rid, toks in split.items():
            np.testing.assert_array_equal(
                spec[rid], toks,
                err_msg=f"truncated-drafter stream diverged for {rid}",
            )

    def test_spec_preempt_replay_bit_identical(self, model):
        """A page_exhaust eviction mid-decode: the preempted request
        replays through the SPECULATIVE path bit-identically (the
        (seed, position) fold-in keys are position-anchored, so the
        replayed verify steps re-derive the same tokens)."""
        FAULTS.reset()
        counters.reset()
        clean = completed_tokens(run_requests(model, **SPEC, spec_k=2))
        FAULTS.configure("page_exhaust=1")
        try:
            eng = run_requests(model, **SPEC, spec_k=2)
        finally:
            FAULTS.reset()
        assert any(r.preempt_count > 0 for r in eng.results.values())
        for rid, toks in completed_tokens(eng).items():
            np.testing.assert_array_equal(toks, clean[rid])
        assert eng.pool.used == 0

    @pytest.mark.parametrize("spec_draft_depth", [None, 1])
    def test_spec_prefix_warm_hit_bit_identical(self, model,
                                                spec_draft_depth):
        """Prefix-cache warm hits compose with speculation: the warm
        round enters decode from the cached terminal logits and its
        VERIFY steps must still commit the cold round's exact stream."""
        counters.reset()
        cold_plain = completed_tokens(run_requests(model, prefill_chunk=2))
        eng = make_engine(model, prefix_cache=True, **SPEC, spec_k=2,
                          spec_draft_depth=spec_draft_depth)
        for i in range(3):
            assert eng.submit(req(i)) is None
        eng.run(max_steps=800)
        cold = completed_tokens(eng)
        hits0 = eng.prefix.stats.hits
        for i in range(3):
            assert eng.submit(req(i, rid=f"r{i}w")) is None
        eng.run(max_steps=800)
        check_accounting(eng)
        eng.verify_invariants(idle=True)
        assert eng.prefix.stats.hits > hits0, (
            "warm round never hit the prefix index"
        )
        warm = completed_tokens(eng)
        for i in range(3):
            np.testing.assert_array_equal(warm[f"r{i}w"], cold[f"r{i}"])
            np.testing.assert_array_equal(
                warm[f"r{i}w"], cold_plain[f"r{i}"],
                err_msg="spec+prefix stream diverged from plain split",
            )

    def test_spec_deadline_mid_decode_typed(self, model):
        """A deadline sweeping between speculative iterations terminates
        typed and returns the pages that iteration."""
        eng = make_engine(model, **SPEC, spec_k=2,
                          clock=FakeClock(step_dt=1.0))
        assert eng.submit(req(0, max_new=4, deadline=2.5)) is None
        eng.run(max_steps=100)
        check_accounting(eng)
        res = eng.results["r0"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert eng.pool.used == 0


# ------------------------------------------------- engine config gates


class TestSpecConfig:
    def test_spec_requires_fused_iteration(self, model):
        with pytest.raises(ValueError, match="fused_iteration"):
            make_engine(model, prefill_chunk=2, spec_decode=True)

    def test_spec_k_validated(self, model):
        with pytest.raises(ValueError, match="spec_k"):
            make_engine(model, **{**SPEC, "spec_k": 0})

    def test_spec_draft_depth_validated(self, model):
        with pytest.raises(ValueError, match="spec_draft_depth"):
            make_engine(model, **SPEC, spec_draft_depth=99)

    def test_budget_charges_verify_width(self):
        """The decode lane is charged the VERIFY width (device work):
        2 verify rows of width 3 consume the same budget as 6 plain
        decode rows, shrinking prefill grants accordingly."""
        tb = TokenBudget(budget=8, chunk=3)
        # plain: 2 decode tokens leave room for both chunks
        assert tb.plan_iteration(2, [3, 3]) == [True, True]
        # speculative: 2 rows * width 3 = 6 tokens; only the head chunk
        # keeps the forward-progress floor
        assert tb.plan_iteration(6, [3, 3]) == [True, False]
        # the floor survives even a fully spent budget
        assert tb.plan_iteration(8, [3, 3]) == [True, False]


# ------------------------------------------ dispatch/signature contract


class TestSpecDispatchContract:
    def test_flat_signature_and_multi_token_steps(self, model):
        """After one warm request compiles both signature classes, a
        mixed multi-request speculative trace compiles NOTHING new
        (verify widths/mixes are data), performs at most one dispatch
        per iteration, and — with the exact drafter — commits MORE
        tokens than it runs verify steps (the >1 accepted token per
        step the ISSUE's CPU record requires)."""
        counters.reset()
        eng = make_engine(model, **SPEC, spec_k=3)
        assert eng.submit(req(9, max_new=4)) is None
        eng.run(max_steps=200)
        sigs0 = engine_mod._spec_iteration_jit._cache_size()
        d0, i0 = eng.dispatches, eng.iterations
        steps0 = counters.get("serve.decode_steps")
        for i in range(3):
            assert eng.submit(req(i, max_new=4)) is None
        eng.run(max_steps=500)
        check_accounting(eng)
        assert engine_mod._spec_iteration_jit._cache_size() == sigs0, (
            "a speculative descriptor mix drifted the compile signature"
        )
        dispatches = eng.dispatches - d0
        iterations = eng.iterations - i0
        assert 0 < dispatches <= iterations, (dispatches, iterations)
        # decode-committed tokens only (the first token of each request
        # lands at the final prefill chunk, not a verify step)
        committed = sum(
            len(r.tokens) - 1 for rid, r in eng.results.items()
            if r.outcome is Outcome.COMPLETED and rid != "r9"
        )
        verify_steps = counters.get("serve.decode_steps") - steps0
        assert committed > verify_steps, (
            f"{committed} tokens over {verify_steps} verify steps — "
            "speculation never beat one token per step"
        )

    def test_spec_counters_and_gauge(self, model):
        counters.reset()
        gauges.reset()
        eng = run_requests(model, **SPEC, spec_k=2)
        drafted = counters.get("serve.spec.drafted")
        accepted = counters.get("serve.spec.accepted")
        rejected = counters.get("serve.spec.rejected")
        assert drafted == eng._spec_drafted > 0
        assert accepted == eng._spec_accepted
        assert drafted == accepted + rejected
        assert gauges.get("serve.spec_accept_frac") == pytest.approx(
            accepted / drafted
        )

    def test_bench_serve_spec_record_shape(self, model):
        """bench.py's speculation on/off record (ISSUE 11 satellite):
        the in-bench acceptance (>1 accepted token per verify step,
        fewer verify steps than plain decode steps, zero in-trace
        compiles, f32 bit-parity) ran if the record returns; pin its
        field contract here on the tiny parity-tier model."""
        import bench

        rec = bench.bench_serve_spec(True, model=model, seed=0)
        for k in ("accept_rate", "accepted_per_step", "drafted",
                  "accepted", "verify_steps_spec", "decode_steps_plain",
                  "tokens_per_sec_spec", "tokens_per_sec_plain",
                  "tps_ratio_spec_over_plain", "compiles_in_trace",
                  "jit_recompiles_in_trace", "spec_k", "arrival_seed",
                  "max_batch"):
            assert k in rec, k
        assert rec["metric"].startswith("serve_spec_accepted_tokens")
        # the exact full-depth drafter on the f32 tier: every draft
        # accepted, so the mean accepted-per-step is bounded only by the
        # remaining-budget cap and must clear 1
        assert rec["accept_rate"] == 1.0
        assert rec["accepted_per_step"]["mean"] > 1.0
        assert rec["verify_steps_spec"] < rec["decode_steps_plain"]
        assert rec["spec_tokens_bit_identical_to_plain"] is True
        assert rec["compiles_in_trace"] in (0, -1)
        assert all(
            v in (0, -1) for v in rec["jit_recompiles_in_trace"].values()
        ), rec["jit_recompiles_in_trace"]

    def test_trace_contract_pins_spec_and_page_copy(self):
        """The committed trace contract pins ``serving.iteration_spec``
        to EXACTLY the steady + final signature pair with the cache
        donated (DTL11x budget: descriptor raggedness must stay data),
        and the PR 10 follow-on copy jits to ONE donated fixed-shape
        signature each — the registry<->lowered-aliasing half is
        machine-checked by ``lint --trace --check``
        (tests/test_static_analysis.py); this pin keeps the contract's
        content from being weakened in a future re-emit."""
        contract = json.loads(
            (REPO / "tools" / "trace_contracts.json").read_text()
        )
        spec = contract["entries"]["serving.iteration_spec"]
        assert spec["max_signatures"] == 2
        assert [s["label"] for s in spec["signatures"]] == [
            "steady", "final"
        ]
        assert spec["donate"] == ["cache"]
        assert spec["max_host_callbacks"] == 0
        # the spec + prefix-cache composition: same program over the
        # arena-extended ring-widened cache, same two-signature budget
        arena = contract["entries"]["serving.iteration_spec_prefix"]
        assert arena["max_signatures"] == 2
        assert arena["donate"] == ["cache"]
        # one signature per cache tree: the plain prefix engine's arena
        # tree plus the speculative prefix engine's ring-widened one
        copy = contract["entries"]["serving.page_copy"]
        assert copy["max_signatures"] == 2
        assert [s["label"] for s in copy["signatures"]] == [
            "publish", "publish_spec"
        ]
        assert copy["donate"] == ["cache"]
        assert copy["max_host_visible_outputs"] == 0
        across = contract["entries"]["serving.page_copy_across"]
        assert across["max_signatures"] == 1
        assert across["donate"] == ["dst_cache"]
        assert across["max_host_visible_outputs"] == 0
        # the quantized prefix engine's int8 + scale-pool trees (ISSUE
        # 14) are their OWN entries — signature 0 of an entry is what
        # the audit genuinely lowers and alias-audits, so the quant
        # trees' extra scale leaves must prove their donation aliasing
        # here instead of silently loosening the shared 0 budget above
        for name, label in (
            ("serving.page_copy_quant", "publish_quant"),
            ("serving.page_copy_across_quant", "restore_quant"),
        ):
            q = contract["entries"][name]
            assert q["max_signatures"] == 1
            assert [s["label"] for s in q["signatures"]] == [label]
            assert q["max_host_visible_outputs"] == 0


# ------------------------------------------------ degraded-drafter drill


class TestSpecVerifyAbortDrill:
    def test_abort_degrades_one_iteration_bit_identical(self, model):
        """The ``spec_verify_abort`` drill: the drafter fails for ONE
        iteration; that iteration runs plain decode (verify width 1)
        through the same jit signature, the stream stays bit-identical,
        and EVERY request still ends in a typed outcome."""
        FAULTS.reset()
        counters.reset()
        clean = completed_tokens(run_requests(model, **SPEC, spec_k=2))
        sigs0 = engine_mod._spec_iteration_jit._cache_size()
        FAULTS.configure("spec_verify_abort=1")
        try:
            eng = run_requests(model, **SPEC, spec_k=2)
            fired = FAULTS.fired.get("spec_verify_abort")
        finally:
            FAULTS.reset()
        assert fired == 1
        assert counters.get("serve.spec.fallbacks") == 1
        assert counters.get("serve.fault_spec_verify_abort") == 1
        # the fallback is a width-1 verify row — same signature, no
        # recompile
        assert engine_mod._spec_iteration_jit._cache_size() == sigs0
        # 100% typed-outcome accounting: every submitted request ends in
        # a typed outcome (here: completed), none lost, none duplicated
        assert sorted(eng.results) == [f"r{i}" for i in range(3)]
        for rid, toks in completed_tokens(eng).items():
            np.testing.assert_array_equal(
                toks, clean[rid],
                err_msg=f"degraded iteration changed the stream of {rid}",
            )

    def test_abort_untaken_when_nothing_decodes(self, model):
        """Eligibility: the site is consulted only when decode slots
        exist, so an armed fault cannot silently expire during a
        prefill-only phase."""
        FAULTS.reset()
        eng = make_engine(model, **SPEC, spec_k=2, token_budget=1)
        FAULTS.arm("spec_verify_abort", 1)
        try:
            assert eng.submit(req(0)) is None
            eng.step()  # first chunk only: no decoding slot yet
            assert FAULTS.fired.get("spec_verify_abort") is None
            eng.run(max_steps=200)
            check_accounting(eng)
            assert FAULTS.fired.get("spec_verify_abort") == 1
        finally:
            FAULTS.reset()
        assert eng.results["r0"].outcome is Outcome.COMPLETED
