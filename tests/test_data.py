"""Data layer tests: BPE tokenizer contract, folder dataset, loader batching,
tar-shard streaming."""

import io
import random
import tarfile
from pathlib import Path

import numpy as np
import pytest
from PIL import Image

from dalle_pytorch_tpu.data import (
    DataLoader,
    ImageFolderDataset,
    SimpleTokenizer,
    TarImageTextDataset,
    TarLoader,
    TextImageDataset,
    default_bpe_path,
    expand_urls,
)

needs_vocab = pytest.mark.skipif(
    default_bpe_path() is None, reason="bpe_simple_vocab_16e6.txt not available"
)


@pytest.fixture(scope="module")
def tok():
    if default_bpe_path() is None:
        pytest.skip("bpe vocab unavailable")
    return SimpleTokenizer()


@needs_vocab
class TestSimpleTokenizer:
    def test_vocab_size(self, tok):
        assert tok.vocab_size == 49408

    @pytest.mark.parametrize(
        "text",
        [
            "hello world",
            "a painting of a fox sitting in a field at sunrise",
            "Ünïcödé, accents & <html> entities!",
            "numbers 12345 and punctuation?!...",
        ],
    )
    def test_round_trip(self, tok, text):
        ids = tok.encode(text)
        assert ids and all(0 < i < tok.vocab_size for i in ids)
        out = tok.decode(ids)
        # byte-BPE round trip is lossy only in case/whitespace normalization
        # (decode re-spaces at every </w>, exactly like the reference's
        # .replace('</w>', ' '), tokenizer.py:134)
        import re

        norm = lambda s: re.sub(r"\s+", "", s.lower())
        assert norm(out) == norm(text)

    def test_tokenize_contract(self, tok):
        arr = tok.tokenize(["hi there", "a cat"], context_length=16)
        assert arr.shape == (2, 16) and arr.dtype == np.int32
        n = len(tok.encode("hi there"))
        assert (arr[0, n:] == 0).all() and (arr[0, :n] > 0).all()

    def test_tokenize_too_long(self, tok):
        long = "word " * 300
        with pytest.raises(RuntimeError):
            tok.tokenize(long, context_length=8)
        arr = tok.tokenize(long, context_length=8, truncate_text=True)
        assert arr.shape == (1, 8) and (arr > 0).all()

    def test_decode_skips_pads(self, tok):
        ids = tok.encode("blue bird")
        padded = ids + [49000, 49001]
        assert tok.decode(padded, pad_tokens={49000, 49001}) == tok.decode(ids)

    def test_known_clip_encoding(self, tok):
        """'hello world' under the standard CLIP vocab is [3306, 1002] —
        pins vocab construction (merge slicing, </w> handling) exactly."""
        assert tok.encode("hello world") == [3306, 1002]


def write_sample(folder, stem, caption="a red square", size=32, corrupt=False):
    img = Image.new("RGB", (size, size), (200, 30, 30))
    p = folder / f"{stem}.png"
    if corrupt:
        p.write_bytes(b"not an image at all")
    else:
        img.save(p)
    (folder / f"{stem}.txt").write_text(caption)


@needs_vocab
class TestTextImageDataset:
    def test_pairing_and_shapes(self, tmp_path):
        for i in range(4):
            write_sample(tmp_path, f"s{i}", caption=f"sample number {i}")
        (tmp_path / "orphan.txt").write_text("no image")  # unpaired: excluded
        ds = TextImageDataset(str(tmp_path), text_len=16, image_size=16)
        assert len(ds) == 4
        tokens, image = ds[0]
        assert tokens.shape == (16,) and tokens.dtype == np.int32
        assert image.shape == (16, 16, 3) and 0.0 <= image.min() <= image.max() <= 1.0

    def test_corrupt_image_skipped(self, tmp_path):
        write_sample(tmp_path, "bad", corrupt=True)
        write_sample(tmp_path, "good")
        ds = TextImageDataset(str(tmp_path), text_len=8, image_size=16)
        tokens, image = ds[ds.keys.index("bad")]
        assert image.shape == (16, 16, 3)  # substituted with the good sample

    def test_empty_caption_skipped(self, tmp_path):
        write_sample(tmp_path, "a")
        (tmp_path / "b.png").write_bytes((tmp_path / "a.png").read_bytes())
        (tmp_path / "b.txt").write_text("")
        ds = TextImageDataset(str(tmp_path), text_len=8, image_size=16)
        tokens, _ = ds[ds.keys.index("b")]
        assert (tokens > 0).any()  # substitute had a real caption


@needs_vocab
class TestDataLoader:
    def test_batching_and_sharding(self, tmp_path):
        for i in range(10):
            write_sample(tmp_path, f"s{i}")
        ds = TextImageDataset(str(tmp_path), text_len=8, image_size=16)
        dl = DataLoader(ds, batch_size=2, shuffle=True, seed=1)
        batches = list(dl)
        assert len(batches) == 5
        assert batches[0]["text"].shape == (2, 8)
        assert batches[0]["image"].shape == (2, 16, 16, 3)

        # two-host sharding: disjoint and half-size
        dl0 = DataLoader(ds, 2, shuffle=False, process_index=0, process_count=2)
        dl1 = DataLoader(ds, 2, shuffle=False, process_index=1, process_count=2)
        assert len(dl0) == len(dl1) == 2
        assert set(dl0._indices()).isdisjoint(dl1._indices())

    def test_image_folder(self, tmp_path):
        for i in range(3):
            Image.new("RGB", (24, 24), (i * 40, 0, 0)).save(tmp_path / f"i{i}.png")
        ds = ImageFolderDataset(str(tmp_path), image_size=16)
        dl = DataLoader(
            ds, batch_size=3, shuffle=False, collate_fn=ImageFolderDataset.collate
        )
        (batch,) = list(dl)
        assert batch["image"].shape == (3, 16, 16, 3)


class TestExpandUrls:
    def test_braces(self):
        urls = expand_urls("shard-{0000..0003}.tar")
        assert urls == [f"shard-{i:04d}.tar" for i in range(4)]

    def test_plain(self):
        assert expand_urls("/x/y.tar") == ["/x/y.tar"]


@needs_vocab
class TestTarPipeline:
    def make_shard(self, path, n=4, start=0, with_bad=False):
        with tarfile.open(path, "w") as tf:
            for i in range(start, start + n):
                img = Image.new("RGB", (24, 24), (10 * i, 20, 30))
                buf = io.BytesIO()
                img.save(buf, format="PNG")
                self._add(tf, f"sample{i:04d}.png", buf.getvalue())
                self._add(tf, f"sample{i:04d}.txt", f"caption {i}".encode())
            if with_bad:
                self._add(tf, "bad0001.png", b"garbage bytes")
                self._add(tf, "bad0001.txt", b"broken image")

    @staticmethod
    def _add(tf, name, data):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))

    def test_stream_and_batch(self, tmp_path):
        self.make_shard(tmp_path / "shard-0000.tar", n=4, start=0)
        self.make_shard(tmp_path / "shard-0001.tar", n=4, start=4)
        ds = TarImageTextDataset(
            str(tmp_path / "shard-{0000..0001}.tar"), text_len=8, image_size=16
        )
        samples = list(ds)
        assert len(samples) == 8
        batches = list(TarLoader(ds, batch_size=4))
        assert len(batches) == 2
        assert batches[0]["text"].shape == (4, 8)
        assert batches[0]["image"].shape == (4, 16, 16, 3)

    def test_warn_and_continue(self, tmp_path, capsys):
        self.make_shard(tmp_path / "s.tar", n=2, with_bad=True)
        ds = TarImageTextDataset(str(tmp_path / "s.tar"), text_len=8, image_size=16)
        samples = list(ds)
        assert len(samples) == 2  # bad sample dropped, stream continued

    def test_host_sharding(self, tmp_path):
        for i in range(4):
            self.make_shard(tmp_path / f"shard-{i:04d}.tar", n=2, start=2 * i)
        spec = str(tmp_path / "shard-{0000..0003}.tar")
        a = TarImageTextDataset(spec, text_len=8, image_size=16, process_index=0, process_count=2)
        b = TarImageTextDataset(spec, text_len=8, image_size=16, process_index=1, process_count=2)
        assert set(a._my_shards()).isdisjoint(b._my_shards())
        assert len(list(a)) == len(list(b)) == 4


class TestMetricsLogger:
    """§5.5 observability additions: histogram + artifact upload (the
    reference logs wandb.Histogram(codes) in train_vae.py:262 and uploads
    checkpoint artifacts in train_dalle.py:637-649)."""

    class FakeWandb:
        def __init__(self):
            self.logged, self.artifacts = [], []
            self.run = self

        def Histogram(self, v):
            return ("hist", np.asarray(v).shape)

        def log(self, d, step=None):
            self.logged.append((d, step))

        def Artifact(self, name, type="model", metadata=None):
            class A:
                def __init__(self):
                    self.name, self.type, self.metadata = name, type, metadata
                    self.files = []

                def add_file(self, p):
                    self.files.append(p)

            return A()

        def log_artifact(self, a):
            self.artifacts.append(a)

        def finish(self):
            pass

    def test_histogram_and_artifact_with_wandb(self, tmp_path):
        from dalle_pytorch_tpu.utils.metrics import MetricsLogger

        logger = MetricsLogger(enabled=True)
        logger._wandb = self.FakeWandb()
        logger.log_histogram("codes", np.arange(12).reshape(3, 4), step=7)
        (d, step), = logger._wandb.logged
        assert step == 7 and d["codes"] == ("hist", (12,))

        f = tmp_path / "m.ckpt"
        f.write_bytes(b"x")
        logger.log_artifact("trained-vae", str(f), metadata={"dim": 8})
        (a,) = logger._wandb.artifacts
        assert a.name == "trained-vae" and a.files == [str(f)]
        assert a.metadata == {"dim": 8}

    def test_noop_without_wandb(self, capsys):
        from dalle_pytorch_tpu.utils.metrics import MetricsLogger

        logger = MetricsLogger(enabled=True)
        logger.log_histogram("codes", np.asarray([1, 1, 2, 5]), step=0)
        logger.log_artifact("x", "/nonexistent/path")  # must not raise
        out = capsys.readouterr().out
        assert "histogram" in out and "unique=3" in out


class TestHloBreakdown:
    """bench.py --breakdown's parser (utils/hlo_breakdown.py): per-module
    FLOPs from compiled HLO, the analog of the reference's DeepSpeed
    flops-profiler table (ref train_dalle.py:473-480)."""

    def test_dot_flops_from_compiled_hlo(self):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.utils.hlo_breakdown import (
            format_table,
            parse_hlo_flops,
        )

        def f(x, w1, w2):
            with jax.named_scope("layer_a"):
                h = x @ w1
            with jax.named_scope("layer_b"):
                return h @ w2

        x = jnp.zeros((8, 32))
        w1, w2 = jnp.zeros((32, 64)), jnp.zeros((64, 16))
        comp = jax.jit(f).lower(x, w1, w2).compile()
        groups = parse_hlo_flops(comp.as_text())
        flat = {k: v["fwd"] + v["bwd"] for k, v in groups.items()}
        # 2*8*32*64 and 2*8*64*16 FLOPs, charged to their scopes
        by_scope = {k.split("/")[-1]: v for k, v in flat.items()}
        assert by_scope.get("layer_a") == 2 * 8 * 32 * 64
        assert by_scope.get("layer_b") == 2 * 8 * 64 * 16
        table = format_table(groups, step_time_s=0.001, peak_flops=1e12)
        assert "layer_a" in table and "TOTAL" in table

    def test_custom_call_and_backward_split(self):
        from dalle_pytorch_tpu.utils.hlo_breakdown import parse_hlo_flops

        hlo = """
HloModule m
ENTRY e {
  %p0 = f32[4,8]{1,0} parameter(0)
  %cc = f32[4,8]{1,0} custom-call(%p0), custom_call_target="tpu_custom_call", metadata={op_name="jit(f)/jvp(M)/attn/flash_fwd"}
  %w = f32[8,2]{1,0} parameter(1)
  %d = f32[4,2]{1,0} dot(%cc, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/transpose(jvp(M))/head/dot_general"}
}
"""
        def cc(line):
            if "tpu_custom_call" not in line:
                return None
            return ("attn[pallas]", "fwd", 123.0)

        groups = parse_hlo_flops(hlo, custom_call_flops=cc)
        assert groups["attn[pallas]"]["fwd"] == 123.0
        assert groups["head"]["bwd"] == 2 * 4 * 2 * 8


def test_analyze_trace_tool(tmp_path):
    """tools/analyze_trace.py digests a Chrome-format profiler trace into
    the per-category table (the measured-time complement of
    bench.py --breakdown)."""
    import gzip
    import json
    import sys

    tools = Path(__file__).resolve().parent.parent / "tools"
    sys.path.insert(0, str(tools))
    import analyze_trace

    events = [
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "jit_train_step(123)",
         "ts": 0.0, "dur": 100.0, "args": {}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.7", "ts": 1.0,
         "dur": 60.0, "args": {"hlo_category": "convolution fusion",
                               "deduplicated_name": "fusion.1"}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fn.3", "ts": 62.0,
         "dur": 30.0, "args": {"hlo_category": "custom-call"}},
        # outside the module window: must be excluded
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.9", "ts": 200.0,
         "dur": 50.0, "args": {"hlo_category": "loop fusion"}},
    ]
    out = analyze_trace.analyze(events, None, 10)
    assert "jit_train_step" in out
    assert "convolution fusion" in out and "custom-call" in out
    assert "loop fusion" not in out  # outside the window
    d = tmp_path / "prof"
    (d / "plugins" / "profile" / "x").mkdir(parents=True)
    with gzip.open(d / "plugins" / "profile" / "x" / "m.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    loaded = analyze_trace.load_trace(str(d))
    assert analyze_trace.analyze(loaded, "train_step", 10) == out
