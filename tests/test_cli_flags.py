"""CLI flag-surface parity vs the reference's train_dalle.py.

The reference's user-facing contract is its argparse surface
(/root/reference/train_dalle.py:33-135). This test diffs that surface
against ours so a reference user can port a launch command unchanged:
every reference flag must either exist verbatim here or appear in the
explicit, documented substitution table below. It reads the reference
file with a regex rather than importing it (the reference pulls in torch
CUDA modules at import time).
"""

import re
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
REFERENCE = Path("/root/reference/train_dalle.py")

# Reference flags deliberately replaced by a TPU-native analog (not a gap
# — each row is a conscious substitution, documented at the cited site).
SUBSTITUTED = {
    # DeepSpeed flops-profiler dump -> XLA trace capture + HLO FLOPs table
    # (train_dalle.py --profile_trace_dir/--profile_step, bench.py --breakdown)
    "--flops_profiler": ("--profile_trace_dir", "--profile_step"),
}


def _ref_flags():
    # every quoted '--flag' in an add_argument(...) call; calls span lines
    # (e.g. the reference's --wds at train_dalle.py:48-53), so match over
    # each call's full argument span, not per-line
    text = REFERENCE.read_text()
    flags = set()
    for m in re.finditer(r"add_argument\(", text):
        span = text[m.end():m.end() + 400]
        span = span.split(")")[0]  # flags precede any ')' in the call
        flags.update(re.findall(r"'(--[\w\-]+)'", span))
    return flags


def _our_flags():
    sys.path.insert(0, str(REPO))
    try:
        from train_dalle import build_parser
    finally:
        sys.path.pop(0)
    parser = build_parser()
    flags = set()
    for action in parser._actions:
        flags.update(s for s in action.option_strings if s.startswith("--"))
    return flags


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference tree absent")
def test_reference_flag_surface_is_covered():
    ref, ours = _ref_flags(), _our_flags()
    assert ref, "regex found no reference flags — parsing broke"
    missing = []
    for flag in sorted(ref):
        if flag in ours:
            continue
        subs = SUBSTITUTED.get(flag)
        if subs:
            absent = [s for s in subs if s not in ours]
            assert not absent, (
                f"substitution for {flag} lists {absent} which our parser "
                "does not define — fix the table or the parser"
            )
            continue
        missing.append(flag)
    assert not missing, (
        f"reference flags with no analog here: {missing} — add them (or a "
        "documented substitution) so reference launch commands port cleanly"
    )


def test_substitution_table_is_not_stale():
    # a substituted flag that later lands verbatim should be dropped from
    # the table so the docs stay honest
    ours = _our_flags()
    stale = [f for f in SUBSTITUTED if f in ours]
    assert not stale, f"flags now implemented verbatim, prune from table: {stale}"
