"""Training-utility tests: checkpoint formats, schedulers, model factory,
download cache."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dalle_pytorch_tpu.models import DALLE, DiscreteVAE
from dalle_pytorch_tpu.models.factory import (
    dalle_from_checkpoint,
    save_dalle_checkpoint,
    save_vae_checkpoint,
    vae_from_checkpoint,
)
from dalle_pytorch_tpu.parallel import TrainState, create_train_state, make_runtime
from dalle_pytorch_tpu.utils import (
    ExponentialDecay,
    ReduceLROnPlateau,
    download,
    gumbel_temperature,
    load_checkpoint,
    load_sharded_checkpoint,
    save_checkpoint,
    save_sharded_checkpoint,
)


class TestPlainCheckpoint:
    def test_round_trip(self, tmp_path):
        state = {
            "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
            "step": jnp.array(7),
        }
        path = str(tmp_path / "ck.ckpt")
        save_checkpoint(path, state, meta={"epoch": 3, "name": "x"})
        restored, meta = load_checkpoint(path, target=state)
        assert meta == {"epoch": 3, "name": "x"}
        np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
        assert int(restored["step"]) == 7

    def test_no_torn_file_on_failure(self, tmp_path):
        path = tmp_path / "ck.ckpt"
        save_checkpoint(str(path), {"a": jnp.ones(2)})
        assert path.exists() and not path.with_suffix(".ckpt.tmp").exists()


class TestShardedCheckpoint:
    def test_round_trip_with_rotation(self, tmp_path):
        rt = make_runtime(fsdp=2, tp=2)
        params = {"k": jnp.arange(64.0).reshape(8, 8)}
        opt = optax.adam(1e-3)
        state, shardings = create_train_state(params, opt, rt)

        root = str(tmp_path / "cp")
        for step in (1, 2, 3):
            save_sharded_checkpoint(root, step, state, meta={"epoch": step}, keep_n=2)
        import pathlib

        kept = sorted(p.name for p in pathlib.Path(root).glob("step_*"))
        assert kept == ["step_00000002", "step_00000003"]

        restored, meta, step = load_sharded_checkpoint(
            root, jax.tree_util.tree_map(np.asarray, state)
        )
        assert step == 3 and meta == {"epoch": 3}
        np.testing.assert_array_equal(
            np.asarray(restored.params["k"]), np.asarray(state.params["k"])
        )


class TestSchedules:
    def test_reduce_on_plateau(self):
        s = ReduceLROnPlateau(lr=1.0, factor=0.5, patience=2, cooldown=0)
        for _ in range(3):
            s.step(10.0)  # first call sets best, then 2 bad
        assert s.lr == 1.0
        s.step(10.0)  # 3rd bad > patience -> decay
        assert s.lr == 0.5
        s.step(1.0)  # improvement resets
        assert s.best == 1.0
        d = s.state_dict()
        s2 = ReduceLROnPlateau(lr=9.9)
        s2.load_state_dict(d)
        assert s2.lr == 0.5 and s2.best == 1.0

    def test_exponential(self):
        s = ExponentialDecay(1.0, 0.5)
        assert s.step() == 0.5 and s.step() == 0.25

    def test_gumbel_anneal(self):
        assert gumbel_temperature(0, 1.0, 1e-6, 0.5) == 1.0
        assert gumbel_temperature(10**9, 1.0, 1e-6, 0.5) == 0.5


class TestFactory:
    def test_vae_round_trip(self, tmp_path):
        vae = DiscreteVAE(image_size=16, num_tokens=8, codebook_dim=16,
                          num_layers=2, hidden_dim=8)
        img = jnp.zeros((1, 16, 16, 3))
        params = vae.init(
            {"params": jax.random.key(0), "gumbel": jax.random.key(0)}, img
        )["params"]
        path = str(tmp_path / "vae.ckpt")
        save_vae_checkpoint(path, vae, params, extra={"epoch": 5})
        vae2, params2, meta = vae_from_checkpoint(path)
        assert vae2 == vae  # flax modules compare by config
        assert meta["epoch"] == 5
        chex_leaves = zip(
            jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
        )
        assert all(np.array_equal(a, b) for a, b in chex_leaves)

    def test_dalle_round_trip_with_vae(self, tmp_path):
        vae = DiscreteVAE(image_size=16, num_tokens=8, codebook_dim=16,
                          num_layers=2, hidden_dim=8)
        img = jnp.zeros((1, 16, 16, 3))
        vae_params = vae.init(
            {"params": jax.random.key(0), "gumbel": jax.random.key(0)}, img
        )["params"]
        dalle = DALLE(dim=32, depth=1, num_text_tokens=16, text_seq_len=4,
                      num_image_tokens=8, image_fmap_size=4, heads=2, dim_head=8)
        text = jnp.zeros((1, 4), jnp.int32)
        image = jnp.zeros((1, 16), jnp.int32)
        params = dalle.init(jax.random.key(0), text, image)["params"]

        path = str(tmp_path / "dalle.ckpt")
        save_dalle_checkpoint(path, dalle, params, vae, vae_params,
                              extra={"epoch": 2})
        d2, p2, v2, vp2, meta = dalle_from_checkpoint(path)
        assert d2 == dalle and v2 == vae and meta["epoch"] == 2
        logits_a = dalle.apply({"params": params}, text, image)
        logits_b = d2.apply({"params": p2}, text, image)
        np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b))


class TestDownload:
    def test_local_copy_and_cache(self, tmp_path):
        src = tmp_path / "weights.bin"
        src.write_bytes(b"\x01\x02\x03")
        out = download(str(src), root=str(tmp_path / "cache"))
        assert open(out, "rb").read() == b"\x01\x02\x03"
        src.write_bytes(b"changed")  # cached: second call must not re-copy
        out2 = download(str(src), root=str(tmp_path / "cache"))
        assert out2 == out and open(out2, "rb").read() == b"\x01\x02\x03"
