"""Unified-telemetry tests (utils/telemetry.py + metrics.py Histogram):
span nesting on a fake clock, histogram percentile correctness vs numpy,
ring-buffer overflow and rotation, fail-open sink faults, the flight
recorder under a REAL SIGTERM in a subprocess, and the serving-engine
acceptance invariant — every request produces a complete
admit→terminal span chain whose typed outcomes sum to the engine's own
counters under a fault-injected overload run.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import urllib.request

import numpy as np
import pytest

from dalle_pytorch_tpu.serving.types import FakeClock
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import (
    Histogram,
    Throughput,
    counters,
    histograms,
)
from dalle_pytorch_tpu.utils.telemetry import (
    TELEMETRY,
    Telemetry,
    validate_flight_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- histogram


class TestHistogram:
    def test_count_sum_min_max_exact(self):
        rng = np.random.RandomState(0)
        vals = np.exp(rng.randn(2000))
        h = Histogram()
        for v in vals:
            h.observe(float(v))
        assert h.count == 2000
        np.testing.assert_allclose(h.sum, vals.sum(), rtol=1e-9)
        assert h.min == vals.min() and h.max == vals.max()

    @pytest.mark.parametrize("q", [50, 95, 99])
    def test_percentiles_within_bucket_factor_of_numpy(self, q):
        """The contract: a reported percentile is the upper bound of its
        value's log-spaced bucket, so it brackets numpy's order statistic
        within one bucket growth factor (10^0.1 ~ 1.2589) either side."""
        rng = np.random.RandomState(q)
        # lognormal spanning ~5 decades — the span-duration regime
        vals = np.exp(rng.randn(5000) * 1.5 - 4)
        h = Histogram()
        for v in vals:
            h.observe(float(v))
        ratio = h.percentile(q) / np.percentile(vals, q)
        growth = 10 ** 0.1
        assert 1 / growth <= ratio <= growth * 1.001, (q, ratio)

    def test_empty_and_overflow(self):
        h = Histogram(lo=1e-3, hi=1.0)
        assert h.percentile(50) == 0.0
        assert h.snapshot()["count"] == 0
        h.observe(50.0)  # beyond hi -> overflow bucket
        assert h.percentile(99) == 50.0  # overflow reports the exact max
        assert h.buckets()[-1] == (float("inf"), 1)

    def test_percentile_capped_at_observed_max(self):
        h = Histogram()
        h.observe(0.5)
        # bucket upper bound would be > 0.5; the cap keeps the report
        # inside the observed range
        assert h.percentile(99) == 0.5

    def test_registry_on_demand_and_reset(self):
        histograms.observe("t.x_s", 0.1)
        histograms.observe("t.x_s", 0.2)
        assert histograms.get("t.x_s").count == 2
        assert "t.x_s" in histograms.snapshot("t.")
        histograms.reset()
        assert histograms.get("t.x_s") is None


class TestThroughputWindowFix:
    def test_fires_every_window_steps_with_ragged_samples(self):
        """The old ``total % (samples * window)`` test silently stopped
        firing once per-step sample counts varied (last-batch remainder,
        ragged serving batches); steps are the window unit now."""
        t = Throughput(window=3)
        fired = [t.update(s) is not None for s in (4, 4, 2, 4, 3, 1, 5)]
        assert fired == [False, False, True, False, False, True, False]

    def test_rate_sums_ragged_samples(self):
        t = Throughput(window=2)
        t._t0 -= 1.0  # pretend the window took ~1s
        assert t.update(3) is None
        rate = t.update(1)
        assert rate is not None and 3.5 < rate < 4.5  # (3+1)/~1s

    def test_old_bug_scenario_constant_then_remainder(self):
        # constant batches of 4, then a size-2 remainder: the old code
        # never fired again after the remainder broke the multiple
        t = Throughput(window=2)
        seq = [4, 4, 2, 4, 4, 4]
        fires = sum(t.update(s) is not None for s in seq)
        assert fires == 3


# ------------------------------------------------------- span machinery


@pytest.fixture
def tel(tmp_path):
    """Private instrumented Telemetry on a FakeClock (the serving Clock
    protocol, injected — span timing is deterministic)."""
    t = Telemetry(clock=FakeClock(), ring_size=64)
    t.configure(enabled=True, flight_dir=str(tmp_path / "flight"))
    yield t
    t.reset()


class TestSpans:
    def test_nesting_parents_and_fake_clock_durations(self, tel):
        with tel.span("train.outer", step=7) as outer:
            tel.clock.advance(1.0)
            with tel.span("train.inner") as inner:
                tel.clock.advance(0.25)
            tel.event("train.mark", note="x")
        recs = list(tel._buf)
        by = {(r.get("name"), r["ph"]): r for r in recs}
        assert by[("train.outer", "B")]["parent"] is None
        assert by[("train.inner", "B")]["parent"] == outer
        assert by[("train.mark", "I")]["parent"] == outer
        assert by[("train.inner", "E")]["dur_s"] == pytest.approx(0.25)
        assert by[("train.outer", "E")]["dur_s"] == pytest.approx(1.25)
        assert by[("train.outer", "B")]["step"] == 7
        # durations land in the <name>_s histograms
        assert histograms.get("train.outer_s").count == 1
        assert histograms.get("train.inner_s").sum == pytest.approx(0.25)

    def test_begin_end_non_lexical(self, tel):
        a = tel.begin("serve.request", request_id="r1")
        b = tel.begin("serve.request", request_id="r2")
        tel.clock.advance(2.0)
        tel.end(b, outcome="completed")
        tel.end(a, outcome="cancelled")
        ends = [r for r in tel._buf if r["ph"] == "E"]
        assert {e["outcome"] for e in ends} == {"completed", "cancelled"}
        assert all(e["dur_s"] == pytest.approx(2.0) for e in ends)

    def test_drain_and_validate(self, tel):
        with tel.span("a"):
            tel.event("e")
        path = tel.drain("test")
        s = validate_flight_file(path)
        assert s["spans"] == 1 and s["unclosed"] == []
        assert s["by_name"] == {"a": 2, "e": 1, "telemetry.drain": 1}

    def test_unclosed_span_is_the_postmortem(self, tel):
        tel.begin("train.step", step=3)
        path = tel.drain("crash")
        s = validate_flight_file(path)
        assert s["unclosed_records"][0]["name"] == "train.step"
        assert s["unclosed_records"][0]["step"] == 3

    def test_ring_overflow_without_dir_drops_oldest_counted(self, tmp_path):
        t = Telemetry(ring_size=8)
        t.configure(enabled=True)  # NO flight dir -> drop, not drain
        for i in range(20):
            t.event("spam", i=i)
        assert len(t._buf) == 8
        assert t.dropped == 12
        assert counters.get("telemetry.dropped") == 12
        # oldest dropped: the survivors are the 8 newest
        assert [r["i"] for r in t._buf] == list(range(12, 20))
        t.reset()

    def test_ring_full_rotates_to_flight_file(self, tel):
        for i in range(200):  # ring_size=64 -> several rotation drains
            tel.event("spam", i=i)
        tel.drain("tail")
        assert tel.dropped == 0
        s = validate_flight_file(tel._flight_path)
        assert s["by_name"]["spam"] == 200  # nothing lost

    def test_flight_file_rotation_caps_bytes(self, tel):
        # cap sized for exactly ONE rotation over this record volume, so
        # both generations survive: a span whose B/E pair straddles the
        # rotation must still balance (the validator stitches .1 first)
        tel.configure(flight_max_bytes=12_000)
        sid = tel.begin("serve.request", request_id="straddle")
        for i in range(300):
            tel.event("spam", i=i)
            if i % 50 == 0:
                tel.drain("tick")
        tel.end(sid, outcome="completed")
        tel.drain("tail")
        assert os.path.exists(tel._flight_path + ".1")  # rotated generation
        s = validate_flight_file(tel._flight_path)
        assert s["unclosed"] == [] and s["orphan_ends"] == 0, s
        assert s["by_name"]["spam"] == 300  # nothing lost across the cut
        assert s["spans"] >= 1  # the straddling pair matched up

    def test_double_rotation_orphan_end_is_counted_not_fatal(self, tel):
        # past TWO rotations the B horizon is genuinely gone; the E must
        # be counted as an orphan, not raise on an uncorrupted file
        tel.configure(flight_max_bytes=1_500)
        sid = tel.begin("serve.request", request_id="long")
        for i in range(400):
            tel.event("spam", i=i)
            if i % 40 == 0:
                tel.drain("tick")
        tel.end(sid, outcome="completed")
        tel.drain("tail")
        s = validate_flight_file(tel._flight_path)
        assert s["orphan_ends"] == 1 and s["unclosed"] == [], s

    def test_disabled_is_true_noop(self, tmp_path):
        threads_before = threading.active_count()
        t = Telemetry()
        with t.span("x", a=1) as sid:
            assert sid is None
        t.event("y")
        assert t.begin("z") is None
        t.end(None)
        assert t.drain("nope") is None
        assert not t._buf and not t._open
        assert threading.active_count() == threads_before
        assert not (tmp_path / "flight").exists()
        assert histograms.get("x_s") is None


class TestFailOpen:
    def test_sink_fault_injectable_and_contained(self, tel):
        FAULTS.arm("telemetry_sink_fail", 1)
        tel.event("x")
        assert tel.drain("faulted") is None  # swallowed, not raised
        assert tel.sink_errors == 1
        assert counters.get("telemetry.sink_errors") == 1
        assert FAULTS.fired["telemetry_sink_fail"] == 1
        # next drain works again (transient by contract)
        tel.event("y")
        path = tel.drain("ok")
        assert path and validate_flight_file(path)["by_name"].get("y") == 1

    def test_on_signal_hook_failure_never_raises(self):
        from dalle_pytorch_tpu.utils.resilience import PreemptionHandler

        def bad_hook(signum):
            raise OSError("observability broke")

        with PreemptionHandler(signals=(signal.SIGTERM,),
                               on_signal=bad_hook) as p:
            os.kill(os.getpid(), signal.SIGTERM)  # must not raise
            assert p.triggered


# -------------------------------------------------------- exposition


class TestExposition:
    def test_dump_renders_all_three_metric_kinds(self, tel):
        counters.inc("serve.submitted", 2)
        from dalle_pytorch_tpu.utils.metrics import gauges

        gauges.set("serve.running", 1.5)
        with tel.span("serve.decode_step"):
            tel.clock.advance(0.01)
        out = tel.dump()
        assert "serve_submitted 2" in out
        assert "serve_running 1.5" in out
        assert 'serve_decode_step_s_bucket{le="+Inf"} 1' in out
        assert "serve_decode_step_s_count 1" in out
        assert 'serve_decode_step_s{quantile="0.99"}' in out
        for line in out.splitlines():
            if line and not line.startswith("#"):
                float(line.rpartition(" ")[2])  # every sample line parses

    def test_metrics_http_endpoint_localhost(self, tel):
        counters.inc("serve.completed", 5)
        port = tel.serve_metrics(0)  # 0 -> ephemeral free port
        assert port
        assert tel.serve_metrics(0) == port  # idempotent
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "serve_completed 5" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/other", timeout=10
            )
        before = threading.active_count()
        tel.configure(enabled=False)  # teardown stops the server thread
        assert threading.active_count() < before

    def test_disabled_serves_nothing(self):
        t = Telemetry()
        assert t.serve_metrics(0) is None


# ------------------------------------------- host-side-only guarantee


def test_telemetry_is_host_side_only():
    """The span path must never touch the device: a per-token sync would
    be a measurement that destroys what it measures. Enforced by the
    import-layering checker (tools/lint.py DTL021, rule
    'host-only-utils' — docs/DESIGN.md §11), which checks every import
    node including lazy function-level ones and covers the whole
    host-side layer (telemetry, metrics, faults, resilience), not just
    the two modules the old source-grep pinned. This test is the thin
    gate: the checker must find NOTHING there."""
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo / "tools"))
    from lint import default_config, run_lint

    res = run_lint(
        default_config(str(repo)),
        paths=[
            "dalle_pytorch_tpu/utils/telemetry.py",
            "dalle_pytorch_tpu/utils/telemetry_names.py",
            "dalle_pytorch_tpu/utils/metrics.py",
            "dalle_pytorch_tpu/utils/faults.py",
            "dalle_pytorch_tpu/utils/resilience.py",
        ],
        checkers=["layering"],
    )
    assert res.clean, [f.render() for f in res.findings]


# ------------------------------------------------- engine span chains


def small_dalle():
    from dalle_pytorch_tpu.models import DALLE

    return DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )


@pytest.fixture(scope="module")
def model():
    import jax
    import jax.numpy as jnp

    dalle = small_dalle()
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    # page size 2 so the tiny model genuinely grows pages mid-decode —
    # same geometry as tests/test_serving.py
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def _req(i, max_new=4, **kw):
    from dalle_pytorch_tpu.serving import Request

    rng = np.random.RandomState(100 + i)
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
        max_new_tokens=max_new, **kw,
    )


class TestEngineSpanChains:
    def test_overload_every_request_has_typed_span_chain(self, model, tmp_path):
        """ISSUE acceptance: under a fault-injected overload run
        (page_exhaust + prefill_fail + bounded queue + deadlines), EVERY
        submitted request — completed, rejected, preempted-to-cap, or
        deadline-expired — appears in the flight recorder as a span chain
        ending in its typed outcome, and the span-outcome counts equal the
        engine's own accounting."""
        from dalle_pytorch_tpu.serving import Engine, EngineConfig

        TELEMETRY.configure(enabled=True, flight_dir=str(tmp_path / "fl"))
        FAULTS.configure("page_exhaust=1,prefill_fail=1")
        dalle, params = model
        clock = FakeClock(step_dt=1.0)
        eng = Engine(
            dalle, params,
            EngineConfig(max_batch=2, page_budget=7, queue_limit=3,
                         prefill_attempts=2),
            clock=clock,
        )
        for i in range(8):
            eng.submit(_req(
                i, max_new=4,
                deadline=None if i % 2 else 40.0,
                priority=i % 3,
            ))
        eng.run(max_steps=1000)
        path = TELEMETRY.drain("test")
        summary = validate_flight_file(path)
        assert summary["unclosed"] == [], summary["unclosed_records"]
        assert TELEMETRY.dropped == 0

        spans = {}  # request span id -> (B rec, E rec)
        children = {}
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("name") == "serve.request":
                    pair = spans.setdefault(rec["id"], [None, None])
                    pair[0 if rec["ph"] == "B" else 1] = rec
                elif rec.get("name") == "serve.prefill" and rec["ph"] == "B":
                    children.setdefault(rec["parent"], []).append(rec)

        # one complete B..E chain per submission, each typed
        assert len(spans) == 8
        outcome_counts = {}
        for sid, (b, e) in spans.items():
            assert b is not None and e is not None, (sid, b, e)
            assert e["outcome"], e
            outcome_counts[e["outcome"]] = outcome_counts.get(e["outcome"], 0) + 1
        engine_outcomes = {
            k: v for k, v in eng.stats()["outcomes"].items() if v
        }
        assert outcome_counts == engine_outcomes
        # every admitted request's prefill span is parented to ITS chain
        admitted_span_ids = {
            sid for sid, (b, _) in spans.items() if sid in children
        }
        assert len(admitted_span_ids) >= counters.get("serve.completed")
        for sid in admitted_span_ids:
            rid = spans[sid][0]["request_id"]
            assert all(c["request_id"] == rid for c in children[sid])
        # queue-wait histogram saw every admission
        assert histograms.get("serve.queue_wait_s").count == \
            counters.get("serve.admitted")

    def test_sink_faults_never_break_the_engine(self, model, tmp_path):
        """Observability fails open: with every drain write failing and a
        ring small enough to force rotation mid-run, the engine still
        completes with clean accounting — telemetry I/O errors must never
        propagate into the serve loop."""
        from dalle_pytorch_tpu.serving import (
            Engine, EngineConfig, Outcome, check_accounting,
        )

        TELEMETRY.configure(
            enabled=True, flight_dir=str(tmp_path / "fl"), ring_size=8,
        )
        FAULTS.arm("telemetry_sink_fail", 10_000)
        dalle, params = model
        eng = Engine(dalle, params, EngineConfig(max_batch=2),
                     clock=FakeClock(step_dt=1.0))
        for i in range(3):
            assert eng.submit(_req(i)) is None
        results = eng.run(max_steps=1000)
        check_accounting(eng)
        assert all(r.outcome is Outcome.COMPLETED for r in results.values())
        assert TELEMETRY.sink_errors > 0  # the failure was real, and counted

    def test_decode_spans_per_iteration_not_per_token(self, model, tmp_path):
        """The span path adds ONE host-side record pair per engine
        iteration (all active slots advance together), not one per token
        per slot — the 'no per-token device syncs' overhead shape."""
        from dalle_pytorch_tpu.serving import Engine, EngineConfig

        TELEMETRY.configure(enabled=True, flight_dir=str(tmp_path / "fl"))
        dalle, params = model
        eng = Engine(dalle, params, EngineConfig(max_batch=2),
                     clock=FakeClock(step_dt=1.0))
        for i in range(2):
            eng.submit(_req(i, max_new=4))
        eng.run(max_steps=1000)
        path = TELEMETRY.drain("t")
        by = validate_flight_file(path)["by_name"]
        total_tokens = 2 * 4
        # B+E per iteration; iterations < total generated tokens because
        # both slots advance in the same jitted step
        assert by["serve.decode_step"] < total_tokens
        assert by["serve.decode_step"] % 2 == 0


# ------------------------------------------------ SIGTERM + smoke gate


_SIGTERM_SCRIPT = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from dalle_pytorch_tpu.utils.resilience import PreemptionHandler
    from dalle_pytorch_tpu.utils.telemetry import TELEMETRY

    TELEMETRY.configure(enabled=True, flight_dir=sys.argv[1])

    def on_signal(signum):
        TELEMETRY.event("train.preempt_signal", signum=signum)
        TELEMETRY.drain("preempt_signal")

    with PreemptionHandler(on_signal=on_signal) as p:
        step = 0
        print("READY", flush=True)
        while not p.triggered:
            with TELEMETRY.span("train.step", step=step):
                time.sleep(0.01)
            step += 1
    sys.exit(0)
""")


def test_sigterm_drains_flight_recorder_real_signal(tmp_path):
    """A real SIGTERM delivered to a separate process mid-step leaves a
    valid, parseable flight-recorder file — drained inside the signal
    handler, before any shutdown work (the kill-and-resume shape of
    tests/test_resilience.py, applied to the telemetry contract). The
    full-CLI version of this runs in test_e2e.py's preemption test."""
    flight = tmp_path / "flight"
    script = tmp_path / "loop.py"
    script.write_text(_SIGTERM_SCRIPT.format(repo=REPO))
    proc = subprocess.Popen(
        [sys.executable, str(script), str(flight)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "READY"
        import time

        time.sleep(0.15)  # let a few steps land
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    files = sorted(flight.glob("flight-*.jsonl"))
    assert files, out
    summary = validate_flight_file(str(files[0]))
    assert summary["by_name"].get("train.step", 0) >= 2, summary
    assert summary["by_name"].get("train.preempt_signal") == 1, summary
    # spans balance: the interrupted step's E lands via the atexit drain
    assert summary["unclosed"] == [], summary["unclosed_records"]


def test_telemetry_smoke_gate(tmp_path):
    """The release gate (tools/telemetry_smoke.py): serve_smoke's
    3-request scenario — run CHUNKED and monolithic, plus the mid-prefill
    deadline drill and the interference scenario — with telemetry on:
    flight JSONL parses, spans balance (per-chunk spans included),
    /metrics renders. Run as a real subprocess, the way a release
    pipeline runs it."""
    out = subprocess.run(
        [sys.executable, "tools/telemetry_smoke.py",
         "--dir", str(tmp_path / "fl")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "telemetry smoke OK" in out.stderr
    summary = json.loads(
        [l for l in out.stdout.splitlines() if l.startswith('{"flight_file')][0]
    )
    # 3 chunked + 3 monolithic + 3 fused + 3 speculative + 6
    # quantized-KV (3 split + 3 fused int8 pages; ISSUE 14) + 6
    # prefix-cache cold/warm completions, 1 mid-prefill deadline drill,
    # + 6 from the recovery drill (2 fault-free reference, 2 cold
    # pre-crash, 2 replayed post-restart — the crashed incarnation's 2
    # open chains are the postmortem, not outcomes) + 8 from the
    # post-decode stage drill (3 clean full-pipeline + 3 absorbing
    # transient stage faults within the retry budget, plus the two
    # exhaustion drills landing TYPED DEGRADED: tokens-only and
    # unranked; DESIGN §8.5) — the warm round's full-hit requests (no
    # prefill span at all) must still close their serve.request chains
    # typed
    assert summary["request_outcomes"] == {
        "completed": 36, "deadline_exceeded": 1,
        "completed_tokens_only": 1, "completed_unranked": 1,
    }
    assert summary["prefill_chunk_spans"] >= 2
    assert summary["spec_verify_spans"] >= 1
    assert summary["interference_max_gap_ms"] > 0
