"""Paged KV-cache tests: page-level append/gather semantics, paged vs
flat vs 4-D decode parity (the cache format may only change storage, never
sampled tokens), frontier-windowed paged decode, and ragged decode offsets
(continuous batching) pinned bit-exact against per-sequence decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import (
    DALLE,
    generate_image_tokens,
    init_decode_cache,
    merge_decode_caches,
    set_decode_offsets,
)
from dalle_pytorch_tpu.ops import kv_policy, paged_kv


def small_dalle(**kw):
    defaults = dict(
        dim=32,
        depth=2,
        num_text_tokens=16,
        text_seq_len=4,
        num_image_tokens=12,
        image_fmap_size=2,
        heads=2,
        dim_head=8,
        attn_types=("full", "axial_row"),
        shift_tokens=True,
        rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


def dalle_inputs(dalle, b=2, seed=0):
    rng = np.random.RandomState(seed)
    text = jnp.asarray(
        rng.randint(1, dalle.num_text_tokens, size=(b, dalle.text_seq_len)), jnp.int32
    )
    image = jnp.asarray(
        rng.randint(0, dalle.num_image_tokens, size=(b, dalle.image_seq_len)), jnp.int32
    )
    return text, image


# ------------------------------------------------------------- page ops


class TestPageOps:
    def test_append_gather_roundtrip_across_page_boundary(self):
        """A block written at an offset that straddles page boundaries must
        read back exactly, with untouched positions still zero."""
        b, L, f, page = 2, 10, 3, 4
        pool = paged_kv.alloc(b, L, f, page)
        assert pool.shape == (b, 3, page, f)
        table = paged_kv.identity_table(b, 3)

        rng = np.random.RandomState(0)
        rows = jnp.asarray(rng.rand(b, 5, f), jnp.float32)
        start = jnp.asarray([3, 3], jnp.int32)  # rows span pages 0, 1 and 2
        pool = paged_kv.append(pool, table, start, rows)

        flat = np.asarray(paged_kv.gather(pool, table))
        expect = np.zeros((b, 3 * page, f), np.float32)
        expect[:, 3:8] = np.asarray(rows)
        np.testing.assert_array_equal(flat, expect)

    def test_append_exactly_at_page_boundary(self):
        b, f, page = 1, 2, 4
        pool = paged_kv.alloc(b, 8, f, page)
        table = paged_kv.identity_table(b, 2)
        row = jnp.ones((b, 1, f))
        pool = paged_kv.append(pool, table, jnp.asarray([4], jnp.int32), row)
        flat = np.asarray(paged_kv.gather(pool, table))
        assert flat[0, 4].sum() == f  # first row of page 1
        assert flat[0, :4].sum() == 0 and flat[0, 5:].sum() == 0

    def test_append_per_sequence_offsets(self):
        """Each sequence writes at its OWN index — the ragged-offsets core."""
        b, f, page = 3, 2, 4
        pool = paged_kv.alloc(b, 12, f, page)
        table = paged_kv.identity_table(b, 3)
        rows = jnp.arange(b * f, dtype=jnp.float32).reshape(b, 1, f) + 1
        idx = jnp.asarray([0, 5, 11], jnp.int32)
        flat = np.asarray(paged_kv.gather(paged_kv.append(pool, table, idx, rows), table))
        for i, p in enumerate([0, 5, 11]):
            np.testing.assert_array_equal(flat[i, p], np.asarray(rows)[i, 0])
            assert np.delete(flat[i], p, axis=0).sum() == 0

    def test_out_of_capacity_rows_are_dropped(self):
        b, f, page = 1, 2, 4
        pool = paged_kv.alloc(b, 4, f, page)
        table = paged_kv.identity_table(b, 1)
        rows = jnp.ones((b, 2, f))
        pool = paged_kv.append(pool, table, jnp.asarray([3], jnp.int32), rows)
        flat = np.asarray(paged_kv.gather(pool, table))
        assert flat[0, 3].sum() == f  # in-capacity row landed
        assert flat[0, :3].sum() == 0  # the overflow row vanished, no wrap

    def test_reset_rows_and_tables(self):
        """Eviction reset (serving engine): the victim's rows go back to
        pristine — zero pages, identity table (GLOBAL ids r * n_pages + i)
        — with other rows untouched."""
        rng = np.random.RandomState(2)
        pool = jnp.asarray(rng.rand(3, 2, 4, 2), jnp.float32)
        table = jnp.asarray([[1, 0], [0, 1], [1, 0]], jnp.int32)
        pool2 = paged_kv.reset_rows(pool, 1)
        assert np.asarray(pool2)[1].sum() == 0
        np.testing.assert_array_equal(np.asarray(pool2)[[0, 2]], np.asarray(pool)[[0, 2]])
        table2 = paged_kv.reset_table_rows(table, [0, 2])
        np.testing.assert_array_equal(
            np.asarray(table2), [[0, 1], [0, 1], [4, 5]]
        )

    def test_identity_table_is_global(self):
        """identity_table row r maps logical page i to GLOBAL physical
        page r * n_pages + i — the flattened-view id space that lets a
        table entry reference another row's storage (prefix sharing)."""
        t = np.asarray(paged_kv.identity_table(3, 2))
        np.testing.assert_array_equal(t, [[0, 1], [2, 3], [4, 5]])

    def test_cross_row_gather_and_append(self):
        """A table entry naming another row's physical page reads (and
        writes through to) that row's storage — the prefix-sharing seam."""
        b, f, page = 2, 2, 4
        pool = paged_kv.alloc(b, 8, f, page)  # (2, 2, 4, 2); global ids 0..3
        table = paged_kv.identity_table(b, 2)
        rows = jnp.full((b, 1, f), 7.0)
        pool = paged_kv.append(
            pool, table, jnp.asarray([0, 0], jnp.int32), rows
        )
        # remap row 1's logical page 0 onto row 0's physical page 0
        shared = table.at[1, 0].set(0)
        flat = np.asarray(paged_kv.gather(pool, shared))
        np.testing.assert_array_equal(flat[1, 0], flat[0, 0])
        # a write through the shared entry lands in row 0's storage
        pool2 = paged_kv.append(
            pool, shared, jnp.asarray([8, 1], jnp.int32),  # row 1 pos 1
            jnp.full((b, 1, f), 3.0),
        )
        assert np.asarray(pool2)[0, 0, 1].sum() == f * 3.0

    def test_copy_pages_zeroes_past_valid(self):
        """copy_pages moves whole physical pages and zeroes destination
        rows past the per-page valid count — the publish / copy-on-write
        primitive (a published terminal page must not leak image K/V)."""
        rng = np.random.RandomState(3)
        pool = jnp.asarray(rng.rand(2, 2, 4, 2), jnp.float32)
        out = np.asarray(paged_kv.copy_pages(pool, src=[1], dst=[3], valid=[2]))
        src = np.asarray(pool).reshape(4, 4, 2)[1]
        np.testing.assert_array_equal(out[1, 1, :2], src[:2])
        assert out[1, 1, 2:].sum() == 0
        # other pages untouched
        np.testing.assert_array_equal(out[0], np.asarray(pool)[0])

    def test_gather_variants_match(self):
        rng = np.random.RandomState(1)
        pool = jnp.asarray(rng.rand(2, 3, 4, 8), jnp.float32)
        table = paged_kv.identity_table(2, 3)
        np.testing.assert_allclose(
            np.asarray(paged_kv.gather(pool, table, variant="take")),
            np.asarray(paged_kv.gather(pool, table, variant="onehot")),
            atol=1e-6,
        )


# ------------------------------------------------- format parity (model)


class TestFormatParity:
    def test_paged_flat_4d_sample_identical_tokens(self, monkeypatch):
        """The cache format may only change the arrays XLA lays out, never
        the sampled tokens. Page size 4 forces multi-page pools so the
        parity covers page-boundary appends inside the real decode loop
        (prefill block + scan), not just single pages."""
        monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "4")
        jax.clear_caches()  # page size is read at trace time
        try:
            dalle = small_dalle()
            text, image = dalle_inputs(dalle)
            params = dalle.init(jax.random.key(0), text, image)["params"]
            toks = {
                fmt: np.asarray(
                    generate_image_tokens(
                        dalle, params, text, jax.random.key(7), cache_format=fmt
                    )
                )
                for fmt in kv_policy.FORMATS
            }
            np.testing.assert_array_equal(toks["paged"], toks["4d"])
            np.testing.assert_array_equal(toks["flat"], toks["4d"])
        finally:
            jax.clear_caches()

    @pytest.mark.parametrize("kw", [dict(), dict(attn_types=("conv_like", "axial_col"))])
    def test_paged_decode_matches_forward(self, kw, monkeypatch):
        """Sequential paged decode_step reproduces the full-forward logits
        at every position (multi-page, page size 4)."""
        monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "4")
        dalle = small_dalle(**kw)
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        full_logits = np.asarray(dalle.apply({"params": params}, text, image))
        internal = np.concatenate(
            (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
        )
        cache = init_decode_cache(dalle, params, 2, cache_format="paged")
        assert any(
            getattr(p[-1], "key", None) == "cached_key_pages"
            for p, _ in jax.tree_util.tree_leaves_with_path(cache)
        )
        for i in range(dalle.total_seq_len):
            step_logits, mutated = dalle.apply(
                {"params": params, "cache": cache},
                jnp.asarray(internal[:, i]),
                jnp.array(i, jnp.int32),
                method=DALLE.decode_step,
                mutable=["cache"],
            )
            cache = mutated["cache"]
            np.testing.assert_allclose(
                np.asarray(step_logits), full_logits[:, i],
                atol=2e-3, rtol=1e-3,
                err_msg=f"paged decode/forward mismatch at position {i} ({kw})",
            )

    def test_windowed_paged_decode_matches_full(self, monkeypatch):
        """Frontier-sized paged pools (the segmented scan's resize_kv path,
        truncating pools and page tables at page granularity) must produce
        the same logits as the full-extent pool."""
        from dalle_pytorch_tpu.models.sampling import decode_tokens

        monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "4")
        jax.clear_caches()
        try:
            dalle = small_dalle()
            text, image = dalle_inputs(dalle)
            params = dalle.init(jax.random.key(0), text, image)["params"]
            internal = jnp.concatenate((dalle.remap_text(text), image), axis=1)
            n_internal = dalle.text_len_internal + dalle.image_seq_len
            tokens = jnp.zeros((2, n_internal), jnp.int32)
            tokens = jax.lax.dynamic_update_slice(tokens, internal, (0, 0))
            out = {}
            for seg in (0, 4):  # unsegmented vs resize every 4 positions
                out[seg] = np.asarray(
                    decode_tokens(
                        dalle, params, tokens, dalle.text_len_internal,
                        jax.random.key(3), prefill_len=dalle.text_len_internal,
                        window_seg=seg, cache_format="paged",
                    )
                )
            np.testing.assert_array_equal(out[0], out[4])
        finally:
            jax.clear_caches()


# ------------------------------------------------ ragged offsets (model)


class TestRaggedOffsets:
    def _replay(self, dalle, params, internal, row, upto):
        """Decode sequence ``row`` alone (batch 1, paged) to position upto."""
        cache = init_decode_cache(dalle, params, 1, cache_format="paged")
        for i in range(upto):
            _, mutated = dalle.apply(
                {"params": params, "cache": cache},
                jnp.asarray(internal[row : row + 1, i]),
                jnp.array(i, jnp.int32),
                method=DALLE.decode_step,
                mutable=["cache"],
            )
            cache = mutated["cache"]
        return cache

    def test_merged_ragged_step_matches_per_sequence(self, monkeypatch):
        """THE continuous-batching contract: two sequences replayed to
        different offsets, merged into one batch, stepped ONCE with vector
        positions — logits must equal each sequence's own next step (up to
        the ~1-ulp summation-order drift of batch-2 vs batch-1 einsum
        chunking)."""
        monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "4")
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        internal = np.concatenate(
            (np.asarray(dalle.remap_text(text)), np.asarray(image)), axis=1
        )
        offs = (6, 8)  # one mid-image, one further along — different pages
        caches = [
            self._replay(dalle, params, internal, r, o) for r, o in enumerate(offs)
        ]
        merged = merge_decode_caches(caches)

        tok = jnp.asarray(
            [internal[r, o] for r, o in enumerate(offs)], jnp.int32
        )
        pos = jnp.asarray(offs, jnp.int32)
        ragged_logits, mutated = dalle.apply(
            {"params": params, "cache": merged}, tok, pos,
            method=DALLE.decode_step, mutable=["cache"],
        )

        for r, o in enumerate(offs):
            ref, _ = dalle.apply(
                {"params": params, "cache": caches[r]},
                tok[r : r + 1], jnp.array(o, jnp.int32),
                method=DALLE.decode_step, mutable=["cache"],
            )
            np.testing.assert_allclose(
                np.asarray(ragged_logits[r : r + 1]), np.asarray(ref),
                atol=1e-5, rtol=1e-5,
                err_msg=f"ragged step diverged from per-sequence decode (seq {r})",
            )
        # the merged cache advanced every sequence's own frontier
        idx = [
            np.asarray(x)
            for p, x in jax.tree_util.tree_leaves_with_path(mutated["cache"])
            if getattr(p[-1], "key", None) == "cache_index"
        ]
        for leaf in idx:
            np.testing.assert_array_equal(leaf, np.asarray(offs) + 1)

    def test_set_decode_offsets_rejects_unpaged(self):
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        cache = init_decode_cache(dalle, params, 2, cache_format="flat")
        with pytest.raises(ValueError, match="paged"):
            set_decode_offsets(cache, jnp.asarray([1, 2], jnp.int32))

    def test_set_decode_offsets_places_every_index(self, monkeypatch):
        monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "4")
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        cache = init_decode_cache(dalle, params, 2, cache_format="paged")
        offs = jnp.asarray([3, 7], jnp.int32)
        cache = set_decode_offsets(cache, offs)
        for p, x in jax.tree_util.tree_leaves_with_path(cache):
            if getattr(p[-1], "key", None) in ("cache_index", "shift_index"):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(offs))


# ------------------------------------------------- sweep bench (slow tier)


def test_bench_decode_sweep_and_ragged_records():
    """Drive bench.py's batch sweep + continuous-batching sections on CPU
    (listed in tests/slow_tests.txt): every sweep record must carry the
    named derived bound and its cache format, so a TPU run of the same
    code emits the observability the layout policy stands on."""
    import subprocess
    import sys
    import json
    import os as _os

    env = dict(_os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--sweep", "--ragged"],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    records = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    sweep = [r for r in records if r["metric"].startswith("decode_sweep")]
    ragged = [r for r in records if "continuous_batching" in r["metric"]]
    assert sweep and ragged
    for r in sweep:
        assert r["bound_name"] == "kv_sweep_weight_stream_hbm_roofline"
        assert r["roofline_tokens_per_sec"] > 0
        assert r["cache_format"] in ("paged", "flat", "4d")
        assert "policy_default_format" in r
    # the derived bound itself is monotone in batch (the in-source claim)
    by_fmt = {}
    for r in sweep:
        by_fmt.setdefault(r["cache_format"], []).append(
            (r["batch"], r["roofline_tokens_per_sec"])
        )
    for pts in by_fmt.values():
        pts = sorted(pts)
        assert all(b2 >= b1 for (_, b1), (_, b2) in zip(pts, pts[1:]))
    assert ragged[0]["cache_format"] == "paged"
    offs = ragged[0]["ragged_offsets"]
    assert len(set(offs)) == len(offs) > 1  # genuinely ragged


# ----------------------------------------------------------- the policy


class TestPolicy:
    def test_policy_defaults(self):
        assert kv_policy.choose_cache_format(1) == "4d"
        assert kv_policy.choose_cache_format(8) == "flat"
        for b in (2, 4, 16, 32, 64):
            assert kv_policy.choose_cache_format(b) == "paged"

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("DALLE_TPU_KV_FORMAT", "paged")
        assert kv_policy.choose_cache_format(8) == "paged"
        monkeypatch.setenv("DALLE_TPU_KV_FORMAT", "bogus")
        with pytest.raises(ValueError):
            kv_policy.choose_cache_format(8)
        monkeypatch.delenv("DALLE_TPU_KV_FORMAT")
        monkeypatch.setenv("DALLE_TPU_FLAT_KV", "1")
        assert kv_policy.choose_cache_format(2) == "flat"
        monkeypatch.setenv("DALLE_TPU_FLAT_KV", "0")
        assert kv_policy.choose_cache_format(8) == "4d"
        monkeypatch.setenv("DALLE_TPU_FLAT_KV", "maybe")
        with pytest.raises(ValueError):
            kv_policy.choose_cache_format(8)

    def test_invalid_override_is_named_error_listing_formats(self, monkeypatch):
        """An unknown format must fail AT POLICY RESOLUTION with the named
        error, naming every valid format — not as a shape error deep inside
        cache init. Covers all three override channels."""
        monkeypatch.setenv("DALLE_TPU_KV_FORMAT", "paged2")
        with pytest.raises(kv_policy.InvalidKVFormatError) as ei:
            kv_policy.choose_cache_format(4)
        for fmt in kv_policy.FORMATS:
            assert fmt in str(ei.value)
        assert "DALLE_TPU_KV_FORMAT" in str(ei.value)
        monkeypatch.delenv("DALLE_TPU_KV_FORMAT")

        with pytest.raises(kv_policy.InvalidKVFormatError, match="cache_format"):
            kv_policy.resolve_format("bogus", 4)
        with pytest.raises(kv_policy.InvalidKVFormatError):
            with kv_policy.format_override("bogus"):
                pass
        # ... and through the model entry point (init at trace time)
        dalle = small_dalle()
        text, image = dalle_inputs(dalle)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        with pytest.raises(kv_policy.InvalidKVFormatError):
            init_decode_cache(dalle, params, 2, cache_format="bogus")
        # the named error stays a ValueError for pre-existing callers
        assert issubclass(kv_policy.InvalidKVFormatError, ValueError)

    def test_choices_are_recorded(self):
        n0 = len(kv_policy.CHOICE_LOG)
        fmt = kv_policy.choose_cache_format(16)
        assert kv_policy.CHOICE_LOG[n0:] == [
            {"cache_format": fmt, "batch": 16,
             "reason": "policy: batch-invariant page-local updates"}
        ]

    def test_format_override_nests_and_restores(self):
        with kv_policy.format_override("flat"):
            assert kv_policy.choose_cache_format(32) == "flat"
            with kv_policy.format_override("paged"):
                assert kv_policy.choose_cache_format(32) == "paged"
            assert kv_policy.choose_cache_format(32) == "flat"
        assert kv_policy.choose_cache_format(32) == "paged"
