"""Fleet traffic-simulator tests (tools/traffic_sim.py; DESIGN §8.4).

The modeled lane's in-run asserts (typed accounting, replay seeding,
goodput bounds, storm amplification guard) fire inside the tool; these
tests pin the harness itself: seeded reproducibility of whole lane
records, retry-storm amplification with desynchronized respawn
ladders, correlated-outage MTTR accounting, modeled-vs-real fidelity
cross-validation, and the subprocess release gates the CI tiers run
(--quick in the fast tier, --sweep behind -m slow).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import traffic_sim as ts  # noqa: E402
from dalle_pytorch_tpu.utils.faults import FAULTS  # noqa: E402


@pytest.fixture(autouse=True)
def clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def small_spec(**kw):
    kw.setdefault("n_replicas", 3)
    kw.setdefault("max_batch", 4)
    kw.setdefault("queue_limit", 32)
    return ts.FleetSpec(**kw)


def small_workload(**kw):
    kw.setdefault("n_requests", 400)
    kw.setdefault("qps", 40.0)
    kw.setdefault("max_new_lo", 4)
    kw.setdefault("max_new_hi", 8)
    return ts.Workload(**kw)


def run_small_lane(seed=0, **wkw):
    spec = small_spec()
    w = small_workload(seed=seed, **wkw)
    router = ts.build_modeled_router(
        spec, ts.IterationCostModel(), seed=seed
    )
    return ts.run_lane(
        router, ts.generate_workload(w), ts.ClientPolicy(seed=seed)
    )


class TestSeededReproducibility:
    def test_identical_seed_identical_record(self):
        """Two fresh fleets, same seed: every field of the lane record
        — outcomes, percentiles, occupancy trace, iteration counts —
        must be bit-equal (the replay contract every scenario builds
        on)."""
        a = run_small_lane(seed=7)
        FAULTS.reset()
        b = run_small_lane(seed=7)
        assert json.dumps(a, sort_keys=True) == json.dumps(
            b, sort_keys=True
        )

    def test_different_seed_different_trace(self):
        a = run_small_lane(seed=1)
        FAULTS.reset()
        b = run_small_lane(seed=2)
        assert json.dumps(a, sort_keys=True) != json.dumps(
            b, sort_keys=True
        )

    def test_workload_generators_seeded(self):
        for arrival in ("poisson", "diurnal", "burst"):
            w = small_workload(arrival=arrival, seed=3)
            xs = ts.generate_workload(w)
            ys = ts.generate_workload(w)
            assert [l.t_arrival for l in xs] == [l.t_arrival for l in ys]
            assert [l.base.seed for l in xs] == [l.base.seed for l in ys]
            # arrivals are sorted and priorities span the spread
            ts_arr = [l.t_arrival for l in xs]
            assert ts_arr == sorted(ts_arr)
            assert {l.base.priority for l in xs} == {0, 1, 2}


class TestTypedAccounting:
    def test_every_logical_request_final_under_overload(self):
        """3x saturation: heavy shed, every logical request still ends
        with exactly one typed final outcome and the counts add up."""
        rec = run_small_lane(seed=0, qps=250.0, n_requests=600)
        assert sum(rec["outcomes"].values()) == rec["logical_requests"]
        assert rec["shed_frac"] > 0.0          # overload genuinely shed
        assert rec["retries"] > 0              # closed loop genuinely retried
        assert rec["completed"] == rec["outcomes"].get("completed", 0)

    def test_retry_hints_observed(self):
        """Load-typed rejects carry retry_after_s and the fleet's
        router.retry_after_s histogram sees them."""
        from dalle_pytorch_tpu.utils.metrics import histograms

        h0 = histograms.get("router.retry_after_s")
        n0 = h0.count if h0 is not None else 0
        rec = run_small_lane(seed=0, qps=250.0, n_requests=600)
        assert rec["shed_frac"] > 0.0
        h = histograms.get("router.retry_after_s")
        assert h is not None and h.count > n0


class TestRetryStorm:
    def _storm(self, seed=0):
        spec = small_spec()
        base = small_workload(n_requests=500)
        return ts.run_storm(
            spec, base, sat_qps=35.0, cost=ts.IterationCostModel(),
            seed=seed, kills=spec.n_replicas, respawn_fails=1,
        )

    def test_amplification_guard_and_desync(self):
        """run_storm's own asserts are the guard; pin the evidence it
        returns: lockstep first-rung delays without jitter, distinct
        with it, and jitter+hints completing at least as much."""
        storm = self._storm(seed=0)
        b = storm["baseline"]["ladder_first_rung_s"]
        g = storm["guarded"]["ladder_first_rung_s"]
        assert len(set(b)) == 1, b
        assert len(set(g)) == len(g) > 1, g
        assert all(d <= b[0] for d in g)   # full jitter only shortens
        assert (
            storm["guarded"]["completed"]
            >= storm["baseline"]["completed"]
        )

    def test_storm_rejects_are_load_typed(self):
        # a tiny queue and a long, fail-extended outage: the closed
        # loop MUST shed — and everything still lands typed
        spec = small_spec(queue_limit=8, respawn_base_delay=2.0)
        storm = ts.run_storm(
            spec, small_workload(n_requests=500), sat_qps=50.0,
            cost=ts.IterationCostModel(), seed=1,
            kills=spec.n_replicas, respawn_fails=1,
        )
        for tag in ("baseline", "guarded"):
            out = storm[tag]["outcomes"]
            assert sum(out.values()) == storm[tag]["logical_requests"]
        # the unjittered/no-hint baseline exhausts its retry budget
        # inside the outage and sheds load-typed; guarded clients wait
        # the hint out and lose no more than it did
        assert storm["baseline"]["outcomes"].get("rejected", 0) > 0
        assert (
            storm["guarded"]["outcomes"].get("rejected", 0)
            <= storm["baseline"]["outcomes"].get("rejected", 0)
        )


class TestCorrelatedOutageMTTR:
    def test_respawn_mttr_accounted(self):
        """A full-fleet correlated kill respawns every replica; the
        serve.recovery_s histogram deltas give a positive MTTR at
        least one base respawn delay long."""
        spec = small_spec(respawn_base_delay=0.5)
        storm = ts.run_storm(
            spec, small_workload(n_requests=400), sat_qps=35.0,
            cost=ts.IterationCostModel(), seed=3,
            kills=spec.n_replicas, respawn_fails=0,
        )
        # both runs kill the full fleet once: one respawn per replica each
        assert storm["respawns_observed"] == 2 * spec.n_replicas
        assert storm["mttr_mean_s"] is not None
        assert storm["mttr_mean_s"] >= 0.5 * spec.respawn_base_delay
        for tag in ("baseline", "guarded"):
            states = storm[tag]["replica_states"]
            assert all(s == "healthy" for s in states.values()), states


@pytest.mark.slow
class TestFidelity:
    def test_modeled_matches_real_tiny_fleet(self):
        """The cross-validation contract: a matched StubEngine fleet
        predicts the real tiny-model fleet's shed fraction, p99 TTFT
        and mean occupancy within FIDELITY_TOL (run_fidelity asserts
        in-run; we additionally pin completion-count agreement)."""
        rec = ts.run_fidelity(n_requests=200, seed=0)
        for key, tol in ts.FIDELITY_TOL.items():
            if key in rec["diffs"]:
                assert rec["diffs"][key] <= tol, (key, rec["diffs"])
        assert rec["real"]["completed"] > 0
        assert (
            abs(rec["modeled"]["completed"] - rec["real"]["completed"])
            <= 0.05 * rec["real"]["completed"] + 2
        )


# ----------------------------------------------------- release gates


def test_traffic_sim_quick_subprocess_gate():
    """The fast-tier gate: --quick must push >=100k modeled requests
    through a >=4-replica fleet inside its wall budget with every
    in-run assert (accounting, replay, frontier bounds, storm guard)
    green, and print a well-formed frontier record."""
    out = subprocess.run(
        [sys.executable, "tools/traffic_sim.py", "--quick", "--seed", "0"],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout)
    assert rec["totals"]["modeled_requests"] >= 100_000
    assert rec["fleet"]["n_replicas"] >= 4
    assert rec["totals"]["wall_s"] < 60.0
    assert rec["frontier"]["sustainable_qps"] is not None
    assert rec["storm"]["mttr_mean_s"] is not None
    for l in rec["frontier"]["levels"]:
        assert sum(l["outcomes"].values()) == l["logical_requests"]


@pytest.mark.slow
def test_traffic_sim_sweep_subprocess_gate():
    """The slow-tier grid: every arrival shape, prefix templates on."""
    out = subprocess.run(
        [sys.executable, "tools/traffic_sim.py", "--sweep", "--seed", "0"],
        capture_output=True, text=True, timeout=600, cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout)
    assert set(rec["arrival_grid"]) == {"diurnal", "burst"}
    hit = max(
        l["prefix_hit_frac"] for l in rec["frontier"]["levels"]
    )
    assert hit > 0.0        # template reuse engaged the prefix model
