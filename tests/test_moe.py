"""Mixture-of-experts + expert-parallelism tests (ops/moe.py).

The reference has no MoE; these pin the beyond-parity Switch layer: routing
semantics, capacity overflow, the load-balance aux, DALLE integration, and
ep-sharded-vs-single-device equivalence on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.ops.moe import MoEFeedForward
from dalle_pytorch_tpu.parallel import (
    create_train_state,
    make_runtime,
    make_train_step,
    params_shardings,
    shard_pytree,
)


class TestMoELayer:
    def make(self, e=4, cap=4.0):
        return MoEFeedForward(dim=16, num_experts=e, mult=2.0, capacity_factor=cap)

    def test_output_shape_and_aux(self):
        moe = self.make()
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 12, 16), jnp.float32)
        params = moe.init(jax.random.key(0), x)["params"]
        out, mut = moe.apply({"params": params}, x, mutable=["moe_aux"])
        assert out.shape == x.shape
        (aux,) = jax.tree_util.tree_leaves(mut["moe_aux"])
        # Switch aux is >= 1 (equals 1 at perfect balance)
        assert float(aux) >= 1.0 - 1e-5

    def test_matches_manual_expert_computation(self):
        """With generous capacity, every token's output must equal
        prob * expert_mlp(token) for its argmax expert."""
        moe = self.make(e=2, cap=8.0)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(1, 6, 16), jnp.float32)
        params = moe.init(jax.random.key(0), x)["params"]
        out = moe.apply({"params": params}, x)

        gate = np.asarray(params["gate"]["kernel"], np.float64)
        w_in = np.asarray(params["experts_in"], np.float64)
        w_out = np.asarray(params["experts_out"], np.float64)
        xs = np.asarray(x[0], np.float64)
        logits = xs @ gate
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        from math import erf

        for t in range(6):
            eidx = int(np.argmax(probs[t]))
            h = xs[t] @ w_in[eidx]
            h, g = np.split(h, 2)
            act = h * (g * 0.5 * (1 + np.vectorize(erf)(g / np.sqrt(2))))
            expected = probs[t, eidx] * (act @ w_out[eidx])
            np.testing.assert_allclose(
                np.asarray(out[0, t]), expected, atol=1e-4
            )

    def test_capacity_overflow_drops_to_zero(self):
        """With capacity 1 and all tokens routed to one expert, only the
        first token per example gets processed; the rest output exactly 0."""
        moe = MoEFeedForward(dim=8, num_experts=2, mult=2.0, capacity_factor=0.1)
        x = jnp.ones((1, 10, 8), jnp.float32)  # identical tokens, same expert
        params = moe.init(jax.random.key(0), x)["params"]
        out = np.asarray(moe.apply({"params": params}, x))
        assert np.abs(out[0, 0]).max() > 0
        np.testing.assert_array_equal(out[0, 1:], 0.0)


class TestDALLEMoE:
    def make(self, **kw):
        return DALLE(
            dim=32,
            depth=2,
            num_text_tokens=64,
            text_seq_len=8,
            num_image_tokens=32,
            image_fmap_size=4,
            heads=4,
            dim_head=8,
            attn_types=("full",),
            shift_tokens=False,
            ff_experts=4,
            **kw,
        )

    def batch(self, b=4):
        rng = np.random.RandomState(2)
        return (
            jnp.asarray(rng.randint(1, 64, size=(b, 8)), jnp.int32),
            jnp.asarray(rng.randint(0, 32, size=(b, 16)), jnp.int32),
        )

    def test_moe_layers_present_and_train(self):
        dalle = self.make()
        text, image = self.batch()
        params = dalle.init(jax.random.key(0), text, image)["params"]
        # every 2nd layer's ff is an MoE (moe_every=2 default)
        ff1 = params["transformer"]["ff_1"]["fn"]["fn"]
        assert "experts_in" in ff1 and "gate" in ff1
        # dense layers remain dense
        assert "Dense_0" in params["transformer"]["ff_0"]["fn"]["fn"]

        def loss(p):
            out, mut = dalle.apply(
                {"params": p}, text, image, return_loss=True,
                mutable=["moe_aux"],
            )
            return out + 1e-2 * sum(jax.tree_util.tree_leaves(mut["moe_aux"]))

        l, g = jax.jit(jax.value_and_grad(loss))(params)
        assert np.isfinite(float(l))
        gate_g = g["transformer"]["ff_1"]["fn"]["fn"]["gate"]["kernel"]
        assert np.abs(np.asarray(gate_g)).max() > 0  # aux reaches the gate

    def test_ep_sharded_matches_single_device(self):
        dalle = self.make()
        text, image = self.batch(b=8)
        params = dalle.init(jax.random.key(0), text, image)["params"]

        def loss(p):
            return dalle.apply({"params": p}, text, image, return_loss=True)

        l0, g0 = jax.jit(jax.value_and_grad(loss))(params)

        rt = make_runtime(dp=2, ep=4)
        sh = params_shardings(params, rt.mesh)
        p_sh = shard_pytree(params, sh)
        # expert leaves actually shard over ep
        exp = p_sh["transformer"]["ff_1"]["fn"]["fn"]["experts_in"]
        assert exp.addressable_shards[0].data.shape[0] == 1  # 4 experts / ep=4
        l1, g1 = jax.jit(
            jax.value_and_grad(loss), in_shardings=(sh,), out_shardings=(None, sh)
        )(p_sh)

        np.testing.assert_allclose(float(l0), float(l1), rtol=2e-5)
        for a, e in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), atol=1e-5, rtol=1e-3
            )

    def test_moe_train_step_reduces_loss(self):
        import optax

        rt = make_runtime(dp=2, ep=4)
        dalle = self.make()
        text, image = self.batch(b=8)
        batch = {"text": text, "image": image}
        params = dalle.init(jax.random.key(0), text, image)["params"]
        opt = optax.adam(1e-3)
        state, shardings = create_train_state(params, opt, rt)

        def loss_fn(p, b, rng):
            out, mut = dalle.apply(
                {"params": p}, b["text"], b["image"], return_loss=True,
                mutable=["moe_aux"],
            )
            return out + 1e-2 * sum(jax.tree_util.tree_leaves(mut["moe_aux"]))

        step = make_train_step(loss_fn, opt, rt, shardings)
        losses = []
        for i in range(3):
            state, loss = step(state, batch, jax.random.key(i))
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_decode_runs(self):
        """KV-decode with MoE layers: single-token routing must work."""
        from dalle_pytorch_tpu.models import generate_image_tokens

        dalle = self.make()
        text, image = self.batch(b=2)
        params = dalle.init(jax.random.key(0), text, image)["params"]
        toks = generate_image_tokens(dalle, params, text, jax.random.key(1))
        seq = np.asarray(toks)
        assert seq.shape == (2, 16)
        assert (seq >= 0).all() and (seq < 32).all()


class TestMoEMemoryModes:
    """MoE must compose with O(1)-activation-memory execution: the Switch
    aux loss rides the (delta, aux) channel of the pure-closure block fns
    (ops/reversible.py) instead of sow, so remat/reversible training sees
    the identical load-balance objective (VERDICT r3 ask #4)."""

    def make(self, **kw):
        return DALLE(
            dim=32, depth=2, num_text_tokens=30, text_seq_len=6,
            num_image_tokens=16, image_fmap_size=3, heads=2, dim_head=8,
            attn_types=("full",), shift_tokens=False,
            ff_experts=4, moe_every=2, **kw,
        )

    def batch(self):
        rng = np.random.RandomState(0)
        return (
            jnp.asarray(rng.randint(1, 30, (2, 6)), jnp.int32),
            jnp.asarray(rng.randint(0, 16, (2, 9)), jnp.int32),
        )

    def _run(self, model, params, text, image):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p}, text, image, return_loss=True,
                mutable=["moe_aux"],
            )
            aux = sum(jax.tree_util.tree_leaves(mut["moe_aux"]))
            return out + 1e-2 * aux, (out, aux)

        (_, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        return float(loss), float(aux), grads

    def test_remat_matches_sequential_exactly(self):
        text, image = self.batch()
        seq = self.make()
        params = seq.init(jax.random.key(0), text, image)["params"]
        l0, a0, g0 = self._run(seq, params, text, image)
        l1, a1, g1 = self._run(self.make(remat=True), params, text, image)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)
        np.testing.assert_allclose(a0, a1, rtol=1e-5)
        for a, e in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e), atol=1e-5, rtol=1e-3)

    def test_reversible_trains_and_aux_reaches_gate(self):
        text, image = self.batch()
        rev = self.make(reversible=True)
        params = rev.init(jax.random.key(0), text, image)["params"]
        loss, aux, grads = self._run(rev, params, text, image)
        assert np.isfinite(loss) and aux >= 1.0 - 1e-5
        flat = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in flat)
        gate_g = grads["transformer"]["ff_1"]["fn"]["fn"]["gate"]["kernel"]
        assert np.abs(np.asarray(gate_g)).max() > 0

    def test_reversible_custom_vjp_forward_matches_direct_wiring(self):
        """The custom-VJP primal (training path) must produce the same loss
        and aux as the bound direct wiring (init path) on identical params."""
        text, image = self.batch()
        rev = self.make(reversible=True)
        out, vars0 = jax.jit(
            lambda k: rev.init_with_output(k, text, image, return_loss=True),
        )(jax.random.key(0))
        params = vars0["params"]
        aux0 = sum(jax.tree_util.tree_leaves(vars0["moe_aux"]))
        loss1, mut = rev.apply(
            {"params": params}, text, image, return_loss=True, mutable=["moe_aux"]
        )
        aux1 = sum(jax.tree_util.tree_leaves(mut["moe_aux"]))
        np.testing.assert_allclose(float(out), float(loss1), rtol=1e-5)
        np.testing.assert_allclose(float(aux0), float(aux1), rtol=1e-5)
