"""Replicated-front-door tests — every fleet robustness behavior of the
router (serving/router.py) pinned deterministically on CPU: the replica
health state machine (breaker open/backoff/readmit, stall heartbeat,
invariant-violation quarantine), BIT-IDENTICAL cross-replica failover,
shared-clock deadline semantics across a failover, graceful drain,
fleet-watermark degradation, global typed admission, and the combined
chaos scenario where 100% of submitted requests must end in exactly one
typed outcome. Plus the labeled-metrics substrate the per-replica series
stand on (utils/metrics.py child registries).

Same tiny model + page-size-2 override as tests/test_serving.py so decode
genuinely crosses page boundaries mid-flight.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import DALLE
from dalle_pytorch_tpu.serving import (
    Engine,
    EngineConfig,
    FakeClock,
    Outcome,
    RejectReason,
    ReplicaState,
    Request,
    Router,
    RouterConfig,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, gauges, histograms
from dalle_pytorch_tpu.utils.resilience import RetryPolicy


@pytest.fixture(scope="module")
def model():
    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), shift_tokens=True, rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    image = jnp.asarray(rng.randint(0, 12, size=(2, 4)), jnp.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


@pytest.fixture(autouse=True)
def tiny_pages(monkeypatch):
    monkeypatch.setenv("DALLE_TPU_KV_PAGE_SIZE", "2")
    yield


def prompt(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.randint(1, 16, size=(4,)).astype(np.int32)


def req(i, max_new=4, **kw):
    kw.setdefault("seed", i)
    return Request(
        request_id=f"r{i}", prompt=prompt(i), max_new_tokens=max_new, **kw
    )


def make_router(model, n=2, clock=None, router_kw=None, **eng_kw):
    dalle, params = model
    eng_kw.setdefault("max_batch", 2)
    return Router(
        dalle, params,
        RouterConfig(n_replicas=n, **(router_kw or {})),
        EngineConfig(**eng_kw),
        clock=clock or FakeClock(step_dt=0.1),
    )


def accounting_holds(router):
    router.verify_invariants()
    outcomes = router.stats()["outcomes"]
    assert sum(outcomes.values()) == router.stats()["submitted"]
    return outcomes


# --------------------------------------------------- labeled metrics (pure)


class TestLabeledMetrics:
    def test_counter_label_variants_and_total(self):
        counters.inc("x.n")
        counters.inc("x.n", 2, labels={"replica": "0"})
        counters.inc("x.n", 3, labels={"replica": "1"})
        assert counters.get("x.n") == 1
        assert counters.get("x.n", labels={"replica": "0"}) == 2
        assert counters.total("x.n") == 6
        snap = counters.snapshot("x.")
        assert snap == {
            "x.n": 1, 'x.n{replica="0"}': 2, 'x.n{replica="1"}': 3,
        }

    def test_child_registries_bind_and_compose(self):
        c0 = counters.child({"replica": 0})
        c0.inc("y.n")
        c0.child({"shard": 1}).inc("y.n")
        assert counters.get("y.n", labels={"replica": "0"}) == 1
        assert counters.get("y.n", labels={"replica": "0", "shard": "1"}) == 1
        assert counters.child(None) is counters  # unlabeled path is free
        g = gauges.child({"replica": 2})
        g.set("y.g", 0.5)
        assert gauges.get("y.g", labels={"replica": 2}) == 0.5
        h = histograms.child({"replica": 2})
        h.observe("y.h", 1.0)
        assert histograms.get("y.h", labels={"replica": "2"}).count == 1
        assert histograms.get("y.h") is None  # labeled != unlabeled series

    def test_prometheus_dump_renders_labels(self):
        from dalle_pytorch_tpu.utils.telemetry import TELEMETRY

        counters.inc("z.n", 4, labels={"replica": "1"})
        gauges.set("z.g", 2.0, labels={"replica": "1"})
        histograms.observe("z.h", 0.25, labels={"replica": "1"})
        dump = TELEMETRY.dump()
        assert 'z_n{replica="1"} 4' in dump
        assert 'z_g{replica="1"} 2' in dump
        assert 'z_h_count{replica="1"} 1' in dump
        # label'd bucket lines merge the le label with the series labels
        assert 'z_h_bucket{replica="1",le=' in dump
        # exposition still parses line-for-line (name{...} value)
        for line in dump.splitlines():
            if not line or line.startswith("#"):
                continue
            name, _, value = line.rpartition(" ")
            float(value)
            assert name


# ------------------------------------------------------- health machine


class TestHealthMachine:
    def test_breaker_opens_backs_off_and_readmits(self, model):
        """k consecutive prefill failures open the breaker (DEGRADED, no
        new admissions); the RetryPolicy backoff readmits it, after which
        queued work flows again."""
        clock = FakeClock(step_dt=1.0)
        router = make_router(
            model, n=1, clock=clock,
            router_kw=dict(
                breaker_threshold=2,
                breaker_backoff=RetryPolicy(
                    attempts=5, base_delay=4.0, max_delay=60.0,
                    jitter=0.0, retry_on=(),
                ),
            ),
            prefill_attempts=10,
        )
        FAULTS.arm("prefill_fail", 3)
        assert router.submit(req(0)) is None
        assert router.submit(req(1)) is None
        router.run(max_steps=300)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 2
        assert FAULTS.fired.get("prefill_fail") == 3
        assert counters.get("router.breaker_opens") == 1
        assert counters.get("router.readmits") == 1
        # the replica ended back in service
        assert router.replica_states()[0] == "healthy"

    def test_second_router_does_not_inherit_breaker_deltas(self, model):
        """Health baselines snapshot the process-global labeled counters
        at replica construction: a second Router in the same process (the
        smoke/bench clean-then-chaos shape) must not read the first
        fleet's accumulated prefill retries as a spurious first-check
        delta and pop its breaker with zero failures of its own."""
        router_kw = dict(
            breaker_threshold=2,
            breaker_backoff=RetryPolicy(
                attempts=5, base_delay=2.0, max_delay=60.0,
                jitter=0.0, retry_on=(),
            ),
        )
        FAULTS.arm("prefill_fail", 3)
        first = make_router(
            model, n=1, clock=FakeClock(step_dt=1.0),
            router_kw=router_kw, prefill_attempts=10,
        )
        assert first.submit(req(0)) is None
        first.run(max_steps=300)
        assert first.results["r0"].outcome is Outcome.COMPLETED
        opens = counters.get("router.breaker_opens")
        assert opens >= 1  # the first fleet's breaker genuinely tripped
        second = make_router(
            model, n=1, clock=FakeClock(step_dt=1.0),
            router_kw=router_kw, prefill_attempts=10,
        )
        assert second.submit(req(1)) is None
        second.run(max_steps=300)
        assert second.results["r1"].outcome is Outcome.COMPLETED
        assert counters.get("router.breaker_opens") == opens  # no new trip
        assert second.replica_states()[0] == "healthy"

    def test_health_flap_backoff_prevents_admission_livelock(self, model):
        """Repeated spurious health flaps DEGRADE replicas over and over;
        exponential backoff makes each flap progressively quieter instead
        of bouncing admissions forever — everything still completes in
        bounded steps."""
        router = make_router(
            model, n=2, clock=FakeClock(step_dt=1.0),
            router_kw=dict(breaker_backoff=RetryPolicy(
                attempts=10, base_delay=1.0, max_delay=8.0,
                jitter=0.0, retry_on=(),
            )),
        )
        FAULTS.arm("health_flap", 4)
        for i in range(3):
            assert router.submit(req(i)) is None
        router.run(max_steps=500)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 3
        assert FAULTS.fired.get("health_flap") == 4
        assert counters.get("router.breaker_opens") == 4

    def test_stall_heartbeat_declares_dead_and_fails_over(self, model):
        """A replica that stops making step progress while holding work is
        declared DEAD by the heartbeat; its request completes on a
        sibling."""
        clock = FakeClock(step_dt=1.0)
        router = make_router(
            model, n=2, clock=clock,
            router_kw=dict(stall_timeout_s=2.5),
        )
        assert router.submit(req(0)) is None
        # let it land in flight, then stall the busy replica repeatedly
        for _ in range(2):
            router.step()
        holder = next(r for r in router._replicas if r.inflight)
        FAULTS.arm("replica_stall", 5)
        router.run(max_steps=300)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 1
        assert holder.state is ReplicaState.DEAD
        assert holder.death_reason == "stall_timeout"
        # the fleet survived: the sibling is still serving
        assert any(
            r.state is not ReplicaState.DEAD for r in router._replicas
        )

    def test_invariant_violation_quarantines_replica(self, model):
        """The health machine probes Engine.verify_invariants every
        iteration: a corrupt engine (accounting no longer sums) is
        declared DEAD immediately and its work fails over."""
        router = make_router(model, n=2)
        assert router.submit(req(0)) is None
        for _ in range(2):
            router.step()
        holder = next(r for r in router._replicas if r.inflight)
        holder.engine._submitted += 1  # corrupt: a request got "lost"
        router.run(max_steps=300)
        assert holder.state is ReplicaState.DEAD
        assert holder.death_reason == "invariant_violation"
        res = router.results["r0"]
        assert res.outcome is Outcome.COMPLETED
        assert "failovers=1" in res.detail


# ------------------------------------------------------------- failover


class TestFailover:
    def run_clean(self, model, n_req=2, max_new=4):
        router = make_router(model, n=2)
        for i in range(n_req):
            assert router.submit(req(i, max_new=max_new)) is None
        router.run(max_steps=500)
        return {
            rid: np.asarray(r.tokens) for rid, r in router.results.items()
        }

    def test_cross_replica_replay_bit_identical(self, model):
        """THE acceptance criterion: a request prefilled and PARTIALLY
        DECODED on replica A, requeued when A dies, completes on replica
        B with tokens bit-identical to an uninterrupted run — the
        (seed, position) replay contract across replica boundaries."""
        clean = self.run_clean(model)
        router = make_router(model, n=2)
        for i in range(2):
            assert router.submit(req(i)) is None
        # step until some request has visibly decoded a partial prefix
        for _ in range(200):
            router.step()
            partial = [
                s for r in router._replicas for s in r.engine.slots
                if s and len(s.entry.generated) >= 2
            ]
            if partial:
                break
        assert partial, "no request reached partial decode"
        FAULTS.arm("replica_crash", 1)
        router.run(max_steps=500)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 2
        assert counters.get("router.replica_deaths") == 1
        assert counters.get("router.failovers") >= 1
        failed_over = [
            r for r in router.results.values() if "failovers=1" in r.detail
        ]
        assert failed_over, "no request actually failed over"
        for rid, r in router.results.items():
            np.testing.assert_array_equal(
                np.asarray(r.tokens), clean[rid],
                err_msg=f"{rid} tokens diverged across replica failover",
            )
        # failover latency was measured
        fh = histograms.get("router.failover_latency_s")
        assert fh is not None and fh.count >= 1

    def test_deadline_expires_during_failover_shared_clock(self, model):
        """Deadlines are absolute instants on the ONE clock shared by all
        replicas: a request decoding on replica B when B dies keeps the
        same deadline while requeued, and expires typed if no sibling can
        take it in time."""
        clock = FakeClock(step_dt=1.0)
        router = make_router(
            model, n=2, clock=clock,
            router_kw=dict(breaker_backoff=RetryPolicy(
                attempts=3, base_delay=100.0, max_delay=100.0,
                jitter=0.0, retry_on=(),
            )),
        )
        # degrade replica 0 for a long time: the fleet's only admitting
        # replica is #1
        FAULTS.arm("health_flap", 1)
        router.step()
        assert router.replica_states()[0] == "degraded"
        deadline = clock.now() + 8.0
        assert router.submit(Request(
            request_id="victim", prompt=prompt(0), max_new_tokens=4,
            seed=0, deadline=deadline,
        )) is None
        # let it prefill + decode a bit on replica 1
        for _ in range(3):
            router.step()
        holder = router._replicas[1]
        assert "victim" in holder.inflight
        router.kill(holder.id, "crash")
        # no healthy replica: the requeued request waits at the router
        # while the shared clock keeps advancing past its deadline
        router.run(max_steps=300)
        res = router.results["victim"]
        assert res.outcome is Outcome.DEADLINE_EXCEEDED
        assert "router queue" in res.detail
        accounting_holds(router)

    def test_failover_cap_is_typed(self, model):
        router = make_router(model, n=2, router_kw=dict(max_failovers=0))
        assert router.submit(req(0)) is None
        for _ in range(2):
            router.step()
        assert any(r.inflight for r in router._replicas)
        FAULTS.arm("replica_crash", 1)
        router.run(max_steps=300)
        res = router.results["r0"]
        assert res.outcome is Outcome.PREEMPT_CAP
        assert "max_failovers" in res.detail
        accounting_holds(router)

    def test_fleet_death_flushes_typed_no_replica(self, model):
        router = make_router(model, n=1, max_batch=1)
        for i in range(2):
            assert router.submit(req(i)) is None
        for _ in range(2):
            router.step()
        router.kill(0, "crash")
        router.run(max_steps=50)
        outcomes = accounting_holds(router)
        assert outcomes["rejected"] == 2
        for r in router.results.values():
            assert r.reject_reason is RejectReason.NO_REPLICA
        # and new submissions reject immediately, typed
        res = router.submit(req(5))
        assert res is not None
        assert res.reject_reason is RejectReason.NO_REPLICA
        accounting_holds(router)


# ---------------------------------------------------------------- drain


class TestDrain:
    def test_graceful_drain_finishes_inflight_routes_rest(self, model):
        router = make_router(model, n=2, max_batch=1)
        for i in range(3):
            assert router.submit(req(i)) is None
        for _ in range(2):
            router.step()  # one request in flight per replica, one queued
        drained = next(r for r in router._replicas if r.inflight)
        inflight_rid = next(iter(drained.inflight))
        admitted_before = drained.engine._submitted
        router.drain(drained.id)
        assert drained.state is ReplicaState.DRAINING
        router.run(max_steps=500)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 3
        # the in-flight request FINISHED on the draining replica (it was
        # not requeued: zero failovers)
        assert "failovers" not in router.results[inflight_rid].detail
        # no new admissions after the drain call, and the replica retired
        assert drained.engine._submitted == admitted_before
        assert drained.state is ReplicaState.DEAD
        assert drained.death_reason == "drained"
        assert counters.get("router.drained") == 1


# ----------------------------------------------- global admission & shed


class TestGlobalAdmission:
    def test_router_queue_full_typed(self, model):
        router = make_router(model, n=1, router_kw=dict(queue_limit=1))
        assert router.submit(req(0)) is None
        res = router.submit(req(1))
        assert res is not None
        assert res.reject_reason is RejectReason.QUEUE_FULL
        assert counters.get("router.shed") == 1
        router.run(max_steps=300)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 1 and outcomes["rejected"] == 1

    def test_demand_exceeds_every_pool_typed(self, model):
        router = make_router(model, n=2, page_budget=2)
        res = router.submit(req(0))
        assert res is not None
        assert res.reject_reason is RejectReason.DEMAND_EXCEEDS_POOL
        accounting_holds(router)

    def test_duplicate_and_bounds_raise(self, model):
        router = make_router(model, n=1)
        assert router.submit(req(0)) is None
        with pytest.raises(ValueError, match="duplicate"):
            router.submit(req(0))
        with pytest.raises(ValueError, match="max_new_tokens"):
            router.submit(req(1, max_new=99))
        router.run(max_steps=300)
        accounting_holds(router)

    def test_watermark_degradation_spans_fleet(self, model):
        """The clamp responds to AGGREGATE occupancy: replica 1 is
        completely empty when r1 lands on it, yet r1 is clamped because
        replica 0's resident pages push the FLEET over the watermark —
        per-engine occupancy alone would never clamp here."""
        router = make_router(
            model, n=2, max_batch=1,
            high_watermark=0.25, degraded_max_new_tokens=2,
        )
        assert router.submit(req(0, max_new=4)) is None
        # step 1 dispatches r0 into an engine; step 2 runs that engine's
        # admission, making its prompt pages resident
        for _ in range(2):
            router.step()
        assert router.fleet_occupancy() > 0.25
        empty = [r for r in router._replicas if not r.inflight]
        assert empty and empty[0].engine.pool.occupancy == 0.0
        assert router.submit(req(1, max_new=4)) is None
        router.run(max_steps=500)
        outcomes = accounting_holds(router)
        assert outcomes["completed"] == 2
        r0, r1 = router.results["r0"], router.results["r1"]
        assert r0.clamped_max_new_tokens is None and len(r0.tokens) == 4
        assert r1.clamped_max_new_tokens == 2 and len(r1.tokens) == 2

    def test_combined_chaos_all_typed(self, model):
        """The fleet acceptance scenario: a replica crash + a health flap
        + injected prefill and page faults + deadlines + a cancel, all in
        one run — no hang, and every submitted request ends in exactly
        one typed outcome."""
        FAULTS.configure(
            "replica_crash=1,health_flap=1,prefill_fail=1,page_exhaust=1"
        )
        clock = FakeClock(step_dt=0.5)
        router = make_router(
            model, n=3, clock=clock, max_batch=2, page_budget=7,
            router_kw=dict(queue_limit=6),
        )
        immediate = []
        for i in range(8):
            r = router.submit(req(
                i, max_new=4,
                deadline=None if i % 2 else 60.0,
                priority=i % 3,
            ))
            if r is not None:
                immediate.append(r)
        router.step()
        router.cancel("r3")
        router.run(max_steps=1000)
        outcomes = accounting_holds(router)
        assert sum(outcomes.values()) == 8
        assert outcomes["rejected"] == len(immediate)
        assert outcomes["cancelled"] >= 1
        assert counters.get("router.replica_deaths") == 1
        assert FAULTS.fired.get("replica_crash") == 1
        # live replicas drained their pools; every engine's accounting holds
        for rep in router._replicas:
            if rep.state is not ReplicaState.DEAD:
                rep.engine.verify_invariants(idle=True)


# ------------------------------------------------- engine invariant surface


class TestEngineInvariants:
    def test_verify_invariants_mid_flight_and_idle(self, model):
        dalle, params = model
        eng = Engine(dalle, params, EngineConfig(max_batch=2),
                     clock=FakeClock(step_dt=0.1))
        assert eng.submit(req(0)) is None
        eng.step()
        eng.verify_invariants()          # valid mid-flight
        with pytest.raises(AssertionError, match="not idle"):
            eng.verify_invariants(idle=True)
        eng.run(max_steps=200)
        eng.verify_invariants(idle=True)

    def test_verify_invariants_detects_corruption(self, model):
        dalle, params = model
        eng = Engine(dalle, params, EngineConfig(max_batch=2),
                     clock=FakeClock(step_dt=0.1))
        assert eng.submit(req(0)) is None
        eng.run(max_steps=200)
        eng._submitted += 1  # a request vanished without a result
        with pytest.raises(AssertionError, match="submitted"):
            eng.verify_invariants()


# ----------------------------------------------------- release gates


@pytest.mark.slow
def test_serve_smoke_replicas_tool():
    """The --replicas 2 chaos drill must pass clean AND compose with an
    env-armed prefill fault."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for extra_env in ({}, {"DALLE_TPU_FAULTS": "prefill_fail=1"}):
        env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
        out = subprocess.run(
            [sys.executable, "tools/serve_smoke.py", "--replicas", "2"],
            capture_output=True, text=True, timeout=600, env=env, cwd=repo,
        )
        assert out.returncode == 0, (extra_env, out.stderr[-2000:])
        assert "replica crash drill bit-identically" in out.stderr


@pytest.mark.slow
def test_bench_serve_replicas_record():
    """bench.py --serve --replicas 3 must emit the chaos-gate record (the
    in-bench asserts — typed outcomes, bit-parity, one death — already
    ran if the record prints)."""
    import json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--serve", "--replicas", "3"],
        capture_output=True, text=True, timeout=1200, env=env, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    rep = [r for r in recs if r["metric"].startswith("serve_replicas")]
    assert len(rep) == 1
    r = rep[0]
    assert r["n_replicas"] == 3
    assert r["bit_identical_vs_clean"] is True
    assert r["chaos_requests_failed_over"] >= 1
    assert sum(r["chaos_outcomes"].values()) == r["n_requests"] + 3
    assert list(r["chaos_replica_states"].values()).count("dead") == 1
    assert r["failover_latency_p50_ms"] is not None
