"""End-to-end "rainbow shapes" demo — the scripted analog of the reference's
``examples/rainbow_dalle.ipynb`` (41 cells: synthetic dataset -> train
DiscreteVAE -> train DALLE -> sample, incl. a generalization check on
held-out captions).

Builds a tiny synthetic dataset of colored shapes with captions, trains the
image tokenizer (DiscreteVAE) and then a small DALL-E on it through the real
CLIs, and finally samples images for both seen and HELD-OUT captions (color x
shape combos never shown during training — the notebook's generalization
eval).

Run from the repo root (CPU works; a TPU chip just makes it faster):

    python examples/rainbow.py --workdir ./rainbow_demo

Expect a few minutes on CPU. Pass --epochs_vae / --epochs_dalle to train
longer (sharper samples), or --image_size 64 for bigger shapes.
"""

import argparse
import subprocess
import sys
from pathlib import Path

import numpy as np
from PIL import Image

REPO = Path(__file__).resolve().parent.parent

COLORS = {
    "red": (220, 40, 40),
    "green": (40, 200, 60),
    "blue": (50, 70, 230),
    "yellow": (230, 220, 50),
    "purple": (160, 60, 200),
    "orange": (240, 140, 40),
}
SHAPES = ("square", "circle", "triangle")
# combos excluded from training data and sampled at the end — the
# generalization eval from the reference notebook's final cells
HELD_OUT = {("purple", "square"), ("orange", "circle"), ("red", "triangle")}


def draw(color: str, shape: str, size: int) -> np.ndarray:
    arr = np.zeros((size, size, 3), np.uint8)
    c = np.array(COLORS[color], np.uint8)
    half = size // 2
    yy, xx = np.mgrid[:size, :size]
    r = int(size * 0.28)
    if shape == "square":
        m = (abs(yy - half) < r) & (abs(xx - half) < r)
    elif shape == "circle":
        m = (yy - half) ** 2 + (xx - half) ** 2 < r * r
    else:  # triangle
        m = (yy > half - r) & (yy < half + r) & (abs(xx - half) * 2 < (yy - (half - r)))
    arr[m] = c
    return arr


def build_dataset(root: Path, size: int, copies: int) -> int:
    root.mkdir(parents=True, exist_ok=True)
    i = 0
    for _ in range(copies):
        for color in COLORS:
            for shape in SHAPES:
                if (color, shape) in HELD_OUT:
                    continue
                stem = root / f"sample_{i:04d}"
                Image.fromarray(draw(color, shape, size)).save(stem.with_suffix(".png"))
                stem.with_suffix(".txt").write_text(f"a {color} {shape}")
                i += 1
    return i


def run(argv: list[str]) -> None:
    print("+", " ".join(argv), flush=True)
    subprocess.run([sys.executable] + argv, check=True, cwd=REPO)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", default="./rainbow_demo")
    p.add_argument("--image_size", type=int, default=32)
    p.add_argument("--copies", type=int, default=4,
                   help="dataset copies of each (color, shape) combo")
    p.add_argument("--epochs_vae", type=int, default=25)
    p.add_argument("--epochs_dalle", type=int, default=20)
    p.add_argument("--num_images", type=int, default=2,
                   help="samples per caption at the end")
    args = p.parse_args()

    work = Path(args.workdir).resolve()
    data = work / "data"
    n = build_dataset(data, args.image_size, args.copies)
    print(f"dataset: {n} image/caption pairs at {data}")

    vae_ckpt = work / "vae.ckpt"
    run([
        "train_vae.py",
        "--image_folder", str(data),
        "--image_size", str(args.image_size),
        "--num_layers", "2",
        "--num_tokens", "256",
        "--emb_dim", "64",
        "--hidden_dim", "32",
        "--num_resnet_blocks", "1",
        "--batch_size", "8",
        "--epochs", str(args.epochs_vae),
        "--learning_rate", "3e-3",
        "--output_file_name", str(vae_ckpt),
        "--samples_dir", str(work / "vae_samples"),
    ])

    dalle_ckpt = work / "dalle"
    run([
        "train_dalle.py",
        "--image_text_folder", str(data),
        "--vae_path", str(vae_ckpt),
        "--dim", "128",
        "--depth", "4",
        "--heads", "4",
        "--dim_head", "32",
        "--text_seq_len", "16",
        "--attn_types", "full,axial_row",
        "--batch_size", "8",
        "--epochs", str(args.epochs_dalle),
        "--learning_rate", "2e-3",
        "--truncate_captions",
        "--dalle_output_file_name", str(dalle_ckpt),
    ])

    seen = [("green", "square"), ("blue", "circle")]
    prompts = "|".join(
        f"a {c} {s}" for c, s in seen + sorted(HELD_OUT)
    )
    run([
        "generate.py",
        "--dalle_path", f"{dalle_ckpt}.ckpt",
        "--text", prompts,
        "--num_images", str(args.num_images),
        "--batch_size", str(args.num_images),
        "--outputs_dir", str(work / "outputs"),
    ])
    print(
        f"\ndone — samples in {work / 'outputs'} "
        f"(first two prompts were in training; the rest are held-out "
        f"color/shape combos the model never saw)"
    )


if __name__ == "__main__":
    main()
