#!/usr/bin/env python
"""Serving-engine release gate: continuous-batching passes on CPU.

Builds a tiny DALLE in-process (no checkpoint needed) and drives the full
engine lifecycle twice — once with CHUNKED prefill (budget-bounded
prompt chunks interleaved with decode; the production serving shape) and
once monolithic — verifying the accounting invariant each time: every
request ends in a typed outcome, all pages return to the pool, and the
two modes produce BIT-identical tokens. A third, deterministic drill
(FakeClock) lands a deadline MID-PREFILL and asserts the pages come back
that iteration. Exit 0 iff all requests of both passes COMPLETE and the
drill terminates typed — the gate a release pipeline runs before
shipping a serving build::

    python tools/serve_smoke.py

Composes with the fault registry for pipeline fault drills. The chunked
pass runs FIRST, so an armed ``prefill_fail`` fires at CHUNK granularity
and the retry must resume from the last completed chunk::

    DALLE_TPU_FAULTS="prefill_fail=1" python tools/serve_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_tiny_model():
    """The gate's model: tiny, rotary, shift-tokens — built in-process so
    the gate needs no checkpoint. Shared with tools/telemetry_smoke.py."""
    import jax
    import numpy as np

    from dalle_pytorch_tpu.models import DALLE

    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = rng.randint(1, 16, size=(1, 4)).astype(np.int32)
    image = rng.randint(0, 12, size=(1, 4)).astype(np.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


def main() -> int:
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, Request, check_accounting,
    )

    dalle, params = build_tiny_model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 16, size=(4,)).astype(np.int32) for _ in range(3)]

    def run_pass(label: str, **cfg_kw) -> dict:
        engine = Engine(dalle, params, EngineConfig(max_batch=2, **cfg_kw))
        for i in range(3):
            rejected = engine.submit(Request(
                request_id=f"smoke{i}",
                prompt=prompts[i],
                max_new_tokens=dalle.image_seq_len,
                seed=i,
            ))
            assert rejected is None, rejected
        results = engine.run(max_steps=1000)
        check_accounting(engine)
        for rid in sorted(results):
            print(json.dumps({"pass": label, **results[rid].to_json()}))
        print(json.dumps({"pass": label, "stats": engine.stats()}))
        return results

    # chunked first: an env-armed prefill_fail fires at CHUNK granularity
    # and must be absorbed by the resume-from-last-chunk retry
    chunked = run_pass("chunked", prefill_chunk=2)
    mono = run_pass("monolithic")

    ok = True
    for rid in sorted(mono):
        ok = ok and mono[rid].outcome is Outcome.COMPLETED
        ok = ok and chunked[rid].outcome is Outcome.COMPLETED
        if not np.array_equal(
            np.asarray(mono[rid].tokens), np.asarray(chunked[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} chunked tokens diverge from "
                  "monolithic", file=sys.stderr)

    # mid-prefill deadline drill: token_budget=1 throttles prefill to one
    # chunk per iteration (the forward-progress floor), the FakeClock makes
    # "expires mid-prefill" an exact step count, and the pages must be back
    # the iteration the deadline sweeps — never held to the end of the
    # prompt the way a monolithic prefill would
    drill = Engine(
        dalle, params,
        EngineConfig(max_batch=2, prefill_chunk=2, token_budget=1),
        clock=FakeClock(step_dt=1.0),
    )
    assert drill.submit(Request(
        request_id="drill", prompt=prompts[0],
        max_new_tokens=dalle.image_seq_len, seed=0, deadline=0.5,
    )) is None
    drill.run(max_steps=100)
    check_accounting(drill)
    res = drill.results["drill"]
    print(json.dumps({"pass": "mid_prefill_deadline", **res.to_json()}))
    if res.outcome is not Outcome.DEADLINE_EXCEEDED or res.tokens is not None:
        ok = False
        print("serve smoke FAILED: mid-prefill deadline drill did not "
              f"terminate typed mid-prefill ({res.outcome.value})",
              file=sys.stderr)
    if drill.pool.used != 0:
        ok = False
        print("serve smoke FAILED: mid-prefill termination leaked "
              f"{drill.pool.used} pages", file=sys.stderr)

    if not ok:
        print("serve smoke FAILED: not every request completed", file=sys.stderr)
        return 1
    print("serve smoke OK: 3/3 completed chunked AND monolithic "
          "(bit-identical), mid-prefill deadline drill typed, pool drained",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
