#!/usr/bin/env python
"""Serving-engine release gate: a 3-request continuous-batching pass on CPU.

Builds a tiny DALLE in-process (no checkpoint needed), submits three
requests through the full engine lifecycle (admit -> prefill -> slot
insert -> vector-position decode -> complete), and verifies the accounting
invariant: every request ends in a typed outcome, all pages return to the
pool. Exit 0 iff all three COMPLETE — the gate a release pipeline runs
before shipping a serving build::

    python tools/serve_smoke.py

Composes with the fault registry for pipeline fault drills (the injected
fault must be absorbed, e.g. a transient prefill failure retried)::

    DALLE_TPU_FAULTS="prefill_fail=1" python tools/serve_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import jax
    import numpy as np

    from dalle_pytorch_tpu.models import DALLE
    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, Outcome, Request, check_accounting,
    )

    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = rng.randint(1, 16, size=(1, 4)).astype(np.int32)
    image = rng.randint(0, 12, size=(1, 4)).astype(np.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]

    engine = Engine(dalle, params, EngineConfig(max_batch=2))
    for i in range(3):
        rejected = engine.submit(Request(
            request_id=f"smoke{i}",
            prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
            max_new_tokens=dalle.image_seq_len,
            seed=i,
        ))
        assert rejected is None, rejected
    results = engine.run(max_steps=1000)
    check_accounting(engine)

    ok = True
    for rid in sorted(results):
        r = results[rid]
        print(json.dumps(r.to_json()))
        ok = ok and r.outcome is Outcome.COMPLETED
    print(json.dumps({"stats": engine.stats()}))
    if not ok:
        print("serve smoke FAILED: not every request completed", file=sys.stderr)
        return 1
    print("serve smoke OK: 3/3 completed, pool drained", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
