#!/usr/bin/env python
"""Serving-engine release gate: continuous-batching passes on CPU.

Builds a tiny DALLE in-process (no checkpoint needed) and drives the full
engine lifecycle seven times — CHUNKED prefill (budget-bounded prompt
chunks interleaved with decode; the production serving shape),
monolithic, FUSED (the whole iteration as one ragged ``_iteration_jit``
dispatch; ROADMAP 1), SPECULATIVE (ROADMAP 2: each decode row
self-drafts and the single ragged dispatch verifies — exact acceptance
makes the stream bit-identical to plain decode by construction),
QUANTIZED-KV split and fused (ISSUE 14: int8 paged pools + per-(token,
head) scale pools, dequantized at read — the two quantized passes must
match each other BITWISE, and match the unquantized passes to the
pinned token-agreement floor, never bitwise), and a
PREFIX-CACHE cold/warm replay (ROADMAP 3: the same 3-request scenario
twice through one engine with the content-addressed page index on; the
warm round must hit and match the cold round bitwise) — verifying the
accounting invariant each time:
every request ends in a typed outcome, all pages return to the pool
(the prefix pass additionally checks refcount accounting — references
== mapped table entries, no leaks after drain), and all modes produce
BIT-identical tokens.
A further deterministic drill (FakeClock) lands a deadline MID-PREFILL
and asserts the pages come back that iteration. Exit 0 iff all requests
of all three passes COMPLETE and the drill terminates typed — the gate
a release pipeline runs before shipping a serving build::

    python tools/serve_smoke.py

A post-decode STAGE drill (docs/DESIGN.md §8.5) additionally drives the
tokens -> VAE decode -> CLIP rerank pipeline: clean completions with
images bit-identical to a direct VAE decode, transient stage faults
(``vae_decode_fail``/``rerank_fail``/``stage_timeout``) absorbed by
retry with unchanged bits, and retry exhaustion completing
typed-degraded (``completed_tokens_only`` / ``completed_unranked``) —
never stalled.

Composes with the fault registry for pipeline fault drills. The chunked
pass runs FIRST, so an armed ``prefill_fail`` fires at CHUNK granularity
and the retry must resume from the last completed chunk; an armed
``prefix_hash_collide`` forges a warm-round probe (token verification
must degrade it to cold prefill, tokens still bit-identical) and
``prefix_publish_fail`` drops a cold-round publish (fail-open — later
rounds republish)::

    DALLE_TPU_FAULTS="prefill_fail=1" python tools/serve_smoke.py
    DALLE_TPU_FAULTS="prefix_hash_collide=1" python tools/serve_smoke.py

``--replicas N`` additionally drives the replicated front door
(serving/router.py) through a chaos drill: N replicas serve 2N chunked
requests, ``replica_crash`` is armed MID-RUN to kill the busiest
replica, and the gate requires every request to COMPLETE with tokens
bit-identical to a no-crash router pass — the cross-replica failover
contract. Env-armed faults compose with the drill the same way::

    DALLE_TPU_FAULTS="prefill_fail=1" python tools/serve_smoke.py --replicas 2

Accounting everywhere is asserted through the PUBLIC
``Engine.verify_invariants`` / ``Router.verify_invariants`` — the gate
checks the same invariant surface the router's health machine probes in
production, not a private test helper.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def lint_preflight(label: str = "serve smoke") -> int:
    """Static-analysis + trend pre-flight (docs/DESIGN.md §11), all four
    stages in escalation order: first the AST stage alone (``lint.py
    --check`` — stdlib-only, so a corrupt tree still fails in
    milliseconds), then the bench TREND gate (``bench_trend.py --check``
    — also stdlib-only: the committed BENCH_r*.json history must hold
    its per-metric tolerances, so a perf regression fails red before a
    correctness smoke even runs; ISSUE 19), then the TRACE + SHARD
    composition (``lint.py --trace --shard --check``, one subprocess —
    the CLI composes both contract stages in one exit code, so the
    preflight pays one jax+package import, not two): every serving jit
    this gate is about to drive must match its committed
    compile-signature/donation/readback/HBM contract
    (tools/trace_contracts.json) AND hold the committed "no collectives
    in serving" baseline, with the train step holding its per-mesh-kind
    collective/sharding contract (tools/shard_contracts.json), BEFORE a
    request is admitted. Subprocesses on purpose: the AST stage must
    not inherit this process's jax initialization, and the contract
    stages re-import the package fresh so a broken import fails the
    gate, not the drill."""
    import subprocess

    for stage, script, args in (
        ("lint", "lint.py", ["--check"]),
        ("bench-trend", "bench_trend.py", ["--check"]),
        ("contract-lint", "lint.py", ["--trace", "--shard", "--check"]),
    ):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / script), *args],
            capture_output=True, text=True, cwd=REPO,
        )
        if proc.returncode != 0:
            print(f"{label} FAILED: {stage} pre-flight found invariant "
                  f"violations:\n{proc.stdout}{proc.stderr}",
                  file=sys.stderr)
            return proc.returncode
    return 0


def build_tiny_model():
    """The gate's model: tiny, rotary, shift-tokens — built in-process so
    the gate needs no checkpoint. Shared with tools/telemetry_smoke.py."""
    import jax
    import numpy as np

    from dalle_pytorch_tpu.models import DALLE

    dalle = DALLE(
        dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
        num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
        attn_types=("full",), rotary_emb=True,
    )
    rng = np.random.RandomState(0)
    text = rng.randint(1, 16, size=(1, 4)).astype(np.int32)
    image = rng.randint(0, 12, size=(1, 4)).astype(np.int32)
    params = dalle.init(jax.random.key(0), text, image)["params"]
    return dalle, params


def build_tiny_stages(config=None):
    """A ``StageSpec`` over the CANONICAL tiny VAE + CLIP — the same
    configs the trace-contract registry pins for ``serving.vae_decode``
    / ``serving.clip_rerank`` (tools/lint/trace/registry.py), so every
    gate that builds stages through this helper (this drill,
    tools/chaos_soak.py, bench.py --serve, the unit tests) dispatches
    the exact contracted signatures. VAE params are the decode-scope
    tree (``init(..., method="decode")``): the pipeline's contract is
    token ids -> pixels."""
    import jax
    import numpy as np

    from dalle_pytorch_tpu.models.clip import CLIP
    from dalle_pytorch_tpu.models.vae import DiscreteVAE
    from dalle_pytorch_tpu.serving import StageSpec

    if str(REPO / "tools") not in sys.path:
        sys.path.insert(0, str(REPO / "tools"))
    from lint.trace.registry import CANON_CLIP, CANON_VAE

    vae = DiscreteVAE(**CANON_VAE)
    vae_params = vae.init(
        jax.random.key(1), np.zeros((1, vae.image_seq_len), np.int32),
        method="decode",
    )["params"]
    clip = CLIP(**CANON_CLIP)
    clip_params = clip.init(
        jax.random.key(2), np.ones((1, clip.text_seq_len), np.int32),
        np.zeros((1, vae.image_size, vae.image_size, vae.channels),
                 np.float32),
    )["params"]
    kw = {} if config is None else {"config": config}
    return StageSpec(vae=vae, vae_params=vae_params, clip=clip,
                     clip_params=clip_params, **kw)


def run_stage_drill(dalle, params) -> bool:
    """The post-decode pipeline gate (docs/DESIGN.md §8.5): four passes
    over a staged engine on FakeClock (deterministic backoff windows).

    1. CLEAN: 3 requests complete the full tokens -> VAE -> rerank
       pipeline; every image must be BIT-identical to a direct
       ``vae.apply(method="decode")`` of the request's own tokens.
    2. TRANSIENT faults: ``vae_decode_fail=2`` + ``rerank_fail=1`` +
       ``stage_timeout=1`` armed — all within the retry budget, so all
       3 requests still COMPLETE with tokens AND images bit-identical
       to the clean pass, with the retries counted.
    3. VAE retry EXHAUSTION (one request, 3 armed failures): the
       request completes typed-degraded ``completed_tokens_only``.
    4. RERANK exhaustion: typed-degraded ``completed_unranked`` — the
       decoded image survives, bit-identical to the clean pass.

    Env-composed drills (the DTL033 registry contract) ride the same
    passes — counts <= 2 are absorbed by retry (pass 2's shape),
    higher counts surface as typed-degraded outcomes, never stalls::

        DALLE_TPU_FAULTS="vae_decode_fail=2" python tools/serve_smoke.py
        DALLE_TPU_FAULTS="rerank_fail=1,stage_timeout=1" python tools/serve_smoke.py
    """
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, Request,
    )
    from dalle_pytorch_tpu.utils.faults import FAULTS
    from dalle_pytorch_tpu.utils.metrics import counters

    spec = build_tiny_stages()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 16, size=(4,)).astype(np.int32)
               for _ in range(3)]

    def run_pass(label, n_req, arm=()):
        eng = Engine(
            dalle, params, EngineConfig(max_batch=2, prefill_chunk=2),
            stages=spec, clock=FakeClock(step_dt=0.05),
        )
        for site, count in arm:
            FAULTS.arm(site, count)
        for i in range(n_req):
            assert eng.submit(Request(
                request_id=f"stage{i}", prompt=prompts[i],
                max_new_tokens=dalle.image_seq_len, seed=40 + i,
            )) is None
        results = eng.run(max_steps=4000)
        eng.verify_invariants(idle=True)
        for rid in sorted(results):
            print(json.dumps({"pass": label, **results[rid].to_json()}))
        print(json.dumps({"pass": label, "stats": eng.stats()}))
        return results

    ok = True
    clean = run_pass("stage_clean", 3)
    for rid, res in clean.items():
        if res.outcome is not Outcome.COMPLETED or res.image is None \
                or res.rerank_score is None:
            ok = False
            print(f"serve smoke FAILED: stage clean {rid} not fully "
                  f"completed ({res.outcome.value})", file=sys.stderr)
            continue
        direct = np.asarray(spec.vae.apply(
            {"params": spec.vae_params},
            np.asarray(res.tokens, np.int32)[None, :], method="decode",
        ))[0].astype(np.float32)
        if not np.array_equal(direct, res.image):
            ok = False
            print(f"serve smoke FAILED: stage clean {rid} image diverges "
                  "from a direct VAE decode of its own tokens",
                  file=sys.stderr)

    retries0 = counters.get("serve.stage.retries")
    faulted = run_pass("stage_faults", 3, arm=(
        ("vae_decode_fail", 2), ("rerank_fail", 1), ("stage_timeout", 1),
    ))
    if counters.get("serve.stage.retries") <= retries0:
        ok = False
        print("serve smoke FAILED: stage fault pass consumed no retries",
              file=sys.stderr)
    for rid, res in faulted.items():
        if res.outcome is not Outcome.COMPLETED:
            ok = False
            print(f"serve smoke FAILED: {rid} did not absorb transient "
                  f"stage faults ({res.outcome.value})", file=sys.stderr)
        elif not (np.array_equal(np.asarray(res.tokens),
                                 np.asarray(clean[rid].tokens))
                  and np.array_equal(res.image, clean[rid].image)):
            ok = False
            print(f"serve smoke FAILED: {rid} tokens/image diverged across "
                  "stage retries", file=sys.stderr)

    # exhaustion passes: every armed count == the retry budget, so the
    # arms are fully consumed in-pass (no reset — env-armed sites for
    # later passes stay intact)
    attempts = spec.config.retry.attempts
    tokens_only = run_pass("stage_degrade_vae", 1,
                           arm=(("vae_decode_fail", attempts),))
    res = tokens_only["stage0"]
    if res.outcome is not Outcome.COMPLETED_TOKENS_ONLY \
            or res.tokens is None or res.image is not None:
        ok = False
        print("serve smoke FAILED: VAE exhaustion did not degrade to "
              f"completed_tokens_only ({res.outcome.value})", file=sys.stderr)
    unranked = run_pass("stage_degrade_rerank", 1,
                        arm=(("rerank_fail", attempts),))
    res = unranked["stage0"]
    if res.outcome is not Outcome.COMPLETED_UNRANKED or res.image is None \
            or res.rerank_score is not None:
        ok = False
        print("serve smoke FAILED: rerank exhaustion did not degrade to "
              f"completed_unranked ({res.outcome.value})", file=sys.stderr)
    elif not np.array_equal(res.image, clean["stage0"].image):
        ok = False
        print("serve smoke FAILED: completed_unranked image diverges from "
              "the clean pass", file=sys.stderr)
    return ok


def run_replicated_drill(dalle, params, n_replicas: int,
                         preempt=None) -> bool:
    """The --replicas chaos drill: kill one replica mid-run, require all
    requests COMPLETE with tokens bit-identical to a no-crash pass."""
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        EngineConfig, Outcome, Request, Router, RouterConfig,
    )
    from dalle_pytorch_tpu.utils.faults import FAULTS

    rng = np.random.RandomState(2)
    n_req = 2 * n_replicas
    prompts = [
        rng.randint(1, 16, size=(4,)).astype(np.int32) for _ in range(n_req)
    ]

    def run_pass(crash: bool):
        router = Router(
            dalle, params,
            RouterConfig(n_replicas=n_replicas),
            EngineConfig(max_batch=2, prefill_chunk=2),
        )
        for i in range(n_req):
            assert router.submit(Request(
                request_id=f"rep{i}", prompt=prompts[i],
                max_new_tokens=dalle.image_seq_len, seed=100 + i,
            )) is None
        steps = 0
        while router.step():
            steps += 1
            assert steps < 2000, "replicated drill made no progress"
            if preempt is not None and preempt.triggered:
                router.shutdown()
                print("serve smoke: SIGTERM — fleet drained",
                      file=sys.stderr)
                sys.exit(0)
            # arm the kill once work is demonstrably in flight (mid-run),
            # exactly once per pass
            if crash and steps == 3:
                FAULTS.arm("replica_crash", 1)
        router.verify_invariants()
        return router

    clean = run_pass(crash=False)
    chaos = run_pass(crash=True)
    ok = True
    dead = [s for s in chaos.replica_states().values() if s == "dead"]
    if len(dead) != 1:
        ok = False
        print(f"serve smoke FAILED: replica drill expected 1 dead replica, "
              f"states {chaos.replica_states()}", file=sys.stderr)
    for i in range(n_req):
        rid = f"rep{i}"
        res = chaos.results[rid]
        print(json.dumps({"pass": "replicated_chaos", **res.to_json()}))
        if res.outcome is not Outcome.COMPLETED:
            ok = False
            print(f"serve smoke FAILED: {rid} did not complete under "
                  f"replica_crash ({res.outcome.value})", file=sys.stderr)
        elif not np.array_equal(
            np.asarray(res.tokens), np.asarray(clean.results[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} tokens diverged across "
                  "replica failover", file=sys.stderr)
    print(json.dumps({"pass": "replicated_chaos", "stats": chaos.stats()}))
    return ok


def _drive(router, preempt, snapshot_dir=None, max_steps=2000,
           label="serve smoke"):
    """Drive a router to idle, honoring SIGTERM: the preemption handler's
    flag triggers the serving shutdown path — fleet-wide graceful drain,
    journal seal, prefix snapshot flush — then a clean exit (the serving
    analog of the trainer's emergency checkpoint; docs/DESIGN.md §8.3)."""
    steps = 0
    while router.step():
        steps += 1
        assert steps < max_steps, f"{label}: router made no progress"
        if preempt is not None and preempt.triggered:
            router.shutdown(snapshot_dir=snapshot_dir)
            print(f"{label}: SIGTERM — fleet drained, journal sealed"
                  + (", snapshot flushed" if snapshot_dir else ""),
                  file=sys.stderr)
            sys.exit(0)


def run_recovery_drill(dalle, params, preempt=None) -> bool:
    """The kill-restore-replay pass (docs/DESIGN.md §8.3): a journaled
    prefix-cache router completes two cold requests, snapshots its warm
    index, admits two more, and then the process "dies" mid-flight —
    journal unsealed, router abandoned. A second router restores the
    snapshot (verify-on-load) and replays the journal's unfinished
    requests. The gate: every crash-set request COMPLETES with tokens
    bit-identical to a fault-free reference run, and — when the
    snapshot verified — at least one post-restart request is a prefix
    HIT against the restored arena (it comes back *warm*).

    Env-composed drills (the DTL033 registry contract)::

        DALLE_TPU_FAULTS="journal_torn=1" python tools/serve_smoke.py
        DALLE_TPU_FAULTS="snapshot_corrupt=1" python tools/serve_smoke.py

    A torn tail drops the LAST admitted record — the drill resubmits it
    as the client retry the contract prescribes (tokens still
    bit-identical); a corrupt snapshot is verified-rejected and the
    restart proceeds COLD (no warm-hit requirement, but the rejection
    must be counted)."""
    import tempfile

    import numpy as np

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, Outcome, Request, RequestJournal, Router,
        RouterConfig, replay_unfinished,
    )
    from dalle_pytorch_tpu.utils.faults import FAULTS
    from dalle_pytorch_tpu.utils.metrics import counters

    rng = np.random.RandomState(3)
    tmpl = [rng.randint(1, 16, size=(4,)).astype(np.int32) for _ in range(2)]
    cold = [
        Request(request_id="rec0", prompt=tmpl[0], max_new_tokens=4, seed=50),
        Request(request_id="rec1", prompt=tmpl[1], max_new_tokens=4, seed=51),
    ]
    # the crash set: rec2 reuses template 0, so its post-restart replay
    # must hit the RESTORED index (published by rec0's cold run)
    crash_set = [
        Request(request_id="rec2", prompt=tmpl[0], max_new_tokens=4, seed=52),
        Request(request_id="rec3", prompt=tmpl[1], max_new_tokens=4, seed=53),
    ]

    ref_engine = Engine(
        dalle, params, EngineConfig(max_batch=2, prefill_chunk=2)
    )
    for req in crash_set:
        assert ref_engine.submit(req) is None
    reference = {
        rid: np.asarray(res.tokens)
        for rid, res in ref_engine.run(max_steps=1000).items()
    }

    tmp = tempfile.mkdtemp(prefix="serve_smoke_recovery_")
    jpath = os.path.join(tmp, "journal.jsonl")
    snapdir = os.path.join(tmp, "prefix_snapshot")
    cfg = EngineConfig(max_batch=2, prefill_chunk=2, prefix_cache=True)

    router = Router(
        dalle, params, RouterConfig(n_replicas=1), cfg,
        journal=RequestJournal(jpath),
    )
    for req in cold:
        assert router.submit(req) is None
    _drive(router, preempt, snapshot_dir=snapdir)
    router.verify_invariants()
    eng = router._replicas[0].engine
    eng.save_prefix_snapshot(snapdir)
    for req in crash_set:
        assert router.submit(req) is None
    router.step()
    router.step()  # demonstrably in flight ...
    router._journal.close()  # ... and now the process is dead

    # the engine's counters are per-replica labeled series (it lives
    # under a router) — read the replica-0 series
    rejected0 = counters.get(
        "serve.snapshot.rejected", labels={"replica": "0"}
    )
    torn0 = counters.get("serve.journal.torn")
    router2 = Router(
        dalle, params, RouterConfig(n_replicas=1), cfg,
        journal=RequestJournal(jpath),
    )
    eng2 = router2._replicas[0].engine
    restored = eng2.load_prefix_snapshot(snapdir)
    replayed = set(replay_unfinished(
        jpath, router2.submit, now=router2.clock.now()
    ))
    torn = counters.get("serve.journal.torn") - torn0
    for req in crash_set:
        # a torn tail lost this admission: the client retries it
        if req.request_id not in replayed:
            assert torn > 0, (
                f"{req.request_id} missing from replay without a torn tail"
            )
            assert router2.submit(req) is None
    _drive(router2, preempt, snapshot_dir=snapdir)
    router2.verify_invariants()

    ok = True
    for req in crash_set:
        res = router2.results[req.request_id]
        print(json.dumps({"pass": "recovery", **res.to_json()}))
        if res.outcome is not Outcome.COMPLETED:
            ok = False
            print(f"serve smoke FAILED: {req.request_id} did not complete "
                  f"after restart ({res.outcome.value})", file=sys.stderr)
        elif not np.array_equal(
            np.asarray(res.tokens), reference[req.request_id]
        ):
            ok = False
            print(f"serve smoke FAILED: {req.request_id} replayed tokens "
                  "diverge from the fault-free reference", file=sys.stderr)
    if restored:
        if eng2.prefix.stats.hits < 1:
            ok = False
            print("serve smoke FAILED: no post-restart request hit the "
                  "restored prefix snapshot", file=sys.stderr)
    else:
        if counters.get(
            "serve.snapshot.rejected", labels={"replica": "0"}
        ) <= rejected0:
            ok = False
            print("serve smoke FAILED: snapshot load failed without a "
                  "counted rejection", file=sys.stderr)
    print(json.dumps({
        "pass": "recovery",
        "snapshot_restored": bool(restored),
        "journal_replayed": sorted(replayed),
        "journal_torn_dropped": torn,
        "prefix_hits_after_restart": eng2.prefix.stats.hits,
        "stats": router2.stats(),
    }))
    return ok


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n_replicas = (
        int(argv[argv.index("--replicas") + 1]) if "--replicas" in argv else 0
    )

    if lint_preflight() != 0:
        return 1

    from dalle_pytorch_tpu.utils.resilience import PreemptionHandler
    from dalle_pytorch_tpu.utils.telemetry import TELEMETRY

    # SIGTERM contract (docs/DESIGN.md §8.3, the serving analog of the
    # trainer's preemption path): the signal hook drains the flight
    # recorder immediately; the router drive loops poll ``triggered``
    # and run graceful drain + journal seal + snapshot flush before a
    # clean exit.
    with PreemptionHandler(
        on_signal=lambda s: TELEMETRY.drain("preempt_signal")
    ) as preempt:
        return _run_passes(n_replicas, preempt)


def _run_passes(n_replicas: int, preempt) -> int:
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, Request,
    )

    dalle, params = build_tiny_model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 16, size=(4,)).astype(np.int32) for _ in range(3)]

    def run_pass(label: str, **cfg_kw) -> dict:
        engine = Engine(dalle, params, EngineConfig(max_batch=2, **cfg_kw))
        for i in range(3):
            rejected = engine.submit(Request(
                request_id=f"smoke{i}",
                prompt=prompts[i],
                max_new_tokens=dalle.image_seq_len,
                seed=i,
            ))
            assert rejected is None, rejected
        results = engine.run(max_steps=1000)
        engine.verify_invariants(idle=True)
        for rid in sorted(results):
            print(json.dumps({"pass": label, **results[rid].to_json()}))
        print(json.dumps({"pass": label, "stats": engine.stats()}))
        return results

    # chunked first: an env-armed prefill_fail fires at CHUNK granularity
    # and must be absorbed by the resume-from-last-chunk retry
    chunked = run_pass("chunked", prefill_chunk=2)
    mono = run_pass("monolithic")
    # fused ragged-iteration pass (ROADMAP 1): the whole iteration — every
    # granted chunk plus the decode rows — as ONE _iteration_jit dispatch;
    # tokens must be BIT-identical to both split passes. Runs after the
    # split passes so an env-armed fault budget drills the split chunk
    # retry first, but composes with DALLE_TPU_FAULTS the same way
    # (chunk-granular prefill_fail with resume-from-last-chunk)
    fused = run_pass("fused", prefill_chunk=2, fused_iteration=True)
    # speculative pass (ROADMAP 2): every decode row self-drafts spec_k
    # tokens and the single ragged dispatch VERIFIES them; exact
    # acceptance makes the stream bit-identical to all the passes above
    # by construction — asserted below. Composes with DALLE_TPU_FAULTS:
    # an armed ``spec_verify_abort`` degrades one iteration to plain
    # decode (same signature, tokens unchanged)::
    #
    #     DALLE_TPU_FAULTS="spec_verify_abort=1" python tools/serve_smoke.py
    spec = run_pass("spec", prefill_chunk=2, fused_iteration=True,
                    spec_decode=True, spec_k=2)
    # quantized-KV passes (ISSUE 14): int8 paged pools with per-(token,
    # head) scale pools, dequantized at read time. Parity tiers: the two
    # QUANTIZED passes (split-chunked vs fused) must be BIT-identical to
    # each other — the standing quant-vs-quant contract — while
    # quant-vs-unquantized is held to the PINNED token-agreement floor
    # (ops/kv_policy.py:KV_QUANT_TOKEN_AGREEMENT_MIN), never a bitwise
    # claim. Composes with DALLE_TPU_FAULTS like every pass above.
    quant = run_pass("kv_quant_chunked", prefill_chunk=2, kv_quant="int8")
    quant_fused = run_pass("kv_quant_fused", prefill_chunk=2,
                           fused_iteration=True, kv_quant="int8")

    # prefix-cache cold/warm replay (ROADMAP 3): ONE engine with the
    # content-addressed page index runs the SAME 3-request scenario
    # twice. The cold round publishes every prompt's pages; the warm
    # round must HIT (> 0 probes matched) and produce tokens
    # bit-identical to the cold round — the cross-request reuse contract
    # — with the refcount accounting (sum of references == mapped table
    # entries; no leaked pages after drain) asserted through the same
    # public verify_invariants the other passes use
    prefix_engine = Engine(dalle, params, EngineConfig(
        max_batch=2, prefill_chunk=2, prefix_cache=True,
    ))

    def run_prefix_round(label: str) -> dict:
        for i in range(3):
            rejected = prefix_engine.submit(Request(
                request_id=f"smoke{i}.{label}", prompt=prompts[i],
                max_new_tokens=dalle.image_seq_len, seed=i,
            ))
            assert rejected is None, rejected
        prefix_engine.run(max_steps=1000)
        prefix_engine.verify_invariants(idle=True)
        results = {
            rid.split(".")[0]: res
            for rid, res in prefix_engine.results.items()
            if rid.endswith(f".{label}")
        }
        for rid in sorted(results):
            print(json.dumps({"pass": label, **results[rid].to_json()}))
        print(json.dumps({
            "pass": label, "stats": prefix_engine.stats(),
            "prefix": {"hits": prefix_engine.prefix.stats.hits,
                       "misses": prefix_engine.prefix.stats.misses,
                       "pages": len(prefix_engine.prefix)},
        }))
        return results

    cold = run_prefix_round("prefix_cold")
    hits_before_warm = prefix_engine.prefix.stats.hits
    warm = run_prefix_round("prefix_warm")

    ok = True
    if prefix_engine.prefix.stats.hits <= hits_before_warm:
        ok = False
        print("serve smoke FAILED: warm prefix round never hit the index",
              file=sys.stderr)
    for rid in sorted(cold):
        for round_name, res in (("cold", cold[rid]), ("warm", warm[rid])):
            if res.outcome is not Outcome.COMPLETED:
                ok = False
                print(f"serve smoke FAILED: {rid} {round_name} prefix round "
                      f"did not complete ({res.outcome.value})",
                      file=sys.stderr)
        if not np.array_equal(
            np.asarray(cold[rid].tokens), np.asarray(warm[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} warm (cache-hit) tokens "
                  "diverge from the cold round", file=sys.stderr)
        if not np.array_equal(
            np.asarray(cold[rid].tokens), np.asarray(chunked[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} prefix-engine tokens diverge "
                  "from the uncached chunked pass", file=sys.stderr)
    for rid in sorted(mono):
        ok = ok and mono[rid].outcome is Outcome.COMPLETED
        ok = ok and chunked[rid].outcome is Outcome.COMPLETED
        ok = ok and fused[rid].outcome is Outcome.COMPLETED
        ok = ok and spec[rid].outcome is Outcome.COMPLETED
        if not np.array_equal(
            np.asarray(mono[rid].tokens), np.asarray(chunked[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} chunked tokens diverge from "
                  "monolithic", file=sys.stderr)
        if not np.array_equal(
            np.asarray(mono[rid].tokens), np.asarray(fused[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} fused tokens diverge from "
                  "the split path", file=sys.stderr)
        if not np.array_equal(
            np.asarray(mono[rid].tokens), np.asarray(spec[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} speculative tokens diverge "
                  "from plain decode — the exact-acceptance contract is "
                  "broken", file=sys.stderr)

    # quantized-KV gate: quant-vs-quant bitwise, quant-vs-f32 thresholded
    from dalle_pytorch_tpu.ops.kv_policy import KV_QUANT_TOKEN_AGREEMENT_MIN

    agree_num = agree_den = 0
    for rid in sorted(quant):
        ok = ok and quant[rid].outcome is Outcome.COMPLETED
        ok = ok and quant_fused[rid].outcome is Outcome.COMPLETED
        if not np.array_equal(
            np.asarray(quant[rid].tokens), np.asarray(quant_fused[rid].tokens)
        ):
            ok = False
            print(f"serve smoke FAILED: {rid} quantized fused tokens "
                  "diverge from the quantized split path — the "
                  "quant-vs-quant bitwise contract is broken",
                  file=sys.stderr)
        both = min(len(quant[rid].tokens), len(chunked[rid].tokens))
        agree_num += int(np.sum(
            np.asarray(quant[rid].tokens)[:both]
            == np.asarray(chunked[rid].tokens)[:both]
        ))
        agree_den += both
    agreement = agree_num / max(agree_den, 1)
    if agreement < KV_QUANT_TOKEN_AGREEMENT_MIN:
        ok = False
        print(f"serve smoke FAILED: kv-int8 token agreement {agreement:.3f} "
              f"below the pinned {KV_QUANT_TOKEN_AGREEMENT_MIN} floor",
              file=sys.stderr)
    print(json.dumps({
        "pass": "kv_quant", "token_agreement_vs_unquant": agreement,
        "floor": KV_QUANT_TOKEN_AGREEMENT_MIN,
    }))

    # mid-prefill deadline drill: token_budget=1 throttles prefill to one
    # chunk per iteration (the forward-progress floor), the FakeClock makes
    # "expires mid-prefill" an exact step count, and the pages must be back
    # the iteration the deadline sweeps — never held to the end of the
    # prompt the way a monolithic prefill would
    drill = Engine(
        dalle, params,
        EngineConfig(max_batch=2, prefill_chunk=2, token_budget=1),
        clock=FakeClock(step_dt=1.0),
    )
    assert drill.submit(Request(
        request_id="drill", prompt=prompts[0],
        max_new_tokens=dalle.image_seq_len, seed=0, deadline=0.5,
    )) is None
    drill.run(max_steps=100)
    drill.verify_invariants(idle=True)
    res = drill.results["drill"]
    print(json.dumps({"pass": "mid_prefill_deadline", **res.to_json()}))
    if res.outcome is not Outcome.DEADLINE_EXCEEDED or res.tokens is not None:
        ok = False
        print("serve smoke FAILED: mid-prefill deadline drill did not "
              f"terminate typed mid-prefill ({res.outcome.value})",
              file=sys.stderr)
    if drill.pool.used != 0:
        ok = False
        print("serve smoke FAILED: mid-prefill termination leaked "
              f"{drill.pool.used} pages", file=sys.stderr)

    # kill-restore-replay recovery pass (docs/DESIGN.md §8.3): journaled
    # router + prefix snapshot survive a mid-flight process death with
    # bit-identical replay and a warm restored cache
    ok = run_recovery_drill(dalle, params, preempt) and ok

    # post-decode stage pipeline (docs/DESIGN.md §8.5): full
    # tokens->VAE->rerank completion with bit-identical images, transient
    # stage faults absorbed by retry, exhaustion typed-degraded
    ok = run_stage_drill(dalle, params) and ok

    if n_replicas:
        ok = run_replicated_drill(
            dalle, params, n_replicas, preempt=preempt
        ) and ok

    if not ok:
        print("serve smoke FAILED: not every request completed", file=sys.stderr)
        return 1
    print("serve smoke OK: 3/3 completed chunked, monolithic, fused, "
          "SPECULATIVE (exact-acceptance bit-parity), QUANTIZED-KV "
          "(split-vs-fused bitwise, agreement >= pinned floor vs f32) "
          "AND the prefix-cache "
          "cold/warm replay (bit-identical, warm round "
          "hit the index), mid-prefill deadline drill typed, pool drained, "
          "kill-restore-replay recovery drill bit-identical with a warm "
          "restored cache, POST-DECODE stage drill (bit-identical images, "
          "transient stage faults absorbed, exhaustion typed-degraded)"
          + (f", {2 * n_replicas}/{2 * n_replicas} completed the "
             f"{n_replicas}-replica crash drill bit-identically"
             if n_replicas else ""),
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
