#!/usr/bin/env python
"""Fleet-scale traffic simulator: retry storms, correlated outages, and
capacity frontiers over virtual time (docs/DESIGN.md §8.4).

A discrete-event workload harness over the injectable serving ``Clock``
that drives hundreds of thousands of simulated requests through an
N-replica ``Router`` fleet in faster-than-real time. Two lanes
cross-validate each other:

* **modeled lane** — the REAL ``Router`` (health machine, breaker,
  respawn ladders, failover, shed, dispatch — every line of
  serving/router.py) over a fleet of ``StubEngine``s: host-only models
  of the engine's admission/step/can_admit/verify_invariants surface
  built from the SAME scheduler primitives the real engine uses
  (``Scheduler``/``PagePool``/``TokenBudget``/``pages_for``), replacing
  only the device work with a per-iteration cost distribution
  calibrated from committed BENCH records (~1.0 ms/token bf16 decode on
  v5e, BENCH_r04 / ROADMAP). This is what reaches 100k+ requests in
  seconds.
* **fidelity lane** — the real tiny-model engine fleet on a
  ``FakeClock``, thousands of requests, asserting the modeled lane's
  predicted shed fraction / p99 TTFT / occupancy trajectory within the
  tolerances documented in DESIGN §8.4.

Workloads are seeded generators (Poisson / diurnal / burst arrivals,
zipf-of-prefix template mixes, tenant priority + deadline spreads) plus
a CLOSED-LOOP client model: every typed reject or deadline miss
re-enters the arrival stream through client backoff
(``RetryPolicy.delay``), optionally honoring the server's
``retry_after_s`` hint — which is what makes retry storms real. Fault
schedules composed from the existing chaos sites (``replica_crash``,
``replica_stall``, ``health_flap``, ``replica_respawn_fail``) produce
correlated outage storms.

Virtual-time semantics: the in-process fleet is genuinely
time-multiplexed (``Router.step`` drives every engine sequentially
under one lock), so each busy engine iteration advances the ONE shared
clock by its drawn cost; an idle fleet jumps straight to the next
event (arrival, client retry, breaker readmission, respawn). QPS
numbers are therefore per-process, comparable across scenarios.

In-run asserts (the run fails loudly, not statistically): 100%
typed-outcome accounting (``Router.verify_invariants`` plus
every-logical-request-final), no admission livelock (terminal progress
watchdog), goodput monotone-bounded past saturation, replay-consistent
seeding (one level re-run must produce an identical record), and the
storm-amplification guard — goodput at 2x saturation with jittered
backoff + honored hints >= the unjittered/no-hint baseline, with
desynchronized respawn ladders (no lockstep re-collision).

Modes::

    python tools/traffic_sim.py --smoke      # ~seconds, fast-tier gate
    python tools/traffic_sim.py --quick      # >=100k requests, <60s
    python tools/traffic_sim.py --sweep      # frontier grid (slow tier)
    python tools/traffic_sim.py --fidelity 600   # cross-validate lanes
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import os
import sys
import time
from collections import OrderedDict
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import random

import numpy as np

from dalle_pytorch_tpu.serving.scheduler import (
    Entry, PagePool, Scheduler, TokenBudget, pages_for,
)
from dalle_pytorch_tpu.serving.types import (
    FakeClock, Outcome, RejectReason, Request, RequestResult,
)
from dalle_pytorch_tpu.utils.faults import FAULTS
from dalle_pytorch_tpu.utils.metrics import counters, histograms
from dalle_pytorch_tpu.utils.resilience import RetryPolicy, retry_after_hint

# retriable load-typed rejections; DEMAND_EXCEEDS_POOL is permanent
_RETRIABLE = (RejectReason.QUEUE_FULL, RejectReason.NO_REPLICA)


# ------------------------------------------------------------ cost model


@dataclass(frozen=True)
class IterationCostModel:
    """Virtual cost of one engine scheduling iteration in the modeled
    lane. Defaults are calibrated from the committed accelerator
    records: decode ~1.0 ms/token bf16 on v5e (BENCH_r04; ROADMAP
    "decode at ~1.0 ms/token"), prefill amortized well under decode
    (compute-bound batch processing of the whole chunk — the 0.9
    ms/token batch-1 decode figure in DESIGN §6 bounds it above), plus
    a fixed per-iteration dispatch overhead. ``jitter_frac`` draws
    multiplicative lognormal noise from the engine's seeded RNG so two
    replicas never run in artificial lockstep; ``constant`` (used by
    the fidelity-matched configuration) charges exactly ``fixed_s`` per
    iteration, idle or not — the semantics of ``FakeClock.tick``."""

    decode_ms_per_token: float = 1.0
    prefill_ms_per_token: float = 0.12
    fixed_overhead_ms: float = 0.3
    jitter_frac: float = 0.08
    constant: bool = False
    fixed_s: float = 0.0
    tick_idle: bool = False
    # post-decode stage rows (serving/postdecode.py, DESIGN §8.5): the
    # per-image VAE decode / CLIP rerank cost charged on top of token
    # work. 0.0 = the stage model contributes no virtual time.
    vae_ms_per_image: float = 0.0
    rerank_ms_per_image: float = 0.0

    def cost_s(self, decode_tokens: int, prefill_tokens: int,
               rng: Optional[random.Random]) -> float:
        if self.constant:
            return self.fixed_s
        if decode_tokens == 0 and prefill_tokens == 0:
            return self.fixed_overhead_ms / 1e3 if self.tick_idle else 0.0
        ms = (
            self.fixed_overhead_ms
            + self.decode_ms_per_token * decode_tokens
            + self.prefill_ms_per_token * prefill_tokens
        )
        if self.jitter_frac > 0.0 and rng is not None:
            ms *= math.exp(rng.gauss(0.0, self.jitter_frac))
        return ms / 1e3

    def stage_cost_s(self, vae_images: int, reranked: int,
                     rng: Optional[random.Random]) -> float:
        """Virtual cost of this iteration's post-decode stage rows,
        charged on top of token work (zero under the fidelity-matched
        constant clock — its fixed per-iteration tick already covers
        everything the engine did)."""
        if self.constant or (vae_images == 0 and reranked == 0):
            return 0.0
        ms = (
            self.vae_ms_per_image * vae_images
            + self.rerank_ms_per_image * reranked
        )
        if ms > 0.0 and self.jitter_frac > 0.0 and rng is not None:
            ms *= math.exp(rng.gauss(0.0, self.jitter_frac))
        return ms / 1e3

    @staticmethod
    def matched(step_dt: float) -> "IterationCostModel":
        """The fidelity-matched configuration: every iteration costs
        exactly ``step_dt``, like a real engine stepping a
        ``FakeClock(step_dt=...)``."""
        return IterationCostModel(
            constant=True, fixed_s=step_dt, tick_idle=True,
        )


# ------------------------------------------------------------ stub engine


class _StubModel:
    """The two model attributes the router reads off a replica's engine
    (``proto.dalle.image_seq_len`` at submit validation; text length for
    page math)."""

    def __init__(self, text_len_internal: int, image_seq_len: int):
        self.text_len_internal = text_len_internal
        self.image_seq_len = image_seq_len


@dataclass(frozen=True)
class StubEngineConfig:
    """The EngineConfig subset the modeled lane exercises, with the
    same defaults/semantics (serving/engine.py:EngineConfig)."""

    max_batch: int = 8
    page: int = 4
    page_budget: Optional[int] = None      # None = max_batch * pages/slot
    queue_limit: int = 64
    high_watermark: float = 0.85
    degraded_max_new_tokens: Optional[int] = None
    max_preemptions: int = 3
    prefill_chunk: Optional[int] = None    # None = whole prompt at once
    token_budget: Optional[int] = None     # None = max_batch + chunk
    # prefix-template model: LRU capacity in TEMPLATES (0 = off). A full
    # hit shares the template's prompt pages (charged to __prefix__) and
    # skips prefill entirely — the TTFT / hit-rate / arena-share lever.
    prefix_templates: int = 0
    # post-decode stage model (serving/postdecode.py semantics): tokens-
    # complete requests pass VAE_DECODE -> [CLIP_RERANK] -> DONE under a
    # per-iteration stage budget, with enqueue-time pressure degradation
    # to the typed COMPLETED_TOKENS_ONLY outcome.
    stages: bool = False
    stage_budget: int = 2              # stage rows per iteration
    stage_queue_limit: int = 64        # staged backlog -> degrade at entry
    stage_high_watermark: float = 1.0  # occupancy past this -> degrade
    stage_rerank: bool = True


class StubEngine:
    """Host-only model of the engine surface the Router drives.

    Same admission policy (strict head-of-line, watermark clamp, worst-
    case page charging), same preempt-and-requeue discipline (lazy page
    growth, lowest-effective-priority victim, ``max_preemptions`` ->
    typed PREEMPT_CAP), same typed-outcome accounting — only the device
    work is replaced by token counters and a drawn per-iteration cost
    that the engine itself charges to the shared clock. Emits the
    labeled heartbeat counters the router's health machinery reads
    (``serve.admitted`` / ``serve.decode_steps`` / ``serve.prefill_chunks``)
    so stall detection, the breaker and progress accounting all run the
    REAL router code paths."""

    PREFIX_HOLDER = "__prefix__"

    def __init__(self, model: _StubModel, config: StubEngineConfig,
                 cost: IterationCostModel, clock,
                 metric_labels: Optional[dict] = None,
                 fleet_occupancy: Optional[Callable[[], float]] = None,
                 seed: int = 0):
        self.dalle = model
        self.config = config
        self.clock = clock
        self.page = config.page
        self.T = model.text_len_internal
        self.n_pages_slot = pages_for(
            self.T + model.image_seq_len, self.page
        )
        total = config.page_budget or config.max_batch * self.n_pages_slot
        self.pool = PagePool(total)
        self.sched = Scheduler(config.queue_limit)
        self.slots: List[Optional[Entry]] = [None] * config.max_batch
        self.results: Dict[str, RequestResult] = {}
        self._live: set = set()
        self._outcome_counts: Dict[Outcome, int] = {o: 0 for o in Outcome}
        self._submitted = 0
        self._seq = 0
        self._cancel_requested: set = set()
        self.prefix = None                  # router's snapshot path: unused
        self._fleet_occupancy = fleet_occupancy
        self._cost = cost
        self._rng = random.Random(seed)
        self.counters = counters.child(metric_labels)
        self.iterations = 0
        chunk = config.prefill_chunk or self.T
        budget = (
            config.token_budget
            if config.token_budget is not None
            else config.max_batch + chunk
        )
        self._budget = TokenBudget(budget=budget, chunk=chunk)
        self._chunk = chunk
        # per-slot prefill progress / decode tally, keyed by request_id
        self._prompt_left: Dict[str, int] = {}
        self._gen: Dict[str, int] = {}
        # post-decode stage queue (config.stages): tokens-complete
        # entries parked for VAE/rerank rows; they stay LIVE but hold
        # no slot or pages — serving/postdecode.py semantics
        self._staged: List[Entry] = []
        self._stage: Dict[str, str] = {}       # rid -> vae_decode|clip_rerank
        self._stage_hit: Dict[str, Optional[str]] = {}
        # prefix-template LRU: key -> [pages, refcount]
        self._templates: "OrderedDict[bytes, list]" = OrderedDict()

    # -- the submit/cancel/step surface ------------------------------

    def submit(self, request: Request) -> Optional[RequestResult]:
        if not (0 < request.max_new_tokens <= self.dalle.image_seq_len):
            raise ValueError(
                f"max_new_tokens must be in "
                f"[1, {self.dalle.image_seq_len}], "
                f"got {request.max_new_tokens}"
            )
        if (
            request.request_id in self.results
            or request.request_id in self._live
        ):
            raise ValueError(
                f"duplicate request_id {request.request_id!r}"
            )
        self._submitted += 1
        self.counters.inc("serve.submitted")
        now = self.clock.now()
        entry = Entry(request=request, submit_time=now, seq=self._seq)
        self._seq += 1
        if self._worst_case_pages(request.max_new_tokens) > self.pool.total:
            return self._reject(entry, RejectReason.DEMAND_EXCEEDS_POOL)
        if not self.sched.submit(entry):
            return self._reject(entry, RejectReason.QUEUE_FULL)
        self._live.add(request.request_id)
        return None

    def cancel(self, request_id: str) -> None:
        self._cancel_requested.add(request_id)

    def can_admit(self, request: Request) -> bool:
        """The router dispatch gate, same contract as the real engine:
        free slot, empty internal queue, and the worst-case demand of
        the budget the request would receive fits the free pages plus
        what the template arena could reclaim (refcount-0 templates —
        the stub analog of ``prefix.reclaimable_pages()``)."""
        if not any(s is None for s in self.slots):
            return False
        if len(self.sched):
            return False
        eff, _ = self._clamped_budget(request.max_new_tokens)
        avail = self.pool.free + sum(
            pages for pages, ref in self._templates.values() if ref == 0
        )
        return self._worst_case_pages(eff) <= avail

    def step(self) -> bool:
        self._sweep_terminations()
        self._admit()
        decode_tokens, prefill_tokens = self._advance()
        vae_rows, rerank_rows = self._stage_advance()
        worked = bool(
            decode_tokens or prefill_tokens or vae_rows or rerank_rows
        )
        if worked:
            self.iterations += 1
        dt = self._cost.cost_s(decode_tokens, prefill_tokens, self._rng)
        dt += self._cost.stage_cost_s(vae_rows, rerank_rows, self._rng)
        if dt > 0:
            self.clock.advance(dt)
        return worked or bool(self.sched) or bool(self._staged) or any(
            s is not None for s in self.slots
        )

    def live_requests(self) -> List[Request]:
        queued = [e.request for e in self.sched.entries()]
        running = [
            s.request for s in sorted(
                (s for s in self.slots if s is not None),
                key=lambda e: e.seq,
            )
        ]
        staged = [
            e.request for e in sorted(self._staged, key=lambda e: e.seq)
        ]
        return queued + running + staged

    def verify_invariants(self, idle: bool = False) -> None:
        slot_ids = {
            s.request_id for s in self.slots if s is not None
        }
        queued_ids = self.sched.ids()
        staged_ids = {e.request_id for e in self._staged}
        assert not (slot_ids & queued_ids), (
            f"running AND queued: {sorted(slot_ids & queued_ids)}"
        )
        assert not (staged_ids & (slot_ids | queued_ids)), (
            f"staged AND running/queued: "
            f"{sorted(staged_ids & (slot_ids | queued_ids))}"
        )
        assert self._live == slot_ids | queued_ids | staged_ids, (
            f"live {len(self._live)} != slots {len(slot_ids)} + "
            f"queued {len(queued_ids)} + staged {len(staged_ids)}"
        )
        assert len(self.results) + len(self._live) == self._submitted, (
            f"{self._submitted} submitted, {len(self.results)} results, "
            f"{len(self._live)} live"
        )
        holders = self.pool.holders()
        assert holders <= slot_ids | {self.PREFIX_HOLDER}, (
            f"pages held by non-running {sorted(holders - slot_ids)}"
        )
        if idle:
            assert not self._live and not slot_ids

    # -- internals ---------------------------------------------------

    def _clamped_budget(self, want: int) -> Tuple[int, bool]:
        cfg = self.config
        occ = (
            self._fleet_occupancy()
            if self._fleet_occupancy is not None
            else self.pool.occupancy
        )
        if (
            cfg.degraded_max_new_tokens is not None
            and occ > cfg.high_watermark
            and want > cfg.degraded_max_new_tokens
        ):
            return cfg.degraded_max_new_tokens, True
        return want, False

    def _worst_case_pages(self, max_new: int) -> int:
        return pages_for(self.T + max_new - 1, self.page)

    def _template_key(self, request: Request) -> bytes:
        return request.prompt.tobytes()

    def _reclaim_templates(self, want: int) -> None:
        """Evict refcount-0 templates LRU-first until ``want`` pages are
        free (the stub analog of the index's last-resort eviction
        tier)."""
        if want <= self.pool.free:
            return
        for key in list(self._templates):
            pages, ref = self._templates[key]
            if ref:
                continue
            del self._templates[key]
            self.pool.release(self.PREFIX_HOLDER, pages)
            if want <= self.pool.free:
                return

    def _admit(self) -> None:
        now = self.clock.now()
        while any(s is None for s in self.slots) and len(self.sched):
            entry = self.sched.peek()
            eff, clamped = self._clamped_budget(
                entry.request.max_new_tokens
            )
            hit = False
            if self.config.prefix_templates:
                key = self._template_key(entry.request)
                hit = key in self._templates
            prompt_pages = 0 if hit else pages_for(self.T, self.page)
            demand = self._worst_case_pages(eff)
            if demand - (pages_for(self.T, self.page) - prompt_pages) \
                    > self.pool.free:
                self._reclaim_templates(
                    demand - (pages_for(self.T, self.page) - prompt_pages)
                )
            charge = demand - (pages_for(self.T, self.page) - prompt_pages)
            if charge > self.pool.free:
                return                       # strict head-of-line
            self.sched.pop()
            rid = entry.request_id
            # charge the prompt pages now (worst-case admission already
            # verified the rest fits; growth below is lazy)
            assert self.pool.alloc(rid, prompt_pages)
            entry.effective_max_new = eff
            entry.clamped = clamped
            entry.admit_time = now
            if clamped:
                self.counters.inc("serve.clamped")
            if hit:
                key = self._template_key(entry.request)
                self._templates.move_to_end(key)
                self._templates[key][1] += 1
                entry.hit_class = "full"
                self._prompt_left[rid] = 0
                # prefill skipped entirely: first token samples now
                entry.ttft_s = now - entry.submit_time
            else:
                self._prompt_left[rid] = self.T
            self._gen[rid] = 0
            idx = self.slots.index(None)
            self.slots[idx] = entry
            self.counters.inc("serve.admitted")

    def _advance(self) -> Tuple[int, int]:
        """One iteration of device work: decode every active row (one
        token each), then budgeted prefill chunks, split-path style
        (``TokenBudget.plan``: decode charged first, token grants in
        chunk multiples, possibly several chunks per slot per
        iteration, strict head-of-line)."""
        now = self.clock.now()
        decode_tokens = 0
        for entry in self.slots:
            if entry is None:
                continue
            rid = entry.request_id
            if self._prompt_left[rid] > 0:
                continue
            gen = self._gen[rid] + 1
            self._gen[rid] = gen
            decode_tokens += 1
            if entry.ttft_s is None:
                entry.ttft_s = now - entry.submit_time
            s = self.T + gen
            if s < self.T + entry.effective_max_new and s % self.page == 0:
                if not self._grow(entry):
                    continue   # entry was preempted (or capped)
            if gen >= entry.effective_max_new:
                self._finish(entry, Outcome.COMPLETED)
        if decode_tokens:
            self.counters.inc("serve.decode_steps")
        prefilling = sorted(
            (e for e in self.slots
             if e is not None and self._prompt_left[e.request_id] > 0),
            key=lambda e: (-self.sched.effective_priority(e), e.seq),
        )
        grants = self._budget.plan(
            decode_tokens,
            [self._prompt_left[e.request_id] for e in prefilling],
        )
        prefill_tokens = 0
        for entry, grant in zip(prefilling, grants):
            rid = entry.request_id
            while grant > 0:
                chunk = min(self._chunk, self._prompt_left[rid])
                if self._prompt_left[rid] - chunk == 1:
                    chunk += 1   # split-path 1-token-tail merge
                self._prompt_left[rid] -= chunk
                grant -= chunk
                prefill_tokens += chunk
                self.counters.inc("serve.prefill_chunks")
            if self._prompt_left[rid] == 0:
                # prefill completion samples the first token
                if entry.ttft_s is None:
                    entry.ttft_s = now - entry.submit_time
                if entry.prefill_attempts == 0:
                    entry.prefill_attempts = 1
                self._publish_template(entry)
        return decode_tokens, prefill_tokens

    def _grow(self, entry: Entry) -> bool:
        """Lazy +1 page at a page boundary; on exhaustion preempt the
        lowest-effective-priority victim (youngest on ties) — possibly
        the grower itself — and retry the allocation."""
        rid = entry.request_id
        while not self.pool.alloc(rid, 1):
            self._reclaim_templates(1)
            if self.pool.free >= 1:
                continue
            victims = [e for e in self.slots if e is not None]
            victim = min(
                victims,
                key=lambda e: (self.sched.effective_priority(e), -e.seq),
            )
            self._preempt(victim)
            if victim is entry:
                return False
        return True

    def _preempt(self, entry: Entry) -> None:
        rid = entry.request_id
        self._release_slot(entry)
        entry.preempt_count += 1
        self.counters.inc("serve.preempted")
        if entry.preempt_count > self.config.max_preemptions:
            self._terminal(entry, Outcome.PREEMPT_CAP,
                           detail="max_preemptions exceeded")
            return
        # replay from scratch on readmission (the (seed, position)
        # replay contract makes this invisible to the client)
        self.sched.requeue(entry)

    def _release_slot(self, entry: Entry) -> None:
        rid = entry.request_id
        idx = self.slots.index(entry)
        self.slots[idx] = None
        self.pool.free_all(rid)
        if entry.hit_class == "full" and self.config.prefix_templates:
            key = self._template_key(entry.request)
            if key in self._templates:
                self._templates[key][1] -= 1
        entry.hit_class = None
        self._prompt_left.pop(rid, None)
        self._gen.pop(rid, None)

    def _publish_template(self, entry: Entry) -> None:
        """Cold prefill completion publishes the template (fail-open,
        like the real index: skipped when the arena cannot fit)."""
        if not self.config.prefix_templates:
            return
        key = self._template_key(entry.request)
        if key in self._templates:
            return
        pages = pages_for(self.T, self.page)
        while len(self._templates) >= self.config.prefix_templates:
            old = next(iter(self._templates))
            if self._templates[old][1]:
                return                     # LRU head referenced: skip
            del self._templates[old]
            self.pool.release(self.PREFIX_HOLDER, pages)
        if not self.pool.alloc(self.PREFIX_HOLDER, pages):
            return
        self._templates[key] = [pages, 0]

    def _sweep_terminations(self) -> None:
        now = self.clock.now()
        if self._cancel_requested:
            for rid in list(self._cancel_requested):
                entry = self.sched.remove(rid)
                if entry is None:
                    entry = next(
                        (e for e in self.slots
                         if e is not None and e.request_id == rid),
                        None,
                    )
                    if entry is not None:
                        self._release_slot(entry)
                if entry is None:
                    entry = next(
                        (e for e in self._staged if e.request_id == rid),
                        None,
                    )
                    if entry is not None:
                        self._stage_remove(entry)
                if entry is not None:
                    self._terminal(entry, Outcome.CANCELLED)
                self._cancel_requested.discard(rid)
        for entry in self.sched.expired(now):
            self._terminal(entry, Outcome.DEADLINE_EXCEEDED,
                           detail="deadline passed in queue")
        for entry in list(self.slots):
            if entry is None:
                continue
            d = entry.request.deadline
            if d is not None and now > d:
                self._release_slot(entry)
                self._terminal(entry, Outcome.DEADLINE_EXCEEDED,
                               detail="deadline passed mid-flight")
        for entry in list(self._staged):
            d = entry.request.deadline
            if d is not None and now > d:
                self._stage_remove(entry)
                self._terminal(entry, Outcome.DEADLINE_EXCEEDED,
                               detail="deadline passed mid-stage")

    def _finish(self, entry: Entry, outcome: Outcome) -> None:
        hit = entry.hit_class          # cleared by _release_slot
        self._release_slot(entry)
        if outcome is Outcome.COMPLETED and self.config.stages:
            self._stage_enqueue(entry, hit)
            return
        self.counters.inc("serve.completed")
        self._terminal(entry, outcome,
                       detail=f"prefix_hit:{hit}" if hit else "")

    # -- post-decode stage model (config.stages) ---------------------

    def _stage_enqueue(self, entry: Entry, hit: Optional[str]) -> None:
        """Tokens-complete entry enters the modeled pipeline. Pressure
        degradation happens HERE, at the stage boundary, exactly like
        the real pipeline: a typed COMPLETED_TOKENS_ONLY instead of an
        unbounded stage backlog."""
        cfg = self.config
        self.counters.inc("serve.stage.enqueued")
        occ = (
            self._fleet_occupancy()
            if self._fleet_occupancy is not None
            else self.pool.occupancy
        )
        if len(self._staged) >= cfg.stage_queue_limit:
            self.counters.inc("serve.stage.degraded")
            self._terminal(entry, Outcome.COMPLETED_TOKENS_ONLY,
                           detail="stage_backlog")
            return
        if occ > cfg.stage_high_watermark:
            self.counters.inc("serve.stage.degraded")
            self._terminal(entry, Outcome.COMPLETED_TOKENS_ONLY,
                           detail="stage_watermark")
            return
        rid = entry.request_id
        self._stage[rid] = "vae_decode"
        self._stage_hit[rid] = hit
        self._staged.append(entry)

    def _stage_remove(self, entry: Entry) -> None:
        self._staged.remove(entry)
        self._stage.pop(entry.request_id, None)
        self._stage_hit.pop(entry.request_id, None)

    def _stage_advance(self) -> Tuple[int, int]:
        """One iteration of budgeted stage rows, completion-priority
        like the real pipeline (rerank-stage rows dispatch before fresh
        VAE rows). Returns (vae_rows, rerank_rows) for the cost model."""
        if not self._staged:
            return 0, 0
        budget = self.config.stage_budget
        vae_rows = rerank_rows = 0
        order = sorted(
            self._staged,
            key=lambda e: (self._stage[e.request_id] != "clip_rerank",
                           e.seq),
        )
        for entry in order:
            if budget <= 0:
                break
            budget -= 1
            rid = entry.request_id
            if self._stage[rid] == "clip_rerank":
                rerank_rows += 1
                self.counters.inc("serve.stage.reranked")
                self._stage_complete(entry)
            else:
                vae_rows += 1
                self.counters.inc("serve.stage.vae_images")
                if self.config.stage_rerank:
                    self._stage[rid] = "clip_rerank"
                else:
                    self._stage_complete(entry)
        return vae_rows, rerank_rows

    def _stage_complete(self, entry: Entry) -> None:
        hit = self._stage_hit.get(entry.request_id)
        self._stage_remove(entry)
        self.counters.inc("serve.completed")
        self._terminal(entry, Outcome.COMPLETED,
                       detail=f"prefix_hit:{hit}" if hit else "")

    def _terminal(self, entry: Entry, outcome: Outcome,
                  detail: str = "") -> None:
        now = self.clock.now()
        rid = entry.request_id
        self._live.discard(rid)
        if outcome is not Outcome.COMPLETED:
            self.counters.inc(f"serve.{outcome.value}")
        self._outcome_counts[outcome] += 1
        self.results[rid] = RequestResult(
            request_id=rid,
            outcome=outcome,
            tokens=None,
            preempt_count=entry.preempt_count,
            prefill_attempts=entry.prefill_attempts,
            clamped_max_new_tokens=(
                entry.effective_max_new if entry.clamped else None
            ),
            queue_latency_s=(
                None if entry.admit_time is None
                else entry.admit_time - entry.submit_time
            ),
            ttft_s=entry.ttft_s,
            total_latency_s=now - entry.submit_time,
            detail=detail,
        )

    def _reject(self, entry: Entry, reason: RejectReason) -> RequestResult:
        self.counters.inc("serve.rejected")
        self.counters.inc(f"serve.rejected.{reason.value}")
        hint = None
        if reason is RejectReason.QUEUE_FULL:
            occ = (
                self._fleet_occupancy()
                if self._fleet_occupancy is not None
                else self.pool.occupancy
            )
            hint = retry_after_hint(occ)
        result = RequestResult(
            request_id=entry.request_id,
            outcome=Outcome.REJECTED,
            reject_reason=reason,
            total_latency_s=0.0,
            retry_after_s=hint,
        )
        self.results[entry.request_id] = result
        self._outcome_counts[Outcome.REJECTED] += 1
        return result


# -------------------------------------------------------------- workloads


@dataclass(frozen=True)
class Workload:
    """Seeded workload generator spec. Arrivals: ``poisson`` (exponential
    inter-arrival at ``qps``), ``diurnal`` (sinusoidal rate over
    ``period_s``, +/- ``diurnal_amp``), ``burst`` (on/off square wave:
    rate ``qps/duty`` for ``duty`` of each period, near-zero
    otherwise). Templates draw zipf(s) over ``n_templates`` prompt
    templates (the prefix-reuse lever); tenants draw a priority from
    ``priority_weights`` and, with probability ``deadline_frac``, a
    deadline ``deadline_lo..deadline_hi`` seconds out."""

    n_requests: int = 1000
    qps: float = 50.0
    arrival: str = "poisson"            # poisson | diurnal | burst
    period_s: float = 60.0
    diurnal_amp: float = 0.5
    duty: float = 0.25
    n_templates: int = 32
    zipf_s: float = 1.1
    text_len: int = 16
    vocab: int = 15                     # prompt token values in [1, vocab]
    max_new_lo: int = 8
    max_new_hi: int = 24
    priority_weights: Tuple[float, ...] = (0.6, 0.3, 0.1)  # prio 0,1,2
    deadline_frac: float = 0.3
    deadline_lo: float = 2.0
    deadline_hi: float = 10.0
    seed: int = 0


@dataclass
class _Logical:
    """One logical client request across its retry attempts."""

    base: Request
    t_arrival: float
    deadline_window: Optional[float]
    attempt: int = 0
    final: Optional[RequestResult] = None
    final_t: Optional[float] = None     # virtual time the final landed
    retried: int = 0


def _template_prompt(tpl: int, text_len: int, vocab: int) -> np.ndarray:
    # deterministic per-template token row (Weyl-ish hash, no RNG state)
    return np.asarray(
        [((tpl + 1) * 2654435761 + i * 97) % vocab + 1
         for i in range(text_len)],
        np.int32,
    )


def generate_workload(w: Workload) -> List[_Logical]:
    """The seeded arrival stream: a list of logical requests sorted by
    arrival time. Deterministic in ``w.seed`` (replay-consistent
    seeding is asserted in-run)."""
    rng = random.Random(w.seed)
    # zipf CDF over templates
    weights = [1.0 / (k ** w.zipf_s) for k in range(1, w.n_templates + 1)]
    total_w = sum(weights)
    cdf, acc = [], 0.0
    for wt in weights:
        acc += wt / total_w
        cdf.append(acc)
    prompts = [
        _template_prompt(tpl, w.text_len, w.vocab)
        for tpl in range(w.n_templates)
    ]
    import bisect
    out: List[_Logical] = []
    t = 0.0
    for i in range(w.n_requests):
        if w.arrival == "poisson":
            t += rng.expovariate(w.qps)
        elif w.arrival == "diurnal":
            rate = w.qps * (
                1.0 + w.diurnal_amp
                * math.sin(2.0 * math.pi * t / w.period_s)
            )
            t += rng.expovariate(max(rate, w.qps * 0.05))
        elif w.arrival == "burst":
            t += rng.expovariate(w.qps / w.duty)
            if (t % w.period_s) > w.period_s * w.duty:
                # off phase: jump to the next on-window
                t = (t // w.period_s + 1.0) * w.period_s
        else:
            raise ValueError(f"unknown arrival {w.arrival!r}")
        tpl = bisect.bisect_left(cdf, rng.random())
        prio = rng.choices(
            range(len(w.priority_weights)), weights=w.priority_weights,
        )[0]
        window = None
        if rng.random() < w.deadline_frac:
            window = rng.uniform(w.deadline_lo, w.deadline_hi)
        req = Request(
            request_id=f"q{i}",
            prompt=prompts[tpl],
            max_new_tokens=rng.randint(w.max_new_lo, w.max_new_hi),
            deadline=None if window is None else t + window,
            priority=prio,
            seed=w.seed * 100_000 + i,
        )
        out.append(_Logical(base=req, t_arrival=t, deadline_window=window))
    return out


# --------------------------------------------------------------- clients


@dataclass(frozen=True)
class ClientPolicy:
    """Closed-loop client retry model: a load-typed reject or a deadline
    miss re-enters the arrival stream after a backoff. ``honor_hints``
    uses the server's ``retry_after_s`` (jittered by the policy's own
    jitter so honoring a shared hint still desynchronizes); otherwise
    the client backs off on its own ``RetryPolicy.delay`` ladder.
    ``retry.attempts`` is the total attempt budget per logical request
    — exhaustion makes the last typed result final, which is exactly
    how a retry storm turns into lost goodput."""

    retry: RetryPolicy = RetryPolicy(
        attempts=4, base_delay=0.05, max_delay=2.0, jitter=0.5,
        retry_on=(),
    )
    honor_hints: bool = True
    retry_deadline_miss: bool = False
    seed: int = 0

    def backoff(self, attempt: int, hint: Optional[float],
                rng: random.Random) -> float:
        if self.honor_hints and hint is not None:
            d = hint
            if self.retry.jitter > 0.0:
                d *= 1.0 - self.retry.jitter * rng.random()
            return d
        return self.retry.delay(attempt, rng)


# ------------------------------------------------------------ lane driver


class _Watchdog(RuntimeError):
    pass


def run_lane(router, logicals: List[_Logical], policy: ClientPolicy,
             fault_schedule: Optional[List[Tuple[float, str, int]]] = None,
             occupancy_every: int = 64,
             watchdog_iters: int = 200_000) -> dict:
    """Drive one lane to completion: release arrivals and client
    retries against the shared virtual clock, step the router, deliver
    typed results back to the clients, jump idle gaps to the next
    event. Returns the lane record. Raises ``_Watchdog`` on admission
    livelock (no terminal progress for ``watchdog_iters`` fleet
    iterations) — the no-livelock in-run assert."""
    clock = router.clock
    crng = random.Random(policy.seed ^ 0x5EED)
    arrivals = sorted(logicals, key=lambda l: l.t_arrival)
    ai = 0
    retries: List[Tuple[float, int, _Logical]] = []   # heap by due time
    rseq = 0
    outstanding: Dict[str, _Logical] = {}
    pending_final = len(logicals)
    iters = 0
    idle_jumps = 0
    last_progress_iter = 0
    occ_trace: List[Tuple[float, float]] = []
    t0 = clock.now()
    schedule = sorted(fault_schedule or [])
    si = 0

    def submit(lg: _Logical, now: float) -> None:
        nonlocal pending_final
        lg.attempt += 1
        rid = (
            lg.base.request_id if lg.attempt == 1
            else f"{lg.base.request_id}.r{lg.attempt - 1}"
        )
        deadline = None
        if lg.deadline_window is not None:
            deadline = now + lg.deadline_window
        req = replace(
            lg.base, request_id=rid, deadline=deadline,
        )
        rejected = router.submit(req)
        if rejected is None:
            outstanding[rid] = lg
        else:
            deliver(lg, rejected, now)

    def deliver(lg: _Logical, res: RequestResult, now: float) -> None:
        nonlocal pending_final, rseq
        retriable = (
            res.outcome is Outcome.REJECTED
            and res.reject_reason in _RETRIABLE
        ) or (
            policy.retry_deadline_miss
            and res.outcome is Outcome.DEADLINE_EXCEEDED
        )
        if retriable and lg.attempt < max(1, policy.retry.attempts):
            delay = policy.backoff(
                lg.attempt - 1, res.retry_after_s, crng
            )
            lg.retried += 1
            heapq.heappush(retries, (now + delay, rseq, lg))
            rseq += 1
            return
        lg.final = res
        lg.final_t = now
        pending_final -= 1

    while pending_final > 0:
        now = clock.now()
        while si < len(schedule) and schedule[si][0] <= now:
            _, site, n = schedule[si]
            FAULTS.arm(site, n)
            si += 1
        while ai < len(arrivals) and arrivals[ai].t_arrival <= now:
            submit(arrivals[ai], now)
            ai += 1
        while retries and retries[0][0] <= now:
            _, _, lg = heapq.heappop(retries)
            submit(lg, clock.now())
        router.step()
        iters += 1
        # deliver new terminal results (outstanding is bounded by the
        # in-system population, so this poll is cheap)
        if outstanding:
            done = [
                rid for rid in outstanding if rid in router.results
            ]
            for rid in done:
                lg = outstanding.pop(rid)
                deliver(lg, router.results[rid], clock.now())
            if done:
                last_progress_iter = iters
        if iters % occupancy_every == 0:
            occ_trace.append(
                (clock.now() - t0, router.fleet_occupancy())
            )
        if iters % 512 == 0:
            router.verify_invariants()
        if clock.now() <= now:
            # virtual time frozen (idle fleet / dead fleet): jump to the
            # next event — arrival, client retry, breaker readmission,
            # or respawn — the discrete-event skip
            nxt = []
            if ai < len(arrivals):
                nxt.append(arrivals[ai].t_arrival)
            if retries:
                nxt.append(retries[0][0])
            if si < len(schedule):
                nxt.append(schedule[si][0])
            for r in router._replicas:
                if r.respawn_at is not None:
                    nxt.append(r.respawn_at)
                if r.retry_at is not None:
                    nxt.append(r.retry_at)
            if nxt:
                target = min(nxt)
                if target > now:
                    clock.advance(target - now)
                    idle_jumps += 1
                else:
                    clock.advance(1e-4)
            elif outstanding:
                clock.advance(1e-4)
            elif pending_final > 0:
                raise _Watchdog(
                    f"{pending_final} logical requests pending with no "
                    f"future event and an idle fleet"
                )
        if iters - last_progress_iter > watchdog_iters and outstanding:
            raise _Watchdog(
                f"no terminal progress in {watchdog_iters} iterations: "
                f"{len(outstanding)} outstanding"
            )
    router.verify_invariants()
    return _lane_record(router, logicals, occ_trace, clock.now() - t0,
                        iters, idle_jumps)


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    ys = sorted(xs)
    i = min(len(ys) - 1, int(math.ceil(q * len(ys))) - 1)
    return ys[max(0, i)]


def _arena_share(router) -> float:
    """Fraction of fleet pool pages held by prefix templates at end of
    run (modeled lane only; the real engine reports the analogous
    ``serve.prefix_pages`` gauge)."""
    held, total = 0, 0
    for r in router._replicas:
        eng = r.engine
        if not hasattr(eng, "_templates"):
            return 0.0
        held += sum(pages for pages, _ in eng._templates.values())
        total += eng.pool.total
    return (held / total) if total else 0.0


def _lane_record(router, logicals, occ_trace, duration, iters,
                 idle_jumps) -> dict:
    outcomes: Dict[str, int] = {}
    ttfts: List[float] = []
    lat: List[float] = []
    img_lat: List[float] = []
    client_lat: List[float] = []
    hits = 0
    completed = 0
    degraded = 0
    retries_total = 0
    shed = 0
    for lg in logicals:
        res = lg.final
        assert res is not None, lg.base.request_id
        outcomes[res.outcome.value] = outcomes.get(res.outcome.value, 0) + 1
        retries_total += lg.retried
        if res.outcome in (
            Outcome.COMPLETED_TOKENS_ONLY, Outcome.COMPLETED_UNRANKED,
        ):
            # successes of the degradation policy: the request finished
            # typed, it just shed post-decode work under pressure
            degraded += 1
        if res.outcome is Outcome.COMPLETED:
            completed += 1
            if res.ttft_s is not None:
                ttfts.append(res.ttft_s)
            if res.total_latency_s is not None:
                lat.append(res.total_latency_s)
                # with the stage model on, a COMPLETED entry's total
                # latency IS submit -> image (stages precede DONE)
                img_lat.append(res.total_latency_s)
            if lg.final_t is not None:
                # client-perceived: arrival -> final, across every
                # retry and the router queue — the SLO the frontier
                # holds (engine-side ttft_s excludes fleet queueing)
                client_lat.append(lg.final_t - lg.t_arrival)
            if res.detail.startswith("prefix_hit:"):
                hits += 1
        elif (
            res.outcome is Outcome.REJECTED
            and res.reject_reason in _RETRIABLE
        ):
            shed += 1
    stats = router.stats()
    n = len(logicals)
    occs = [o for _, o in occ_trace]
    return {
        "logical_requests": n,
        "router_submitted": stats["submitted"],
        "outcomes": dict(sorted(outcomes.items())),
        "completed": completed,
        # goodput counts every TYPED successful finish — full
        # completions plus the degradation policy's tokens-only/
        # unranked outcomes (shedding stage work must not read as a
        # goodput collapse; the cost of degrading shows in
        # degraded_frac, not here)
        "goodput_qps": (
            (completed + degraded) / duration
        ) if duration > 0 else 0.0,
        "shed_frac": shed / n if n else 0.0,
        "retries": retries_total,
        "ttft_p50_s": _percentile(ttfts, 0.50),
        "ttft_p99_s": _percentile(ttfts, 0.99),
        "latency_p99_s": _percentile(lat, 0.99),
        "request_image_p50_s": _percentile(img_lat, 0.50),
        "request_image_p99_s": _percentile(img_lat, 0.99),
        "degraded_frac": degraded / n if n else 0.0,
        "client_latency_p50_s": _percentile(client_lat, 0.50),
        "client_latency_p99_s": _percentile(client_lat, 0.99),
        "prefix_hit_frac": (hits / completed) if completed else 0.0,
        "arena_share": _arena_share(router),
        "occupancy_mean": (sum(occs) / len(occs)) if occs else 0.0,
        "occupancy_trace": [
            [round(t, 4), round(o, 4)] for t, o in occ_trace[:200]
        ],
        "virtual_s": duration,
        "arrival_span_s": (
            max(lg.t_arrival for lg in logicals)
            - min(lg.t_arrival for lg in logicals)
        ) if logicals else 0.0,
        "fleet_iterations": iters,
        "idle_jumps": idle_jumps,
        "replica_states": router.replica_states(),
    }


# ---------------------------------------------------------- fleet builders


@dataclass(frozen=True)
class FleetSpec:
    """Modeled-lane fleet shape. ``respawn_jitter`` > 0 turns on the
    seeded backoff jitter in the router's respawn/readmission ladders
    (the satellite fix this sim motivates); the storm baseline runs it
    at 0.0 — the historical lockstep schedule."""

    n_replicas: int = 4
    max_batch: int = 32
    queue_limit: int = 256
    text_len: int = 16
    image_seq_len: int = 64
    page: int = 4
    prefix_templates: int = 0
    degraded_max_new_tokens: Optional[int] = None
    respawn: bool = True
    respawn_base_delay: float = 1.0
    respawn_jitter: float = 0.0
    backoff_seed: int = 0
    stall_timeout_s: float = 30.0
    # post-decode stage model knobs (StubEngineConfig passthrough)
    stages: bool = False
    stage_budget: int = 2
    stage_queue_limit: int = 64
    stage_high_watermark: float = 1.0


def build_modeled_router(spec: FleetSpec, cost: IterationCostModel,
                         seed: int = 0):
    """The REAL Router over a StubEngine fleet, via the
    ``engine_factory`` seam. Imported lazily: router pulls in the
    engine module (jax) — the modeled lane pays that import once but
    never traces anything."""
    from dalle_pytorch_tpu.serving.router import Router, RouterConfig

    model = _StubModel(spec.text_len, spec.image_seq_len)
    stub_cfg = StubEngineConfig(
        max_batch=spec.max_batch,
        page=spec.page,
        queue_limit=spec.max_batch,     # router gate keeps it empty
        degraded_max_new_tokens=spec.degraded_max_new_tokens,
        prefill_chunk=spec.text_len,
        prefix_templates=spec.prefix_templates,
        stages=spec.stages,
        stage_budget=spec.stage_budget,
        stage_queue_limit=spec.stage_queue_limit,
        stage_high_watermark=spec.stage_high_watermark,
    )
    builds = [0]                        # respawn generations get new RNGs

    def factory(rid, clock=None, metric_labels=None, fleet_occupancy=None):
        builds[0] += 1
        return StubEngine(
            model, stub_cfg, cost, clock,
            metric_labels=metric_labels,
            fleet_occupancy=fleet_occupancy,
            seed=seed * 7919 + rid * 101 + builds[0],
        )

    cfg = RouterConfig(
        n_replicas=spec.n_replicas,
        queue_limit=spec.queue_limit,
        respawn=spec.respawn,
        respawn_backoff=RetryPolicy(
            attempts=3, base_delay=spec.respawn_base_delay,
            max_delay=60.0, jitter=spec.respawn_jitter, retry_on=(),
        ),
        breaker_backoff=RetryPolicy(
            attempts=5, base_delay=spec.respawn_base_delay,
            max_delay=60.0, jitter=spec.respawn_jitter, retry_on=(),
        ),
        backoff_seed=spec.backoff_seed,
        stall_timeout_s=spec.stall_timeout_s,
    )
    return Router(
        None, None, cfg, engine_config=None,
        clock=FakeClock(), engine_factory=factory,
    )


# -------------------------------------------------------------- scenarios


def run_frontier(spec: FleetSpec, base: Workload, policy: ClientPolicy,
                 qps_levels: List[float], slo_p99_s: float,
                 cost: IterationCostModel, seed: int) -> dict:
    """Sweep offered QPS levels over a fresh fleet each, report the
    capacity frontier: the highest level whose p99 client latency
    (arrival -> final, across retries) holds the SLO with <1% shed,
    plus goodput/shed/occupancy curves. In-run asserts: accounting,
    replay-consistent seeding (level 0 re-run bit-equal), goodput
    monotone-bounded past saturation."""
    levels = []
    for li, qps in enumerate(qps_levels):
        FAULTS.reset()
        w = replace(base, qps=qps, seed=seed + li)
        router = build_modeled_router(spec, cost, seed=seed + li)
        rec = run_lane(router, generate_workload(w), policy)
        rec["offered_qps"] = qps
        levels.append(rec)

    # replay-consistent seeding: the first level, re-run with the same
    # seed, must produce an IDENTICAL record
    FAULTS.reset()
    w0 = replace(base, qps=qps_levels[0], seed=seed)
    router = build_modeled_router(spec, cost, seed=seed)
    rec0 = run_lane(router, generate_workload(w0), policy)
    rec0["offered_qps"] = qps_levels[0]
    assert json.dumps(rec0, sort_keys=True) == json.dumps(
        levels[0], sort_keys=True
    ), "replay with identical seed diverged"

    # goodput monotone-bounded past saturation: never exceeds offered
    # load, and the post-peak tail never collapses below half the peak
    # (a collapse is the retry-storm signature this harness exists to
    # catch)
    peak = max(l["goodput_qps"] for l in levels)
    peak_i = max(range(len(levels)),
                 key=lambda i: levels[i]["goodput_qps"])
    for l in levels:
        # conservation: completions per virtual second never exceed the
        # REALIZED arrival rate (the nominal level plus Poisson variance)
        realized = (
            l["logical_requests"] / l["virtual_s"]
            if l["virtual_s"] > 0 else float("inf")
        )
        assert l["goodput_qps"] <= realized * 1.001, (
            l["offered_qps"], l["goodput_qps"], realized,
        )
    for l in levels[peak_i:]:
        assert l["goodput_qps"] >= 0.5 * peak, (
            f"goodput collapsed past saturation: "
            f"{l['goodput_qps']:.1f} < 0.5 * {peak:.1f} "
            f"at offered {l['offered_qps']}"
        )

    sustainable = None
    for l in levels:
        # the SLO holds on CLIENT-perceived p99 latency (arrival ->
        # final across retries and fleet queueing); engine-side TTFT
        # stays flat under overload because queue wait lands upstream
        p99 = l["client_latency_p99_s"]
        if p99 is not None and p99 <= slo_p99_s and l["shed_frac"] < 0.01:
            sustainable = l["offered_qps"]
    return {
        "slo_p99_ttft_s": slo_p99_s,
        "sustainable_qps": sustainable,
        "peak_goodput_qps": peak,
        "levels": [
            {k: v for k, v in l.items() if k != "occupancy_trace"}
            for l in levels
        ],
    }


def _mttr_snapshot() -> Tuple[int, float]:
    """(count, sum) over every labeled serve.recovery_s series — the
    respawn MTTR histogram the router observes."""
    n, s = 0, 0.0
    for labels in (
        {"replica": str(i)} for i in range(64)
    ):
        h = histograms.get("serve.recovery_s", labels=labels)
        if h is not None:
            n += h.count
            s += h.sum
    return n, s


def run_storm(spec: FleetSpec, base: Workload, sat_qps: float,
              cost: IterationCostModel, seed: int,
              kills: int = 2, respawn_fails: int = 1) -> dict:
    """The retry-storm scenario: 2x saturation offered load, a
    correlated outage (``kills`` replicas crashed back-to-back through
    the ``replica_crash`` chaos site, plus ``replica_fails`` armed
    ``replica_respawn_fail``s to stretch the ladders), run twice:

    * baseline — jitter-free respawn ladders, clients ignoring
      ``retry_after_s`` (the pre-PR behavior);
    * guarded — seeded jitter in the ladders + clients honoring hints.

    Asserts bounded amplification: guarded goodput >= baseline goodput,
    and the guarded run's respawn ladders are desynchronized (distinct
    ladder delays) while the baseline's are lockstep."""
    outage_t = 1.0   # virtual seconds in: fleet is warm and loaded
    schedule = [(outage_t, "replica_crash", kills)]
    if respawn_fails:
        schedule.append((outage_t, "replica_respawn_fail", respawn_fails))

    def one(jitter: float, honor: bool, tag: str) -> dict:
        FAULTS.reset()
        w = replace(base, qps=2.0 * sat_qps, seed=seed)
        pol = ClientPolicy(
            retry=RetryPolicy(
                attempts=5, base_delay=0.02, max_delay=1.0,
                jitter=0.5 if honor else 0.0, retry_on=(),
            ),
            honor_hints=honor, seed=seed,
        )
        sp = replace(
            spec, respawn_jitter=jitter, backoff_seed=seed + 17,
        )
        router = build_modeled_router(sp, cost, seed=seed)
        # observe the ladder the outage schedules: capture per-replica
        # rung delays (respawn_at - now at scheduling time) as they
        # appear — the lockstep-vs-desynchronized evidence
        delays: Dict[int, List[float]] = {}
        orig_sched = router._schedule_respawn_locked

        def spy(r):
            before = router.clock.now()
            orig_sched(r)
            if r.respawn_at is not None:
                delays.setdefault(r.id, []).append(
                    r.respawn_at - before
                )
        router._schedule_respawn_locked = spy
        rec = run_lane(router, generate_workload(w), pol,
                       fault_schedule=schedule)
        rec["offered_qps"] = 2.0 * sat_qps
        # storm goodput: completions over the DEMAND window. Dividing
        # by full run duration would punish hint-honoring clients for
        # waiting out the outage and reward a baseline that sheds fast
        # and finishes early — the opposite of the guard's point.
        rec["storm_goodput_qps"] = (
            rec["completed"] / rec["arrival_span_s"]
            if rec["arrival_span_s"] > 0 else 0.0
        )
        rec["ladder_first_rung_s"] = [
            round(delays[rid][0], 6) for rid in sorted(delays)
        ]
        rec["tag"] = tag
        return rec

    m0 = _mttr_snapshot()
    baseline = one(jitter=0.0, honor=False, tag="baseline")
    guarded = one(jitter=0.5, honor=True, tag="jitter+hints")
    m1 = _mttr_snapshot()

    # desynchronization: first-rung delays all equal without jitter,
    # distinct with it (no lockstep re-collision)
    b_first = baseline["ladder_first_rung_s"][:kills]
    g_first = guarded["ladder_first_rung_s"][:kills]
    assert len(set(b_first)) <= 1, (
        f"baseline ladders unexpectedly jittered: {b_first}"
    )
    if kills >= 2:
        assert len(set(g_first)) == len(g_first), (
            f"jittered ladders still lockstep: {g_first}"
        )
    assert guarded["completed"] >= baseline["completed"], (
        f"storm amplification guard failed: jitter+hints completed "
        f"{guarded['completed']} < baseline {baseline['completed']}"
    )
    assert guarded["storm_goodput_qps"] >= baseline["storm_goodput_qps"], (
        f"storm amplification guard failed: jitter+hints goodput "
        f"{guarded['storm_goodput_qps']:.2f} < baseline "
        f"{baseline['storm_goodput_qps']:.2f}"
    )
    respawns = m1[0] - m0[0]
    mttr = ((m1[1] - m0[1]) / respawns) if respawns else None
    return {
        "offered_qps": 2.0 * sat_qps,
        "kills": kills,
        "respawn_fails_armed": respawn_fails,
        "respawns_observed": respawns,
        "mttr_mean_s": mttr,
        "baseline": {
            k: v for k, v in baseline.items() if k != "occupancy_trace"
        },
        "guarded": {
            k: v for k, v in guarded.items() if k != "occupancy_trace"
        },
    }


# --------------------------------------------------------- fidelity lane

# modeled-vs-real tolerance contract (docs/DESIGN.md §8.4): the modeled
# lane must predict the real tiny-model fleet's aggregates within these
FIDELITY_TOL = {
    "shed_frac_abs": 0.10,
    "ttft_p99_rel": 0.50,
    "occupancy_abs": 0.15,
}


def run_fidelity(n_requests: int = 600, seed: int = 0,
                 step_dt: float = 0.004,
                 qps: float = 40.0) -> dict:
    """Cross-validate the lanes: the REAL tiny-model engine fleet on a
    ``FakeClock(step_dt)`` versus a StubEngine fleet matched to it
    (same page geometry, batch, queue, chunking — introspected off a
    real replica; every iteration charged exactly ``step_dt``, the
    ``FakeClock.tick`` semantics). Same workload, same seed, same
    closed-loop clients. Asserts the modeled lane's shed fraction, p99
    TTFT and mean occupancy within ``FIDELITY_TOL``."""
    from serve_smoke import build_tiny_model

    from dalle_pytorch_tpu.serving import (
        EngineConfig, Router, RouterConfig,
    )

    dalle, params = build_tiny_model()
    n_replicas = 2
    ecfg = EngineConfig(max_batch=2, prefill_chunk=2)
    rcfg = RouterConfig(n_replicas=n_replicas, queue_limit=64)
    w = Workload(
        n_requests=n_requests, qps=qps, arrival="poisson",
        n_templates=8, text_len=dalle.text_seq_len,
        vocab=dalle.num_text_tokens - 1,
        max_new_lo=2, max_new_hi=dalle.image_seq_len,
        deadline_frac=0.0, seed=seed,
    )
    pol = ClientPolicy(seed=seed)

    # real lane
    FAULTS.reset()
    real_router = Router(
        dalle, params, rcfg, ecfg, clock=FakeClock(step_dt=step_dt),
    )
    proto = real_router._replicas[0].engine
    real = run_lane(real_router, generate_workload(w), pol)

    # modeled lane, matched to the real replica's geometry
    model = _StubModel(proto.T, dalle.image_seq_len)
    stub_cfg = StubEngineConfig(
        max_batch=ecfg.max_batch,
        page=proto.page,
        page_budget=proto.pool.total,
        queue_limit=ecfg.queue_limit,
        high_watermark=ecfg.high_watermark,
        degraded_max_new_tokens=ecfg.degraded_max_new_tokens,
        max_preemptions=ecfg.max_preemptions,
        prefill_chunk=ecfg.prefill_chunk,
        token_budget=ecfg.token_budget,
    )
    cost = IterationCostModel.matched(step_dt)

    def factory(rid, clock=None, metric_labels=None,
                fleet_occupancy=None):
        return StubEngine(
            model, stub_cfg, cost, clock,
            metric_labels=metric_labels,
            fleet_occupancy=fleet_occupancy, seed=seed,
        )

    FAULTS.reset()
    stub_router = Router(
        None, None, rcfg, engine_config=None,
        clock=FakeClock(), engine_factory=factory,
    )
    modeled = run_lane(stub_router, generate_workload(w), pol)

    diffs = {
        "shed_frac_abs": abs(
            modeled["shed_frac"] - real["shed_frac"]
        ),
        "occupancy_abs": abs(
            modeled["occupancy_mean"] - real["occupancy_mean"]
        ),
    }
    if real["ttft_p99_s"] and modeled["ttft_p99_s"]:
        diffs["ttft_p99_rel"] = (
            abs(modeled["ttft_p99_s"] - real["ttft_p99_s"])
            / real["ttft_p99_s"]
        )
    for key, tol in FIDELITY_TOL.items():
        if key in diffs:
            assert diffs[key] <= tol, (
                f"fidelity divergence: {key} = {diffs[key]:.4f} > "
                f"tolerance {tol} (modeled "
                f"{modeled.get(key.split('_abs')[0].split('_rel')[0])} "
                f"vs real)"
            )
    strip = lambda r: {
        k: v for k, v in r.items() if k != "occupancy_trace"
    }
    return {
        "n_requests": n_requests,
        "step_dt": step_dt,
        "offered_qps": qps,
        "tolerances": dict(FIDELITY_TOL),
        "diffs": {k: round(v, 6) for k, v in diffs.items()},
        "real": strip(real),
        "modeled": strip(modeled),
    }


# ----------------------------------------------------------- mode records


def _mode_record(mode: str, seed: int) -> dict:
    """BENCH-style record skeleton (tools/bench.py convention: one
    self-describing JSON object per run, committed next to the code it
    measures)."""
    return {
        "tool": "traffic_sim",
        "schema": 1,
        "mode": mode,
        "seed": seed,
        "cost_model": {
            "decode_ms_per_token": IterationCostModel.decode_ms_per_token,
            "prefill_ms_per_token": IterationCostModel.prefill_ms_per_token,
            "fixed_overhead_ms": IterationCostModel.fixed_overhead_ms,
            "source": "BENCH_r04 / ROADMAP: ~1.0 ms/token bf16 decode v5e",
        },
    }


def _count_requests(frontier: dict, storm: Optional[dict]) -> int:
    n = sum(l["logical_requests"] for l in frontier["levels"])
    n += frontier["levels"][0]["logical_requests"]   # the replay re-run
    if storm is not None:
        n += storm["baseline"]["logical_requests"]
        n += storm["guarded"]["logical_requests"]
    return n


def run_modeled(mode: str, seed: int) -> dict:
    """The modeled-lane scenario suite at one of three sizes:

    * ``smoke``  — seconds; the fast-tier subprocess gate.
    * ``quick``  — >=100k logical requests through a 4-replica fleet,
      frontier + storm, <60s wall on CPU (asserted).
    * ``sweep``  — the full grid: every arrival shape, prefix-template
      mix on, a wider QPS ladder (slow tier).
    """
    t_wall = time.monotonic()
    cost = IterationCostModel()
    if mode == "smoke":
        spec = FleetSpec(n_replicas=4, max_batch=8, queue_limit=64)
        base = Workload(n_requests=1_500, n_templates=16)
        qps_levels = [30.0, 70.0]
        storm_kills = spec.n_replicas       # full-fleet correlated outage
    elif mode == "quick":
        spec = FleetSpec(n_replicas=4, max_batch=16, queue_limit=256)
        base = Workload(n_requests=16_000, max_new_lo=8, max_new_hi=16)
        qps_levels = [50.0, 65.0, 80.0, 95.0, 110.0]
        storm_kills = spec.n_replicas
    elif mode == "sweep":
        spec = FleetSpec(
            n_replicas=4, max_batch=32, queue_limit=256,
            prefix_templates=16,
        )
        base = Workload(n_requests=24_000)
        qps_levels = [30.0, 40.0, 50.0, 60.0, 70.0, 80.0, 90.0]
        storm_kills = spec.n_replicas
    else:
        raise ValueError(f"unknown mode {mode!r}")

    policy = ClientPolicy(seed=seed)
    frontier = run_frontier(
        spec, base, policy, qps_levels, slo_p99_s=2.0,
        cost=cost, seed=seed,
    )
    sat = frontier["sustainable_qps"] or frontier["peak_goodput_qps"]
    storm_base = replace(base, n_requests=max(
        1_000, base.n_requests // 3
    ))
    storm = run_storm(
        spec, storm_base, sat_qps=sat, cost=cost, seed=seed,
        kills=storm_kills, respawn_fails=1,
    )

    # post-decode stage frontier (DESIGN §8.5): the same capacity sweep
    # with per-image VAE/CLIP stage rows charged to the clock and the
    # pipeline's pressure degradation armed (watermark 0.95), including
    # a 2x-overload level that must finish TYPED — request->image p99
    # and the degraded fraction are the columns this adds
    stage_cost = replace(
        cost, vae_ms_per_image=4.0, rerank_ms_per_image=2.0,
    )
    # stage_budget=1 caps the pipeline at one row (half a completion)
    # per iteration while short token jobs finish >1 per iteration at
    # saturation — overload overflows the small stage backlog and the
    # policy must shed TYPED, not queue unboundedly
    stage_spec = replace(
        spec, stages=True, stage_budget=1, stage_queue_limit=8,
        stage_high_watermark=0.95,
    )
    stage_base = replace(
        base, n_requests=min(base.n_requests, 4_000),
        max_new_lo=4, max_new_hi=8,
    )
    stage_frontier = run_frontier(
        stage_spec, stage_base, policy,
        [qps_levels[0], 2.0 * qps_levels[-1]], slo_p99_s=2.0,
        cost=stage_cost, seed=seed + 3,
    )
    over = stage_frontier["levels"][-1]
    assert over["degraded_frac"] > 0.0, (
        "2x overload never tripped the stage degradation policy: "
        f"{over}"
    )
    assert over["request_image_p99_s"] is not None, over

    rec = _mode_record(mode, seed)
    rec["fleet"] = {
        "n_replicas": spec.n_replicas,
        "max_batch": spec.max_batch,
        "queue_limit": spec.queue_limit,
        "prefix_templates": spec.prefix_templates,
    }
    rec["frontier"] = frontier
    rec["storm"] = storm
    rec["stage_frontier"] = stage_frontier
    n_total = _count_requests(frontier, storm)
    n_total += _count_requests(stage_frontier, None)
    rec["totals"] = {
        "modeled_requests": n_total,
        "wall_s": round(time.monotonic() - t_wall, 3),
    }
    rec["asserts"] = [
        "typed_accounting_100pct",
        "replay_consistent_seeding",
        "goodput_bounded_past_saturation",
        "storm_amplification_guard",
        "respawn_ladder_desynchronized",
        "stage_overload_degrades_typed",
    ]
    if mode == "sweep":
        # the grid rides on top: one frontier per arrival shape
        rec["arrival_grid"] = {}
        for shape in ("diurnal", "burst"):
            shaped = replace(
                base, arrival=shape,
                n_requests=base.n_requests // 2,
            )
            f = run_frontier(
                spec, shaped, policy, qps_levels[1::2],
                slo_p99_s=2.0, cost=cost, seed=seed + 1,
            )
            rec["arrival_grid"][shape] = f
            rec["totals"]["modeled_requests"] += _count_requests(f, None)
    if mode == "quick":
        assert rec["totals"]["modeled_requests"] >= 100_000, rec["totals"]
        assert spec.n_replicas >= 4
        assert rec["totals"]["wall_s"] < 60.0, (
            f"quick mode exceeded its wall budget: "
            f"{rec['totals']['wall_s']}s"
        )
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
    )
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--smoke", action="store_true",
                   help="seconds-scale gate (fast tier)")
    g.add_argument("--quick", action="store_true",
                   help=">=100k modeled requests, <60s")
    g.add_argument("--sweep", action="store_true",
                   help="full frontier grid (slow tier)")
    g.add_argument("--fidelity", type=int, metavar="N", default=None,
                   help="cross-validate lanes on N real requests")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=Path, default=None,
                    help="write the record JSON here (default stdout)")
    args = ap.parse_args(argv)

    if args.fidelity is not None:
        rec = _mode_record("fidelity", args.seed)
        rec["fidelity"] = run_fidelity(
            n_requests=args.fidelity, seed=args.seed,
        )
        ok = "lanes agree within tolerance"
    else:
        mode = (
            "smoke" if args.smoke else "quick" if args.quick else "sweep"
        )
        rec = run_modeled(mode, args.seed)
        ok = (
            f"{rec['totals']['modeled_requests']} modeled requests, "
            f"wall {rec['totals']['wall_s']}s, sustainable "
            f"{rec['frontier']['sustainable_qps']} qps"
        )
    text = json.dumps(rec, indent=2, sort_keys=True)
    if args.out is not None:
        args.out.write_text(text + "\n")
        print(f"traffic sim: wrote {args.out} ({ok})")
    else:
        print(text)
        print(f"traffic sim: OK ({ok})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
