#!/usr/bin/env python
"""Digest a jax.profiler trace into per-category / per-op device-time tables.

The measured-time complement to ``bench.py --breakdown`` (which charges
FLOPs from the compiled HLO): capture a trace with
``train_dalle.py --profile_trace_dir DIR`` (or jax.profiler directly), then

    python tools/analyze_trace.py DIR [--module NAME] [--top N]

reads the Chrome-format ``*.trace.json.gz`` the profiler writes (no
tensorboard needed), picks the longest-running XLA module (or the one
matching --module), and prints device time by HLO category and by
deduplicated op family — e.g. on the flagship train step this shows the
dense matmuls at ~86% of peak, the pallas attention custom-calls, and the
elementwise/optimizer tail (the numbers that motivated, and then bounded,
the round-4 kernel work).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import sys


def load_trace(path: str) -> list:
    files = sorted(glob.glob(path + "/**/*.trace.json.gz", recursive=True))
    if not files:
        files = sorted(glob.glob(path)) if path.endswith(".gz") else []
    if not files:
        sys.exit(f"no *.trace.json.gz under {path}")
    with gzip.open(files[-1]) as f:
        return json.load(f)["traceEvents"]


def analyze(events: list, module: str | None, top: int) -> str:
    lanes = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            lanes[(e["pid"], e["tid"])] = e["args"].get("name", "")

    mods = [
        e for e in events
        if e.get("ph") == "X" and lanes.get((e.get("pid"), e.get("tid"))) == "XLA Modules"
        and (module is None or module in e.get("name", ""))
    ]
    if not mods:
        return "no XLA module executions in trace" + (
            f" matching {module!r}" if module else ""
        )
    target = max(mods, key=lambda m: m["dur"])
    t0, t1 = target["ts"], target["ts"] + target["dur"]

    cats: collections.Counter = collections.Counter()
    fams: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or lanes.get((e.get("pid"), e.get("tid"))) != "XLA Ops":
            continue
        # multi-device traces have one lane set per device (pid): only the
        # target module's own device may be charged, or N devices' ops
        # stack into one window and shares exceed 100%
        if e.get("pid") != target.get("pid"):
            continue
        if e["ts"] < t0 or e["ts"] >= t1:
            continue
        args = e.get("args", {})
        cat = args.get("hlo_category", "?")
        if cat == "while":
            continue  # wrapper op: its children are counted individually
        cats[cat] += e["dur"]
        fam = (args.get("deduplicated_name") or e["name"]).split(".")[0]
        fams[fam] += e["dur"]

    span = target["dur"] / 1e3
    lines = [f"module {target['name'][:70]}  span {span:.2f} ms", ""]
    lines.append(f"{'HLO category':<28}{'ms':>10}{'share':>8}")
    lines.append("-" * 46)
    for c, d in cats.most_common(top):
        lines.append(f"{c:<28}{d / 1e3:>10.2f}{d / 1e3 / span:>8.1%}")
    lines.append("")
    lines.append(f"{'op family (deduplicated)':<28}{'ms':>10}{'share':>8}")
    lines.append("-" * 46)
    for n, d in fams.most_common(top):
        lines.append(f"{n:<28}{d / 1e3:>10.2f}{d / 1e3 / span:>8.1%}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", help="profiler output dir (or a .trace.json.gz)")
    ap.add_argument("--module", default=None,
                    help="substring of the XLA module to analyze "
                         "(default: longest execution)")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args()
    print(analyze(load_trace(args.trace_dir), args.module, args.top))


if __name__ == "__main__":
    main()
