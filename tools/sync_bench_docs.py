#!/usr/bin/env python
"""Regenerate the headline perf lines in the docs from the benchmark record.

Single source of truth: ``docs/BENCH_LATEST.jsonl`` — the metric lines a
``python bench.py`` run prints (refresh it with
``python bench.py | grep '^{' > docs/BENCH_LATEST.jsonl`` on the TPU box).
This script rewrites the marked blocks in README.md, PARITY.md and
docs/DESIGN.md from that record so the prose can never drift from the
measurement (the round-4 advisor found three documents citing three
different rounds' numbers). ``tests/test_docs_numbers.py`` asserts the
blocks match, so a stale doc fails the suite instead of shipping.

    python tools/sync_bench_docs.py          # rewrite the docs
    python tools/sync_bench_docs.py --check  # exit 1 if any doc is stale
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RECORD = REPO / "docs" / "BENCH_LATEST.jsonl"

BEGIN = "<!-- bench:generated (tools/sync_bench_docs.py; do not hand-edit) -->"
END = "<!-- bench:end -->"


def load_metrics() -> dict:
    if not RECORD.exists():
        sys.exit(
            f"{RECORD} missing — refresh it on the TPU box with:\n"
            "  python bench.py | grep '^{' > docs/BENCH_LATEST.jsonl"
        )
    metrics = {}
    for line in RECORD.read_text().splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        row = json.loads(line)
        metrics[row["metric"]] = row
    return metrics


def _fmt_pct(x: float) -> str:
    return f"{100.0 * x:.1f}%"


def render_readme(m: dict) -> str:
    mfu = m["train_mfu_dalle_depth12_dim1024_seq1280_1chip"]
    gen = m["gen_latency_p50_image1024_tokens_1chip"]
    gen8 = m["gen_latency_p50_image1024_tokens_1chip_int8"]
    lines = [
        f"On one {mfu['device']} chip: **{_fmt_pct(mfu['value'])} MFU** "
        f"({mfu['vs_baseline']:.2f}x the 45% target; "
        f"{mfu['step_time_ms']:.0f} ms/step, "
        f"{mfu['samples_per_sec']:.0f} samples/sec), "
        f"**{gen['ms_per_token']:.2f} ms/token** bf16 generation and "
        f"**{gen8['ms_per_token']:.2f} ms/token** with `--int8` weight-only "
        f"quantized serving."
    ]
    tp = sorted(
        (m[k] for k in m if k.startswith("gen_throughput_tokens_per_sec")),
        key=lambda r: r["batch"],
    )
    if tp:
        parts = ", ".join(
            f"{r['value']:,.0f} tok/s at batch {r['batch']} "
            f"({r['scaling_vs_batch1']:.1f}x batch-1)" for r in tp
        )
        lines.append(f"Batched int8 serving: {parts}.")
    vae = m.get("train_vae_step_time_img128_l3_r2_batch8")
    clip = m.get("train_clip_step_time_dim512_d6x6_img256_batch16")
    if vae and clip:
        lines.append(
            f"The other trainers: DiscreteVAE {vae['value']:.1f} ms/step "
            f"({vae['achieved_tflops']:.0f} TF/s, "
            f"{vae['samples_per_sec']:.0f} samples/sec) and CLIP "
            f"{clip['value']:.1f} ms/step ({clip['achieved_tflops']:.0f} TF/s) "
            f"at their reference-default configs in bf16."
        )
    return "\n".join(lines)


def render_parity(m: dict) -> str:
    mfu = m["train_mfu_dalle_depth12_dim1024_seq1280_1chip"]
    gen = m["gen_latency_p50_image1024_tokens_1chip"]
    gen8 = m["gen_latency_p50_image1024_tokens_1chip_int8"]
    return (
        f"  (bf16 and int8 serving). Latest single-chip {mfu['device']}: "
        f"**{_fmt_pct(mfu['value'])} MFU** (target >=45%), "
        f"**{gen['value'] / 1e3:.2f} s** p50 for 1024 image tokens "
        f"({gen['ms_per_token']:.2f} ms/token bf16, "
        f"**{gen8['ms_per_token']:.2f} ms/token int8**)."
    )


def render_design(m: dict) -> str:
    gen = m["gen_latency_p50_image1024_tokens_1chip"]
    gen8 = m["gen_latency_p50_image1024_tokens_1chip_int8"]
    return (
        f"Measured on one chip ({gen['device']}): "
        f"{gen['ms_per_token']:.2f} ms/token bf16, "
        f"{gen8['ms_per_token']:.2f} ms/token int8."
    )


TARGETS = {
    REPO / "README.md": render_readme,
    REPO / "PARITY.md": render_parity,
    REPO / "docs" / "DESIGN.md": render_design,
}


def sync(check: bool) -> int:
    metrics = load_metrics()
    stale = []
    for path, render in TARGETS.items():
        text = path.read_text()
        pattern = re.compile(
            re.escape(BEGIN) + r"\n.*?" + re.escape(END), re.DOTALL
        )
        if not pattern.search(text):
            print(f"ERROR: {path.name} has no bench block markers", file=sys.stderr)
            return 2
        try:
            block = f"{BEGIN}\n{render(metrics)}\n{END}"
        except KeyError as e:
            sys.exit(
                f"{RECORD.name} is missing metric {e} needed by {path.name} — "
                "it must come from a FULL `python bench.py` run, not a "
                "single-section (--patterns/--vae/...) capture"
            )
        new = pattern.sub(lambda _m: block, text, count=1)
        if new != text:
            if check:
                stale.append(path.name)
            else:
                path.write_text(new)
                print(f"updated {path.name}")
    if check and stale:
        print(
            f"stale bench numbers in: {', '.join(stale)} — run "
            "tools/sync_bench_docs.py",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(sync(check="--check" in sys.argv))
