#!/usr/bin/env python
"""Generate the vendored pretrained-checkpoint manifests.

A manifest is the frozen key -> (shape, dtype) inventory of a published
checkpoint the reference's default ``train_dalle.py`` path consumes:

- OpenAI dVAE ``encoder.pkl`` / ``decoder.pkl``
  (reference vae.py:29-30,107-108; architecture from the public
  github.com/openai/DALL-E ``encoder.py``/``decoder.py``), and
- taming-transformers VQGAN imagenet f=16 / 1024-codebook ``last.ckpt`` +
  ``model.yaml`` (reference vae.py:150-174; architecture from the public
  CompVis/taming-transformers ``model.py``/``vqgan.py`` driven by the
  published ddconfig).

Two modes:

- default: derive the inventory from the architecture itself — the channel
  arithmetic below is written out in torch conventions (OIHW convs,
  ``weight``/``bias`` leaves) INDEPENDENTLY of this package's flax modules,
  so the manifest tests in tests/test_ckpt_manifest.py genuinely cross-check
  the converters rather than comparing the converters to themselves;
- ``--from-real DIR``: regenerate from the actual downloaded files
  (DIR/encoder.pkl, DIR/decoder.pkl, DIR/last.ckpt) and fail LOUDLY if the
  result differs from the architecture-derived manifest. Run this whenever
  the published files are available to re-certify the vendored JSONs.

Output: dalle_pytorch_tpu/models/ckpt_manifests/*.json
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

OUT_DIR = (
    Path(__file__).resolve().parent.parent
    / "dalle_pytorch_tpu" / "models" / "ckpt_manifests"
)


def _conv(keys: dict, name: str, cin: int, cout: int, k: int, leaf_w="w", leaf_b="b"):
    keys[f"{name}.{leaf_w}"] = {"shape": [cout, cin, k, k], "dtype": "float32"}
    keys[f"{name}.{leaf_b}"] = {"shape": [cout], "dtype": "float32"}


def openai_dvae_manifest(kind: str) -> dict:
    """OpenAI dVAE module state dict. Encoder: 7x7 input conv, 4 groups x 2
    bottleneck blocks (res path 3,3,3,1 kernels; 1x1 id_path on channel
    change), maxpool between groups, relu + 1x1 conv to 8192 logits.
    Decoder mirrors it: 1x1 input conv from the one-hot, res path 1,3,3,3
    kernels, nearest-2x upsample between groups, 1x1 conv to 2*3 stats."""
    n_hid, vocab, n_blk = 256, 8192, 2
    keys: dict = {}
    if kind == "encoder":
        _conv(keys, "blocks.input", 3, n_hid, 7)
        cin = n_hid
        for g, mult in enumerate((1, 2, 4, 8), start=1):
            cout = mult * n_hid
            for b in range(1, n_blk + 1):
                p = f"blocks.group_{g}.block_{b}"
                if cin != cout:
                    _conv(keys, f"{p}.id_path", cin, cout, 1)
                hid = cout // 4
                _conv(keys, f"{p}.res_path.conv_1", cin, hid, 3)
                _conv(keys, f"{p}.res_path.conv_2", hid, hid, 3)
                _conv(keys, f"{p}.res_path.conv_3", hid, hid, 3)
                _conv(keys, f"{p}.res_path.conv_4", hid, cout, 1)
                cin = cout
        _conv(keys, "blocks.output.conv", 8 * n_hid, vocab, 1)
    else:
        n_init = 128
        _conv(keys, "blocks.input", vocab, n_init, 1)
        cin = n_init
        for g, mult in enumerate((8, 4, 2, 1), start=1):
            cout = mult * n_hid
            for b in range(1, n_blk + 1):
                p = f"blocks.group_{g}.block_{b}"
                if cin != cout:
                    _conv(keys, f"{p}.id_path", cin, cout, 1)
                hid = cout // 4
                _conv(keys, f"{p}.res_path.conv_1", cin, hid, 1)
                _conv(keys, f"{p}.res_path.conv_2", hid, hid, 3)
                _conv(keys, f"{p}.res_path.conv_3", hid, hid, 3)
                _conv(keys, f"{p}.res_path.conv_4", hid, cout, 3)
                cin = cout
        _conv(keys, "blocks.output.conv", n_hid, 2 * 3, 1)
    return keys


# the published imagenet f=16 / 1024 model.yaml (reference vae.py:155-158)
VQGAN_F16_1024_CONFIG = {
    "target": "taming.models.vqgan.VQModel",
    "n_embed": 1024,
    "embed_dim": 256,
    "ddconfig": {
        "double_z": False,
        "z_channels": 256,
        "resolution": 256,
        "in_channels": 3,
        "out_ch": 3,
        "ch": 128,
        "ch_mult": [1, 1, 2, 2, 4],
        "num_res_blocks": 2,
        "attn_resolutions": [16],
        "dropout": 0.0,
    },
}


def vqgan_manifest(cfg: dict = VQGAN_F16_1024_CONFIG) -> dict:
    """taming VQModel ``state_dict`` (model keys only — the published
    last.ckpt also carries ``loss.*`` LPIPS/discriminator weights the
    inference wrapper skips). Norms are GroupNorm(32) with 1-d
    weight/bias; convs are 3x3 pad-1 except the marked 1x1s."""
    dd = cfg["ddconfig"]
    ch, ch_mult = dd["ch"], list(dd["ch_mult"])
    nrb, attn_res = dd["num_res_blocks"], set(dd["attn_resolutions"])
    z, res = dd["z_channels"], dd["resolution"]
    keys: dict = {}

    def norm(name, c):
        keys[f"{name}.weight"] = {"shape": [c], "dtype": "float32"}
        keys[f"{name}.bias"] = {"shape": [c], "dtype": "float32"}

    def conv(name, cin, cout, k):
        _conv(keys, name, cin, cout, k, leaf_w="weight", leaf_b="bias")

    def resnet(prefix, cin, cout):
        norm(f"{prefix}.norm1", cin)
        conv(f"{prefix}.conv1", cin, cout, 3)
        norm(f"{prefix}.norm2", cout)
        conv(f"{prefix}.conv2", cout, cout, 3)
        if cin != cout:
            conv(f"{prefix}.nin_shortcut", cin, cout, 1)
        return cout

    def attn(prefix, c):
        norm(f"{prefix}.norm", c)
        for p in ("q", "k", "v", "proj_out"):
            conv(f"{prefix}.{p}", c, c, 1)

    # ----- encoder
    conv("encoder.conv_in", dd["in_channels"], ch, 3)
    cur, cur_res = ch, res
    for i, mult in enumerate(ch_mult):
        cout = ch * mult
        for j in range(nrb):
            cur = resnet(f"encoder.down.{i}.block.{j}", cur, cout)
            if cur_res in attn_res:
                attn(f"encoder.down.{i}.attn.{j}", cout)
        if i != len(ch_mult) - 1:
            conv(f"encoder.down.{i}.downsample.conv", cout, cout, 3)
            cur_res //= 2
    norm("encoder.norm_out", cur)
    conv("encoder.conv_out", cur, (2 if dd["double_z"] else 1) * z, 3)

    # ----- decoder
    block_in = ch * ch_mult[-1]
    cur_res = res // 2 ** (len(ch_mult) - 1)
    conv("decoder.conv_in", z, block_in, 3)
    cur = block_in
    cur = resnet("decoder.mid.block_1", cur, cur)
    attn("decoder.mid.attn_1", cur)
    cur = resnet("decoder.mid.block_2", cur, cur)
    for i in reversed(range(len(ch_mult))):
        cout = ch * ch_mult[i]
        for j in range(nrb + 1):
            cur = resnet(f"decoder.up.{i}.block.{j}", cur, cout)
            if cur_res in attn_res:
                attn(f"decoder.up.{i}.attn.{j}", cout)
        if i != 0:
            conv(f"decoder.up.{i}.upsample.conv", cout, cout, 3)
            cur_res *= 2
    norm("decoder.norm_out", cur)
    conv("decoder.conv_out", cur, dd["out_ch"], 3)

    # ----- encoder mid (appended here to keep the walk readable above)
    block_in = ch * ch_mult[-1]
    resnet("encoder.mid.block_1", block_in, block_in)
    attn("encoder.mid.attn_1", block_in)
    resnet("encoder.mid.block_2", block_in, block_in)

    # ----- quantizer + couplers
    keys["quantize.embedding.weight"] = {
        "shape": [cfg["n_embed"], cfg["embed_dim"]], "dtype": "float32"
    }
    conv("quant_conv", z, cfg["embed_dim"], 1)
    conv("post_quant_conv", cfg["embed_dim"], z, 1)
    return keys


def write_manifests():
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    out = {
        "openai_dvae_encoder.json": openai_dvae_manifest("encoder"),
        "openai_dvae_decoder.json": openai_dvae_manifest("decoder"),
        "vqgan_f16_1024.json": {
            "config": VQGAN_F16_1024_CONFIG,
            "state_dict": vqgan_manifest(),
        },
    }
    for name, data in out.items():
        path = OUT_DIR / name
        path.write_text(json.dumps(data, indent=1, sort_keys=True) + "\n")
        n = len(data.get("state_dict", data))
        print(f"wrote {path} ({n} keys)")


def check_against_real(real_dir: str):
    """Regenerate from the real files and diff against the derived manifest
    (run wherever the published checkpoints are available)."""
    import numpy as np

    from dalle_pytorch_tpu.models.pretrained import load_torch_checkpoint

    def inventory(sd):
        return {
            k: {"shape": list(np.asarray(v).shape), "dtype": str(np.asarray(v).dtype)}
            for k, v in sd.items()
        }

    def diff(actual, derived):
        """Human-diagnosable differences: missing/extra keys AND per-key
        shape/dtype drift (a same-key resized or fp16-stored tensor must
        be reported, not just detected)."""
        out = []
        for k in sorted(set(actual) | set(derived)):
            if k not in actual:
                out.append(f"missing from real: {k}")
            elif k not in derived:
                out.append(f"unexpected in real: {k}")
            elif actual[k] != derived[k]:
                out.append(f"{k}: real {actual[k]} != manifest {derived[k]}")
        return out

    real = Path(real_dir)
    problems = []
    for fname, derived in (
        ("encoder.pkl", openai_dvae_manifest("encoder")),
        ("decoder.pkl", openai_dvae_manifest("decoder")),
    ):
        d = diff(inventory(load_torch_checkpoint(str(real / fname))), derived)
        if d:
            problems.append((fname, d))
    ckpt = real / "last.ckpt"
    if ckpt.exists():
        actual = {
            k: v for k, v in inventory(load_torch_checkpoint(str(ckpt))).items()
            if not k.startswith("loss.")
        }
        d = diff(actual, vqgan_manifest())
        if d:
            problems.append(("last.ckpt", d))
    if problems:
        for fname, d in problems:
            print(f"MISMATCH {fname} ({len(d)} differences):")
            for line in d[:30]:
                print(f"  {line}")
        raise SystemExit(1)
    print("real checkpoints match the derived manifests (shapes AND dtypes)")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--from-real", default=None, metavar="DIR",
                    help="directory holding encoder.pkl / decoder.pkl / "
                         "last.ckpt to re-certify the manifests against")
    args = ap.parse_args()
    if args.from_real:
        check_against_real(args.from_real)
    write_manifests()
