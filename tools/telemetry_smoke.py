#!/usr/bin/env python
"""Telemetry release gate: serve_smoke's 3-request scenario with the
flight recorder on, then validate every observability artifact.

Runs ``tools/serve_smoke.py``'s continuous-batching pass in-process with
telemetry enabled, drains the ring, and checks the three contracts a
release needs (docs/DESIGN.md §9):

1. the flight-recorder JSONL parses line-for-line and its spans BALANCE
   (every ``E`` matches a prior ``B``; nothing left open after a clean
   run; zero ring drops);
2. every serving request appears as a ``serve.request`` span chain
   ending in a typed outcome that sums to the engine's own counters —
   including the CHUNKED-prefill pass, whose ``serve.prefill_chunk``
   spans and ``serve.ttft_s`` histogram must be present, and the
   prefix-cache cold/warm replay, whose warm full-hit requests open no
   prefill span at all yet must still close their chains typed, and the
   SPECULATIVE pass, whose per-iteration ``serve.spec_verify`` spans
   (draft+verify+accept dispatch plus synchronous readback) must appear
   balanced with the ``serve_spec_*`` counter series rendering in
   ``/metrics``;
3. the ``/metrics`` exposition renders (every sample line parses as
   ``name{...} value``);
4. the long-prompt-arrival-during-steady-decode interference scenario
   (bench.py:bench_serve_interference, quick mode on the tiny model)
   runs with the recorder on, its max-decode-gap metric is finite, and
   the spans it adds still balance;
5. a 2-replica router pass (serving/router.py) runs traced: every
   request gets a balanced ``router.request`` span chain ending typed,
   the per-replica labeled series (``serve_submitted{replica="0"}``)
   render in the exposition, and ``Engine.verify_invariants`` /
   ``Router.verify_invariants`` — the same public invariant surface the
   router's health machine probes — hold after the run;
6. a controller-on pass (serving/control.py, ISSUE 19) runs traced on
   virtual time: every Controller evaluation lands as one
   ``serve.control.decision`` instant event in the flight file (one per
   decision-log entry — the auditable decision record), the spans the
   pass adds still balance, and the ``serve_vitals_*`` gauges plus the
   ``serve_control_*`` series render in ``/metrics``.

Exit 0 iff all hold::

    python tools/telemetry_smoke.py [--dir DIR]

Composes with fault drills the same way serve_smoke does — e.g.
``DALLE_TPU_FAULTS="prefill_fail=1" python tools/telemetry_smoke.py``
must still pass, with the retry visible in the trace.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _non_postmortem_unclosed(path, summary) -> list:
    """Unclosed spans OTHER than serve_smoke's recovery-drill
    postmortem. A crashed incarnation's ``serve.request`` /
    ``router.request`` chains legally stay open in the flight file —
    they ARE the postmortem of what died in flight (docs/DESIGN.md §9)
    — PROVIDED the restarted incarnation re-opened and closed the same
    request typed later in the file (the §8.3 replay contract). Anything
    else unclosed is a real balance failure."""
    by_id: dict = {}      # span id -> (B record, file position)
    closed: set = set()
    with open(path) as f:
        for pos, line in enumerate(f):
            rec = json.loads(line)
            if rec.get("ph") == "B":
                by_id[rec["id"]] = (rec, pos)
            elif rec.get("ph") == "E":
                closed.add(rec["id"])
    out = []
    for rec in summary["unclosed_records"]:
        _, open_pos = by_id.get(rec["id"], (rec, -1))
        if rec["name"] in ("serve.request", "router.request") and any(
            b["id"] in closed
            and b["name"] == rec["name"]
            and b.get("request_id") == rec.get("request_id")
            and b_pos > open_pos  # the REPLAY chain, not a pre-crash one
            for b, b_pos in by_id.values()
        ):
            continue
        out.append(rec)
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--dir" in argv:
        out_dir = argv[argv.index("--dir") + 1]
    else:
        out_dir = tempfile.mkdtemp(prefix="dalle_telemetry_smoke_")

    import serve_smoke

    # static-analysis pre-flight (docs/DESIGN.md §11), ALL THREE stages:
    # the AST lint fails a corrupt tree fast, the trace stage
    # (`lint.py --trace --check`) holds the serving jits to their
    # committed compile-signature/donation/readback/HBM contracts, and
    # the shard stage (`lint.py --shard --check`) to the committed
    # no-collectives-in-serving baseline, before the recorder or any
    # engine exists. serve_smoke would also run it, but this gate must
    # fail even when a future refactor stops composing the two.
    if serve_smoke.lint_preflight(label="telemetry smoke") != 0:
        return 1

    from dalle_pytorch_tpu.utils.metrics import counters
    from dalle_pytorch_tpu.utils.telemetry import (
        TELEMETRY,
        validate_flight_file,
    )

    TELEMETRY.configure(enabled=True, flight_dir=out_dir)

    rc = serve_smoke.main()
    if rc != 0:
        print("telemetry smoke FAILED: serve_smoke returned nonzero",
              file=sys.stderr)
        return 1

    path = TELEMETRY.drain("smoke")
    if path is None:
        print("telemetry smoke FAILED: drain produced no flight file",
              file=sys.stderr)
        return 1

    # -- 1. parse + span balance ------------------------------------------
    summary = validate_flight_file(path)
    ok = True

    def check(cond: bool, what: str) -> None:
        nonlocal ok
        if not cond:
            ok = False
            print(f"telemetry smoke FAILED: {what}", file=sys.stderr)

    unbalanced = _non_postmortem_unclosed(path, summary)
    check(unbalanced == [],
          f"unbalanced spans beyond the recovery-drill postmortem: "
          f"{unbalanced}")
    check(TELEMETRY.dropped == 0,
          f"{TELEMETRY.dropped} ring drops in a 3-request run")
    check(TELEMETRY.sink_errors == 0,
          f"{TELEMETRY.sink_errors} flight-recorder sink errors")

    # -- 2. one complete span chain per request, typed outcome ------------
    # submissions span the unlabeled engines AND the recovery drill's
    # router-owned (replica-labeled) engines; a chain is accounted when
    # it either ended typed or is the crash postmortem counted above
    n_req = counters.get("serve.submitted")
    for rid in ("0", "1"):
        n_req += counters.get("serve.submitted", labels={"replica": rid})
    check(n_req >= 3, f"expected >=3 submissions, saw {n_req}")
    outcomes: dict = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("name") == "serve.request" and rec["ph"] == "E":
                check("outcome" in rec,
                      f"serve.request span ended without outcome: {rec}")
                o = rec.get("outcome")
                outcomes[o] = outcomes.get(o, 0) + 1
    unclosed_serve = sum(
        1 for rec in summary["unclosed_records"]
        if rec["name"] == "serve.request"
    )
    check(sum(outcomes.values()) + unclosed_serve == n_req,
          f"{n_req} submitted but {sum(outcomes.values())} request spans "
          f"ended + {unclosed_serve} postmortem ({outcomes})")
    n_completed = counters.get("serve.completed")
    for rid in ("0", "1"):
        n_completed += counters.get(
            "serve.completed", labels={"replica": rid}
        )
    check(outcomes.get("completed", 0) == n_completed,
          f"span outcomes {outcomes} disagree with counter "
          f"serve.completed={n_completed}")

    # chunked-prefill observability: serve_smoke's chunked pass must have
    # left per-chunk spans and the TTFT histogram behind. Count via the
    # validator's by_name (B+E records, rotated generations included)
    # rather than re-parsing the file by hand.
    n_chunk_spans = summary["by_name"].get("serve.prefill_chunk", 0) // 2
    check(n_chunk_spans >= 2,
          f"expected >=2 serve.prefill_chunk spans from the chunked pass, "
          f"saw {n_chunk_spans}")
    from dalle_pytorch_tpu.utils.metrics import histograms
    check(histograms.get("serve.ttft_s") is not None,
          "serve.ttft_s histogram missing after the serving passes")

    # speculative-pass observability (ISSUE 11): every speculative
    # iteration opened one serve.spec_verify span (validate_flight_file
    # already proved balance above), and the draft/accept accounting
    # rendered as counter series + the accepted-per-step histogram
    n_spec_spans = summary["by_name"].get("serve.spec_verify", 0) // 2
    check(n_spec_spans >= 1,
          f"expected >=1 serve.spec_verify spans from the speculative "
          f"pass, saw {n_spec_spans}")
    check(histograms.get("serve.spec_accepted_per_step") is not None,
          "serve.spec_accepted_per_step histogram missing after the "
          "speculative pass")

    # -- 3. the exposition renders ----------------------------------------
    dump = TELEMETRY.dump()
    check("serve_submitted" in dump and "_bucket{" in dump,
          "dump() is missing serving counters or histogram buckets")
    for series in ("serve_spec_drafted", "serve_spec_accepted",
                   "serve_spec_accept_frac"):
        check(series in dump,
              f"speculative series {series!r} missing from /metrics")
    for line in dump.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            float(value)
        except ValueError:
            check(False, f"unparseable exposition line: {line!r}")
        check(bool(name), f"unparseable exposition line: {line!r}")

    # -- 4. interference scenario with the recorder on --------------------
    import bench

    interference = bench.bench_serve_interference(
        on_cpu=True, quick=True, model=serve_smoke.build_tiny_model(),
    )
    check(
        interference["value"] > 0
        and interference["monolithic_max_gap_ms"] > 0,
        f"interference gap metric not finite: {interference}",
    )
    ipath = TELEMETRY.drain("interference")
    check(ipath is not None, "interference drain produced no flight file")
    if ipath is not None:
        isummary = validate_flight_file(ipath)
        iunbalanced = _non_postmortem_unclosed(ipath, isummary)
        check(iunbalanced == [],
              f"interference spans left open: {iunbalanced}")

    # -- 5. replicated front door, traced ---------------------------------
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        EngineConfig, Outcome, Request, Router, RouterConfig,
    )

    dalle, params = serve_smoke.build_tiny_model()
    router = Router(
        dalle, params, RouterConfig(n_replicas=2),
        EngineConfig(max_batch=2, prefill_chunk=2),
    )
    rng = np.random.RandomState(3)
    for i in range(4):
        router.submit(Request(
            request_id=f"router{i}",
            prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
            max_new_tokens=dalle.image_seq_len, seed=200 + i,
        ))
    router.run(max_steps=2000)
    router.verify_invariants()          # fleet-level accounting
    for r in router._replicas:
        r.engine.verify_invariants(idle=True)  # each engine, idle-strict
    check(
        all(res.outcome is Outcome.COMPLETED
            for res in router.results.values()),
        f"router pass outcomes: {[r.outcome.value for r in router.results.values()]}",
    )
    rpath = TELEMETRY.drain("router")
    check(rpath is not None, "router drain produced no flight file")
    router_spans = 0
    if rpath is not None:
        rsummary = validate_flight_file(rpath)
        runbalanced = _non_postmortem_unclosed(rpath, rsummary)
        check(runbalanced == [],
              f"router spans left open: {runbalanced}")
        router_spans = rsummary["by_name"].get("router.request", 0) // 2
        check(router_spans >= 4,
              f"expected >=4 router.request spans, saw {router_spans}")
    dump = TELEMETRY.dump()
    for series in ('serve_submitted{replica="0"}',
                   'serve_submitted{replica="1"}',
                   "router_completed", "router_queued"):
        check(series in dump,
              f"per-replica/router series {series!r} missing from /metrics")

    # -- 6. adaptive control loop, traced (ISSUE 19) ----------------------
    from dalle_pytorch_tpu.serving import ControlConfig, Engine, FakeClock

    eng = Engine(dalle, params, EngineConfig(
        max_batch=2, prefill_chunk=2, fused_iteration=True,
        controller=True, cost_ledger=True,
        control=ControlConfig(interval=2),
    ), clock=FakeClock(step_dt=1.0))
    rng = np.random.RandomState(5)
    for i in range(3):
        eng.submit(Request(
            request_id=f"ctrl{i}",
            prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
            max_new_tokens=dalle.image_seq_len, seed=300 + i,
        ))
    eng.run(max_steps=800)
    eng.verify_invariants(idle=True)
    check(
        all(res.outcome is Outcome.COMPLETED
            for res in eng.results.values()),
        f"controller pass outcomes: "
        f"{[r.outcome.value for r in eng.results.values()]}",
    )
    check(len(eng.controller.log) >= 1,
          "controller pass finished without a single evaluation")
    cpath = TELEMETRY.drain("control")
    check(cpath is not None, "control drain produced no flight file")
    decision_events = 0
    if cpath is not None:
        csummary = validate_flight_file(cpath)
        cunbalanced = _non_postmortem_unclosed(cpath, csummary)
        check(cunbalanced == [],
              f"controller-pass spans left open: {cunbalanced}")
        decision_events = csummary["by_name"].get(
            "serve.control.decision", 0
        )
        check(decision_events == len(eng.controller.log),
              f"{len(eng.controller.log)} controller decisions but "
              f"{decision_events} serve.control.decision events in the "
              f"flight file — the audit trail is incomplete")
    dump = TELEMETRY.dump()
    for series in ("serve_vitals_occupancy", "serve_vitals_decode_gap_s",
                   "serve_vitals_roofline_frac", "serve_control_decisions",
                   "serve_control_budget"):
        check(series in dump,
              f"vitals/control series {series!r} missing from /metrics")

    print(json.dumps({
        "flight_file": path,
        "records": summary["records"],
        "spans": summary["spans"],
        "request_outcomes": outcomes,
        "by_name": summary["by_name"],
        "prefill_chunk_spans": n_chunk_spans,
        "spec_verify_spans": n_spec_spans,
        "interference_max_gap_ms": interference["value"],
        "interference_monolithic_max_gap_ms":
            interference["monolithic_max_gap_ms"],
        "router_request_spans": router_spans,
        "control_decision_events": decision_events,
    }))
    if not ok:
        return 1
    print(f"telemetry smoke OK: {n_req} request span chains balanced, "
          f"{summary['records']} records, /metrics renders, interference "
          f"scenario traced, router pass traced with per-replica series, "
          f"controller pass traced with {decision_events} decision events",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
