#!/usr/bin/env python
"""Chaos soak gate: a seeded randomized fault schedule against the
serving fleet, with recovery (respawn + process restart) in the loop.

Every robustness mechanism the serving stack owns is exercised from ONE
randomized schedule instead of one-fault-at-a-time drills: each
iteration the seeded RNG may arm any serving fault site
(``page_exhaust``, ``prefill_fail``, ``decode_stall``,
``request_cancel``, ``replica_crash``, ``replica_stall``,
``health_flap``, ``prefix_hash_collide``, ``prefix_publish_fail``,
``replica_respawn_fail``), and at randomized points the WHOLE PROCESS
"crashes": the router object is abandoned mid-flight exactly as a dead
process would leave it (journal unsealed, in-flight work lost), a fresh
router is built, the prefix-cache snapshot is verify-loaded
(``snapshot_corrupt`` armable here), and the journal replays unfinished
requests (``journal_torn`` armable here — a torn tail is dropped and
the harness resubmits it as the client retry the contract prescribes).
Training-side sites (``download``, ``shard_open``, ...) have no take
site in the serving loop and are deliberately not scheduled.

The client half of the loop is closed too (the traffic-sim storm model,
docs/DESIGN.md §8.4): load-typed rejects (``queue_full`` /
``no_replica``) are NOT terminal to the soak client — it honors the
fleet's ``retry_after_s`` hint (seeded jitter on top) and resubmits
under a fresh attempt id, up to a bounded attempt budget, so the soak
exercises client-driven retry pressure and not just server-side faults.
Mid-run a correlated **outage storm** arms ``replica_crash`` for every
replica at once (``--storm-at``, auto-placed at the midpoint), which is
exactly the schedule whose retry amplification the hints exist to damp.

The gate, checked every iteration and at the end:

* ``Router.verify_invariants`` clean EVERY iteration — accounting can
  never drift, even transiently;
* 100% typed-outcome accounting: every submitted request ends in
  exactly one typed outcome, across crashes and restarts;
* bit-parity: every COMPLETED request's tokens equal a fault-free
  reference run's (the (seed, position) replay contract); a request
  re-delivered after an outcome-record loss must match its original
  delivery bitwise (replay idempotency);
* at least one request completes (a soak that rejects everything is a
  failed soak, not a passed one).

Like the other gate tools, the soak runs the full three-stage lint
pre-flight (AST + trace + shard contracts, docs/DESIGN.md §11) before
arming anything — a chaos pass over a broken build proves nothing.

Quick deterministic mode (the default: ``--iters 120 --seed 0``) is the
fast-tier subprocess gate (tests/test_recovery.py); longer soaks ride
``--iters``/``--seed`` sweeps behind the slow tier::

    python tools/chaos_soak.py
    python tools/chaos_soak.py --iters 2000 --seed 7 --replicas 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# fault sites with a take-site reachable from the router loop, and the
# per-iteration probability of arming each (seeded RNG)
SCHEDULED_SITES = (
    "page_exhaust", "prefill_fail", "decode_stall", "request_cancel",
    "replica_crash", "replica_stall", "health_flap",
    "prefix_hash_collide", "prefix_publish_fail", "replica_respawn_fail",
    "vae_decode_fail", "rerank_fail", "stage_timeout",
)
# restart-time sites: armed just before a journal/snapshot load
RESTART_SITES = ("journal_torn", "snapshot_corrupt")


def run_soak(iters: int, seed: int, n_replicas: int, n_req: int,
             fault_p: float, restart_every: int, snap_every: int,
             storm_at: int = -1) -> dict:
    import numpy as np
    from dataclasses import replace

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, RejectReason, Request,
        RequestJournal, Router, RouterConfig, replay_unfinished,
    )
    from dalle_pytorch_tpu.utils.faults import FAULTS
    from serve_smoke import build_tiny_model, build_tiny_stages

    dalle, params = build_tiny_model()
    stages = build_tiny_stages()
    rng = np.random.RandomState(seed)
    prompts = [
        rng.randint(1, 16, size=(4,)).astype(np.int32) for _ in range(n_req)
    ]
    # a few shared prompts so the prefix cache sees real reuse
    for i in range(3, n_req, 3):
        prompts[i] = prompts[0]
    requests = [
        Request(
            request_id=f"soak{i}", prompt=prompts[i],
            max_new_tokens=dalle.image_seq_len, seed=1000 + i,
        )
        for i in range(n_req)
    ]

    # fault-free reference: the bit-parity oracle for every survivor —
    # tokens AND decoded images (the post-decode stages run here too)
    ref_engine = Engine(
        dalle, params, EngineConfig(max_batch=2, prefill_chunk=2),
        stages=stages,
    )
    for req in requests:
        assert ref_engine.submit(req) is None
    reference = ref_engine.run(max_steps=20_000)

    tmp = tempfile.mkdtemp(prefix="chaos_soak_")
    jpath = os.path.join(tmp, "journal.jsonl")
    snapdir = os.path.join(tmp, "prefix_snapshot")
    engine_cfg = EngineConfig(
        max_batch=2, prefill_chunk=2, prefix_cache=True,
    )
    router_cfg = RouterConfig(
        n_replicas=n_replicas, respawn=True,
        stall_timeout_s=5.0,
        # small enough that the outage-storm backlog overflows into
        # load-typed QUEUE_FULL rejects (with retry_after_s hints) the
        # closed-loop client must ride out — a roomy queue would absorb
        # the whole storm and never exercise client retry pressure
        queue_limit=max(2, n_req // 4),
    )
    clock = FakeClock(step_dt=0.25)

    def build_router() -> Router:
        return Router(
            dalle, params, router_cfg, engine_cfg, clock=clock,
            journal=RequestJournal(jpath), stages=stages,
        )

    FAULTS.reset()
    router = build_router()
    if storm_at < 0:
        storm_at = iters // 2 if iters >= 20 else 0
    by_rid = {r.request_id: r for r in requests}
    delivered: dict = {}        # logical rid -> RequestResult (client view)
    submitted: set = set()
    armed_total: dict = {}
    # logical rid -> {"attempt", "due", "rid"}: a load-typed reject the
    # closed-loop client will resubmit ("due" is the virtual resubmit
    # time; None once the attempt is in flight under attempt id "rid")
    retry_state: dict = {}
    client_retries = 0
    hints_honored = 0
    storm_fired_at = None
    restarts = 0
    snapshots = 0
    torn_total = 0
    staged_resumes = 0
    next_req = 0

    def logical(rid: str) -> str:
        return rid.split(".r", 1)[0]

    def classify(lg: str, res) -> None:
        """Closed-loop client: a load-typed reject with attempt budget
        left re-enters the arrival stream after the fleet's
        retry_after_s hint (seeded client jitter on top); anything else
        is the logical request's terminal outcome."""
        nonlocal client_retries, hints_honored
        st = retry_state.get(lg, {"attempt": 0})
        retriable = (
            res.outcome is Outcome.REJECTED
            and res.reject_reason in (
                RejectReason.QUEUE_FULL, RejectReason.NO_REPLICA,
            )
        )
        if retriable and st["attempt"] < 4:
            hint = res.retry_after_s
            if hint is not None:
                hints_honored += 1
            delay = min(
                4.0, hint if hint is not None else 0.25 * 2 ** st["attempt"]
            ) * (1.0 + 0.25 * rng.random())
            retry_state[lg] = {
                "attempt": st["attempt"] + 1,
                "due": clock.now() + delay, "rid": None,
            }
            client_retries += 1
        else:
            retry_state.pop(lg, None)
            delivered[lg] = res

    def fire_retries():
        """Resubmit every due client retry under a fresh attempt id."""
        now = clock.now()
        for lg, st in list(retry_state.items()):
            if st["due"] is None or st["due"] > now:
                continue
            arid = f"{lg}.r{st['attempt']}"
            st["rid"], st["due"] = arid, None
            res = router.submit(replace(by_rid[lg], request_id=arid))
            if res is not None:
                classify(lg, res)

    def poll_results():
        """Deliver new terminal results to the 'client' (attempt ids
        collapse onto their logical request); a re-delivered COMPLETED
        result (outcome record lost to a crash) must match the original
        bitwise — replay idempotency."""
        for rid, res in list(router.results.items()):
            lg = logical(rid)
            if not lg.startswith("soak"):
                continue
            if lg in delivered:
                prev = delivered[lg]
                if (
                    res.outcome is Outcome.COMPLETED
                    and prev.outcome is Outcome.COMPLETED
                ):
                    assert np.array_equal(
                        np.asarray(res.tokens), np.asarray(prev.tokens)
                    ), f"{rid}: re-delivered tokens diverge from original"
                continue
            if res.outcome is Outcome.COMPLETED:
                retry_state.pop(lg, None)
                delivered[lg] = res
                continue
            st = retry_state.get(lg)
            if st is not None:
                # only the latest attempt's terminal result speaks for
                # the logical request; older records are stale
                if st["due"] is None and rid == st["rid"]:
                    classify(lg, res)
                continue
            classify(lg, res)

    def restart():
        """Process death: abandon the router mid-flight, rebuild, load
        the snapshot (verify-on-load), replay the journal — requests
        with a stage-boundary record resume from their LAST COMPLETED
        stage (a journaled image skips VAE entirely; §8.5) — and
        resubmit anything a torn tail dropped (the client-retry
        contract)."""
        nonlocal router, restarts, torn_total, staged_resumes
        restarts += 1
        router._journal.close()  # what a dead process leaves behind
        if rng.random() < 0.5:
            FAULTS.arm("journal_torn", 1)
            armed_total["journal_torn"] = (
                armed_total.get("journal_torn", 0) + 1
            )
        if rng.random() < 0.5:
            FAULTS.arm("snapshot_corrupt", 1)
            armed_total["snapshot_corrupt"] = (
                armed_total.get("snapshot_corrupt", 0) + 1
            )
        router = build_router()
        if Path(snapdir).exists():
            for r in router._replicas:
                if not r.engine.load_prefix_snapshot(snapdir):
                    break  # rejected (corrupt/uncommitted): cold fleet
        torn0 = FAULTS.fired.get("journal_torn", 0)

        def submit_staged(request, tokens, image=None):
            nonlocal staged_resumes
            staged_resumes += 1
            return router.submit_staged(request, tokens, image=image)

        replayed = set(replay_unfinished(
            jpath, router.submit, now=clock.now(),
            submit_staged=submit_staged,
        ))
        torn_total += FAULTS.fired.get("journal_torn", 0) - torn0
        # resubmit what the journal lost (torn tail): the client retry
        # the torn-tail contract prescribes (delivered requests and
        # replayed ones are already accounted)
        for req in requests[:next_req]:
            rid = req.request_id
            if rid in delivered or rid in replayed:
                continue
            if rid in retry_state:
                continue  # the closed-loop client owns this one
            if rid in router.results:
                continue
            if router.submit(req) is not None:
                pass  # typed immediate reject lands in results

    for it in range(iters):
        # staggered arrivals spread across ~80% of the run, with half
        # the workload held back as a storm cohort: while the outage is
        # fresh, demand bursts at several submissions per iteration
        # against a dead fleet and a bounded queue — the retry-storm
        # shape the retry_after_s hints exist to damp (every load-typed
        # reject re-enters through the closed-loop client above)
        storm_window = storm_fired_at is not None and it - storm_fired_at <= 8
        if storm_window:
            burst = min(3, n_req - next_req)
        else:
            cap = n_req - (n_req // 2 if storm_at and it < storm_at else 0)
            arrival_p = min(0.9, n_req / max(1.0, 0.8 * iters))
            burst = 1 if next_req < cap and rng.random() < arrival_p else 0
        for _ in range(burst):
            req = requests[next_req]
            submitted.add(req.request_id)
            next_req += 1
            rejected = router.submit(req)
            if rejected is not None:
                classify(req.request_id, rejected)
        if storm_at and it == storm_at:
            # correlated outage storm: every replica dies at once and
            # the first respawn attempt fails (extending the outage a
            # backoff rung); the NO_REPLICA rejects it sheds are what
            # the client retry pressure rides
            FAULTS.arm("replica_crash", n_replicas)
            FAULTS.arm("replica_respawn_fail", 1)
            armed_total["replica_crash"] = (
                armed_total.get("replica_crash", 0) + n_replicas
            )
            armed_total["replica_respawn_fail"] = (
                armed_total.get("replica_respawn_fail", 0) + 1
            )
            storm_fired_at = it
        if rng.random() < fault_p:
            site = SCHEDULED_SITES[rng.randint(len(SCHEDULED_SITES))]
            FAULTS.arm(site, 1)
            armed_total[site] = armed_total.get(site, 0) + 1
        if snap_every and it and it % snap_every == 0:
            for r in router._replicas:
                if (
                    r.state.value in ("healthy", "degraded", "draining")
                    and r.engine.prefix is not None
                    and len(r.engine.prefix)
                ):
                    r.engine.save_prefix_snapshot(snapdir)
                    snapshots += 1
                    break
        if restart_every and it and it % restart_every == 0:
            restart()
        router.step()
        router.verify_invariants()
        poll_results()
        fire_retries()

    # quiesce: no new faults, drive everything to a terminal outcome
    # (leftover armed faults would keep killing a fleet trying to finish)
    fired = dict(FAULTS.fired)
    FAULTS.reset()
    steps = 0
    while True:
        poll_results()
        missing = submitted - set(delivered)
        if not missing:
            break
        live_ids = {r.request_id for r in router.live_requests()}
        # a retry attempt lost to a crash (admission torn before the
        # journal saw it) never produces a record in this incarnation:
        # re-arm it so fire_retries resubmits under the same attempt id
        for lg, st in retry_state.items():
            if (
                st["due"] is None
                and st["rid"] is not None
                and st["rid"] not in router.results
                and st["rid"] not in live_ids
            ):
                st["due"] = clock.now()
                st["rid"] = None
        fire_retries()
        # client retry for anything lost without a typed record visible
        # to this incarnation (torn admissions after a crash)
        for req in requests[:next_req]:
            rid = req.request_id
            if (
                rid in missing
                and rid in retry_state
            ):
                continue  # the closed-loop client owns this one
            if (
                rid in missing
                and rid not in router.results
                and rid not in live_ids
            ):
                router.submit(req)
        router.step()
        steps += 1
        router.verify_invariants()
        assert steps < 20_000, (
            f"soak quiesce made no progress: {sorted(missing)} undelivered"
        )
    router.verify_invariants()

    # ---- the gate ----
    outcomes: dict = {}
    mismatches = []
    for rid in sorted(submitted):
        res = delivered[rid]
        outcomes[res.outcome.value] = outcomes.get(res.outcome.value, 0) + 1
        ref = reference[rid]
        # survivor bit-parity: tokens for every token-bearing outcome;
        # the decoded image too wherever the pipeline produced one
        # (COMPLETED and the typed-degraded completed_unranked) — the
        # (seed, position) replay contract extended through the stages
        if res.outcome in (
            Outcome.COMPLETED, Outcome.COMPLETED_TOKENS_ONLY,
            Outcome.COMPLETED_UNRANKED,
        ) and not np.array_equal(np.asarray(res.tokens),
                                 np.asarray(ref.tokens)):
            mismatches.append(rid)
        elif res.image is not None and not np.array_equal(
            res.image, ref.image
        ):
            mismatches.append(rid)
        elif (res.outcome is Outcome.COMPLETED
              and res.rerank_score != ref.rerank_score):
            mismatches.append(rid)
    completed = outcomes.get("completed", 0)
    ok = not mismatches and completed >= 1 and len(delivered) >= len(submitted)
    return {
        "ok": bool(ok),
        "iters": iters,
        "seed": seed,
        "n_replicas": n_replicas,
        "submitted": len(submitted),
        "outcomes": outcomes,
        "completed_bit_identical": not mismatches,
        "mismatched": mismatches,
        "faults_armed": armed_total,
        "faults_fired": fired,
        "client_retries": client_retries,
        "retry_hints_honored": hints_honored,
        "storm_at": storm_fired_at,
        "restarts": restarts,
        "snapshots_saved": snapshots,
        "journal_torn_dropped": torn_total,
        "staged_resumes": staged_resumes,
        "replica_states": router.replica_states(),
    }


def run_stage_restart_drill(seed: int = 0) -> dict:
    """Deterministic mid-stage kill/replay drill (docs/DESIGN.md §8.5):
    the process dies with one request parked mid-VAE_DECODE and another
    parked mid-CLIP_RERANK (its decoded image already journaled). The
    restarted fleet must resume EACH from its last journaled stage
    boundary — the mid-rerank request must NOT re-run the VAE (exactly
    one VAE dispatch row in the new incarnation), both must finish
    COMPLETED, and tokens/image/score must be bitwise-identical to a
    fault-free reference run.

    Parking is made deterministic with a long-backoff retry policy (one
    armed stage fault -> the item waits ~100 virtual seconds before its
    next attempt, far longer than the drill runs before "crashing")."""
    import numpy as np

    from dalle_pytorch_tpu.serving import (
        Engine, EngineConfig, FakeClock, Outcome, Request, RequestJournal,
        Router, RouterConfig, replay_unfinished,
    )
    from dalle_pytorch_tpu.serving.postdecode import (
        STAGE_RERANK, STAGE_VAE, StageConfig,
    )
    from dalle_pytorch_tpu.utils.faults import FAULTS
    from dalle_pytorch_tpu.utils.metrics import counters
    from dalle_pytorch_tpu.utils.resilience import RetryPolicy
    from serve_smoke import build_tiny_model, build_tiny_stages

    dalle, params = build_tiny_model()
    parked_cfg = StageConfig(retry=RetryPolicy(
        attempts=5, base_delay=100.0, max_delay=100.0, jitter=0.0,
        retry_on=(),
    ))
    stages = build_tiny_stages(config=parked_cfg)

    rng = np.random.RandomState(seed)
    reqs = [
        Request(
            request_id=f"mid{i}",
            prompt=rng.randint(1, 16, size=(4,)).astype(np.int32),
            max_new_tokens=dalle.image_seq_len, seed=77 + i,
        )
        for i in range(2)
    ]

    # fault-free reference (default stage config — retry timing cannot
    # change stage values, only when they are produced)
    ref_engine = Engine(
        dalle, params, EngineConfig(max_batch=2, prefill_chunk=2),
        stages=build_tiny_stages(),
    )
    for req in reqs:
        assert ref_engine.submit(req) is None
    reference = ref_engine.run(max_steps=20_000)
    assert all(
        reference[r.request_id].outcome is Outcome.COMPLETED for r in reqs
    )

    tmp = tempfile.mkdtemp(prefix="stage_restart_")
    jpath = os.path.join(tmp, "journal.jsonl")
    clock = FakeClock(step_dt=0.05)
    engine_cfg = EngineConfig(max_batch=2, prefill_chunk=2)
    router_cfg = RouterConfig(n_replicas=1, respawn=False)

    def build() -> Router:
        return Router(
            dalle, params, router_cfg, engine_cfg, clock=clock,
            journal=RequestJournal(jpath), stages=stages,
        )

    FAULTS.reset()
    router = build()

    def parked(rid: str, stage: str):
        pd = router._replicas[0].engine.postdecode
        for st in pd._staged:
            if (st.entry.request.request_id == rid and st.stage == stage
                    and st.attempts > 0):
                return st
        return None

    # 1) mid1: tokens -> VAE ok (image journaled) -> first rerank
    #    dispatch fails -> parked mid-CLIP_RERANK on the long backoff
    FAULTS.arm("rerank_fail", 1)
    assert router.submit(reqs[1]) is None
    for _ in range(1500):
        router.step()
        if parked("mid1", STAGE_RERANK) is not None:
            break
    st1 = parked("mid1", STAGE_RERANK)
    assert st1 is not None and st1.image is not None, (
        "mid1 never parked mid-rerank with a decoded image"
    )

    # 2) mid0: tokens -> first VAE dispatch fails -> parked mid-VAE
    FAULTS.arm("vae_decode_fail", 1)
    assert router.submit(reqs[0]) is None
    for _ in range(1500):
        router.step()
        if parked("mid0", STAGE_VAE) is not None:
            break
    st0 = parked("mid0", STAGE_VAE)
    assert st0 is not None and st0.image is None, (
        "mid0 never parked mid-vae"
    )
    assert parked("mid1", STAGE_RERANK) is not None, (
        "mid1 escaped its backoff before the crash"
    )

    # 3) the process dies with both parked mid-stage
    router._journal.close()
    labels = {"replica": "0"}
    vae0 = counters.get("serve.stage.vae_images", labels=labels)
    rr0 = counters.get("serve.stage.reranked", labels=labels)

    # 4) restart: journal replay resumes each from its last completed
    #    stage — mid0 pre-VAE (no image), mid1 post-VAE (image in hand)
    router = build()
    resumes: dict = {}

    def submit_staged(request, tokens, image=None):
        resumes[request.request_id] = image
        return router.submit_staged(request, tokens, image=image)

    replayed = set(replay_unfinished(
        jpath, router.submit, now=clock.now(), submit_staged=submit_staged,
    ))
    assert replayed == {"mid0", "mid1"}, replayed
    assert set(resumes) == {"mid0", "mid1"}, resumes
    assert resumes["mid0"] is None, "mid0 resumed WITH an image pre-VAE"
    assert resumes["mid1"] is not None, "mid1 lost its journaled image"

    for _ in range(1500):
        router.step()
        if all(r.request_id in router.results for r in reqs):
            break
    router.verify_invariants()

    vae_delta = counters.get("serve.stage.vae_images", labels=labels) - vae0
    rr_delta = counters.get("serve.stage.reranked", labels=labels) - rr0
    assert vae_delta == 1, (
        f"expected exactly one VAE row after restart (mid0 only; mid1 "
        f"resumes past VAE), got {vae_delta}"
    )
    assert rr_delta == 2, f"expected both requests reranked, got {rr_delta}"

    for req in reqs:
        res = router.results[req.request_id]
        ref = reference[req.request_id]
        assert res.outcome is Outcome.COMPLETED, (
            f"{req.request_id}: {res.outcome}"
        )
        assert np.array_equal(
            np.asarray(res.tokens), np.asarray(ref.tokens)
        ), f"{req.request_id}: tokens diverge after mid-stage restart"
        assert np.array_equal(res.image, ref.image), (
            f"{req.request_id}: image not bit-identical after restart"
        )
        assert res.rerank_score == ref.rerank_score, (
            f"{req.request_id}: rerank score diverged"
        )
    return {
        "ok": True,
        "staged_resumes": sorted(resumes),
        "vae_rows_after_restart": int(vae_delta),
        "reranked_after_restart": int(rr_delta),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--iters", type=int, default=120,
                    help="fault-injection iterations (quick gate default)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--fault-p", type=float, default=0.25,
                    help="per-iteration probability of arming a fault")
    ap.add_argument("--restart-every", type=int, default=40,
                    help="process-crash-and-restart period (0 = never)")
    ap.add_argument("--snap-every", type=int, default=15,
                    help="prefix snapshot period (0 = never)")
    ap.add_argument("--storm-at", type=int, default=-1,
                    help="iteration of the correlated full-fleet outage "
                         "storm (-1 = midpoint, 0 = never)")
    args = ap.parse_args(argv)

    # static-analysis pre-flight (docs/DESIGN.md §11), the same three
    # stages as the other gate tools (tools/serve_smoke.py): a corrupt
    # tree, a drifted serving-jit contract, or a collective smuggled
    # into a serving program must fail the soak BEFORE any fault is
    # armed — a chaos gate over a broken build proves nothing
    from serve_smoke import lint_preflight

    if lint_preflight(label="chaos soak") != 0:
        return 1

    drill = run_stage_restart_drill(seed=args.seed)
    print("stage restart drill:", json.dumps(drill, sort_keys=True),
          file=sys.stderr)

    summary = run_soak(
        iters=args.iters, seed=args.seed, n_replicas=args.replicas,
        n_req=args.requests, fault_p=args.fault_p,
        restart_every=args.restart_every, snap_every=args.snap_every,
        storm_at=args.storm_at,
    )
    print(json.dumps(summary, indent=1, sort_keys=True))
    if not summary["ok"]:
        print("chaos soak FAILED", file=sys.stderr)
        return 1
    print(
        f"chaos soak OK: {summary['submitted']} requests all typed across "
        f"{summary['restarts']} process restarts, completed survivors "
        "bit-identical to the fault-free reference", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
