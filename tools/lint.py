#!/usr/bin/env python
"""dalle-tpu-lint CLI: AST-based invariant checks for this repo.

Usage::

    python tools/lint.py [--json] [--check] [--checks a,b,...] [paths...]

* no flags: report findings (human-readable), always exit 0;
* ``--check``: exit 1 when any non-suppressed, non-baselined finding
  survives — the release-gate / CI mode (tools/serve_smoke.py and
  tools/telemetry_smoke.py run this as their pre-flight);
* ``--json``: one JSON object per finding on stdout;
* ``--checks``: comma list from {purity, layering, fault-sites,
  telemetry-names, locks} (default: all);
* ``paths``: repo-relative files/dirs to scan (default: the package +
  CLI entrypoints — see tools/lint/config.py).

Finding codes, the suppression comment (``# dtl: disable=DTL0xx``), and
the baseline policy (tools/lint_baseline.json) are documented in
docs/DESIGN.md §11 and tools/lint/__init__.py. The linter is stdlib-only
and never imports the package it checks — it runs in milliseconds with
no jax in sight.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TOOLS_DIR)
# the tools/lint/ package shadows this script on sys.path (regular
# packages win over same-named modules in the same directory)
sys.path.insert(0, _TOOLS_DIR)

from lint import default_config, run_lint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/lint.py",
        description="dalle-tpu-lint: AST-based invariant checks",
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON lines")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any live finding (gate mode)")
    ap.add_argument("--checks", default=None,
                    help="comma list of checkers to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="override the baseline file "
                         "(default: tools/lint_baseline.json)")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs (default: scan roots)")
    args = ap.parse_args(argv)

    config = default_config(_REPO_ROOT)
    if args.baseline is not None:
        import dataclasses

        config = dataclasses.replace(config, baseline_path=args.baseline)
    checkers = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks else None
    )
    try:
        result = run_lint(config, paths=args.paths or None, checkers=checkers)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"lint: error: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        for f in result.findings:
            print(json.dumps(f.to_json()))
    else:
        for f in result.findings:
            print(f.render())
    n = len(result.findings)
    summary = (
        f"lint: {n} finding{'s' if n != 1 else ''} "
        f"({len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined)"
    )
    print(summary, file=sys.stderr)
    for key in result.stale_baseline:
        # a stale entry means the finding it excused is gone: prune it
        print(f"lint: stale baseline entry {key} — remove it from the "
              f"baseline file", file=sys.stderr)
    if args.check and (result.findings or result.stale_baseline):
        # stale entries FAIL the gate too: the baseline can only shrink,
        # and a dead key must not linger to mask a future same-shape
        # violation
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
