#!/usr/bin/env python
"""dalle-tpu-lint CLI: AST + trace + shard-level invariant checks.

Usage::

    python tools/lint.py [--json] [--check] [--checks a,b,...]
                         [--trace] [--shard] [--emit-contract] [paths...]

* no flags: report findings (human-readable), always exit 0;
* ``--check``: exit 1 when any non-suppressed, non-baselined finding
  survives — the release-gate / CI mode (tools/serve_smoke.py,
  tools/telemetry_smoke.py and tools/chaos_soak.py run this as their
  pre-flight);
* ``--json``: one JSON object per finding on stdout;
* ``--checks``: comma list from {purity, layering, fault-sites,
  telemetry-names, locks} (default: all);
* ``--trace``: ALSO run the semantic stage (tools/lint/trace/): trace
  every registered jit entry point to a ClosedJaxpr over abstract avals
  and audit compile signatures, buffer donation/aliasing, host
  syncs/readbacks, and static HBM footprints against the committed
  ``tools/trace_contracts.json`` (DTL1xx codes). This stage imports jax
  and the package (still CPU-only, no device execution) and composes
  with the AST stage in one exit code;
* ``--shard``: ALSO run the mesh stage (tools/lint/shard/): lower
  ``make_train_step`` under each of the six mesh kinds over a forced
  8-device host platform (plus every serving jit under its 1-device
  placement) and audit collective budgets, in/out sharding specs,
  accidental replication, and in-program reshard constraints against
  the committed ``tools/shard_contracts.json`` (DTL15x codes). Host CPU
  only — no TPU anywhere; composes with the other stages in one exit
  code;
* ``--emit-contract`` (with exactly one of ``--trace``/``--shard``):
  print the contract JSON derived from the current registry to stdout
  and exit — the blessed update after an intentional signature/
  footprint/budget change;
* ``--trace-registry`` / ``--contract`` and ``--shard-registry`` /
  ``--shard-contract``: override the registry module / contract file
  per stage (fixture tests use these);
* ``paths``: repo-relative files/dirs for the AST stage (default: the
  package + CLI entrypoints — see tools/lint/config.py). The trace and
  shard stages always audit every registered entry point.

Finding codes, the suppression comment (``# dtl: disable=DTL0xx``), and
the baseline policy (tools/lint_baseline.json) are documented in
docs/DESIGN.md §11, tools/lint/__init__.py (DTL0xx),
tools/lint/trace/__init__.py (DTL1xx), and tools/lint/shard/__init__.py
(DTL15x). Without ``--trace``/``--shard`` the linter is stdlib-only and
never imports the package it checks — it runs in milliseconds with no
jax in sight.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TOOLS_DIR)
# the tools/lint/ package shadows this script on sys.path (regular
# packages win over same-named modules in the same directory)
sys.path.insert(0, _TOOLS_DIR)

from lint import default_config, run_lint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/lint.py",
        description="dalle-tpu-lint: AST-based invariant checks",
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON lines")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any live finding (gate mode)")
    ap.add_argument("--checks", default=None,
                    help="comma list of checkers to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="override the baseline file "
                         "(default: tools/lint_baseline.json)")
    ap.add_argument("--trace", action="store_true",
                    help="also run the trace-level jaxpr/lowering audit "
                         "(DTL1xx; imports jax, CPU-only)")
    ap.add_argument("--shard", action="store_true",
                    help="also run the mesh-aware sharding/collective "
                         "audit (DTL15x; imports jax, forces an 8-device "
                         "host platform, CPU-only)")
    ap.add_argument("--emit-contract", action="store_true",
                    dest="emit_contract",
                    help="with --trace or --shard: print that stage's "
                         "contract JSON derived from the current registry "
                         "and exit")
    ap.add_argument("--contract", default=None,
                    help="override the trace contract file "
                         "(default: tools/trace_contracts.json)")
    ap.add_argument("--trace-registry", default=None, dest="trace_registry",
                    help="override the trace registry module path")
    ap.add_argument("--shard-contract", default=None, dest="shard_contract",
                    help="override the shard contract file "
                         "(default: tools/shard_contracts.json)")
    ap.add_argument("--shard-registry", default=None, dest="shard_registry",
                    help="override the shard registry module path")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs (default: scan roots)")
    args = ap.parse_args(argv)

    config = default_config(_REPO_ROOT)
    if args.baseline is not None:
        import dataclasses

        config = dataclasses.replace(config, baseline_path=args.baseline)
    checkers = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks else None
    )

    if args.emit_contract and args.trace == args.shard:
        print("lint: --emit-contract requires exactly one of --trace / "
              "--shard (each stage owns its own contract file)",
              file=sys.stderr)
        return 2

    extra_findings = None
    stages = set()
    if args.trace or args.shard:
        # env prepared HERE, before any jax import: the semantic stages
        # pull in jax and the audited package; the AST-only invocation
        # stays stdlib-pure and millisecond-fast. CPU-pinned: the audits
        # are abstract/host-only (eval_shape/make_jaxpr/lower + host-CPU
        # compiles for the mesh stage) and must not grab an accelerator.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if args.shard:
            # the mesh audit needs a multi-device host platform (the
            # test suite's own 8-virtual-device setup); must be set
            # before jax initializes its backend
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        extra_findings = []
    if args.trace:
        from lint.trace import emit_contract, run_trace, trace_reports_only

        tcfg = config.trace
        registry = args.trace_registry or tcfg.registry_path
        contract = args.contract or tcfg.contract_path
        try:
            if args.emit_contract:
                reports = trace_reports_only(_REPO_ROOT, registry)
                print(json.dumps(emit_contract(reports), indent=2))
                return 0
            trace_findings, reports = run_trace(
                _REPO_ROOT, registry, contract
            )
        except (ImportError, ValueError, OSError, RuntimeError,
                SyntaxError) as e:
            print(f"lint: trace stage error: {e}", file=sys.stderr)
            return 2
        extra_findings.extend(trace_findings)
        stages.add("trace")
        if not args.as_json:
            # the per-jit report (signatures / readbacks / HBM) goes to
            # stderr: it is operator context, not findings
            for r in sorted(reports, key=lambda r: r["name"]):
                print(
                    f"lint: trace {r['name']}: "
                    f"{len(r['signatures'])} signature(s), "
                    f"{r['max_callbacks']} callback(s), "
                    f"{r['max_host_visible_outputs']} host-visible "
                    f"output(s), {r['max_hbm_bytes']} HBM bytes "
                    f"(aliased {r['signatures'][0]['aliased_bytes']})",
                    file=sys.stderr,
                )
    if args.shard:
        from lint.shard import (
            emit_contract as emit_shard_contract,
            run_shard,
            shard_reports_only,
        )

        scfg = config.shard
        registry = args.shard_registry or scfg.registry_path
        contract = args.shard_contract or scfg.contract_path
        try:
            if args.emit_contract:
                reports = shard_reports_only(_REPO_ROOT, registry)
                print(json.dumps(emit_shard_contract(reports), indent=2))
                return 0
            shard_findings, reports = run_shard(
                _REPO_ROOT, registry, contract
            )
        except (ImportError, ValueError, OSError, RuntimeError,
                SyntaxError, AssertionError) as e:
            print(f"lint: shard stage error: {e}", file=sys.stderr)
            return 2
        extra_findings.extend(shard_findings)
        stages.add("shard")
        if not args.as_json:
            # per-entry mesh report to stderr: operator context
            for r in sorted(reports, key=lambda r: r["name"]):
                mesh = ",".join(f"{k}={v}" for k, v in r["mesh"].items())
                coll = (", ".join(f"{k}:{v}" for k, v in
                                  sorted(r["collectives"].items()))
                        or "none")
                print(
                    f"lint: shard {r['name']} [{mesh or '1-device'}] "
                    f"({r['level']}): collectives {coll}; "
                    f"{r['reshard_constraints']} reshard constraint(s); "
                    f"{r['sharded_in_args']}/{r['in_args']} sharded args",
                    file=sys.stderr,
                )

    try:
        result = run_lint(config, paths=args.paths or None, checkers=checkers,
                          extra_findings=extra_findings,
                          stages=stages or None)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"lint: error: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        for f in result.findings:
            print(json.dumps(f.to_json()))
    else:
        for f in result.findings:
            print(f.render())
    n = len(result.findings)
    summary = (
        f"lint: {n} finding{'s' if n != 1 else ''} "
        f"({len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined)"
    )
    print(summary, file=sys.stderr)
    for key in result.stale_baseline:
        # a stale entry means the finding it excused is gone: prune it
        print(f"lint: stale baseline entry {key} — remove it from the "
              f"baseline file", file=sys.stderr)
    if args.check and (result.findings or result.stale_baseline):
        # stale entries FAIL the gate too: the baseline can only shrink,
        # and a dead key must not linger to mask a future same-shape
        # violation
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
