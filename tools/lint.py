#!/usr/bin/env python
"""dalle-tpu-lint CLI: AST + trace-level invariant checks for this repo.

Usage::

    python tools/lint.py [--json] [--check] [--checks a,b,...]
                         [--trace] [--emit-contract] [paths...]

* no flags: report findings (human-readable), always exit 0;
* ``--check``: exit 1 when any non-suppressed, non-baselined finding
  survives — the release-gate / CI mode (tools/serve_smoke.py and
  tools/telemetry_smoke.py run this as their pre-flight);
* ``--json``: one JSON object per finding on stdout;
* ``--checks``: comma list from {purity, layering, fault-sites,
  telemetry-names, locks} (default: all);
* ``--trace``: ALSO run the semantic stage (tools/lint/trace/): trace
  every registered jit entry point to a ClosedJaxpr over abstract avals
  and audit compile signatures, buffer donation/aliasing, host
  syncs/readbacks, and static HBM footprints against the committed
  ``tools/trace_contracts.json`` (DTL1xx codes). This stage imports jax
  and the package (still CPU-only, no device execution) and composes
  with the AST stage in one exit code;
* ``--emit-contract`` (with ``--trace``): print the contract JSON
  derived from the current registry to stdout and exit — the blessed
  update after an intentional signature/footprint change;
* ``--trace-registry`` / ``--contract``: override the registry module /
  contract file (fixture tests use these);
* ``paths``: repo-relative files/dirs for the AST stage (default: the
  package + CLI entrypoints — see tools/lint/config.py). The trace
  stage always audits every registered entry point.

Finding codes, the suppression comment (``# dtl: disable=DTL0xx``), and
the baseline policy (tools/lint_baseline.json) are documented in
docs/DESIGN.md §11, tools/lint/__init__.py (DTL0xx), and
tools/lint/trace/__init__.py (DTL1xx). Without ``--trace`` the linter
is stdlib-only and never imports the package it checks — it runs in
milliseconds with no jax in sight.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TOOLS_DIR)
# the tools/lint/ package shadows this script on sys.path (regular
# packages win over same-named modules in the same directory)
sys.path.insert(0, _TOOLS_DIR)

from lint import default_config, run_lint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/lint.py",
        description="dalle-tpu-lint: AST-based invariant checks",
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON lines")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any live finding (gate mode)")
    ap.add_argument("--checks", default=None,
                    help="comma list of checkers to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="override the baseline file "
                         "(default: tools/lint_baseline.json)")
    ap.add_argument("--trace", action="store_true",
                    help="also run the trace-level jaxpr/lowering audit "
                         "(DTL1xx; imports jax, CPU-only)")
    ap.add_argument("--emit-contract", action="store_true",
                    dest="emit_contract",
                    help="with --trace: print the contract JSON derived "
                         "from the current registry and exit")
    ap.add_argument("--contract", default=None,
                    help="override the trace contract file "
                         "(default: tools/trace_contracts.json)")
    ap.add_argument("--trace-registry", default=None, dest="trace_registry",
                    help="override the trace registry module path")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs (default: scan roots)")
    args = ap.parse_args(argv)

    config = default_config(_REPO_ROOT)
    if args.baseline is not None:
        import dataclasses

        config = dataclasses.replace(config, baseline_path=args.baseline)
    checkers = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks else None
    )

    trace_findings = None
    if args.trace:
        # imported HERE, not at module top: the trace stage pulls in jax
        # and the audited package; the AST-only invocation stays
        # stdlib-pure and millisecond-fast. CPU-pinned: the audit is
        # abstract (eval_shape/make_jaxpr/lower, no execution) and must
        # not grab an accelerator just to read avals.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from lint.trace import emit_contract, run_trace, trace_reports_only

        tcfg = config.trace
        registry = args.trace_registry or tcfg.registry_path
        contract = args.contract or tcfg.contract_path
        try:
            if args.emit_contract:
                reports = trace_reports_only(_REPO_ROOT, registry)
                print(json.dumps(emit_contract(reports), indent=2))
                return 0
            trace_findings, reports = run_trace(
                _REPO_ROOT, registry, contract
            )
        except (ImportError, ValueError, OSError, RuntimeError,
                SyntaxError) as e:
            print(f"lint: trace stage error: {e}", file=sys.stderr)
            return 2
        if not args.as_json:
            # the per-jit report (signatures / readbacks / HBM) goes to
            # stderr: it is operator context, not findings
            for r in sorted(reports, key=lambda r: r["name"]):
                print(
                    f"lint: trace {r['name']}: "
                    f"{len(r['signatures'])} signature(s), "
                    f"{r['max_callbacks']} callback(s), "
                    f"{r['max_host_visible_outputs']} host-visible "
                    f"output(s), {r['max_hbm_bytes']} HBM bytes "
                    f"(aliased {r['signatures'][0]['aliased_bytes']})",
                    file=sys.stderr,
                )
    elif args.emit_contract:
        print("lint: --emit-contract requires --trace", file=sys.stderr)
        return 2

    try:
        result = run_lint(config, paths=args.paths or None, checkers=checkers,
                          extra_findings=trace_findings)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"lint: error: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        for f in result.findings:
            print(json.dumps(f.to_json()))
    else:
        for f in result.findings:
            print(f.render())
    n = len(result.findings)
    summary = (
        f"lint: {n} finding{'s' if n != 1 else ''} "
        f"({len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined)"
    )
    print(summary, file=sys.stderr)
    for key in result.stale_baseline:
        # a stale entry means the finding it excused is gone: prune it
        print(f"lint: stale baseline entry {key} — remove it from the "
              f"baseline file", file=sys.stderr)
    if args.check and (result.findings or result.stale_baseline):
        # stale entries FAIL the gate too: the baseline can only shrink,
        # and a dead key must not linger to mask a future same-shape
        # violation
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
