#!/usr/bin/env python
"""Standalone checkpoint-directory verifier.

Walks every ``step_*`` dir under a sharded checkpoint root and reports its
verification state (commit marker + per-file sha256 manifest — the format
``save_sharded_checkpoint`` writes, docs/DESIGN.md §9). This is what the
trainer's resume probe runs implicitly; operators run it by hand before
relying on a checkpoint, e.g. ahead of deleting an older known-good one::

    python tools/verify_ckpt.py dalle-cp
    python tools/verify_ckpt.py dalle-cp --step 1200

Exit status: 0 when every step dir verifies, 1 when any is torn/corrupt
(the report names the failing file and reason), 2 when none verifies —
the trainer would refuse to resume from this directory.

Imports only the manifest helpers (no jax/orbax), so it runs anywhere.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.utils.resilience import verify_dir_manifest  # noqa: E402


def verify_root(ckpt_dir: str, step: int | None = None) -> int:
    root = Path(ckpt_dir)
    if step is not None:
        dirs = [root / f"step_{step:08d}"]
        if not dirs[0].is_dir():
            print(f"FAIL  {dirs[0]}: no such step dir")
            return 2
    else:
        dirs = sorted(root.glob("step_*"))
        if not dirs:
            print(f"FAIL  {root}: no step_* dirs")
            return 2

    newest_verified = None
    bad = 0
    for d in dirs:
        ok, reason = verify_dir_manifest(d)
        if ok:
            manifest = json.loads((d / "MANIFEST.json").read_text())
            n = len(manifest.get("files", {}))
            meta = manifest.get("meta") or {}
            tag = " emergency" if meta.get("emergency") else ""
            print(f"OK    {d.name}  ({n} files verified{tag})")
            newest_verified = d.name
        else:
            print(f"FAIL  {d.name}: {reason}")
            bad += 1

    if newest_verified is None:
        print(f"no verified checkpoint under {root} — resume would refuse")
        return 2
    print(f"newest verified: {newest_verified}" +
          (f"  ({bad} torn/corrupt dir(s) would be skipped)" if bad else ""))
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("ckpt_dir", help="sharded checkpoint root (the <name>-cp dir)")
    ap.add_argument("--step", type=int, default=None,
                    help="verify only this step")
    args = ap.parse_args(argv)
    return verify_root(args.ckpt_dir, args.step)


if __name__ == "__main__":
    sys.exit(main())
