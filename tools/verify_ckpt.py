#!/usr/bin/env python
"""Standalone checkpoint-directory verifier.

Walks every ``step_*`` dir under a sharded checkpoint root and reports its
verification state (commit marker + per-file sha256 manifest — the format
``save_sharded_checkpoint`` writes, docs/DESIGN.md §9). This is what the
trainer's resume probe runs implicitly; operators run it by hand before
relying on a checkpoint, e.g. ahead of deleting an older known-good one::

    python tools/verify_ckpt.py dalle-cp
    python tools/verify_ckpt.py dalle-cp --step 1200

``--serving`` verifies the SERVING durable state instead (docs/DESIGN.md
§8.3) — operator CLI parity with training checkpoints: the request
journal (``journal.jsonl``: sidecar manifest when sealed, full parse
scan with torn-tail reporting either way) and the prefix-cache snapshot
(``prefix_snapshot/``: two-phase COMMITTED dir manifest plus the
mandatory chain-digest recompute over every persisted node)::

    python tools/verify_ckpt.py --serving /var/serve-state

Exit status: 0 when every artifact verifies, 1 when any is torn/corrupt
(the report names the failing file and reason), 2 when nothing verifies
— the typed refuse-to-resume outcome (a corrupt journal or snapshot
must never be replayed/restored from).

Imports only the manifest/journal/record helpers (no jax/orbax), so it
runs anywhere.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.utils.resilience import verify_dir_manifest  # noqa: E402


def verify_serving(state_dir: str) -> int:
    """Verify a serving durable-state dir: ``journal.jsonl`` and/or
    ``prefix_snapshot/`` (either may be absent — a fleet that never
    enabled one of them). Exit codes mirror ``verify_root``: 0 all
    present artifacts verify, 1 some do, 2 none do (or none exist)."""
    import json as _json

    from dalle_pytorch_tpu.serving.journal import RequestJournal
    from dalle_pytorch_tpu.serving.prefix_cache import (
        verify_snapshot_records,
    )

    root = Path(state_dir)
    checked = 0
    bad = 0

    jpath = root / "journal.jsonl"
    if jpath.exists():
        checked += 1
        ok, reason = RequestJournal.verify(str(jpath))
        if ok:
            # inspection reads: never move the torn counter or consume
            # an armed drill — the replay read owns those side effects
            n = len(RequestJournal.load(str(jpath), count=False)[0])
            unfinished = len(
                RequestJournal.unfinished(str(jpath), count=False)
            )
            print(f"OK    journal.jsonl  ({n} records, {unfinished} "
                  f"unfinished; {reason})")
        else:
            bad += 1
            print(f"FAIL  journal.jsonl: {reason}")

    snapdir = root / "prefix_snapshot"
    if snapdir.is_dir():
        checked += 1
        ok, reason = verify_dir_manifest(snapdir)
        nodes = []
        if ok:
            try:
                index = _json.loads((snapdir / "index.json").read_text())
                nodes = index["nodes"]
                # the chain root is salted by the engine's KV-format tag
                # (quantized arenas); the snapshot stores the tag its
                # digests were derived under, so the offline recompute
                # uses the same root — an engine restore additionally
                # requires the tag to MATCH its own format
                ok, reason = verify_snapshot_records(
                    nodes, int(index["page_size"]),
                    format_tag=index.get("kv_format", "").encode(),
                )
            except (OSError, ValueError, KeyError, TypeError) as e:
                ok, reason = False, f"unreadable index: {e!r}"
        if ok:
            print(f"OK    prefix_snapshot  ({len(nodes)} nodes, "
                  "chain digests recomputed)")
        else:
            bad += 1
            print(f"FAIL  prefix_snapshot: {reason}")

    if checked == 0:
        print(f"FAIL  {root}: no journal.jsonl or prefix_snapshot/ found")
        return 2
    if bad == checked:
        print(f"no verified serving state under {root} — "
              "restart would come up cold")
        return 2
    return 1 if bad else 0


def verify_root(ckpt_dir: str, step: int | None = None) -> int:
    root = Path(ckpt_dir)
    if step is not None:
        dirs = [root / f"step_{step:08d}"]
        if not dirs[0].is_dir():
            print(f"FAIL  {dirs[0]}: no such step dir")
            return 2
    else:
        dirs = sorted(root.glob("step_*"))
        if not dirs:
            print(f"FAIL  {root}: no step_* dirs")
            return 2

    newest_verified = None
    bad = 0
    for d in dirs:
        ok, reason = verify_dir_manifest(d)
        if ok:
            manifest = json.loads((d / "MANIFEST.json").read_text())
            n = len(manifest.get("files", {}))
            meta = manifest.get("meta") or {}
            tag = " emergency" if meta.get("emergency") else ""
            print(f"OK    {d.name}  ({n} files verified{tag})")
            newest_verified = d.name
        else:
            print(f"FAIL  {d.name}: {reason}")
            bad += 1

    if newest_verified is None:
        print(f"no verified checkpoint under {root} — resume would refuse")
        return 2
    print(f"newest verified: {newest_verified}" +
          (f"  ({bad} torn/corrupt dir(s) would be skipped)" if bad else ""))
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("ckpt_dir", help="sharded checkpoint root (the "
                    "<name>-cp dir), or with --serving a serving "
                    "durable-state dir")
    ap.add_argument("--step", type=int, default=None,
                    help="verify only this step")
    ap.add_argument("--serving", action="store_true",
                    help="verify serving durable state (request journal "
                    "+ prefix-cache snapshot) instead of training "
                    "checkpoints")
    args = ap.parse_args(argv)
    if args.serving:
        return verify_serving(args.ckpt_dir)
    return verify_root(args.ckpt_dir, args.step)


if __name__ == "__main__":
    sys.exit(main())
