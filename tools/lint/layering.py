"""DTL021: declared import layering, checked on real import AST nodes.

Each ``LayerRule`` (tools/lint/config.py) names the files it governs and
the dotted module prefixes they must not import. Both ``import X`` and
``from X import Y`` count; relative imports are resolved to absolute
module paths against the file's package location first, so
``from ..serving import engine`` inside ``ops/`` is the same violation
as the absolute spelling. Function-level (lazy) imports are checked too:
the host-only rules exist precisely because a lazy ``import jax`` in a
signal handler or loader thread is still a jax import.

This checker replaces (and generalizes) the old source-grep pin in
tests/test_telemetry.py — the test now simply asserts this checker finds
nothing in utils/telemetry.py.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Optional, Sequence

from .core import Finding, SourceFile


def _module_package(path: str) -> List[str]:
    """Package parts for a repo-relative file: ``a/b/c.py`` -> ["a","b"],
    ``a/b/__init__.py`` -> ["a","b"]."""
    parts = path.split("/")
    parts[-1] = parts[-1][:-3]  # strip .py
    if parts[-1] == "__init__":
        parts.pop()
    else:
        parts.pop()
    return parts


def _resolve_relative(path: str, level: int, module: Optional[str]) -> str:
    pkg = _module_package(path)
    base = pkg[: len(pkg) - (level - 1)] if level > 1 else pkg
    if module:
        base = base + module.split(".")
    return ".".join(base)


def _forbidden(mod: str, forbid: Sequence[str]) -> Optional[str]:
    for prefix in forbid:
        if mod == prefix or mod.startswith(prefix + "."):
            return prefix
    return None


def check(files: Sequence[SourceFile], config,
          full: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        rules = [
            r for r in config.layer_rules
            if any(
                fnmatch.fnmatch(sf.path, pat) or sf.path == pat
                for pat in r.files
            )
        ]
        if not rules:
            continue
        for node in ast.walk(sf.tree):
            targets: List[str] = []
            if isinstance(node, ast.Import):
                targets = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.level and node.level > 0:
                    base = _resolve_relative(sf.path, node.level, node.module)
                else:
                    base = node.module or ""
                if base:
                    targets.append(base)
                # the from-parent spelling of a submodule import —
                # `from dalle_pytorch_tpu import serving` / `from .. import
                # serving` — lands the forbidden module in the ALIASES,
                # not in node.module; check both
                targets.extend(
                    f"{base}.{a.name}" if base else a.name
                    for a in node.names if a.name != "*"
                )
            for rule in rules:
                # one finding per (import statement, rule), anchored on
                # the shortest offending module path — `from x.serving
                # import engine` is one violation, not two
                hits = [m for m in targets if _forbidden(m, rule.forbid)]
                if hits:
                    mod = min(hits, key=len)
                    findings.append(Finding(
                        "DTL021", sf.path, node.lineno,
                        f"imports `{mod}`, forbidden for layer "
                        f"'{rule.name}' ({rule.why})",
                        anchor=f"{rule.name}:{mod}",
                    ))
    return findings
