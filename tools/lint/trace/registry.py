"""The repo's trace-audit entry points.

This module — unlike the AST stage — IMPORTS the package, because its
job is to enumerate the (shape, dtype, static-arg) signatures the real
code paths can feed each hot jit. Everything is derived from the same
objects production uses:

* the serving jits' signatures come from ``EngineConfig``/model config
  exactly the way ``serving/engine.py`` computes them (chunk widths via
  the engine's own ``_next_chunk``, the top-k ``k`` via the engine's
  formula, cache avals via ``init_decode_cache``/``set_decode_offsets``
  under ``jax.eval_shape``),
* the train entry builds a real ``make_train_step`` (donated state,
  NaN guard on) over a single-device mesh,
* the sampling entry traces ``generate_image_tokens`` end to end.

All avals are abstract (``jax.eval_shape`` — no device execution, no
compilation), over a CANONICAL small config: byte budgets in the
contract are for this config, and what the audit guards is the *shape*
of the program (signature count, donation aliasing, readbacks, relative
footprint), which is config-independent. Changing the canonical config
is an intentional contract change — re-emit with
``python tools/lint.py --trace --emit-contract``.

Adding an entry point: build its abstract args here, declare its donated
args, list every signature the surrounding code can produce, and append
an ``EntryPoint``; then re-emit the contract and commit both (see
docs/DESIGN.md §11).
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import List

# absolute import: this module is loaded by FILE PATH (audit._load_registry,
# same mechanism fixture registries use), so it has no parent package
from lint.trace.types import EntryPoint, Signature

# the canonical audit model: tiny (trace cost, not fidelity, scales with
# size) but structurally the production shape — rotary, full attention,
# the same layer stack the serving gates drive (tools/serve_smoke.py)
CANON_MODEL = dict(
    dim=32, depth=2, num_text_tokens=16, text_seq_len=4,
    num_image_tokens=12, image_fmap_size=2, heads=2, dim_head=8,
    attn_types=("full",), rotary_emb=True,
)
# the canonical engine: chunked prefill on, the production serving shape
CANON_ENGINE = dict(max_batch=2, prefill_chunk=2)

# the canonical post-decode stage models (serving/postdecode.py): a VAE
# whose token space and image_seq_len MATCH the canonical DALLE
# (num_tokens == num_image_tokens, fmap == image_fmap_size, so the
# engine's generated ids are valid decode input), and a CLIP sized to
# the canonical text vocab/seq — the same tiny pair the serve-smoke
# stage drill and the stage bench build
CANON_VAE = dict(
    image_size=4, num_layers=1, num_tokens=12, codebook_dim=16,
    hidden_dim=8,
)
CANON_CLIP = dict(
    dim_text=16, dim_image=16, dim_latent=16, num_text_tokens=16,
    text_enc_depth=1, text_seq_len=4, text_heads=2, text_dim_head=8,
    num_visual_tokens=12, visual_enc_depth=1, visual_heads=2,
    visual_dim_head=8, visual_image_size=4, visual_patch_size=2,
)


def build_entry_points() -> List[EntryPoint]:
    import os

    import jax
    import jax.numpy as jnp

    # Pin the KV page size for this PROCESS (aval derivation here AND the
    # audit traces that follow): tests override DALLE_TPU_KV_PAGE_SIZE to
    # exercise page-boundary arithmetic on tiny models, and the smoke
    # gates' lint pre-flight subprocesses inherit that env — but the
    # committed contract describes the canonical program, so its cache
    # shapes must not drift with the caller's environment.
    from dalle_pytorch_tpu.ops.kv_policy import DEFAULT_PAGE_SIZE

    os.environ["DALLE_TPU_KV_PAGE_SIZE"] = str(DEFAULT_PAGE_SIZE)

    from dalle_pytorch_tpu.models import DALLE
    from dalle_pytorch_tpu.models.sampling import (
        generate_image_tokens,
        init_decode_cache,
        set_decode_offsets,
    )
    from dalle_pytorch_tpu.serving import engine as eng
    from dalle_pytorch_tpu.serving.engine import Engine, EngineConfig

    SDS = jax.ShapeDtypeStruct
    dalle = DALLE(**CANON_MODEL)
    cfg = EngineConfig(**CANON_ENGINE)
    B = cfg.max_batch
    T = dalle.text_len_internal

    text1 = SDS((1, dalle.text_seq_len), jnp.int32)
    image1 = SDS((1, dalle.image_seq_len), jnp.int32)
    params = jax.eval_shape(
        lambda t, i: dalle.init(jax.random.key(0), t, i), text1, image1
    )["params"]
    internal = jax.eval_shape(dalle.remap_text, text1)  # (1, T) with bos

    def cache_avals(b, kv_quant=None):
        def build(p):
            return set_decode_offsets(
                init_decode_cache(
                    dalle, p, b, cache_format="paged", kv_quant=kv_quant
                ),
                jnp.zeros((b,), jnp.int32),
            )
        return jax.eval_shape(build, params)

    cache1 = cache_avals(1)
    cacheB = cache_avals(B)
    # the quantized-KV engine (ops/kv_policy.py kv_quant="int8"): int8
    # content pools + parallel f32 scale pools — the cache aval change
    # behind EngineConfig.kv_quant, derived through the engine's own
    # init path so the committed contract (and its DTL141 byte budget,
    # the standing guard that quantized KV stays roughly half-size)
    # tracks the code
    cacheB_q = cache_avals(B, kv_quant="int8")
    key = jax.eval_shape(lambda: jax.random.key(0))
    keysB = jax.eval_shape(lambda: jnp.stack([jax.random.key(0)] * B))
    # the engine's own top-k formula (Engine.__init__: full-vocab-derived
    # fractional k over the image-only head)
    k_img = max(int((1 - cfg.filter_thres) * dalle.total_tokens), 1)
    i32 = SDS((), jnp.int32)

    # the prefix-cache engine variant (serving/prefix_cache.py): arena
    # rows appended to the BATCHED pools only — the one config knob that
    # changes a serving-jit cache aval. Arena sizing mirrors
    # Engine.__init__ exactly, via the engine's own helpers, so the
    # committed contract tracks the code, not a transcription of it.
    from dalle_pytorch_tpu.ops import kv_policy
    from dalle_pytorch_tpu.serving.engine import (
        _append_arena_rows, arena_rows_for,
    )
    from dalle_pytorch_tpu.serving.scheduler import pages_for

    page = kv_policy.page_size()
    n_pages_slot = pages_for(T + dalle.image_seq_len, page)
    arena_rows = arena_rows_for(None, pages_for(T, page), n_pages_slot)
    cacheB_arena = jax.eval_shape(
        lambda c: _append_arena_rows(c, arena_rows), cacheB
    )
    # quantized prefix engine: arena rows appended to the int8 + scale
    # pools — the publish/COW/restore copy jits run over this tree
    cacheB_q_arena = jax.eval_shape(
        lambda c: _append_arena_rows(c, arena_rows), cacheB_q
    )
    cache1_q = cache_avals(1, kv_quant="int8")
    # the cached terminal logits (the full-hit payload): the prefill
    # jits' third output, derived abstractly from the same trace
    logits1 = jax.eval_shape(
        lambda p, c, i, k: eng._prefill_jit.__wrapped__(
            dalle, p, c, i, k, k_img, 1.0
        ),
        params, cache1, internal, key,
    )[2]

    # the speculative fused engine (ROADMAP 2): the SAME checkpoint with
    # the token-shift ring widened by spec_k rows (the rollback slack) and
    # the block width stretched to carry a full verify row — both derived
    # through the engine's OWN helpers (spec_model / fused_width) so the
    # committed contract tracks the code, not a transcription of it
    from dalle_pytorch_tpu.serving.engine import fused_width, spec_model

    cfg_spec = EngineConfig(
        **CANON_ENGINE, fused_iteration=True, spec_decode=True,
    )
    dalle_spec = spec_model(dalle, cfg_spec.spec_k)
    W_spec = fused_width(cfg_spec)

    def cache_avals_for(model, b):
        def build(p):
            return set_decode_offsets(
                init_decode_cache(model, p, b, cache_format="paged"),
                jnp.zeros((b,), jnp.int32),
            )
        return jax.eval_shape(build, params)

    cacheB_spec = cache_avals_for(dalle_spec, B)
    # spec + prefix-cache composition: arena rows appended to the
    # ring-widened batched pools — page counts are seq-len-derived, so
    # the arena sizing is identical to the plain prefix engine's
    cacheB_spec_arena = jax.eval_shape(
        lambda c: _append_arena_rows(c, arena_rows), cacheB_spec
    )
    # per-slot BASE sampling keys (Engine._base_keys): the spec jit
    # derives the whole (B, W) key matrix from these in-trace
    keysB_base = jax.eval_shape(
        lambda: jnp.stack([jax.random.key(0)] * B)
    )
    # the donated fixed-shape page-copy jits (the PR 10 follow-on): call
    # vectors pad to the engine's copy width — at most one prompt's pages
    # (Engine.__init__: self._copy_pad)
    copy_pad = pages_for(T, page)
    copy_vec = SDS((copy_pad,), jnp.int32)

    # the donated prefix-map jit (this PR's follow-on closing the PR 10
    # set): its ids vector pads to the page-table ROW width, and the
    # shift-ring seam arrives as a keystr-keyed dict of row avals derived
    # from the cache tree itself — the same dict shape every admission
    # call builds from a prefix node's ring
    map_ids = SDS((n_pages_slot,), jnp.int32)

    def ring_avals(cache):
        rows = {}

        def fn(path, x):
            if getattr(path[-1], "key", None) == "shift_hist":
                rows[jax.tree_util.keystr(path)] = SDS(x.shape[1:], x.dtype)
            return x

        jax.tree_util.tree_map_with_path(fn, cache)
        return rows

    # chunk widths exactly as the engine schedules them: simulate the
    # REAL Engine._next_chunk (1-token tails merged) over (T, chunk)
    shim = SimpleNamespace(config=cfg, T=T)
    widths, filled = [], 0
    while filled < T:
        c = Engine._next_chunk(shim, filled)
        widths.append((c, filled + c >= T))
        filled += c
    chunk_widths = sorted({c for c, final in widths if not final})
    final_widths = sorted({c for c, final in widths if final})

    entries = [
        EntryPoint(
            name="serving.prefill",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_prefill_jit",
            fn=eng._prefill_jit,
            lower=eng._prefill_jit.lower,
            static_argnums=(0, 5),
            donate={"cache": 2},
            signatures=[Signature(
                "monolithic",
                (dalle, params, cache1, internal, key, k_img, 1.0),
            )],
        ),
        EntryPoint(
            name="serving.prefill_chunk",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_prefill_chunk_jit",
            fn=eng._prefill_chunk_jit,
            lower=eng._prefill_chunk_jit.lower,
            static_argnums=(0,),
            donate={"cache": 2},
            signatures=[
                Signature(
                    f"chunk_w{c}",
                    (dalle, params, cache1, SDS((1, c), jnp.int32), i32),
                )
                for c in chunk_widths
            ],
        ),
        EntryPoint(
            name="serving.prefill_last",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_prefill_last_jit",
            fn=eng._prefill_last_jit,
            lower=eng._prefill_last_jit.lower,
            static_argnums=(0, 5),
            donate={"cache": 2},
            signatures=[
                Signature(
                    f"final_w{c}",
                    (dalle, params, cache1, SDS((1, c), jnp.int32), i32,
                     k_img, key, 1.0),
                )
                for c in final_widths
            ],
        ),
        EntryPoint(
            name="serving.iteration",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_iteration_jit",
            fn=eng._iteration_jit,
            lower=eng._iteration_jit.lower,
            static_argnums=(0, 9, 10, 12),
            donate={"cache": 2},
            # the fused ragged iteration: descriptor raggedness is DATA,
            # so every steady prefill/decode mix is EXACTLY the "steady"
            # signature; "final" is the one additional class (iterations
            # containing a FINAL chunk run the per-row split-parity
            # heads — any_final is a host-known static). Both compile at
            # warmup; anything beyond these two is the
            # shape-drift-recompile bug class
            signatures=[
                Signature(
                    "steady",
                    (dalle, params, cacheB, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, False),
                ),
                Signature(
                    "final",
                    (dalle, params, cacheB, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, True),
                ),
            ],
        ),
        EntryPoint(
            name="serving.decode",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_decode_jit",
            fn=eng._decode_jit,
            lower=eng._decode_jit.lower,
            static_argnums=(0, 6),
            donate={"cache": 2},
            # steady state is EXACTLY one signature: the engine always
            # dispatches the full max_batch width with vectorized
            # positions/keys — any second signature here is the
            # batch-shape recompile bug class this audit exists to catch
            signatures=[Signature(
                "steady",
                (dalle, params, cacheB, SDS((B,), jnp.int32),
                 SDS((B,), jnp.int32), keysB, k_img, 1.0),
            )],
        ),
        EntryPoint(
            name="serving.iteration_prefix",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_iteration_jit",
            fn=eng._iteration_jit,
            lower=eng._iteration_jit.lower,
            static_argnums=(0, 9, 10, 12),
            donate={"cache": 2},
            # the prefix-cache engine's fused pair: the SAME program
            # logic over the arena-extended batched cache (extra storage
            # rows are content-only — tables/descriptors keep the B-wide
            # shape, so the signature count stays exactly two)
            signatures=[
                Signature(
                    "steady_arena",
                    (dalle, params, cacheB_arena, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, False),
                ),
                Signature(
                    "final_arena",
                    (dalle, params, cacheB_arena, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, True),
                ),
            ],
        ),
        EntryPoint(
            name="serving.decode_prefix",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_decode_jit",
            fn=eng._decode_jit,
            lower=eng._decode_jit.lower,
            static_argnums=(0, 6),
            donate={"cache": 2},
            # prefix-cache split engine: decode over the arena-extended
            # cache — still EXACTLY one steady signature
            signatures=[Signature(
                "steady_arena",
                (dalle, params, cacheB_arena, SDS((B,), jnp.int32),
                 SDS((B,), jnp.int32), keysB, k_img, 1.0),
            )],
        ),
        EntryPoint(
            name="serving.decode_quant",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_decode_jit",
            fn=eng._decode_jit,
            lower=eng._decode_jit.lower,
            static_argnums=(0, 6),
            donate={"cache": 2},
            # the quantized-KV engine's decode: the SAME program logic
            # over int8 pools + scale pools — still EXACTLY one steady
            # signature, at roughly half the cache bytes (the DTL141
            # budget difference vs serving.decode IS the capacity claim)
            signatures=[Signature(
                "steady_quant",
                (dalle, params, cacheB_q, SDS((B,), jnp.int32),
                 SDS((B,), jnp.int32), keysB, k_img, 1.0),
            )],
        ),
        EntryPoint(
            name="serving.iteration_quant",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_iteration_jit",
            fn=eng._iteration_jit,
            lower=eng._iteration_jit.lower,
            static_argnums=(0, 9, 10, 12),
            donate={"cache": 2},
            # the quantized fused iteration: quantize-at-append +
            # in-kernel dequant are in-trace data ops, so the signature
            # budget stays the same steady/final pair as
            # serving.iteration — a third signature is the same
            # shape-drift-recompile bug class
            signatures=[
                Signature(
                    "steady_quant",
                    (dalle, params, cacheB_q, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, False),
                ),
                Signature(
                    "final_quant",
                    (dalle, params, cacheB_q, SDS((B, T), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.bool_), keysB,
                     cfg.prefill_chunk, k_img, 1.0, True),
                ),
            ],
        ),
        EntryPoint(
            name="serving.sample_cached",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_sample_cached_jit",
            fn=eng._sample_cached_jit,
            lower=eng._sample_cached_jit.lower,
            static_argnums=(2,),
            donate={},
            # the full-prefix-hit first token: top-k + categorical over
            # the CACHED terminal logits — the only program a full hit
            # dispatches before entering decode
            signatures=[Signature(
                "hit", (logits1, key, k_img, 1.0),
            )],
        ),
        EntryPoint(
            name="serving.iteration_spec",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_spec_iteration_jit",
            fn=eng._spec_iteration_jit,
            lower=eng._spec_iteration_jit.lower,
            static_argnums=(0, 9, 10, 12, 13, 14),
            donate={"cache": 2},
            # the speculative fused iteration (ROADMAP 2): draft, verify,
            # and accept in ONE dispatch over the ring-widened model.
            # Descriptor raggedness (verify widths 1..spec_k+1, chunk
            # mixes, the spec_verify_abort plain-decode fallback) is all
            # DATA, so the steady state is EXACTLY the "steady" signature
            # plus the warm "final" class (any_final) — the same
            # two-signature budget as serving.iteration; a third
            # signature is the shape-drift-recompile bug class
            signatures=[
                Signature(
                    "steady",
                    (dalle_spec, params, cacheB_spec,
                     SDS((B, T), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.bool_), keysB_base, W_spec, k_img,
                     1.0, False, cfg_spec.spec_k,
                     cfg_spec.spec_draft_depth),
                ),
                Signature(
                    "final",
                    (dalle_spec, params, cacheB_spec,
                     SDS((B, T), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.bool_), keysB_base, W_spec, k_img,
                     1.0, True, cfg_spec.spec_k,
                     cfg_spec.spec_draft_depth),
                ),
            ],
        ),
        EntryPoint(
            name="serving.iteration_spec_prefix",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_spec_iteration_jit",
            fn=eng._spec_iteration_jit,
            lower=eng._spec_iteration_jit.lower,
            static_argnums=(0, 9, 10, 12, 13, 14),
            donate={"cache": 2},
            # the spec engine with the prefix cache on: the SAME program
            # over the arena-extended, ring-widened cache — the same
            # two-signature budget (the serving.iteration_prefix pattern)
            signatures=[
                Signature(
                    "steady_arena",
                    (dalle_spec, params, cacheB_spec_arena,
                     SDS((B, T), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.bool_), keysB_base, W_spec, k_img,
                     1.0, False, cfg_spec.spec_k,
                     cfg_spec.spec_draft_depth),
                ),
                Signature(
                    "final_arena",
                    (dalle_spec, params, cacheB_spec_arena,
                     SDS((B, T), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.int32), SDS((B,), jnp.int32),
                     SDS((B,), jnp.bool_), keysB_base, W_spec, k_img,
                     1.0, True, cfg_spec.spec_k,
                     cfg_spec.spec_draft_depth),
                ),
            ],
        ),
        EntryPoint(
            name="serving.page_copy",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_copy_pages_jit",
            fn=eng._copy_pages_jit,
            lower=eng._copy_pages_jit.lower,
            static_argnums=(),
            donate={"cache": 0},
            # the donated fixed-shape publish/COW page copy (the PR 10
            # follow-on): every call pads its src/dst/valid vectors to
            # the engine's copy width, so ONE signature per cache tree
            # covers publish, map-time COW, and every partial batch —
            # the eager pool-sized .at[].set rewrites this retired
            # stayed on the host path and re-traced per shape. The
            # speculative prefix engine publishes through the same jit
            # over the ring-widened arena tree: its one extra signature
            # is contracted here (the serving.iteration_spec_prefix
            # composition)
            signatures=[
                Signature(
                    "publish", (cacheB_arena, copy_vec, copy_vec, copy_vec),
                ),
                Signature(
                    "publish_spec",
                    (cacheB_spec_arena, copy_vec, copy_vec, copy_vec),
                ),
            ],
        ),
        EntryPoint(
            name="serving.page_copy_quant",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_copy_pages_jit",
            fn=eng._copy_pages_jit,
            lower=eng._copy_pages_jit.lower,
            static_argnums=(),
            donate={"cache": 0},
            # the quantized prefix engine's publish/COW copies (int8 +
            # scale pools). Its OWN entry, not a third serving.page_copy
            # signature: the audit lowers and alias-audits signature 0
            # only and reuses that count for later signatures, so a
            # tree with 4 extra scale leaves under the shared entry
            # would read as 4 host-visible outputs (loosening the
            # budget to 4 for the unquantized path too). As signature 0
            # here it is genuinely lowered: every leaf must alias into
            # the donated cache, keeping BOTH entries at the 0
            # host-visible budget.
            signatures=[Signature(
                "publish_quant",
                (cacheB_q_arena, copy_vec, copy_vec, copy_vec),
            )],
        ),
        EntryPoint(
            name="serving.page_copy_across",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_copy_pages_across_jit",
            fn=eng._copy_pages_across_jit,
            lower=eng._copy_pages_across_jit.lower,
            static_argnums=(),
            donate={"dst_cache": 0},
            # the split engine's partial-hit restore: arena pages out of
            # the batched pools into a private batch-1 prefill cache,
            # destination donated, same padded shape
            signatures=[Signature(
                "restore",
                (cache1, cacheB_arena, copy_vec, copy_vec, copy_vec),
            )],
        ),
        EntryPoint(
            name="serving.prefix_map",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_map_prefix_jit",
            fn=eng._map_prefix_jit,
            lower=eng._map_prefix_jit.lower,
            static_argnums=(),
            donate={"cache": 0},
            # the donated prefix-hit publish/map (the last PR 10 follow-on):
            # page-table row, cache/shift indices, and shift-ring seam land
            # in ONE fixed-shape dispatch — one signature per cache tree it
            # mutates: the fused/full-hit map over the batched arena tree,
            # the split engine's batch-1 seeding (n_ids == 0), and the spec
            # engine's composition over the ring-widened arena tree
            signatures=[
                Signature(
                    "map_batched",
                    (cacheB_arena, i32, map_ids, i32, i32,
                     ring_avals(cacheB_arena)),
                ),
                Signature(
                    "seed_split",
                    (cache1, i32, map_ids, i32, i32, ring_avals(cache1)),
                ),
                Signature(
                    "map_spec",
                    (cacheB_spec_arena, i32, map_ids, i32, i32,
                     ring_avals(cacheB_spec_arena)),
                ),
            ],
        ),
        EntryPoint(
            name="serving.prefix_map_quant",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_map_prefix_jit",
            fn=eng._map_prefix_jit,
            lower=eng._map_prefix_jit.lower,
            static_argnums=(),
            donate={"cache": 0},
            # quantized prefix engine's map/seed — own entry for the same
            # signature-0 aliasing-audit reason as serving.page_copy_quant
            signatures=[
                Signature(
                    "map_quant",
                    (cacheB_q_arena, i32, map_ids, i32, i32,
                     ring_avals(cacheB_q_arena)),
                ),
                Signature(
                    "seed_split_quant",
                    (cache1_q, i32, map_ids, i32, i32, ring_avals(cache1_q)),
                ),
            ],
        ),
        EntryPoint(
            name="serving.page_copy_across_quant",
            path="dalle_pytorch_tpu/serving/engine.py",
            symbol="_copy_pages_across_jit",
            fn=eng._copy_pages_across_jit,
            lower=eng._copy_pages_across_jit.lower,
            static_argnums=(),
            donate={"dst_cache": 0},
            # quantized split-engine partial-hit restore — own entry for
            # the same signature-0 aliasing-audit reason as
            # serving.page_copy_quant
            signatures=[Signature(
                "restore_quant",
                (cache1_q, cacheB_q_arena, copy_vec, copy_vec, copy_vec),
            )],
        ),
        *_stage_entries(),
        _train_entry(dalle, B),
        _block_sparse_entry(dalle, T),
        EntryPoint(
            name="sampling.generate",
            path="dalle_pytorch_tpu/models/sampling.py",
            symbol="generate_image_tokens",
            fn=lambda p, t, k: generate_image_tokens(dalle, p, t, k),
            lower=None,
            static_argnums=(),
            donate={},
            signatures=[Signature(
                "batch1", (params, text1, key),
            )],
        ),
    ]
    return entries


def _stage_entries() -> List[EntryPoint]:
    """The post-decode stage jits (serving/postdecode.py, DESIGN.md §8.5):
    batched fixed-shape VAE decode and CLIP rerank. The pipeline pads
    every dispatch to its configured batch width (StageConfig.batch ==
    the canonical engine's max_batch), so each jit has EXACTLY one
    steady signature — a second signature is the shape-drift-recompile
    bug class, and the in-bench zero-in-trace-compile assertion
    (bench.py --serve, stage record) holds only because of it. VAE
    params are the decode-scope tree (``init(..., method="decode")``):
    the pipeline's contract is token ids -> pixels, so the encoder
    never rides along. No donation: stage tensors are tiny relative to
    the KV pools, and the image must survive the dispatch (it is the
    journal payload and the degraded-completion partial)."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.clip import CLIP
    from dalle_pytorch_tpu.models.vae import DiscreteVAE
    from dalle_pytorch_tpu.serving import postdecode as pd

    SDS = jax.ShapeDtypeStruct
    S = CANON_ENGINE["max_batch"]  # == StageConfig default batch
    vae = DiscreteVAE(**CANON_VAE)
    clip = CLIP(**CANON_CLIP)
    img_seq = SDS((1, vae.image_seq_len), jnp.int32)
    vae_params = jax.eval_shape(
        lambda i: vae.init(jax.random.key(0), i, method="decode"), img_seq
    )["params"]
    text1 = SDS((1, clip.text_seq_len), jnp.int32)
    pix1 = SDS((1, vae.image_size, vae.image_size, vae.channels),
               jnp.float32)
    clip_params = jax.eval_shape(
        lambda t, i: clip.init(jax.random.key(0), t, i), text1, pix1
    )["params"]
    return [
        EntryPoint(
            name="serving.vae_decode",
            path="dalle_pytorch_tpu/serving/postdecode.py",
            symbol="_vae_decode_jit",
            fn=pd._vae_decode_jit,
            lower=pd._vae_decode_jit.lower,
            static_argnums=(0,),
            donate={},
            signatures=[Signature(
                "steady",
                (vae, vae_params, SDS((S, vae.image_seq_len), jnp.int32)),
            )],
        ),
        EntryPoint(
            name="serving.clip_rerank",
            path="dalle_pytorch_tpu/serving/postdecode.py",
            symbol="_clip_rerank_jit",
            fn=pd._clip_rerank_jit,
            lower=pd._clip_rerank_jit.lower,
            static_argnums=(0,),
            donate={},
            # images arrive at the VAE's output size; the in-trace
            # bilinear resize to the CLIP patch grid is data, not shape
            signatures=[Signature(
                "steady",
                (clip, clip_params, SDS((S, clip.text_seq_len), jnp.int32),
                 SDS((S, vae.image_size, vae.image_size, vae.channels),
                     jnp.float32)),
            )],
        ),
    ]


def _block_sparse_entry(dalle, T: int) -> EntryPoint:
    """The pair-grid block-sparse attention kernel
    (ops/block_sparse_attention.py) over a canonical axial layout at the
    audit model's internal sequence — the jit the sparse training/prefill
    paths route through behind DALLE_TPU_SPARSE_KERNEL. Abstract trace
    only (lower=None): Pallas calls abstract-eval fine, and the audit
    guards the program shape (signatures, no readbacks), while the
    numerical contract lives in tests/test_block_sparse.py's interpret
    parity tier."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.ops import block_sparse_attention as bs
    from dalle_pytorch_tpu.ops import masks as masks_lib

    SDS = jax.ShapeDtypeStruct
    n = T + dalle.image_seq_len
    layout = bs.compile_block_layout(
        masks_lib.axial_mask(T, dalle.image_fmap_size, axis=0)[:n, :n], 4, 4
    )
    fn = jax.jit(
        lambda q, k, v: bs.block_sparse_attention(
            q, k, v, layout, interpret=True
        )
    )
    qkv = SDS((1, dalle.heads, n, dalle.dim_head), jnp.float32)
    return EntryPoint(
        name="ops.block_sparse",
        path="dalle_pytorch_tpu/ops/block_sparse_attention.py",
        symbol="block_sparse_attention",
        fn=fn,
        lower=None,
        static_argnums=(),
        donate={},
        signatures=[Signature("axial", (qkv, qkv, qkv))],
    )


def _train_entry(dalle, batch: int) -> EntryPoint:
    """A real ``make_train_step`` (donate=True, nan_guard=True) over a
    single-device mesh, with the canonical model's own weighted-CE loss
    — auditing the builder everything in train_dalle.py runs through."""
    import jax
    import jax.numpy as jnp
    import optax

    from dalle_pytorch_tpu.parallel.mesh import make_runtime
    from dalle_pytorch_tpu.parallel.sharding import (
        opt_state_shardings,
        params_shardings,
    )
    from dalle_pytorch_tpu.parallel.step import (
        TrainState,
        make_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    SDS = jax.ShapeDtypeStruct
    # ONE device, always: the audit must derive the same signatures and
    # byte budgets on a laptop, under the test suite's 8-fake-device
    # XLA_FLAGS, and on a real pod — the contract is about the program,
    # not the host it was traced on
    runtime = make_runtime(devices=jax.devices()[:1])
    optimizer = optax.adam(1e-3)

    def loss_fn(params, batch, rng):
        text, image = batch
        return dalle.apply({"params": params}, text, image, return_loss=True)

    text = SDS((batch, dalle.text_seq_len), jnp.int32)
    image = SDS((batch, dalle.image_seq_len), jnp.int32)
    params = jax.eval_shape(
        lambda t, i: dalle.init(jax.random.key(0), t, i), text, image
    )["params"]
    opt_state = jax.eval_shape(optimizer.init, params)
    i32 = SDS((), jnp.int32)
    state = TrainState(
        step=i32, params=params, opt_state=opt_state,
        skipped=i32, consec_skipped=i32,
    )
    p_shard = params_shardings(params, runtime.mesh)
    replicated = NamedSharding(runtime.mesh, P())
    shardings = TrainState(
        step=replicated, params=p_shard,
        opt_state=opt_state_shardings(opt_state, p_shard, runtime.mesh),
        skipped=replicated, consec_skipped=replicated,
    )
    train_step = make_train_step(
        loss_fn, optimizer, runtime, shardings, donate=True
    )
    key = jax.eval_shape(lambda: jax.random.key(0))
    return EntryPoint(
        name="train.step",
        path="dalle_pytorch_tpu/parallel/step.py",
        symbol="make_train_step",
        fn=train_step,
        lower=train_step.lower,
        static_argnums=(),
        donate={"state": 0},
        signatures=[Signature("step", (state, (text, image), key))],
    )
