"""Trace-stage data types: entry-point registry records.

Deliberately jax-free: a registry module (the repo's
``tools/lint/trace/registry.py`` or a test fixture) imports these to
DECLARE its entry points; the tracing itself lives in ``audit.py``.

An :class:`EntryPoint` names one jitted program the production code
dispatches on a hot path, the closed set of abstract call signatures the
surrounding code can feed it, and the donation contract its source
declares. ``audit.py`` traces each signature to a ClosedJaxpr (abstract
avals only — no device execution) and checks the result against the
committed contract file (``tools/trace_contracts.json``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Signature:
    """One abstract call signature: the full positional argument tuple,
    with dynamic arguments as ``jax.ShapeDtypeStruct`` pytrees and static
    arguments (positions in ``EntryPoint.static_argnums``) as the
    concrete values the caller passes."""

    label: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class EntryPoint:
    """One registered jit entry point.

    ``fn`` is the callable to trace (usually the jitted function itself;
    ``jax.make_jaxpr`` traces through it). ``lower`` is its ``.lower``
    bound method when the target is jitted — the donation/aliasing audit
    reads the lowered computation — or None for plain callables (which
    then must declare no donation). ``donate`` maps the DECLARED donated
    argument names to their positions in the signature; the audit
    verifies the declaration against both the traced program
    (``donated_invars``) and the lowered aliasing
    (``tf.aliasing_output``)."""

    name: str
    path: str                       # repo-relative file (finding anchor)
    symbol: str                     # def name, for line lookup
    fn: Callable[..., Any]
    signatures: Sequence[Signature]
    static_argnums: Tuple[int, ...] = ()
    donate: Dict[str, int] = field(default_factory=dict)
    lower: Optional[Callable[..., Any]] = None
