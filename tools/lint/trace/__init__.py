"""dalle-tpu-lint, stage 2: trace-level program audit (``--trace``).

The AST stage (``tools/lint/``, DTL0xx) checks what the *source* says;
this stage checks what XLA actually gets. Every registered jit entry
point (``registry.py``: the four serving jits, ``make_train_step``,
``generate_image_tokens``) is traced to a ClosedJaxpr over abstract
avals — ``jax.eval_shape``/``jax.make_jaxpr`` on CPU, no device
execution, no compilation — and audited against a committed contract
file (``tools/trace_contracts.json``).

Finding codes (docs/DESIGN.md §11):

=========  ==================================================================
DTL101     a registered entry point has no contract entry (uncommitted)
DTL102     a contract entry matches no registered entry point (stale —
           fails ``--check`` until pruned, like a stale baseline key)
DTL111     the registry derives a compile signature the contract does not
           list — an unlisted signature is a runtime recompile (the
           shape-drift bug class); steady-state ``_decode_jit`` is
           contracted to EXACTLY one signature
DTL112     the contract lists a signature the registry no longer produces
           (stale signature entry)
DTL113     distinct signature count exceeds the entry's budget
DTL121     donation drift: a declared donated arg is not donated in the
           traced program, or the program donates an undeclared arg
DTL122     a donated buffer is not actually aliased input→output in the
           lowered computation (``tf.aliasing_output``) — the donation
           frees nothing and still invalidates the caller's array
DTL131     host-callback eqns (``io_callback``/``pure_callback``/
           ``debug_callback``) exceed the entry's budget
DTL132     host-visible (non-donation-aliased) outputs exceed the entry's
           readback budget — the decode hot loop is contracted to at most
           ONE readback per iteration (the PR 5 lookahead seam)
DTL141     static HBM footprint (argument + output − donated-alias aval
           bytes) exceeds the entry's byte budget — live state silently
           grew
=========  ==================================================================

Unlike the AST stage this package imports jax AND the package under
audit — ``tools/lint/__init__.py`` must never import it; ``tools/
lint.py`` loads it only under ``--trace``. Findings flow through the
same suppression/baseline machinery and compose with the AST stage in
one exit code. ``--emit-contract`` regenerates the contract from the
current registry (the blessed-update workflow after an intentional
change).
"""

from __future__ import annotations

from .audit import (
    audit_entry,
    check_reports,
    emit_contract,
    load_contract,
    run_trace,
    trace_reports_only,
)
from .types import EntryPoint, Signature

__all__ = [
    "EntryPoint",
    "Signature",
    "audit_entry",
    "check_reports",
    "emit_contract",
    "load_contract",
    "run_trace",
    "trace_reports_only",
]
