"""The trace-stage auditor: abstract tracing + contract checking.

For every registered :class:`~.types.EntryPoint` signature this module

* traces the call to a ClosedJaxpr with ``jax.make_jaxpr`` over abstract
  avals (``jax.ShapeDtypeStruct``) — CPU-safe, no device execution, no
  compilation — and
* (for jitted targets) lowers it with ``fn.lower(...)`` to read the
  donation flags (``Lowered.args_info``) and the input→output buffer
  aliasing XLA was actually handed (``tf.aliasing_output`` markers in
  the StableHLO module text).

The per-signature facts (signature key, callback equation counts,
host-visible outputs, argument/output/aliased byte totals) are folded
into one report per entry point and checked against the committed
contract file (``tools/trace_contracts.json``), yielding DTL1xx
findings (see ``tools/lint/trace/__init__.py`` for the code table).

``emit_contract`` regenerates the contract JSON from the current
registry — the blessed-update workflow after an intentional change, the
same shape as re-baselining the AST stage.
"""

from __future__ import annotations

import hashlib
import importlib.util
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..core import Finding
from .types import EntryPoint, Signature

# primitives whose presence in a hot-loop jaxpr means a host round-trip
# (io_callback / pure_callback / debug_callback a.k.a. jax.debug.print);
# matched by name so new callback flavors fail loud rather than slip by
_CALLBACK_NAME_FRAGMENT = "callback"
_CALLBACK_EXTRA = {"debug_print"}

_DTYPE_BYTES = {
    "f64": 8, "i64": 8, "ui64": 8, "c64": 8,
    "f32": 4, "i32": 4, "ui32": 4,
    "f16": 2, "bf16": 2, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
}


# --------------------------------------------------------------- tracing


def _aval_bytes(aval) -> int:
    """Byte size of one aval; extended dtypes (PRNG keys) report their
    true itemsize (a fry key is 2x uint32 = 8 bytes)."""
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * int(aval.dtype.itemsize)


def _leaf_token(leaf) -> str:
    if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
        # python scalar (e.g. a traced temperature float): abstract it the
        # way jit would
        import jax

        leaf = jax.eval_shape(lambda x: x, leaf)
    shape = "x".join(str(int(d)) for d in leaf.shape)
    return f"{leaf.dtype}[{shape}]"


def _sig_key(ep: EntryPoint, sig: Signature) -> str:
    """Deterministic identity of one call signature: per-argument tokens
    joined with ``|``. Static args contribute their repr (hashed when
    long), single arrays their aval, pytrees a content hash plus leaf
    and byte counts — compact enough for a committed contract file,
    exact enough that any shape/dtype/static drift changes the key."""
    import jax

    tokens: List[str] = []
    for i, arg in enumerate(sig.args):
        if i in ep.static_argnums:
            r = repr(arg)
            tokens.append(
                f"s:{r}" if len(r) <= 24
                else "s:#" + hashlib.sha1(r.encode()).hexdigest()[:10]
            )
            continue
        leaves = jax.tree_util.tree_leaves(arg)
        if len(leaves) == 1 and leaves[0] is arg:
            tokens.append(_leaf_token(arg))
        else:
            joined = ";".join(_leaf_token(x) for x in leaves)
            digest = hashlib.sha1(joined.encode()).hexdigest()[:10]
            nbytes = sum(_aval_bytes(x) for x in leaves)
            tokens.append(f"tree#{digest}({len(leaves)}L,{nbytes}B)")
    return "|".join(tokens)


def _iter_subjaxprs(v):
    """Duck-typed jaxpr discovery inside eqn params (works across jax
    versions without importing private core modules): a ClosedJaxpr has
    ``.jaxpr``, a raw Jaxpr has ``.eqns``."""
    if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
        yield v.jaxpr
    elif hasattr(v, "eqns"):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _iter_subjaxprs(x)


def _count_callbacks(jaxpr, out: Dict[str, int]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if _CALLBACK_NAME_FRAGMENT in name or name in _CALLBACK_EXTRA:
            out[name] = out.get(name, 0) + 1
        for v in eqn.params.values():
            for sub in _iter_subjaxprs(v):
                _count_callbacks(sub, out)


def _tensor_bytes(tensor_type: str) -> int:
    """Bytes of an MLIR ``tensor<2x5xf32>`` type string (``tensor<f32>``
    is a scalar). Unknown element types count as 0 — HBM accounting
    degrades, the gate never crashes on an exotic dtype."""
    inner = tensor_type[len("tensor<"):-1]
    parts = inner.split("x")
    dims, elem = [], parts[-1]
    for p in parts[:-1]:
        dims.append(int(p))
    n = 1
    for d in dims:
        n *= d
    return n * _DTYPE_BYTES.get(elem, 0)


_ARG_RE = re.compile(r"%arg(\d+): (tensor<[^>]*>)")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")


def _parse_main_aliasing(text: str) -> List[Tuple[int, str, Optional[int]]]:
    """Per-argument (index, tensor type, aliased-output-index-or-None)
    parsed from the ``@main(...)`` signature of the lowered module text.
    Segments between ``%argN:`` tokens carry each argument's attribute
    dict; quotes inside attributes cannot contain ``%arg``, so token
    splitting is unambiguous."""
    start = text.find("@main(")
    if start < 0:
        return []
    i = start + len("@main(")
    depth = 1
    j = i
    while j < len(text) and depth:
        c = text[j]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        j += 1
    region = text[i:j - 1]
    matches = list(_ARG_RE.finditer(region))
    out: List[Tuple[int, str, Optional[int]]] = []
    for k, m in enumerate(matches):
        seg_end = matches[k + 1].start() if k + 1 < len(matches) else len(region)
        segment = region[m.start():seg_end]
        alias = _ALIAS_RE.search(segment)
        out.append((
            int(m.group(1)), m.group(2),
            int(alias.group(1)) if alias else None,
        ))
    return out


def audit_entry(ep: EntryPoint) -> Dict[str, Any]:
    """Trace every declared signature of one entry point. Returns the
    per-entry report the checkers (and ``--emit-contract``) consume."""
    import jax

    sig_reports: List[Dict[str, Any]] = []
    donated_argnums: List[int] = []
    donated_leaves = 0
    alias_markers = 0
    aliased_outputs = 0
    aliased_bytes = 0
    lowered_checked = False

    for si, sig in enumerate(ep.signatures):
        jaxpr = jax.make_jaxpr(
            ep.fn, static_argnums=ep.static_argnums or ()
        )(*sig.args)
        callbacks: Dict[str, int] = {}
        _count_callbacks(jaxpr.jaxpr, callbacks)
        in_bytes = sum(_aval_bytes(a) for a in jaxpr.in_avals)
        out_bytes = sum(_aval_bytes(a) for a in jaxpr.out_avals)
        n_out = len(jaxpr.out_avals)

        sig_aliased_out = 0
        sig_aliased_bytes = 0
        if ep.lower is not None and si == 0:
            # donation structure is signature-independent (same code
            # path, same donate_argnums) — lower once, on the first
            lowered = ep.lower(*sig.args)
            info_args = lowered.args_info[0]
            for pos, arg_info in enumerate(info_args):
                flags = [
                    bool(getattr(x, "donated", False))
                    for x in jax.tree_util.tree_leaves(arg_info)
                ]
                if any(flags):
                    # map dynamic position back to the original argnum
                    orig = pos
                    for s in sorted(ep.static_argnums or ()):
                        if s <= orig:
                            orig += 1
                    donated_argnums.append(orig)
                    donated_leaves += sum(flags)
            args = _parse_main_aliasing(lowered.as_text())
            marker_outputs = set()
            for _idx, ttype, alias in args:
                if alias is not None:
                    alias_markers += 1
                    marker_outputs.add(alias)
                    sig_aliased_bytes += _tensor_bytes(ttype)
            sig_aliased_out = len(marker_outputs)
            lowered_checked = True
        elif lowered_checked:
            # other signatures alias the same way; reuse sig 0's totals
            sig_aliased_out = aliased_outputs
            sig_aliased_bytes = aliased_bytes
        if si == 0:
            aliased_outputs = sig_aliased_out
            aliased_bytes = sig_aliased_bytes

        sig_reports.append({
            "label": sig.label,
            "key": _sig_key(ep, sig),
            "callbacks": callbacks,
            "n_callbacks": sum(callbacks.values()),
            "n_outputs": n_out,
            "host_visible_outputs": n_out - sig_aliased_out,
            "arg_bytes": in_bytes,
            "out_bytes": out_bytes,
            "aliased_bytes": sig_aliased_bytes,
            "hbm_bytes": in_bytes + out_bytes - sig_aliased_bytes,
        })

    return {
        "name": ep.name,
        "path": ep.path,
        "symbol": ep.symbol,
        "declared_donate": dict(ep.donate),
        "lowered": ep.lower is not None,
        "donated_argnums": sorted(set(donated_argnums)),
        "donated_leaves": donated_leaves,
        "alias_markers": alias_markers,
        "signatures": sig_reports,
        "max_callbacks": max(s["n_callbacks"] for s in sig_reports),
        "max_host_visible_outputs": max(
            s["host_visible_outputs"] for s in sig_reports
        ),
        "max_hbm_bytes": max(s["hbm_bytes"] for s in sig_reports),
    }


# ---------------------------------------------------------- the contract


def load_contract(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(
            f"trace contract {path}: want a JSON object with an "
            f'"entries" map, got {type(data).__name__}'
        )
    return data


def emit_contract(reports: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Contract JSON derived from the current registry + trace — commit
    the output after an INTENTIONAL change (new signature, bigger live
    state), exactly like re-baselining."""
    entries: Dict[str, Any] = {}
    for r in sorted(reports, key=lambda r: r["name"]):
        entries[r["name"]] = {
            "path": r["path"],
            "max_signatures": len(r["signatures"]),
            "signatures": [
                {"label": s["label"], "key": s["key"]}
                for s in r["signatures"]
            ],
            "donate": sorted(r["declared_donate"]),
            "max_host_callbacks": r["max_callbacks"],
            "max_host_visible_outputs": r["max_host_visible_outputs"],
            "max_hbm_bytes": r["max_hbm_bytes"],
        }
    return {"version": 1, "entries": entries}


def _def_line(repo_root: str, rel_path: str, symbol: str) -> int:
    """Line of ``def <symbol>`` in the entry's source file (1 if the
    file or def is missing — the finding still renders)."""
    path = os.path.join(repo_root, rel_path)
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if re.match(rf"\s*def {re.escape(symbol)}\b", line):
                    return i
    except OSError:
        pass
    return 1


def check_reports(
    reports: List[Dict[str, Any]],
    contract: Dict[str, Any],
    contract_path: str,
    repo_root: str,
) -> List[Finding]:
    """Compare audit reports against the committed contract; every
    divergence is a DTL1xx finding anchored on the entry point."""
    findings: List[Finding] = []
    entries = contract.get("entries", {})
    by_name = {r["name"]: r for r in reports}

    def add(code, rep, msg, anchor_suffix="", path=None, line=None):
        rel = path if path is not None else rep["path"]
        findings.append(Finding(
            code=code,
            path=rel,
            line=line if line is not None
            else _def_line(repo_root, rel, rep["symbol"]),
            message=msg,
            anchor=rep["name"] + anchor_suffix,
        ))

    for name in sorted(set(entries) - set(by_name)):
        findings.append(Finding(
            code="DTL102", path=contract_path, line=1,
            message=f"contract entry '{name}' matches no registered "
                    f"trace entry point — prune it (the contract, like "
                    f"the baseline, can only track live code)",
            anchor=name,
        ))

    for rep in reports:
        name = rep["name"]
        c = entries.get(name)
        if c is None:
            add("DTL101", rep,
                f"entry point '{name}' has no committed contract entry — "
                f"run `python tools/lint.py --trace --emit-contract` and "
                f"review the diff")
            continue

        # ---- DTL11x: compile-signature budget -------------------------
        listed = {s["key"]: s.get("label", "") for s in c.get("signatures", [])}
        produced = {s["key"]: s["label"] for s in rep["signatures"]}
        for key, label in sorted(produced.items()):
            if key not in listed:
                add("DTL111", rep,
                    f"'{name}' can be fed signature [{label}] {key} that "
                    f"the contract does not list — an unlisted signature "
                    f"is a recompile the serving/train loop would eat at "
                    f"runtime", anchor_suffix=f":{label}")
        for key, label in sorted(listed.items()):
            if key not in produced:
                add("DTL112", rep,
                    f"contract lists signature [{label}] {key} for "
                    f"'{name}' that the registry no longer produces — "
                    f"stale contract entries must be pruned",
                    anchor_suffix=f":{key[:24]}")
        max_sigs = c.get("max_signatures")
        if max_sigs is not None and len(produced) > max_sigs:
            add("DTL113", rep,
                f"'{name}' is fed {len(produced)} distinct compile "
                f"signatures, contract budget is {max_sigs} — every "
                f"extra signature is a steady-state recompile")

        # ---- DTL12x: donation audit -----------------------------------
        declared = set(c.get("donate", []))
        registry_declared = rep["declared_donate"]
        for arg in sorted(declared - set(registry_declared)):
            add("DTL121", rep,
                f"contract declares donated arg '{arg}' for '{name}' but "
                f"the registry maps no such argument — fix the contract "
                f"or the registry entry", anchor_suffix=f":{arg}")
        if rep["lowered"]:
            actual = set(rep["donated_argnums"])
            for arg in sorted(declared & set(registry_declared)):
                if registry_declared[arg] not in actual:
                    add("DTL121", rep,
                        f"'{name}' declares donation of '{arg}' (arg "
                        f"{registry_declared[arg]}) but the traced "
                        f"program does not donate it — the buffer is "
                        f"double-buffered in HBM for every call",
                        anchor_suffix=f":{arg}")
            declared_nums = {
                registry_declared[a] for a in declared
                if a in registry_declared
            }
            undeclared = actual - declared_nums
            if undeclared:
                add("DTL121", rep,
                    f"'{name}' donates arg(s) {sorted(undeclared)} the "
                    f"contract does not declare — donation is a caller "
                    f"contract (the passed buffer dies) and must be "
                    f"committed, not implicit", anchor_suffix=":undeclared")
            if rep["donated_leaves"] > rep["alias_markers"]:
                add("DTL122", rep,
                    f"'{name}' donates {rep['donated_leaves']} buffers "
                    f"but only {rep['alias_markers']} are aliased "
                    f"input→output in the lowered computation — the "
                    f"unaliased donations free nothing and still "
                    f"invalidate the caller's arrays")
        elif declared:
            add("DTL122", rep,
                f"'{name}' declares donated args {sorted(declared)} but "
                f"is not a jitted target — nothing can alias")

        # ---- DTL13x: host-sync / readback audit -----------------------
        max_cb = c.get("max_host_callbacks")
        if max_cb is not None and rep["max_callbacks"] > max_cb:
            per = {
                k: v for s in rep["signatures"]
                for k, v in s["callbacks"].items()
            }
            add("DTL131", rep,
                f"'{name}' contains {rep['max_callbacks']} host-callback "
                f"eqn(s) {per}, budget {max_cb} — each is a device→host "
                f"round-trip inside a hot-loop jit")
        max_vis = c.get("max_host_visible_outputs")
        if max_vis is not None and rep["max_host_visible_outputs"] > max_vis:
            add("DTL132", rep,
                f"'{name}' exposes {rep['max_host_visible_outputs']} "
                f"host-visible (non-donation-aliased) outputs, budget "
                f"{max_vis} — the per-iteration readback contract "
                f"(one decode step = at most one host read) is broken")

        # ---- DTL14x: static HBM footprint -----------------------------
        max_hbm = c.get("max_hbm_bytes")
        if max_hbm is not None and rep["max_hbm_bytes"] > max_hbm:
            worst = max(rep["signatures"], key=lambda s: s["hbm_bytes"])
            add("DTL141", rep,
                f"'{name}' static HBM footprint {rep['max_hbm_bytes']}B "
                f"(args {worst['arg_bytes']}B + outputs "
                f"{worst['out_bytes']}B - aliased "
                f"{worst['aliased_bytes']}B) exceeds the contract budget "
                f"{max_hbm}B — live state grew; if intentional, re-emit "
                f"the contract")

    return findings


# ------------------------------------------------------------ the runner


def _load_registry(repo_root: str, registry_path: str):
    """Import a registry module by file path (the repo's or a fixture's).
    The repo root goes on sys.path first so the registry can import the
    package it audits."""
    ab = (registry_path if os.path.isabs(registry_path)
          else os.path.join(repo_root, registry_path))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    # registries import `lint.trace.types` absolutely (they are loaded by
    # file path, without a parent package) — make the lint package root
    # importable regardless of how we were invoked
    tools_dir = os.path.join(repo_root, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    name = "_dalle_trace_registry_" + hashlib.sha1(
        ab.encode()
    ).hexdigest()[:8]
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, ab)
    if spec is None or spec.loader is None:
        raise OSError(f"cannot load trace registry {ab}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    if not hasattr(mod, "build_entry_points"):
        raise ValueError(
            f"trace registry {registry_path} must define "
            f"build_entry_points() -> list[EntryPoint]"
        )
    return mod


def run_trace(
    repo_root: str,
    registry_path: str,
    contract_path: str,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """The ``--trace`` stage: load the registry, audit every entry,
    check against the contract. Returns (findings, reports); findings
    feed the shared suppression/baseline machinery in ``core.run_lint``."""
    mod = _load_registry(repo_root, registry_path)
    eps: List[EntryPoint] = mod.build_entry_points()
    reports = [audit_entry(ep) for ep in eps]
    ab_contract = (contract_path if os.path.isabs(contract_path)
                   else os.path.join(repo_root, contract_path))
    if not os.path.exists(ab_contract):
        raise OSError(
            f"trace contract file {contract_path} not found — generate "
            f"it with `python tools/lint.py --trace --emit-contract > "
            f"{contract_path}`"
        )
    contract = load_contract(ab_contract)
    rel_contract = contract_path.replace(os.sep, "/")
    findings = check_reports(reports, contract, rel_contract, repo_root)
    return findings, reports


def trace_reports_only(repo_root: str, registry_path: str):
    """Audit without a contract (``--emit-contract`` path)."""
    mod = _load_registry(repo_root, registry_path)
    return [audit_entry(ep) for ep in mod.build_entry_points()]
